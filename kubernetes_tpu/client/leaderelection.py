"""Leader election: active/passive HA for scheduler & controller-manager.

Parity target: reference pkg/client/leaderelection/leaderelection.go:81,170,
241 — a CAS lease stored as an annotation on an Endpoints object:
tryAcquireOrRenew reads the LeaderElectionRecord, takes the lease if absent/
expired, renews if held, and the loop fires OnStartedLeading/OnStoppedLeading.
Crash-only: a leader that stops renewing is superseded after lease_duration.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from dataclasses import dataclass
from typing import Callable, Optional

from kubernetes_tpu.api import types as api
from kubernetes_tpu.client.rest import ApiError, RESTClient

LEADER_ANNOTATION = "control-plane.alpha.kubernetes.io/leader"

log = logging.getLogger("leaderelection")


@dataclass
class LeaderElectionConfig:
    lock_namespace: str = "kube-system"
    lock_name: str = "leader-lock"
    identity: str = "unknown"
    lease_duration: float = 15.0
    renew_deadline: float = 10.0
    retry_period: float = 2.0


class LeaderElector:
    def __init__(self, client: RESTClient, config: LeaderElectionConfig,
                 on_started_leading: Callable[[], None],
                 on_stopped_leading: Optional[Callable[[], None]] = None,
                 clock=time.monotonic):
        # `clock` drives lease expiry and renew deadlines — durations
        # relative to our own observations, so it must not jump with NTP
        # steps (the reference measures against observedTime the same way,
        # leaderelection.go:81). The acquire/renew TIMESTAMPS serialized
        # into the lease record stay wall-clock: they are cross-process
        # debug data, never compared against this clock.
        self.client = client
        self.cfg = config
        self.on_started = on_started_leading
        self.on_stopped = on_stopped_leading
        self._clock = clock
        self._stop = threading.Event()
        self._is_leader = False
        self._observed_record: Optional[dict] = None
        self._observed_time = 0.0
        self._thread: Optional[threading.Thread] = None

    @property
    def is_leader(self) -> bool:
        return self._is_leader

    # --- the CAS attempt (tryAcquireOrRenew, leaderelection.go:241) ----------

    def try_acquire_or_renew(self) -> bool:
        now = self._clock()
        wall_now = time.time()  # serialized into the record; never compared
        record = {
            "holderIdentity": self.cfg.identity,
            "leaseDurationSeconds": int(self.cfg.lease_duration),
            "acquireTime": wall_now,
            "renewTime": wall_now,
        }
        try:
            ep = self.client.get("endpoints", self.cfg.lock_name,
                                 self.cfg.lock_namespace)
        except ApiError as e:
            if not e.is_not_found:
                return False
            ep = api.Endpoints(metadata=api.ObjectMeta(
                name=self.cfg.lock_name, namespace=self.cfg.lock_namespace,
                annotations={LEADER_ANNOTATION: json.dumps(record)}))
            try:
                self.client.create("endpoints", ep, self.cfg.lock_namespace)
            except ApiError:
                return False
            self._observe(record, now)
            return True

        ann = (ep.metadata.annotations or {})
        raw = ann.get(LEADER_ANNOTATION)
        old = json.loads(raw) if raw else None
        if old is not None:
            if old != self._observed_record:
                self._observe(old, now)
            holder = old.get("holderIdentity")
            # an empty holder is a RELEASED lease (graceful shutdown zeroed
            # it): immediately acquirable — the successor must not wait out
            # a lease nobody holds
            held_by_other = bool(holder) and holder != self.cfg.identity
            lease_valid = (self._observed_time + self.cfg.lease_duration) > now
            if held_by_other and lease_valid:
                return False  # someone else holds an unexpired lease
            if holder == self.cfg.identity:
                record["acquireTime"] = old.get("acquireTime", wall_now)
        ep.metadata.annotations = dict(ann)
        ep.metadata.annotations[LEADER_ANNOTATION] = json.dumps(record)
        try:
            self.client.update("endpoints", ep, self.cfg.lock_namespace)
        except ApiError:
            return False  # CAS lost: someone renewed concurrently
        self._observe(record, now)
        return True

    def _observe(self, record: dict, now: float):
        self._observed_record = record
        self._observed_time = now

    # --- loop (RunOrDie/acquire/renew, leaderelection.go:170) ----------------

    def run(self):
        self._thread = threading.Thread(target=self._loop, name="leader-elector",
                                        daemon=True)
        self._thread.start()
        return self

    def _loop(self):
        # The reference exits the process on lost leadership and relies on a
        # supervisor restart; with no supervisor here, losing the lease
        # re-enters the acquire loop so a healed candidate can lead again —
        # but only when on_stopped is provided, since that callback is the
        # contract for tearing down the previous term's work (re-acquiring
        # without it would run two copies of the leader workload in-process).
        while not self._stop.is_set():
            # acquire
            while not self._stop.is_set():
                if self.try_acquire_or_renew():
                    break
                self._stop.wait(self.cfg.retry_period)
            if self._stop.is_set():
                return
            self._is_leader = True
            threading.Thread(target=self.on_started, daemon=True).start()
            # renew
            while not self._stop.is_set():
                deadline = self._clock() + self.cfg.renew_deadline
                renewed = False
                while self._clock() < deadline and not self._stop.is_set():
                    if self.try_acquire_or_renew():
                        renewed = True
                        break
                    self._stop.wait(self.cfg.retry_period)
                if not renewed:
                    break
                self._stop.wait(self.cfg.retry_period)
            self._is_leader = False
            if not self.on_stopped:
                return  # one term max: nothing can stop the started work
            try:
                self.on_stopped()
            except Exception:
                log.exception("on_stopped_leading callback failed; "
                              "continuing to re-acquire")

    def release(self) -> bool:
        """Zero the lease record so a successor acquires IMMEDIATELY instead
        of waiting out lease_duration (the reference's releaseOnCancel).
        Best-effort CAS: only our own unexpired record is zeroed — racing a
        successor that already took the lease must not evict it."""
        import http.client as _http
        try:
            ep = self.client.get("endpoints", self.cfg.lock_name,
                                 self.cfg.lock_namespace)
        except (ApiError, OSError, _http.HTTPException):
            # a graceful stop may race the apiserver's own shutdown —
            # failing to release degrades to the crash path (the successor
            # waits out the lease); stop() itself must never raise
            return False
        ann = ep.metadata.annotations or {}
        raw = ann.get(LEADER_ANNOTATION)
        old = json.loads(raw) if raw else None
        if not old or old.get("holderIdentity") != self.cfg.identity:
            return False  # not ours (anymore): leave it alone
        released = dict(old)
        released["holderIdentity"] = ""
        released["renewTime"] = time.time()
        ep.metadata.annotations = dict(ann)
        ep.metadata.annotations[LEADER_ANNOTATION] = json.dumps(released)
        try:
            self.client.update("endpoints", ep, self.cfg.lock_namespace)
        except (ApiError, OSError, _http.HTTPException):
            return False  # CAS lost (or server gone): leave it to expiry
        return True

    def stop(self):
        # capture before signalling: the loop clears _is_leader on its way
        # out, and release() itself CAS-guards against a lease we no longer
        # hold, so a stale True here cannot evict a successor
        was_leader = self._is_leader
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        if was_leader:
            # graceful handover: a cleanly-stopped leader releases instead
            # of making the successor wait out the full lease duration —
            # the chaos soak measures this as election_handover_seconds
            self._is_leader = False
            if self.release():
                log.info("released leader lease %s/%s (identity %s)",
                         self.cfg.lock_namespace, self.cfg.lock_name,
                         self.cfg.identity)
