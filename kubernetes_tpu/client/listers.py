"""Typed read views over informer stores.

Parity target: reference pkg/client/cache/listers.go — StoreToPodLister,
StoreToNodeLister (with the readiness filtering the scheduler applies,
factory.go:332,434-454), StoreToServiceLister/StoreToControllerLister/
StoreToReplicaSetLister with GetPodX helpers used by the spreading priority.

Aliasing policy: informer stores hand out the SHARED cached objects; a
consumer mutating one corrupts every other reader (the bug class the
``informer-cache-mutation`` checker and the checked-store test mode exist
for). Listers therefore deep-copy on read by default — consumers own what
they're handed. Hot paths that only READ (the scheduler's per-decision
listings over thousands of nodes/pods) opt out with ``copy_on_read=False``
and inherit the read-only contract; the checked store still polices them
at test time.
"""

from __future__ import annotations

from typing import Callable, List, Optional

from kubernetes_tpu.api import labels as labelsel
from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.serialization import deep_copy
from kubernetes_tpu.client.cache import ThreadSafeStore


class _CopyingLister:
    def __init__(self, store: ThreadSafeStore, copy_on_read: bool = True):
        self.store = store
        self.copy_on_read = copy_on_read

    def _out_list(self, objs: list) -> list:
        if not self.copy_on_read:
            return objs
        return [deep_copy(o) for o in objs]


class PodLister(_CopyingLister):
    def list(self, selector: Optional[labelsel.Selector] = None) -> List[api.Pod]:
        pods = self.store.list()
        if selector is not None and not selector.empty():
            pods = [p for p in pods
                    if selector.matches((p.metadata.labels or {})
                                        if p.metadata else {})]
        return self._out_list(pods)

    def by_node(self, node_name: str) -> List[api.Pod]:
        return self._out_list(self.store.by_index("node", node_name))


class NodeLister(_CopyingLister):
    def __init__(self, store: ThreadSafeStore,
                 predicate: Optional[Callable[[api.Node], bool]] = None,
                 copy_on_read: bool = True):
        super().__init__(store, copy_on_read)
        self.predicate = predicate or node_is_ready

    def list(self) -> List[api.Node]:
        """Ready nodes only — the scheduler never sees NotReady nodes
        (reference getNodeConditionPredicate, factory.go:434-454)."""
        return self._out_list(
            [n for n in self.store.list() if self.predicate(n)])

    def list_all(self) -> List[api.Node]:
        return self._out_list(self.store.list())


def node_is_ready(node: api.Node) -> bool:
    """Schedulable = Ready=True and OutOfDisk!=True and not unschedulable
    (reference factory.go:434-454)."""
    if node.spec and node.spec.unschedulable:
        return False
    conds = (node.status.conditions or []) if node.status else []
    ready = False
    for c in conds:
        if c.type == api.NODE_READY:
            ready = c.status == api.CONDITION_TRUE
        elif c.type == api.NODE_OUT_OF_DISK and c.status == api.CONDITION_TRUE:
            return False
    return ready


class ServiceLister(_CopyingLister):
    def list(self) -> List[api.Service]:
        return self._out_list(self.store.list())

    def get_pod_services(self, pod: api.Pod) -> List[api.Service]:
        """Services whose selector matches the pod (same namespace) —
        reference listers.go GetPodServices."""
        out = []
        pod_labels = (pod.metadata.labels or {}) if pod.metadata else {}
        for svc in self.store.list():
            if svc.metadata.namespace != pod.metadata.namespace:
                continue
            sel = svc.spec.selector if svc.spec else None
            if sel and labelsel.selector_from_map(sel).matches(pod_labels):
                out.append(svc)
        return self._out_list(out)


class ControllerLister(_CopyingLister):
    def list(self) -> List[api.ReplicationController]:
        return self._out_list(self.store.list())

    def get_pod_controllers(self, pod: api.Pod) -> List[api.ReplicationController]:
        out = []
        pod_labels = (pod.metadata.labels or {}) if pod.metadata else {}
        for rc in self.store.list():
            if rc.metadata.namespace != pod.metadata.namespace:
                continue
            sel = rc.spec.selector if rc.spec else None
            if sel and labelsel.selector_from_map(sel).matches(pod_labels):
                out.append(rc)
        return self._out_list(out)


class ReplicaSetLister(_CopyingLister):
    def list(self) -> List[api.ReplicaSet]:
        return self._out_list(self.store.list())

    def get_pod_replica_sets(self, pod: api.Pod) -> List[api.ReplicaSet]:
        out = []
        pod_labels = (pod.metadata.labels or {}) if pod.metadata else {}
        for rs in self.store.list():
            if rs.metadata.namespace != pod.metadata.namespace:
                continue
            sel = rs.spec.selector if rs.spec else None
            if sel is not None and labelsel.selector_from_label_selector(sel).matches(pod_labels):
                out.append(rs)
        return self._out_list(out)
