"""L4 client + shared state-replication runtime.

Parity target: reference pkg/client — restclient (QPS-limited typed HTTP
client), cache (Reflector/Store/FIFO/DeltaFIFO/listers), the informer
framework (pkg/controller/framework), and record (event broadcasting with
dedup). This layer is the system's distributed communication backend: every
component above it (scheduler, controllers, kubelet, proxy, CLI) talks to the
cluster exclusively through it.
"""

from kubernetes_tpu.client.rest import ApiError, RESTClient
from kubernetes_tpu.client.cache import FIFO, DeltaFIFO, ThreadSafeStore, meta_namespace_key
from kubernetes_tpu.client.reflector import ListWatch, Reflector
from kubernetes_tpu.client.informer import Informer
from kubernetes_tpu.client.chaos import install_chaos
