"""Informer: Reflector + keyed cache + event-handler dispatch.

Parity target: reference pkg/controller/framework/controller.go:213
(NewInformer/NewIndexerInformer) — the pattern every controller and the
scheduler's ConfigFactory build on: a local, always-warm cache of one
resource plus add/update/delete callbacks, driven by a single Reflector.

Handlers run on the informer's dispatch thread (one per informer, like the
reference's processLoop goroutine): they must be fast and non-blocking, and
hand real work to a workqueue.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Callable, Dict, List, Optional

from kubernetes_tpu.client.cache import ThreadSafeStore, meta_namespace_key
from kubernetes_tpu.client.reflector import ListWatch, Reflector
from kubernetes_tpu.utils.metrics import REGISTRY as METRICS

log = logging.getLogger("informer")


class Informer:
    def __init__(self, lw: ListWatch, key_func: Callable = meta_namespace_key,
                 indexers: Optional[Dict[str, Callable]] = None,
                 relist_backoff: float = 1.0):
        self.resource = getattr(lw, "resource", "")
        self.store = ThreadSafeStore(indexers, name=self.resource)
        self.key = key_func
        self._handlers: List[dict] = []
        self._events: "queue.Queue" = queue.Queue()
        self._lag_stamped = 0.0
        self.reflector = Reflector(lw, self._Sink(self),
                                   relist_backoff=relist_backoff)
        self._dispatch_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    class _Sink:
        """Applies reflector events to the store synchronously (so the cache
        is updated in event order) and queues handler dispatch."""

        def __init__(self, informer: "Informer"):
            self.inf = informer

        def replace(self, items):
            inf = self.inf
            keyed = {inf.key(o): o for o in items}
            old = {k: inf.store.get(k) for k in inf.store.list_keys()}
            inf.store.replace(keyed)
            for k, o in keyed.items():
                prev = old.get(k)
                if prev is None:
                    inf._events.put(("add", None, o, time.monotonic()))
                else:
                    inf._events.put(("update", prev, o, time.monotonic()))
            for k, prev in old.items():
                if k not in keyed and prev is not None:
                    inf._events.put(("delete", prev, None, time.monotonic()))

        def add(self, obj):
            self.inf.store.add(self.inf.key(obj), obj)
            self.inf._events.put(("add", None, obj, time.monotonic()))

        def update(self, obj):
            prev = self.inf.store.get(self.inf.key(obj))
            self.inf.store.update(self.inf.key(obj), obj)
            self.inf._events.put(("update", prev, obj, time.monotonic()))

        def delete(self, obj):
            prev = self.inf.store.get(self.inf.key(obj)) or obj
            self.inf.store.delete(self.inf.key(obj))
            self.inf._events.put(("delete", prev, None, time.monotonic()))

    def add_event_handler(self, on_add: Optional[Callable] = None,
                          on_update: Optional[Callable] = None,
                          on_delete: Optional[Callable] = None):
        """on_add(obj), on_update(old, new), on_delete(obj)."""
        self._handlers.append({"add": on_add, "update": on_update,
                               "delete": on_delete})
        return self

    def run(self):
        self.reflector.run()
        self._dispatch_thread = threading.Thread(target=self._dispatch,
                                                 name="informer-dispatch",
                                                 daemon=True)
        self._dispatch_thread.start()
        return self

    def stop(self):
        self._stop.set()
        self.reflector.stop()
        self._events.put(None)

    def wait_for_sync(self, timeout: float = 10.0) -> bool:
        return self.reflector.wait_for_sync(timeout)

    @property
    def has_synced(self) -> bool:
        return self.reflector.has_synced

    def _dispatch(self):
        while not self._stop.is_set():
            item = self._events.get()
            if item is None:
                return
            kind, old, new, queued_at = item
            # watch lag: store-apply -> handler dispatch. A growing gauge
            # means handlers (or the work they enqueue) can't keep up with
            # the watch stream for this resource. Sampled (>=10Hz), not
            # per-event: a 30k-object relist must not take the registry
            # lock 30k times on this hot thread.
            now = time.monotonic()
            if now - self._lag_stamped >= 0.1:
                self._lag_stamped = now
                METRICS.set_gauge("informer_watch_lag_seconds",
                                  now - queued_at, resource=self.resource)
            for h in self._handlers:
                try:
                    if kind == "add" and h["add"]:
                        h["add"](new)
                    elif kind == "update" and h["update"]:
                        h["update"](old, new)
                    elif kind == "delete" and h["delete"]:
                        h["delete"](old)
                except Exception:  # HandleCrash: log, keep dispatching
                    log.exception("informer handler failed")
