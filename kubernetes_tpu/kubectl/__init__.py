"""kubectl: the L9 CLI (reference pkg/kubectl + cmd/kubectl).

Run as `python -m kubernetes_tpu.kubectl <command> ...` against an apiserver
(--server host:port). Subcommands mirror the reference cobra tree
(pkg/kubectl/cmd/): get, describe, create, apply, delete, scale, rollout,
label, annotate, cordon/uncordon/drain, run, expose, autoscale, version,
api-versions, cluster-info."""

from kubernetes_tpu.kubectl.cmd import main  # noqa: F401
