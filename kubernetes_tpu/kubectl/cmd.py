"""kubectl subcommands.

Parity target: reference pkg/kubectl/cmd/*.go — one function per cobra
command, argparse instead of cobra. Command inventory covered: get, describe,
create, apply, delete, scale, rollout {status,history,undo,pause,resume},
label, annotate, cordon, uncordon, drain, run, expose, autoscale, version,
api-versions, cluster-info."""

from __future__ import annotations

import argparse
import http.client
import json
import sys
import time
from typing import List, Optional

from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.serialization import scheme
from kubernetes_tpu.apis import autoscaling, extensions as ext
from kubernetes_tpu.client import RESTClient
from kubernetes_tpu.client.rest import ApiError
from kubernetes_tpu.kubectl import printers, resource as res
from kubernetes_tpu.registry.generic import RESOURCES
from kubernetes_tpu.utils import strategicpatch

ANN_LAST_APPLIED = "kubectl.kubernetes.io/last-applied-configuration"
VERSION = "v1.3.0-tpu"


class CommandError(Exception):
    pass


def _client(args) -> RESTClient:
    from kubernetes_tpu.utils.debugserver import client_from_url
    return client_from_url(args.server or "127.0.0.1:8080",
                           user_agent="kubectl",
                           bearer_token=getattr(args, "token", None) or "")


def _ns(args) -> str:
    return getattr(args, "namespace", None) or "default"


def _is_namespaced(resource: str) -> bool:
    rd = RESOURCES.get(resource)
    return rd.namespaced if rd else True


def _get_objs(client, args, pairs, all_namespaces=False):
    out = []
    for resource, name in pairs:
        ns = "" if (all_namespaces or not _is_namespaced(resource)) \
            else _ns(args)
        if name:
            out.append((resource, [client.get(resource, name, ns)]))
        else:
            items, _ = client.list(
                resource, ns,
                label_selector=getattr(args, "selector", None))
            out.append((resource, items))
    return out


# --- get / describe ----------------------------------------------------------

def cmd_get(args) -> int:
    client = _client(args)
    pairs = res.parse_args(args.args)
    blocks = _get_objs(client, args, pairs,
                       all_namespaces=args.all_namespaces)
    outputs = []
    for resource, objs in blocks:
        if not objs and len(blocks) == 1 and args.output in (None, "", "wide"):
            ns_msg = "" if args.all_namespaces else f" in {_ns(args)} namespace"
            print(f"No resources found{ns_msg}.", file=sys.stderr)
            return 0
        outputs.append(printers.print_objs(
            resource, objs, args.output, wide=(args.output == "wide"),
            show_namespace=args.all_namespaces))
    print("\n\n".join(o for o in outputs if o))
    return 0


def _describe_lines(resource: str, obj) -> List[str]:
    """Key: value dump (reference pkg/kubectl/describe.go per-kind
    describers, generalized)."""
    m = obj.metadata or api.ObjectMeta()
    lines = [f"Name:\t{m.name}"]
    if _is_namespaced(resource):
        lines.append(f"Namespace:\t{m.namespace}")
    lines.append("Labels:\t" + (",".join(
        f"{k}={v}" for k, v in sorted((m.labels or {}).items())) or "<none>"))
    lines.append("Annotations:\t" + (",".join(
        sorted(k for k in (m.annotations or {}))) or "<none>"))
    if resource == "pods":
        spec = obj.spec or api.PodSpec()
        st = obj.status or api.PodStatus()
        lines.append(f"Node:\t{spec.node_name or '<none>'}")
        lines.append(f"Status:\t{st.phase or 'Unknown'}")
        lines.append("Containers:")
        for c in spec.containers or []:
            lines.append(f"  {c.name}:")
            lines.append(f"    Image:\t{c.image}")
            req = (c.resources.requests if c.resources else None) or {}
            if req:
                lines.append("    Requests:")
                for k, v in sorted(req.items()):
                    lines.append(f"      {k}:\t{v}")
        conds = st.conditions or []
        if conds:
            lines.append("Conditions:")
            lines.append("  Type\tStatus")
            for c in conds:
                lines.append(f"  {c.type}\t{c.status}")
    elif resource == "nodes":
        st = obj.status or api.NodeStatus()
        lines.append("Conditions:")
        for c in st.conditions or []:
            lines.append(f"  {c.type}\t{c.status}")
        alloc = st.allocatable or {}
        if alloc:
            lines.append("Allocatable:")
            for k, v in sorted(alloc.items()):
                lines.append(f"  {k}:\t{v}")
        if obj.spec and obj.spec.unschedulable:
            lines.append("Unschedulable:\ttrue")
        if obj.spec and obj.spec.taints:
            lines.append("Taints:\t" + ",".join(
                f"{t.key}={t.value}:{t.effect}" for t in obj.spec.taints))
    elif resource == "services":
        spec = obj.spec or api.ServiceSpec()
        lines.append(f"Selector:\t" + (",".join(
            f"{k}={v}" for k, v in sorted((spec.selector or {}).items()))
            or "<none>"))
        lines.append(f"IP:\t{spec.cluster_ip or '<none>'}")
        for p in spec.ports or []:
            lines.append(f"Port:\t{p.name or '<unset>'}\t"
                         f"{p.port}/{p.protocol or 'TCP'}")
    elif resource in ("replicationcontrollers", "replicasets",
                      "deployments", "petsets"):
        spec = obj.spec
        st = obj.status
        lines.append(f"Replicas:\t{(st.replicas if st else 0)} current / "
                     f"{(spec.replicas or 0) if spec else 0} desired")
    return lines


def _object_events(client, resource: str, obj) -> list:
    """This object's Event stream (fetched ONCE per describe; both the
    Scheduling and Events sections render from it)."""
    rd = RESOURCES.get(resource)
    kind = rd.kind if rd else resource
    m = obj.metadata or api.ObjectMeta()
    # non-namespaced kinds' events land in "default" (the recorder's rule)
    ns = m.namespace or "default"
    try:
        evs, _ = client.list(
            "events", ns,
            field_selector=f"involvedObject.kind={kind},"
                           f"involvedObject.name={m.name}")
    except ApiError:
        return []
    return evs


def _scheduling_lines(resource: str, obj, events: list) -> List[str]:
    """Scheduling section for pods (reference describe.go has no analogue —
    this surfaces the decision ledger's provenance): the Unschedulable
    breakdown from the PodScheduled condition, or — for placed pods — the
    chosen node plus the score breakdown and runner-up the scheduler
    stamped onto the Scheduled event."""
    if resource != "pods":
        return []
    st = obj.status
    cond = next((c for c in ((st.conditions or []) if st else [])
                 if c.type == api.POD_SCHEDULED), None)
    if cond is not None and cond.status == api.CONDITION_FALSE \
            and (cond.message or ""):
        return ["Scheduling:", f"  Unschedulable:\t{cond.message}"]
    node = obj.spec.node_name if obj.spec else ""
    if not node:
        return []
    for e in sorted(events, key=lambda e: e.last_timestamp or "",
                    reverse=True):
        msg = e.message or ""
        if e.reason == "Scheduled" and " [score " in msg:
            detail = msg.split(" [", 1)[1].rstrip("]")
            lines = ["Scheduling:", f"  Node:\t{node}"]
            for part in detail.split("; "):
                if part.startswith("runner-up "):
                    lines.append(f"  Runner-up:\t{part[len('runner-up '):]}")
                else:
                    lines.append(f"  Decision:\t{part}")
            return lines
    return []


def _event_lines(evs: list) -> List[str]:
    """Events involving this object (reference describe.go: every describer
    ends with the object's event stream)."""
    if not evs:
        return []
    lines = ["Events:", "  LastSeen\tCount\tFrom\tType\tReason\tMessage"]
    for e in sorted(evs, key=lambda e: e.last_timestamp or ""):
        src = e.source.component if e.source else ""
        if e.source and e.source.host:
            src += f", {e.source.host}"
        lines.append(f"  {e.last_timestamp or ''}\t{e.count}\t{src}\t"
                     f"{e.type}\t{e.reason}\t{e.message}")
    return lines


def cmd_describe(args) -> int:
    client = _client(args)
    pairs = res.parse_args(args.args)
    blocks = _get_objs(client, args, pairs)
    chunks = []
    for resource, objs in blocks:
        for o in objs:
            evs = _object_events(client, resource, o)
            lines = _describe_lines(resource, o)
            lines += _scheduling_lines(resource, o, evs)
            lines += _event_lines(evs)
            chunks.append("\n".join(lines))
    print("\n\n\n".join(chunks))
    return 0


# --- create / apply / delete --------------------------------------------------

def cmd_create(args) -> int:
    client = _client(args)
    if not args.filename:
        raise CommandError("must specify -f")
    for resource, obj, _raw in res.load_files(args.filename):
        ns = (obj.metadata.namespace if obj.metadata else "") or _ns(args)
        created = client.create(resource, obj,
                                ns if _is_namespaced(resource) else "")
        print(f"{RESOURCES[resource].kind.lower()} "
              f"\"{created.metadata.name}\" created")
    return 0


def cmd_apply(args) -> int:
    """Three-way strategic merge against the last-applied annotation
    (reference pkg/kubectl/cmd/apply.go)."""
    client = _client(args)
    if not args.filename:
        raise CommandError("must specify -f")
    for resource, obj, raw in res.load_files(args.filename):
        ns = (obj.metadata.namespace if obj.metadata else "") or _ns(args)
        if not _is_namespaced(resource):
            ns = ""
        name = obj.metadata.name if obj.metadata else ""
        modified = json.dumps(raw, sort_keys=True)
        try:
            live = client.get(resource, name, ns)
        except ApiError as e:
            if not e.is_not_found:
                raise
            if obj.metadata.annotations is None:
                obj.metadata.annotations = {}
            obj.metadata.annotations[ANN_LAST_APPLIED] = modified
            client.create(resource, obj, ns)
            print(f"{RESOURCES[resource].kind.lower()} \"{name}\" created")
            continue
        original = json.loads(
            (live.metadata.annotations or {}).get(ANN_LAST_APPLIED, "{}"))
        # send the two-way (original->desired) strategic patch and let the
        # SERVER merge it onto live under optimistic concurrency
        # (resthandler.go:503-615) — apply no longer races other writers
        # between its GET and write
        patch = strategicpatch.create_two_way_merge_patch(original, raw)
        md = patch.setdefault("metadata", {}) or {}
        patch["metadata"] = md
        ann = md.setdefault("annotations", {}) or {}
        md["annotations"] = ann
        ann[ANN_LAST_APPLIED] = modified
        client.patch(resource, name, patch, ns)
        print(f"{RESOURCES[resource].kind.lower()} \"{name}\" configured")
    return 0


def cmd_delete(args) -> int:
    client = _client(args)
    if args.filename:
        # honor each manifest's own namespace, same as create
        pairs = [(r, o.metadata.name,
                  (o.metadata.namespace if o.metadata else "") or _ns(args))
                 for r, o, _ in res.load_files(args.filename)]
    else:
        pairs = [(r, n, _ns(args)) for r, n in res.parse_args(args.args)]
    for resource, name, ns in pairs:
        if not _is_namespaced(resource):
            ns = ""
        if name is None:
            if not args.all and not args.selector:
                raise CommandError(
                    "resource(s) were provided, but no name, label "
                    "selector, or --all flag specified")
            items, _ = client.list(resource, ns,
                                   label_selector=args.selector)
            names = [o.metadata.name for o in items]
        else:
            names = [name]
        for n in names:
            try:
                client.delete(resource, n, ns)
                print(f"{RESOURCES[resource].kind.lower()} \"{n}\" deleted")
            except ApiError as e:
                if not (e.is_not_found and args.ignore_not_found):
                    raise
    return 0


# --- scale / rollout / autoscale ---------------------------------------------

def cmd_scale(args) -> int:
    client = _client(args)
    pairs = res.parse_args(args.args)
    for resource, name in pairs:
        if name is None:
            raise CommandError("name is required for scale")
        sc = client.get_scale(resource, name, _ns(args))
        sc.spec.replicas = args.replicas
        client.update_scale(resource, name, _ns(args), sc)
        print(f"{RESOURCES[resource].kind.lower()} \"{name}\" scaled")
    return 0


def cmd_rollout(args) -> int:
    client = _client(args)
    sub = args.subcommand
    pairs = res.parse_args(args.args)
    for resource, name in pairs:
        if resource != "deployments":
            raise CommandError(f"rollout is not supported on {resource}")
        ns = _ns(args)
        if sub == "status":
            deadline = time.monotonic() + args.timeout
            while True:
                d = client.get(resource, name, ns)
                want = (d.spec.replicas or 0) if d.spec else 0
                st = d.status or ext.DeploymentStatus()
                if st.updated_replicas >= want and \
                        st.available_replicas >= want:
                    print(f"deployment \"{name}\" successfully rolled out")
                    break
                if time.monotonic() > deadline:
                    raise CommandError(
                        f"deployment \"{name}\" not rolled out: "
                        f"{st.updated_replicas} updated, "
                        f"{st.available_replicas} available, {want} desired")
                time.sleep(0.2)
        elif sub == "history":
            items, _ = client.list("replicasets", ns)
            revs = []
            for rs in items:
                refs = (rs.metadata.owner_references or [])
                if any(r.kind == "Deployment" and r.name == name
                       for r in refs):
                    revs.append(int((rs.metadata.annotations or {}).get(
                        ext.ANN_REVISION, "0")))
            print(f"deployments \"{name}\"")
            print("REVISION")
            for rv in sorted(revs):
                print(rv)
        elif sub == "undo":
            client.rollback_deployment(name, ns, ext.DeploymentRollback(
                name=name,
                rollback_to=ext.RollbackConfig(revision=args.to_revision)))
            print(f"deployment \"{name}\" rolled back")
        elif sub in ("pause", "resume"):
            d = client.get(resource, name, ns)
            d.spec.paused = (sub == "pause")
            client.update(resource, d, ns)
            print(f"deployment \"{name}\" {sub}d")
        else:
            raise CommandError(f"unknown rollout subcommand {sub!r}")
    return 0


def cmd_autoscale(args) -> int:
    client = _client(args)
    pairs = res.parse_args(args.args)
    for resource, name in pairs:
        kind = RESOURCES[resource].kind
        hpa = autoscaling.HorizontalPodAutoscaler(
            metadata=api.ObjectMeta(name=args.name or name,
                                    namespace=_ns(args)),
            spec=autoscaling.HorizontalPodAutoscalerSpec(
                scale_target_ref=autoscaling.CrossVersionObjectReference(
                    kind=kind, name=name),
                min_replicas=args.min, max_replicas=args.max,
                target_cpu_utilization_percentage=args.cpu_percent))
        client.create("horizontalpodautoscalers", hpa, _ns(args))
        print(f"{kind.lower()} \"{name}\" autoscaled")
    return 0


# --- label / annotate ---------------------------------------------------------

def _parse_kv_args(kvs: List[str]):
    sets, removes = {}, []
    for kv in kvs:
        if kv.endswith("-") and "=" not in kv:
            removes.append(kv[:-1])
        elif "=" in kv:
            k, v = kv.split("=", 1)
            sets[k] = v
        else:
            raise CommandError(f"invalid KEY=VAL pair: {kv!r}")
    return sets, removes


def _mutate_map(client, args, which: str) -> int:
    pairs = res.parse_args(args.args)  # _post_parse already removed KEY=VALs
    sets, removes = _parse_kv_args(args.pairs)
    for resource, name in pairs:
        if name is None:
            raise CommandError("name required")
        ns = _ns(args) if _is_namespaced(resource) else ""
        obj = client.get(resource, name, ns)
        cur = dict(getattr(obj.metadata, which) or {})
        for k in sets:
            if k in cur and not args.overwrite and cur[k] != sets[k]:
                raise CommandError(
                    f"'{k}' already has a value ({cur[k]}), and "
                    f"--overwrite is false")
        # PATCH just the touched keys (None deletes under strategic merge) —
        # the GET above is only the --overwrite guard, not a write base, so
        # concurrent writers of other fields can't be clobbered
        delta = dict(sets)
        delta.update({k: None for k in removes})
        client.patch(resource, name, {"metadata": {which: delta}}, ns)
        print(f"{RESOURCES[resource].kind.lower()} \"{name}\" labeled"
              if which == "labels" else
              f"{RESOURCES[resource].kind.lower()} \"{name}\" annotated")
    return 0


def cmd_label(args) -> int:
    return _mutate_map(_client(args), args, "labels")


def cmd_annotate(args) -> int:
    return _mutate_map(_client(args), args, "annotations")


# --- node ops: cordon / uncordon / drain --------------------------------------

def _set_unschedulable(client, name: str, value: bool) -> None:
    client.patch("nodes", name, {"spec": {"unschedulable": value}})


def cmd_cordon(args) -> int:
    client = _client(args)
    for name in args.args:
        _set_unschedulable(client, name, True)
        print(f"node \"{name}\" cordoned")
    return 0


def cmd_uncordon(args) -> int:
    client = _client(args)
    for name in args.args:
        _set_unschedulable(client, name, False)
        print(f"node \"{name}\" uncordoned")
    return 0


def cmd_drain(args) -> int:
    """Cordon + evict pods (reference pkg/kubectl/cmd/drain.go: refuses
    unmanaged/daemon pods unless forced)."""
    client = _client(args)
    for name in args.args:
        pods, _ = client.list("pods",
                              field_selector=f"spec.nodeName={name}")
        # validate the FULL pod list before touching anything (reference
        # drain.go GetPodsForDeletion refuses up front) so a failure never
        # leaves the node partially drained
        victims = []
        for p in pods:
            managed = bool((p.metadata.owner_references or [])
                           or api.ANN_CREATED_BY in
                           (p.metadata.annotations or {}))
            daemon = any(r.kind == "DaemonSet"
                         for r in (p.metadata.owner_references or []))
            if daemon and not args.ignore_daemonsets:
                raise CommandError(
                    f"pod {p.metadata.name} is managed by a DaemonSet; "
                    "use --ignore-daemonsets")
            if not managed and not args.force:
                raise CommandError(
                    f"pod {p.metadata.name} is not managed by a "
                    "controller; use --force to delete it")
            if not daemon:  # daemon pods stay (their controller pins them)
                victims.append(p)
        _set_unschedulable(client, name, True)
        for p in victims:
            client.delete("pods", p.metadata.name, p.metadata.namespace)
            print(f"pod \"{p.metadata.name}\" evicted")
        print(f"node \"{name}\" drained")
    return 0


# --- run / expose -------------------------------------------------------------

def cmd_run(args) -> int:
    """kubectl run NAME --image=... (reference run.go: generates an RC in
    this era; --restart=Never generates a bare pod)."""
    client = _client(args)
    name = args.name
    labels = {"run": name}
    container = api.Container(name=name, image=args.image)
    if args.restart == "Never":
        pod = api.Pod(metadata=api.ObjectMeta(name=name, namespace=_ns(args),
                                              labels=labels),
                      spec=api.PodSpec(containers=[container]))
        client.create("pods", pod, _ns(args))
        print(f"pod \"{name}\" created")
    else:
        rc = api.ReplicationController(
            metadata=api.ObjectMeta(name=name, namespace=_ns(args),
                                    labels=labels),
            spec=api.ReplicationControllerSpec(
                replicas=args.replicas, selector=dict(labels),
                template=api.PodTemplateSpec(
                    metadata=api.ObjectMeta(labels=dict(labels)),
                    spec=api.PodSpec(containers=[container]))))
        client.create("replicationcontrollers", rc, _ns(args))
        print(f"replicationcontroller \"{name}\" created")
    return 0


def cmd_expose(args) -> int:
    """Create a service fronting an RC/RS/deployment/service's selector
    (reference expose.go)."""
    client = _client(args)
    pairs = res.parse_args(args.args)
    for resource, name in pairs:
        obj = client.get(resource, name, _ns(args))
        sel = obj.spec.selector if obj.spec else None
        if isinstance(sel, api.LabelSelector):
            sel = sel.match_labels
        if not sel:
            raise CommandError(f"couldn't find a selector on {resource}/{name}")
        svc = api.Service(
            metadata=api.ObjectMeta(name=args.name or name,
                                    namespace=_ns(args)),
            spec=api.ServiceSpec(
                selector=dict(sel),
                ports=[api.ServicePort(
                    port=args.port,
                    target_port=args.target_port or args.port)]))
        client.create("services", svc, _ns(args))
        print(f"service \"{svc.metadata.name}\" exposed")
    return 0


# --- misc ---------------------------------------------------------------------

def cmd_version(args) -> int:
    print(f"Client Version: {VERSION}")
    try:
        try:
            _client(args).request("GET", "/healthz")
        except ValueError:
            pass  # /healthz answers plain "ok", not JSON — reachable is all
            # that matters (the old blanket except hid this, so a healthy
            # server never printed its version)
        print(f"Server Version: {VERSION}")
    except (ApiError, OSError, http.client.HTTPException):
        # unreachable or misbehaving server (RESTClient re-raises
        # HTTPException after retries): client-only output, never a crash
        pass
    return 0


def _kubelet_endpoint(client, pod_name: str, ns: str):
    """(host, port, pod) of the kubelet serving a pod: pod -> spec.nodeName
    -> node.status.daemonEndpoints + InternalIP (server.go:237 routes)."""
    pod = client.get("pods", pod_name, ns)
    node_name = pod.spec.node_name if pod.spec else ""
    if not node_name:
        raise CommandError(f"pod {pod_name!r} is not scheduled yet")
    node = client.get("nodes", node_name)
    st = node.status
    de = st.daemon_endpoints if st else None
    port = (de.kubelet_endpoint.port
            if de and de.kubelet_endpoint else 0)
    if not port:
        raise CommandError(
            f"node {node_name!r} publishes no kubelet endpoint "
            "(is its kubelet running with a node server?)")
    host = "127.0.0.1"
    for addr in (st.addresses or []):
        if addr.type == "InternalIP" and addr.address:
            host = addr.address
            break
    return host, port, pod


def _default_container(pod, requested: Optional[str]) -> str:
    names = [c.name for c in (pod.spec.containers or [])]
    if requested:
        if requested not in names:
            raise CommandError(
                f"container {requested!r} not in pod (have {names})")
        return requested
    if not names:
        raise CommandError("pod has no containers")
    return names[0]


def cmd_logs(args) -> int:
    """kubectl logs POD [-c C] [--tail N] [-p]: read the container's real
    log stream from the kubelet node server (GetContainerLogs analog)."""
    import http.client as hc
    client = _client(args)
    ns = _ns(args)
    host, port, pod = _kubelet_endpoint(client, args.pod, ns)
    cname = _default_container(pod, args.container)
    q = []
    if args.tail is not None:
        q.append(f"tailLines={args.tail}")
    if args.previous:
        q.append("previous=true")
    conn = hc.HTTPConnection(host, port, timeout=30)
    try:
        conn.request("GET", f"/containerLogs/{ns}/{args.pod}/{cname}"
                            + (("?" + "&".join(q)) if q else ""))
        resp = conn.getresponse()
        body = resp.read().decode("utf-8", "replace")
    finally:
        conn.close()
    if resp.status != 200:
        raise CommandError(f"kubelet: {resp.status} {body.strip()}")
    sys.stdout.write(body)
    return 0


def cmd_exec(args) -> int:
    """kubectl exec POD [-c C] -- CMD...: run an argv in the container's
    context via the kubelet node server."""
    import http.client as hc
    from urllib.parse import quote as _q
    # argparse.REMAINDER swallows flags after the pod name, so -c/--container
    # arrives inside cmd; split at "--" and parse the flag part by hand
    cmd = list(args.cmd)
    if "--" in cmd:
        i = cmd.index("--")
        flags, cmd = cmd[:i], cmd[i + 1:]
        j = 0
        while j < len(flags):
            if flags[j] in ("-c", "--container") and j + 1 < len(flags):
                args.container = flags[j + 1]
                j += 2
            else:
                raise CommandError(f"unknown argument {flags[j]!r} "
                                   "(flags go before --)")
    args.cmd = cmd
    if not args.cmd:
        raise CommandError("command required: kubectl exec POD -- CMD ...")
    client = _client(args)
    ns = _ns(args)
    host, port, pod = _kubelet_endpoint(client, args.pod, ns)
    cname = _default_container(pod, args.container)
    qs = "&".join(f"command={_q(c)}" for c in args.cmd)
    conn = hc.HTTPConnection(host, port, timeout=60)
    try:
        conn.request("POST", f"/exec/{ns}/{args.pod}/{cname}?{qs}")
        resp = conn.getresponse()
        body = resp.read().decode("utf-8", "replace")
    finally:
        conn.close()
    if resp.status != 200:
        raise CommandError(f"kubelet: {resp.status} {body.strip()}")
    out = json.loads(body)
    sys.stdout.write(out.get("output", ""))
    return int(out.get("rc", 0))


def cmd_api_versions(args) -> int:
    groups = sorted({rd.api_version for rd in RESOURCES.values()})
    for g in groups:
        print(g)
    return 0


def cmd_cluster_info(args) -> int:
    print(f"Kubernetes master is running at http://{args.server or '127.0.0.1:8080'}")
    return 0


# --- argparse wiring ----------------------------------------------------------

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="kubectl", description="kubectl controls the cluster")
    p.add_argument("-s", "--server", default=None)
    p.add_argument("--token", default=None)
    p.add_argument("-n", "--namespace", default=None)
    sub = p.add_subparsers(dest="command")

    def add(name, fn, **kw):
        sp = sub.add_parser(name, **kw)
        sp.set_defaults(fn=fn)
        return sp

    g = add("get", cmd_get)
    g.add_argument("args", nargs="+")
    g.add_argument("-o", "--output", default=None)
    g.add_argument("-l", "--selector", default=None)
    g.add_argument("--all-namespaces", action="store_true")

    d = add("describe", cmd_describe)
    d.add_argument("args", nargs="+")
    d.add_argument("-l", "--selector", default=None)

    c = add("create", cmd_create)
    c.add_argument("-f", "--filename", action="append", default=[])

    a = add("apply", cmd_apply)
    a.add_argument("-f", "--filename", action="append", default=[])

    de = add("delete", cmd_delete)
    de.add_argument("args", nargs="*", default=[])
    de.add_argument("-f", "--filename", action="append", default=[])
    de.add_argument("-l", "--selector", default=None)
    de.add_argument("--all", action="store_true")
    de.add_argument("--ignore-not-found", action="store_true")

    sc = add("scale", cmd_scale)
    sc.add_argument("args", nargs="+")
    sc.add_argument("--replicas", type=int, required=True)

    ro = add("rollout", cmd_rollout)
    ro.add_argument("subcommand",
                    choices=["status", "history", "undo", "pause", "resume"])
    ro.add_argument("args", nargs="+")
    ro.add_argument("--to-revision", type=int, default=0)
    ro.add_argument("--timeout", type=float, default=30.0)

    au = add("autoscale", cmd_autoscale)
    au.add_argument("args", nargs="+")
    au.add_argument("--min", type=int, default=1)
    au.add_argument("--max", type=int, required=True)
    au.add_argument("--cpu-percent", type=int, default=80)
    au.add_argument("--name", default=None)

    la = add("label", cmd_label)
    la.add_argument("args", nargs="+")
    la.add_argument("--overwrite", action="store_true")

    an = add("annotate", cmd_annotate)
    an.add_argument("args", nargs="+")
    an.add_argument("--overwrite", action="store_true")

    co = add("cordon", cmd_cordon)
    co.add_argument("args", nargs="+")
    un = add("uncordon", cmd_uncordon)
    un.add_argument("args", nargs="+")
    dr = add("drain", cmd_drain)
    dr.add_argument("args", nargs="+")
    dr.add_argument("--force", action="store_true")
    dr.add_argument("--ignore-daemonsets", action="store_true")

    ru = add("run", cmd_run)
    ru.add_argument("name")
    ru.add_argument("--image", required=True)
    ru.add_argument("--replicas", type=int, default=1)
    ru.add_argument("--restart", default="Always",
                    choices=["Always", "Never", "OnFailure"])

    ex = add("expose", cmd_expose)
    ex.add_argument("args", nargs="+")
    ex.add_argument("--port", type=int, required=True)
    ex.add_argument("--target-port", type=int, default=None)
    ex.add_argument("--name", default=None)

    lo = add("logs", cmd_logs)
    lo.add_argument("pod")
    lo.add_argument("-c", "--container", default=None)
    lo.add_argument("--tail", type=int, default=None)
    lo.add_argument("-p", "--previous", action="store_true")

    exe = add("exec", cmd_exec)
    exe.add_argument("pod")
    exe.add_argument("-c", "--container", default=None)
    exe.add_argument("cmd", nargs=argparse.REMAINDER,
                     help="command after --")

    add("version", cmd_version)
    add("api-versions", cmd_api_versions)
    add("cluster-info", cmd_cluster_info)
    return p


def _post_parse(args):
    """label/annotate mix TYPE NAME and KEY=VAL positionals; split them."""
    if args.command in ("label", "annotate"):
        rest, pairs = [], []
        for a in args.args:
            (pairs if ("=" in a or a.endswith("-")) else rest).append(a)
        args.args, args.pairs = rest, pairs
    return args


def main(argv: Optional[List[str]] = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    if not getattr(args, "fn", None):
        parser.print_help()
        return 1
    _post_parse(args)
    try:
        return args.fn(args)
    except (CommandError, res.ResourceError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    except ApiError as e:
        print(f"Error from server: {e}", file=sys.stderr)
        return 1

