"""Human-readable and machine output for kubectl.

Parity target: reference pkg/kubectl/resource_printer.go — per-kind table
columns (HumanReadablePrinter handlers) plus -o json|yaml|name|wide|jsonpath.
AGE math mirrors translateTimestamp/shortHumanDuration."""

from __future__ import annotations

import json
import time
from typing import List, Optional

import yaml

from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.serialization import scheme
from kubernetes_tpu.utils import jsonpath
from kubernetes_tpu.utils.timeutil import parse_iso


def human_duration(seconds: float) -> str:
    s = int(seconds)
    if s < 0:
        s = 0
    if s < 120:
        return f"{s}s"
    m = s // 60
    if m < 120:
        return f"{m}m"
    h = m // 60
    if h < 48:
        return f"{h}h"
    return f"{h // 24}d"


def age_of(obj) -> str:
    ts = parse_iso(obj.metadata.creation_timestamp if obj.metadata else None)
    if ts is None:
        return "<unknown>"
    # AGE = wall now minus the serialized creationTimestamp
    # kube-verify: disable-next-line=monotonic-duration
    return human_duration(time.time() - ts)


# --- per-kind rows -----------------------------------------------------------

def _pod_row(p: api.Pod, wide: bool) -> List[str]:
    statuses = (p.status.container_statuses or []) if p.status else []
    total = len((p.spec.containers or []) if p.spec else [])
    ready = sum(1 for cs in statuses if cs.ready)
    restarts = sum(cs.restart_count or 0 for cs in statuses)
    phase = (p.status.phase if p.status else "") or "Unknown"
    if p.metadata.deletion_timestamp:
        phase = "Terminating"
    row = [_name(p), f"{ready}/{total}", phase, str(restarts), age_of(p)]
    if wide:
        row.append((p.spec.node_name if p.spec else "") or "<none>")
    return row


def _node_row(n: api.Node, wide: bool) -> List[str]:
    ready = "Unknown"
    for c in ((n.status.conditions or []) if n.status else []):
        if c.type == api.NODE_READY:
            ready = "Ready" if c.status == api.CONDITION_TRUE else "NotReady"
    if n.spec and n.spec.unschedulable:
        ready += ",SchedulingDisabled"
    return [_name(n), ready, age_of(n)]


def _svc_row(s: api.Service, wide: bool) -> List[str]:
    spec = s.spec or api.ServiceSpec()
    ports = ",".join(f"{p.port}/{p.protocol or 'TCP'}"
                     for p in (spec.ports or []))
    return [_name(s), spec.cluster_ip or "<none>", ports or "<none>",
            age_of(s)]


def _rc_like_row(o, wide: bool) -> List[str]:
    desired = (o.spec.replicas or 0) if o.spec else 0
    current = (o.status.replicas or 0) if o.status else 0
    return [_name(o), str(desired), str(current), age_of(o)]


def _deploy_row(d, wide: bool) -> List[str]:
    desired = (d.spec.replicas or 0) if d.spec else 0
    st = d.status
    return [_name(d), str(desired), str(st.replicas if st else 0),
            str(st.updated_replicas if st else 0),
            str(st.available_replicas if st else 0), age_of(d)]


def _job_row(j, wide: bool) -> List[str]:
    desired = (j.spec.completions if j.spec else None)
    succ = j.status.succeeded if j.status else 0
    return [_name(j), str(desired if desired is not None else "<none>"),
            str(succ), age_of(j)]


def _ns_row(n, wide: bool) -> List[str]:
    phase = (n.status.phase if n.status else "") or "Active"
    return [_name(n), phase, age_of(n)]


def _event_row(e, wide: bool) -> List[str]:
    io = e.involved_object
    return [e.last_timestamp or "", e.type or "", e.reason or "",
            f"{io.kind}/{io.name}" if io else "", (e.message or "")[:60]]


def _generic_row(o, wide: bool) -> List[str]:
    return [_name(o), age_of(o)]


_HANDLERS = {
    "pods": (["NAME", "READY", "STATUS", "RESTARTS", "AGE"],
             ["NODE"], _pod_row),
    "nodes": (["NAME", "STATUS", "AGE"], [], _node_row),
    "services": (["NAME", "CLUSTER-IP", "PORT(S)", "AGE"], [], _svc_row),
    "replicationcontrollers": (["NAME", "DESIRED", "CURRENT", "AGE"], [],
                               _rc_like_row),
    "replicasets": (["NAME", "DESIRED", "CURRENT", "AGE"], [], _rc_like_row),
    "petsets": (["NAME", "DESIRED", "CURRENT", "AGE"], [], _rc_like_row),
    "deployments": (["NAME", "DESIRED", "CURRENT", "UP-TO-DATE",
                     "AVAILABLE", "AGE"], [], _deploy_row),
    "jobs": (["NAME", "DESIRED", "SUCCESSFUL", "AGE"], [], _job_row),
    "namespaces": (["NAME", "STATUS", "AGE"], [], _ns_row),
    "events": (["LASTSEEN", "TYPE", "REASON", "OBJECT", "MESSAGE"], [],
               _event_row),
}


def _name(o) -> str:
    return o.metadata.name if o.metadata else ""


def print_table(resource: str, objs: List, wide: bool = False,
                show_namespace: bool = False) -> str:
    headers, wide_headers, row_fn = _HANDLERS.get(
        resource, (["NAME", "AGE"], [], _generic_row))
    headers = list(headers) + (list(wide_headers) if wide else [])
    if show_namespace:
        headers = ["NAMESPACE"] + headers
    rows = []
    for o in objs:
        r = row_fn(o, wide)
        if show_namespace:
            r = [(o.metadata.namespace if o.metadata else "")] + r
        rows.append(r)
    widths = [max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
              for i, h in enumerate(headers)]
    lines = ["   ".join(h.ljust(w) for h, w in zip(headers, widths)).rstrip()]
    for r in rows:
        lines.append("   ".join(c.ljust(w)
                                for c, w in zip(r, widths)).rstrip())
    return "\n".join(lines)


def print_objs(resource: str, objs: List, output: Optional[str],
               wide: bool = False, show_namespace: bool = False) -> str:
    """Dispatch on -o. `objs` is a list; single-item get prints the bare
    object for json/yaml like the reference."""
    if output in (None, "", "wide"):
        return print_table(resource, objs, wide=(output == "wide"),
                           show_namespace=show_namespace)
    if output == "name":
        return "\n".join(f"{_singular(resource)}/{_name(o)}" for o in objs)
    data = [scheme.encode(o) for o in objs]
    payload = data[0] if len(data) == 1 else {
        "kind": "List", "apiVersion": "v1", "items": data}
    if output == "json":
        return json.dumps(payload, indent=2)
    if output == "yaml":
        return yaml.safe_dump(payload, default_flow_style=False)
    if output.startswith("jsonpath="):
        # evaluate against the same payload json/yaml print, so the standard
        # `{.items[*].metadata.name}` idiom works on multi-object output
        tpl = output[len("jsonpath="):]
        return jsonpath.evaluate(tpl, payload)
    raise ValueError(f"unknown output format {output!r}")


def _singular(resource: str) -> str:
    from kubernetes_tpu.registry.generic import RESOURCES
    rd = RESOURCES.get(resource)
    return rd.kind.lower() if rd else (
        resource[:-1] if resource.endswith("s") else resource)
