import sys

from kubernetes_tpu.kubectl.cmd import main

sys.exit(main())
