"""Resource builder: turn CLI arguments into typed objects + resource names.

Parity target: reference pkg/kubectl/resource/builder.go (the Builder that
resolves TYPE NAME / TYPE/NAME / -f file args into visitor streams) and the
short-name expansions in pkg/kubectl/kubectl.go ShortForms."""

from __future__ import annotations

import glob
import json
import os
from typing import Iterable, List, Optional, Tuple

import yaml

from kubernetes_tpu.api.serialization import scheme
from kubernetes_tpu.registry.generic import RESOURCES

SHORT_NAMES = {
    "po": "pods", "pod": "pods",
    "no": "nodes", "node": "nodes",
    "svc": "services", "service": "services",
    "ep": "endpoints",
    "rc": "replicationcontrollers", "replicationcontroller": "replicationcontrollers",
    "rs": "replicasets", "replicaset": "replicasets",
    "deploy": "deployments", "deployment": "deployments",
    "ds": "daemonsets", "daemonset": "daemonsets",
    "job": "jobs",
    "sj": "scheduledjobs", "scheduledjob": "scheduledjobs",
    "hpa": "horizontalpodautoscalers", "horizontalpodautoscaler": "horizontalpodautoscalers",
    "ns": "namespaces", "namespace": "namespaces",
    "pv": "persistentvolumes", "persistentvolume": "persistentvolumes",
    "pvc": "persistentvolumeclaims", "persistentvolumeclaim": "persistentvolumeclaims",
    "quota": "resourcequotas", "resourcequota": "resourcequotas",
    "limits": "limitranges", "limitrange": "limitranges",
    "secret": "secrets",
    "cm": "configmaps", "configmap": "configmaps",
    "sa": "serviceaccounts", "serviceaccount": "serviceaccounts",
    "ev": "events", "event": "events",
    "ing": "ingresses", "ingress": "ingresses",
    "petset": "petsets",
    "pdb": "poddisruptionbudgets", "poddisruptionbudget": "poddisruptionbudgets",
}


class ResourceError(ValueError):
    pass


def resolve_resource(name: str) -> str:
    """TYPE (possibly short or singular) -> canonical plural resource name."""
    n = name.lower()
    if n in RESOURCES:
        return n
    if n in SHORT_NAMES:
        return SHORT_NAMES[n]
    if n.rstrip("s") in SHORT_NAMES:
        return SHORT_NAMES[n.rstrip("s")]
    raise ResourceError(
        f"the server doesn't have a resource type {name!r}")


def parse_args(args: List[str]) -> List[Tuple[str, Optional[str]]]:
    """TYPE1[,TYPE2] [NAME ...] or TYPE/NAME ... -> [(resource, name|None)]"""
    if not args:
        raise ResourceError("you must specify the type of resource to get")
    out: List[Tuple[str, Optional[str]]] = []
    if any("/" in a for a in args):
        for a in args:
            if "/" not in a:
                raise ResourceError(
                    "there is no need to specify a resource type as a "
                    f"separate argument when passing TYPE/NAME: {a!r}")
            typ, name = a.split("/", 1)
            out.append((resolve_resource(typ), name))
        return out
    types = [resolve_resource(t) for t in args[0].split(",")]
    names = args[1:]
    if names and len(types) > 1:
        raise ResourceError("cannot specify names with multiple types")
    if names:
        out.extend((types[0], n) for n in names)
    else:
        out.extend((t, None) for t in types)
    return out


def kind_to_resource(kind: str) -> str:
    for res, rd in RESOURCES.items():
        if rd.kind == kind:
            return res
    raise ResourceError(f"no resource registered for kind {kind!r}")


def load_files(paths: Iterable[str]):
    """-f files/dirs/'-' -> [(resource, typed object, raw dict)]. YAML multi-
    doc and JSON both accepted (reference resource.Builder FilenameParam)."""
    import sys
    out = []
    for path in paths:
        if path == "-":
            out.extend(_load_stream(sys.stdin.read()))
            continue
        if os.path.isdir(path):
            for f in sorted(glob.glob(os.path.join(path, "*"))):
                if f.endswith((".yaml", ".yml", ".json")):
                    out.extend(_load_stream(open(f).read()))
            continue
        if not os.path.exists(path):
            raise ResourceError(f"the path {path!r} does not exist")
        out.extend(_load_stream(open(path).read()))
    return out


def _load_stream(text: str):
    out = []
    text_s = text.lstrip()
    if text_s.startswith("{"):
        docs = [json.loads(text)]
    else:
        docs = [d for d in yaml.safe_load_all(text) if d]
    for doc in docs:
        kind = doc.get("kind")
        if not kind:
            raise ResourceError("object has no kind")
        res = kind_to_resource(kind)
        obj = scheme.decode_into(RESOURCES[res].cls, doc)
        out.append((res, obj, doc))
    return out
