"""Cloud provider seam.

Parity target: reference pkg/cloudprovider/providers.go —
cloudprovider.Interface with LoadBalancer() and Routes() facets consumed
by the service and route controllers. There are no cloud APIs in this
environment, so the shipped implementation is the in-memory FakeCloud
(the analog of pkg/cloudprovider/providers/fake), which records the calls
and allocates load-balancer IPs deterministically; real providers slot in
behind the same three-method facets.
"""

from __future__ import annotations

import ipaddress
import threading
from typing import Dict, List, Optional, Tuple


class CloudProvider:
    """What the controllers need from a cloud (the two facets used by
    servicecontroller.go / routecontroller.go)."""

    # -- LoadBalancer facet ----------------------------------------------------

    def ensure_load_balancer(self, name: str, ports: List[int],
                             node_names: List[str]) -> str:
        """Create/update the LB; returns its ingress IP."""
        raise NotImplementedError

    def delete_load_balancer(self, name: str) -> None:
        raise NotImplementedError

    def get_load_balancer(self, name: str) -> Optional[dict]:
        raise NotImplementedError

    # -- Routes facet ----------------------------------------------------------

    def create_route(self, node_name: str, cidr: str) -> None:
        raise NotImplementedError

    def delete_route(self, node_name: str) -> None:
        raise NotImplementedError

    def list_routes(self) -> Dict[str, str]:
        """node name -> cidr."""
        raise NotImplementedError


class FakeCloud(CloudProvider):
    """Deterministic in-memory cloud: LB IPs from 203.0.113.0/24 (TEST-NET),
    routes in a dict. Thread-safe; every mutating call is recorded in
    `calls` for assertions."""

    def __init__(self, lb_cidr: str = "203.0.113.0/24"):
        self._lock = threading.Lock()
        self._net = ipaddress.ip_network(lb_cidr)
        self._lbs: Dict[str, dict] = {}
        self._routes: Dict[str, str] = {}
        self._next_ip = 0
        self.calls: List[Tuple] = []

    def ensure_load_balancer(self, name, ports, node_names):
        with self._lock:
            self.calls.append(("ensure_lb", name, tuple(ports),
                               tuple(sorted(node_names))))
            lb = self._lbs.get(name)
            if lb is None:
                self._next_ip += 1
                lb = {"ip": str(self._net[self._next_ip])}
                self._lbs[name] = lb
            lb["ports"] = list(ports)
            lb["nodes"] = sorted(node_names)
            return lb["ip"]

    def delete_load_balancer(self, name):
        with self._lock:
            self.calls.append(("delete_lb", name))
            self._lbs.pop(name, None)

    def get_load_balancer(self, name):
        with self._lock:
            lb = self._lbs.get(name)
            return dict(lb) if lb else None

    def create_route(self, node_name, cidr):
        with self._lock:
            self.calls.append(("create_route", node_name, cidr))
            self._routes[node_name] = cidr

    def delete_route(self, node_name):
        with self._lock:
            self.calls.append(("delete_route", node_name))
            self._routes.pop(node_name, None)

    def list_routes(self):
        with self._lock:
            return dict(self._routes)
