"""kubernetes_tpu — a TPU-native cluster orchestrator.

A from-scratch framework with the capabilities of Kubernetes (reference:
v1.3.0-alpha era), re-designed TPU-first: the control plane (API server,
versioned watchable storage, informer-based state replication, controllers,
node agent, proxy, CLI) is host-side Python; the scheduler's filter-and-score
pipeline — the system's computational hot loop — is a batched JAX/XLA kernel
over dense pods x nodes tensors, sharded across a TPU device mesh.

Layer map (mirrors SURVEY.md §1):
  api/        L3  typed resources, selectors, validation, serialization
  storage/    L0  versioned KV + watch window (etcd + watchCache equivalent)
  registry/   L1  generic REST store + per-resource strategies
  apiserver/  L2  HTTP CRUD + LIST/WATCH streaming
  client/     L4  RESTClient, Reflector, FIFO, Informer, listers, events
  scheduler/  L5  shell (cache/factory/binder) + Python oracle + TPU backend
  controllers/L6  reconciliation loops
  kubelet/    L7  node agent (hollow-capable)
  proxy/      L8  service dataplane rule compiler
  kubectl/    L9  CLI
  kubemark/   LX  hollow-node scale harness
  ops/        TPU kernels (tensorize, filter-and-score, greedy commit)
  parallel/   device mesh + sharding helpers
  utils/      workqueue, backoff, clock, trace, flowcontrol
"""

__version__ = "0.1.0"
