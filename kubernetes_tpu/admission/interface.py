"""Admission interface + chain.

Reference pkg/admission/interfaces.go (Attributes, Interface.Admit) and
pkg/admission/chain.go (chainAdmissionHandler runs plugins in order, first
error wins). Plugins may mutate attrs.obj (mutating admission) or raise
AdmissionError (validating admission). The plugin registry mirrors
admission.RegisterPlugin / --admission-control flag parsing
(cmd/kube-apiserver/app/server.go admission assembly).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

CREATE = "CREATE"
UPDATE = "UPDATE"
DELETE = "DELETE"
CONNECT = "CONNECT"


class AdmissionError(Exception):
    """Rejection; surfaces as HTTP 403 Forbidden (the reference wraps plugin
    errors in apierrors.NewForbidden)."""

    def __init__(self, message: str, code: int = 403):
        self.code = code
        super().__init__(message)


@dataclass
class Attributes:
    """Everything a plugin may inspect (reference admission.Attributes)."""

    resource: str = ""          # plural, e.g. "pods"
    subresource: str = ""
    name: str = ""
    namespace: str = ""
    operation: str = CREATE
    obj: object = None          # incoming object (mutable), None for DELETE
    old_obj: object = None      # current object on UPDATE
    kind: str = ""
    user: Optional[object] = None  # auth.user.Info once authn is enabled


class Plugin:
    """Base plugin: override admit(). `handles` limits operations (reference
    admission.Handler.Handles)."""

    name = "Plugin"
    handles = (CREATE, UPDATE, DELETE, CONNECT)

    def admit(self, attrs: Attributes) -> None:
        raise NotImplementedError


class AdmissionChain:
    """Runs plugins in registration order; first raise aborts the request
    (reference chainAdmissionHandler.Admit)."""

    def __init__(self, plugins: Optional[List[Plugin]] = None):
        self.plugins = plugins or []

    def admit(self, attrs: Attributes) -> None:
        for p in self.plugins:
            if attrs.operation in p.handles:
                p.admit(attrs)


_PLUGIN_FACTORIES: Dict[str, Callable[..., Plugin]] = {}


def register_plugin(name: str, factory: Callable[..., Plugin]) -> None:
    _PLUGIN_FACTORIES[name] = factory


def new_chain(names: List[str], **kwargs) -> AdmissionChain:
    """Build a chain from plugin names, comma-order preserved — the
    --admission-control flag equivalent. kwargs (e.g. registry=) are passed to
    each factory that wants them."""
    plugins: List[Plugin] = []
    for n in names:
        try:
            factory = _PLUGIN_FACTORIES[n]
        except KeyError:
            raise ValueError(f"unknown admission plugin {n!r}; known: "
                             f"{sorted(_PLUGIN_FACTORIES)}") from None
        plugins.append(factory(**kwargs))
    return AdmissionChain(plugins)
