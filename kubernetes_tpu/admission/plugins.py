"""Built-in admission plugins.

Parity target: plugin/pkg/admission/* (SURVEY §2.3):
  namespace/lifecycle, namespace/exists, namespace/autoprovision,
  limitranger, resourcequota, serviceaccount, alwayspullimages,
  securitycontext/scdeny, antiaffinity.
Each factory takes registry= (the in-process store view; the reference
plugins use client informers the same way).
"""

from __future__ import annotations

from typing import Dict, Optional

from kubernetes_tpu.admission.interface import (
    CREATE, DELETE, UPDATE, AdmissionError, Attributes, Plugin, register_plugin,
)
from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.quantity import format_memory, parse_cpu, parse_quantity


def _registry_of(kw):
    reg = kw.get("registry")
    if reg is None:
        raise ValueError("admission plugin requires registry=")
    return reg


# --- namespace plugins -------------------------------------------------------

class NamespaceLifecycle(Plugin):
    """Rejects writes into missing or terminating namespaces and deletion of
    the protected namespaces (reference plugin/pkg/admission/namespace/lifecycle)."""

    name = "NamespaceLifecycle"
    handles = (CREATE, UPDATE, DELETE)
    _IMMORTAL = ("default", "kube-system")

    def __init__(self, registry):
        self.registry = registry

    def admit(self, attrs: Attributes) -> None:
        if attrs.resource == "namespaces":
            if attrs.operation == DELETE and attrs.name in self._IMMORTAL:
                raise AdmissionError(
                    f"namespace {attrs.name!r} is immortal and cannot be deleted")
            return
        if not attrs.namespace or attrs.operation != CREATE:
            return
        from kubernetes_tpu.registry.generic import RegistryError
        try:
            ns = self.registry.get("namespaces", attrs.namespace)
        except RegistryError:
            if attrs.namespace == "default":
                return  # default namespace is implicit
            raise AdmissionError(
                f"namespace {attrs.namespace!r} not found", code=404) from None
        phase = ns.status.phase if ns.status else ""
        if phase == "Terminating" or (ns.metadata and ns.metadata.deletion_timestamp):
            raise AdmissionError(
                f"namespace {attrs.namespace!r} is terminating; "
                f"cannot create new content")


class NamespaceExists(Plugin):
    """Rejects any namespaced request whose namespace doesn't exist."""

    name = "NamespaceExists"
    handles = (CREATE, UPDATE, DELETE)

    def __init__(self, registry):
        self.registry = registry

    def admit(self, attrs: Attributes) -> None:
        if not attrs.namespace or attrs.resource == "namespaces":
            return
        from kubernetes_tpu.registry.generic import RegistryError
        try:
            self.registry.get("namespaces", attrs.namespace)
        except RegistryError:
            if attrs.namespace == "default":
                return
            raise AdmissionError(
                f"namespace {attrs.namespace!r} does not exist", code=404) from None


class NamespaceAutoProvision(Plugin):
    """Creates the namespace on first use (reference namespace/autoprovision)."""

    name = "NamespaceAutoProvision"
    handles = (CREATE,)

    def __init__(self, registry):
        self.registry = registry

    def admit(self, attrs: Attributes) -> None:
        if not attrs.namespace or attrs.resource == "namespaces":
            return
        from kubernetes_tpu.registry.generic import RegistryError
        try:
            self.registry.get("namespaces", attrs.namespace)
        except RegistryError:
            try:
                self.registry.create("namespaces", api.Namespace(
                    metadata=api.ObjectMeta(name=attrs.namespace)))
            except RegistryError:
                pass  # raced another request; fine


# --- LimitRanger -------------------------------------------------------------

class LimitRanger(Plugin):
    """Applies LimitRange defaults to pod containers and enforces min/max
    (reference plugin/pkg/admission/limitranger)."""

    name = "LimitRanger"
    handles = (CREATE, UPDATE)

    def __init__(self, registry):
        self.registry = registry

    def admit(self, attrs: Attributes) -> None:
        if attrs.resource != "pods" or attrs.obj is None:
            return
        pod: api.Pod = attrs.obj
        ranges, _ = self.registry.list("limitranges", attrs.namespace)
        for lr in ranges:
            for item in (lr.spec.limits if lr.spec else None) or []:
                if item.type == "Container":
                    self._apply_container_item(pod, item)
                elif item.type == "Pod":
                    self._check_pod_item(pod, item)

    @staticmethod
    def _apply_container_item(pod: api.Pod, item: api.LimitRangeItem):
        for c in (pod.spec.containers if pod.spec else None) or []:
            if c.resources is None:
                c.resources = api.ResourceRequirements()
            req = dict(c.resources.requests or {})
            lim = dict(c.resources.limits or {})
            for rname, v in (item.default_request or {}).items():
                req.setdefault(rname, v)
            for rname, v in (item.default or {}).items():
                lim.setdefault(rname, v)
                req.setdefault(rname, v)
            c.resources.requests = req or None
            c.resources.limits = lim or None
            for rname, vmax in (item.max or {}).items():
                used = lim.get(rname) or req.get(rname)
                if used is not None and _parse(rname, used) > _parse(rname, vmax):
                    raise AdmissionError(
                        f"maximum {rname} usage per Container is {vmax}, "
                        f"but container {c.name!r} asks for {used}")
            for rname, vmin in (item.min or {}).items():
                used = req.get(rname) or lim.get(rname)
                if used is None or _parse(rname, used) < _parse(rname, vmin):
                    raise AdmissionError(
                        f"minimum {rname} usage per Container is {vmin}, "
                        f"but container {c.name!r} asks for {used or 0}")

    @staticmethod
    def _check_pod_item(pod: api.Pod, item: api.LimitRangeItem):
        totals: Dict[str, int] = {}
        for c in (pod.spec.containers if pod.spec else None) or []:
            for rname, v in ((c.resources.requests if c.resources else None) or {}).items():
                totals[rname] = totals.get(rname, 0) + _parse(rname, v)
        for rname, vmax in (item.max or {}).items():
            if totals.get(rname, 0) > _parse(rname, vmax):
                raise AdmissionError(
                    f"maximum {rname} usage per Pod is {vmax}")
        for rname, vmin in (item.min or {}).items():
            if totals.get(rname, 0) < _parse(rname, vmin):
                raise AdmissionError(
                    f"minimum {rname} usage per Pod is {vmin}")


def _parse(rname: str, v) -> int:
    return parse_cpu(v) if rname == api.RESOURCE_CPU else parse_quantity(v)


# --- ResourceQuota -----------------------------------------------------------

# object-count quota keys (reference pkg/quota evaluator registry)
_COUNT_KEYS = {
    "pods": "pods", "services": "services",
    "replicationcontrollers": "replicationcontrollers",
    "secrets": "secrets", "configmaps": "configmaps",
    "persistentvolumeclaims": "persistentvolumeclaims",
}


def quota_usage_of(resource: str, obj) -> Dict[str, int]:
    """Usage delta one object contributes (reference quota evaluators).
    cpu/memory are canonical ints (milliCPU / bytes)."""
    usage: Dict[str, int] = {}
    key = _COUNT_KEYS.get(resource)
    if key:
        usage[key] = 1
    if resource == "pods" and obj is not None:
        req = api.pod_resource_request(obj)
        usage[api.RESOURCE_CPU] = req[api.RESOURCE_CPU]
        usage[api.RESOURCE_MEMORY] = req[api.RESOURCE_MEMORY]
    return usage


def format_usage(rname: str, v: int) -> str:
    if rname == api.RESOURCE_CPU:
        return f"{v}m"
    if rname == api.RESOURCE_MEMORY:
        return format_memory(v)
    return str(v)


class ResourceQuotaPlugin(Plugin):
    """Checks and books quota usage at admission time with a CAS on the
    ResourceQuota status (reference plugin/pkg/admission/resourcequota keeps
    an atomic increment against the quota document the same way)."""

    name = "ResourceQuota"
    handles = (CREATE, DELETE)

    def __init__(self, registry):
        self.registry = registry

    def admit(self, attrs: Attributes) -> None:
        if not attrs.namespace:
            return
        obj = attrs.obj
        sign = 1
        if attrs.operation == DELETE:
            # releasing usage: charge the negated footprint of the object
            # being deleted
            from kubernetes_tpu.registry.generic import RegistryError
            try:
                obj = self.registry.get(attrs.resource, attrs.name, attrs.namespace)
            except RegistryError:
                return
            sign = -1
        delta = quota_usage_of(attrs.resource, obj)
        if not delta:
            return
        quotas, _ = self.registry.list("resourcequotas", attrs.namespace)
        for q in quotas:
            self._charge(q, {k: sign * v for k, v in delta.items()}, attrs)

    def release_create(self, attrs: Attributes) -> None:
        """Compensation hook: the apiserver calls this when a create fails
        after admission charged it, so the booking is rolled back."""
        delta = quota_usage_of(attrs.resource, attrs.obj)
        if not delta:
            return
        quotas, _ = self.registry.list("resourcequotas", attrs.namespace)
        for q in quotas:
            self._charge(q, {k: -v for k, v in delta.items()}, attrs)

    def _charge(self, q: api.ResourceQuota, delta: Dict[str, int],
                attrs: Attributes):
        hard = (q.spec.hard if q.spec else None) or {}
        relevant = {k: v for k, v in delta.items() if k in hard}
        if not relevant:
            return

        def bump(cur: api.ResourceQuota):
            if cur.status is None:
                cur.status = api.ResourceQuotaStatus()
            used = dict(cur.status.used or {})
            for rname, dv in relevant.items():
                limit = _parse(rname, hard[rname])
                cur_used = _parse(rname, used.get(rname, 0))
                if dv > 0 and cur_used + dv > limit:
                    raise AdmissionError(
                        f"exceeded quota: {cur.metadata.name}, "
                        f"requested: {rname}={format_usage(rname, dv)}, "
                        f"used: {rname}={format_usage(rname, cur_used)}, "
                        f"limited: {rname}={hard[rname]}")
                used[rname] = format_usage(rname, max(0, cur_used + dv))
            cur.status.hard = dict(hard)
            cur.status.used = used
            return cur

        self.registry.guaranteed_update(
            "resourcequotas", q.metadata.name, attrs.namespace, bump)


# --- ServiceAccount ----------------------------------------------------------

class ServiceAccountPlugin(Plugin):
    """Defaults pod.spec.serviceAccountName to "default" (reference
    plugin/pkg/admission/serviceaccount; token mounting is the kubelet's
    concern in our split)."""

    name = "ServiceAccount"
    handles = (CREATE,)

    def __init__(self, registry):
        self.registry = registry

    def admit(self, attrs: Attributes) -> None:
        if attrs.resource != "pods" or attrs.obj is None:
            return
        pod: api.Pod = attrs.obj
        if pod.spec and not pod.spec.service_account_name:
            pod.spec.service_account_name = "default"


# --- image / security policy -------------------------------------------------

class AlwaysPullImages(Plugin):
    """Forces imagePullPolicy=Always (reference plugin/pkg/admission/alwayspullimages)."""

    name = "AlwaysPullImages"
    handles = (CREATE, UPDATE)

    def __init__(self, registry=None):
        pass

    def admit(self, attrs: Attributes) -> None:
        if attrs.resource != "pods" or attrs.obj is None:
            return
        for c in (attrs.obj.spec.containers if attrs.obj.spec else None) or []:
            c.image_pull_policy = "Always"


class SecurityContextDeny(Plugin):
    """Denies privileged containers and runAsUser overrides (reference
    plugin/pkg/admission/securitycontext/scdeny)."""

    name = "SecurityContextDeny"
    handles = (CREATE, UPDATE)

    def __init__(self, registry=None):
        pass

    def admit(self, attrs: Attributes) -> None:
        if attrs.resource != "pods" or attrs.obj is None:
            return
        for c in (attrs.obj.spec.containers if attrs.obj.spec else None) or []:
            sc = c.security_context
            if sc is None:
                continue
            if sc.privileged:
                raise AdmissionError(
                    f"container {c.name!r}: privileged containers are not allowed")
            if sc.run_as_user is not None or sc.se_linux_options:
                raise AdmissionError(
                    f"container {c.name!r}: SecurityContext overrides are not allowed")


class AntiAffinityLimit(Plugin):
    """Denies pods with hard pod anti-affinity on any topology key other than
    the hostname label (reference plugin/pkg/admission/antiaffinity
    LimitPodHardAntiAffinityTopology)."""

    name = "LimitPodHardAntiAffinityTopology"
    handles = (CREATE,)

    def __init__(self, registry=None):
        pass

    def admit(self, attrs: Attributes) -> None:
        if attrs.resource != "pods" or attrs.obj is None:
            return
        affinity = attrs.obj.spec.affinity if attrs.obj.spec else None
        anti = affinity.pod_anti_affinity if affinity else None
        for term in (anti.required_during_scheduling_ignored_during_execution
                     if anti else None) or []:
            if term.topology_key and term.topology_key != api.LABEL_HOSTNAME:
                raise AdmissionError(
                    "pod with hard anti-affinity on topology key "
                    f"{term.topology_key!r} is not allowed (only "
                    f"{api.LABEL_HOSTNAME})")


register_plugin("NamespaceLifecycle", lambda **kw: NamespaceLifecycle(_registry_of(kw)))
register_plugin("NamespaceExists", lambda **kw: NamespaceExists(_registry_of(kw)))
register_plugin("NamespaceAutoProvision",
                lambda **kw: NamespaceAutoProvision(_registry_of(kw)))
register_plugin("LimitRanger", lambda **kw: LimitRanger(_registry_of(kw)))
register_plugin("ResourceQuota", lambda **kw: ResourceQuotaPlugin(_registry_of(kw)))
register_plugin("ServiceAccount", lambda **kw: ServiceAccountPlugin(_registry_of(kw)))
register_plugin("AlwaysPullImages", lambda **kw: AlwaysPullImages())
register_plugin("SecurityContextDeny", lambda **kw: SecurityContextDeny())
register_plugin("LimitPodHardAntiAffinityTopology", lambda **kw: AntiAffinityLimit())
