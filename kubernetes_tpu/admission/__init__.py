"""Admission control: mutating/validating plugin chain run between request
decode and storage.

Parity target: reference pkg/admission/ (Interface/Attributes, chain) plus the
plugin inventory of plugin/pkg/admission/ (SURVEY §2.3): NamespaceLifecycle,
NamespaceExists, NamespaceAutoProvision, LimitRanger, ResourceQuota,
ServiceAccount, AlwaysPullImages, SecurityContextDeny, AntiAffinity (the
LimitPodHardAntiAffinityTopology plugin), DenyExecOnPrivileged.
"""

from kubernetes_tpu.admission.interface import (  # noqa: F401
    AdmissionChain,
    AdmissionError,
    Attributes,
    CREATE,
    DELETE,
    Plugin,
    UPDATE,
    new_chain,
    register_plugin,
)
from kubernetes_tpu.admission import plugins  # noqa: F401  (registers built-ins)
