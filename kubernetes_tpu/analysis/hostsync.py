"""host-sync-in-kernel: host/device sync points inside the jitted kernel path.

A ``.item()``, ``np.asarray(traced)``, ``float(traced)``, or a Python
branch on a traced value inside the jit boundary either fails tracing
outright or — worse — silently bakes one batch's values into the compiled
program (a constant-folded kernel that "works" until the second batch).
On the bench path this is also the classic compile-cache poison: the
traced-in constant changes the program hash every solve.

Scope: modules that import jax. The checker finds jit roots
(``@jax.jit``, ``@functools.partial(jax.jit, static_argnames=...)``,
``x = jax.jit(fn)``), computes the local call graph reachable from them
(helpers called from inside the kernel are kernel too), and inside that
set flags:

- ``.item()`` / ``jax.device_get`` / ``.block_until_ready()`` — explicit
  device syncs
- ``np.asarray`` / ``np.array`` of a non-literal — device→host transfer
  (literal lists are host constants and fine)
- ``float()/int()/bool()`` of a non-literal — implicit sync; shape/dtype
  metadata (``x.shape``, ``len(x)``, ``x.ndim``) is static and exempt
- in jit ROOT functions only (where tracedness is known from the
  signature): ``if``/``while`` tests that reference a non-static
  parameter directly — branch on ``jnp.where``/``lax.cond`` instead
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set

from kubernetes_tpu.analysis.core import (
    Checker,
    FileContext,
    Finding,
    dotted_chain,
    walk_same_scope,
)

_STATIC_META_ATTRS = {"shape", "dtype", "ndim", "size"}
_CAST_FUNCS = {"float", "int", "bool"}
_NP_TRANSFER = {"asarray", "array"}


def _imports_jax(tree: ast.Module) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            if any(a.name == "jax" or a.name.startswith("jax.")
                   for a in node.names):
                return True
        elif isinstance(node, ast.ImportFrom):
            if node.module and (node.module == "jax"
                                or node.module.startswith("jax.")):
                return True
    return False


def _jit_static_argnames(call: ast.Call) -> List[str]:
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            try:
                v = ast.literal_eval(kw.value)
            except (ValueError, SyntaxError, TypeError):
                return []
            if isinstance(v, str):
                return [v]
            if isinstance(v, (list, tuple)):
                return [s for s in v if isinstance(s, str)]
    return []


def _jit_decoration(dec: ast.AST) -> Optional[List[str]]:
    """static_argnames if `dec` is a jit decorator, else None."""
    chain = dotted_chain(dec)
    if chain and chain[-1] == "jit":
        return []
    if isinstance(dec, ast.Call):
        inner = dotted_chain(dec.func)
        if inner and inner[-1] == "jit":
            return _jit_static_argnames(dec)
        if inner and inner[-1] == "partial" and dec.args:
            first = dotted_chain(dec.args[0])
            if first and first[-1] == "jit":
                return _jit_static_argnames(dec)
    return None


def _is_literalish(node: ast.AST) -> bool:
    """Host constants: literals, and lists/tuples of literalish things."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, (ast.List, ast.Tuple)):
        return all(_is_literalish(e) for e in node.elts)
    if isinstance(node, ast.UnaryOp):
        return _is_literalish(node.operand)
    return False


def _is_static_metadata(node: ast.AST) -> bool:
    """x.shape / x.shape[0] / len(x) / x.ndim — static under tracing."""
    if isinstance(node, ast.Subscript):
        return _is_static_metadata(node.value)
    if isinstance(node, ast.Attribute):
        return node.attr in _STATIC_META_ATTRS
    if isinstance(node, ast.Call):
        chain = dotted_chain(node.func)
        if chain and chain[-1] in ("len", "range", "enumerate"):
            return True
    if isinstance(node, ast.BinOp):
        return (_is_static_metadata(node.left)
                or _is_static_metadata(node.right))
    return False


class HostSyncChecker(Checker):
    name = "host-sync-in-kernel"
    description = ("host/device sync (.item(), np.asarray, float(), traced "
                   "branching) inside the jitted kernel path")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterable[Finding]:
        if not _imports_jax(tree):
            return
        functions: Dict[str, ast.FunctionDef] = {}
        roots: Dict[str, List[str]] = {}  # fn name -> static_argnames
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                functions.setdefault(node.name, node)
                for dec in node.decorator_list:
                    statics = _jit_decoration(dec)
                    if statics is not None:
                        roots[node.name] = statics
            elif isinstance(node, ast.Call):
                # jitted = jax.jit(fn, ...)
                chain = dotted_chain(node.func)
                if chain and chain[-1] == "jit" and node.args and \
                        isinstance(node.args[0], ast.Name):
                    roots[node.args[0].id] = _jit_static_argnames(node)

        kernel: Set[str] = set(roots)
        frontier = list(roots)
        while frontier:
            fn = functions.get(frontier.pop())
            if fn is None:
                continue
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Call):
                    chain = dotted_chain(sub.func)
                    if chain and len(chain) == 1 and \
                            chain[0] in functions and chain[0] not in kernel:
                        kernel.add(chain[0])
                        frontier.append(chain[0])

        for name in sorted(kernel):
            fn = functions.get(name)
            if fn is None:
                continue
            yield from self._check_kernel_fn(
                fn, ctx, statics=roots.get(name), is_root=name in roots)

    @staticmethod
    def _host_list_names(fn) -> Set[str]:
        """Names bound to Python lists built in this function (``chans =
        []`` + appends): np.asarray of those is a host constant, not a
        device transfer."""
        def is_host_list(value) -> bool:
            if isinstance(value, (ast.List, ast.ListComp)):
                return True
            if isinstance(value, ast.Call):
                return dotted_chain(value.func) in (["list"], ["range"])
            return False

        out: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.AnnAssign) and node.value is not None:
                if is_host_list(node.value) and \
                        isinstance(node.target, ast.Name):
                    out.add(node.target.id)
            elif isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) and is_host_list(node.value):
                        out.add(tgt.id)
                    elif isinstance(tgt, ast.Tuple) and \
                            isinstance(node.value, ast.Tuple) and \
                            len(tgt.elts) == len(node.value.elts):
                        out.update(
                            t.id for t, v in zip(tgt.elts, node.value.elts)
                            if isinstance(t, ast.Name) and is_host_list(v))
        return out

    def _check_kernel_fn(self, fn, ctx: FileContext,
                         statics: Optional[List[str]],
                         is_root: bool) -> Iterable[Finding]:
        host_lists = self._host_list_names(fn)
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                yield from self._check_call(node, ctx, fn.name, host_lists)
        if not is_root:
            return
        traced = {a.arg for a in
                  list(fn.args.args) + list(fn.args.kwonlyargs)}
        traced -= set(statics or ())
        traced.discard("self")
        for node in walk_same_scope(fn):
            if isinstance(node, (ast.If, ast.While)):
                bad = self._traced_name_in_test(node.test, traced)
                if bad:
                    yield self.finding(
                        ctx, node,
                        f"branching on traced value '{bad}' inside jitted "
                        f"'{fn.name}' — use jnp.where/lax.cond, or declare "
                        "it in static_argnames")

    @classmethod
    def _traced_name_in_test(cls, test: ast.AST,
                             traced: Set[str]) -> Optional[str]:
        """A traced param referenced by VALUE in a branch test. References
        through static metadata (x.shape, x.dtype, x.ndim, len(x)) don't
        count — those are concrete under tracing."""
        if isinstance(test, ast.Attribute) and \
                test.attr in _STATIC_META_ATTRS:
            return None
        if isinstance(test, ast.Call):
            chain = dotted_chain(test.func)
            if chain and chain[-1] in ("len", "isinstance", "hasattr",
                                       "issubdtype", "getattr"):
                return None
        if isinstance(test, ast.Name):
            return test.id if test.id in traced else None
        for child in ast.iter_child_nodes(test):
            hit = cls._traced_name_in_test(child, traced)
            if hit:
                return hit
        return None

    def _check_call(self, call: ast.Call, ctx: FileContext,
                    fn_name: str,
                    host_lists: Set[str] = frozenset()) -> Iterable[Finding]:
        chain = dotted_chain(call.func)
        where = f"inside kernel-path function '{fn_name}'"
        if not chain:
            # method on a computed receiver, e.g. x.sum().item()
            if isinstance(call.func, ast.Attribute):
                if call.func.attr == "item":
                    yield self.finding(
                        ctx, call,
                        f".item() {where} forces a device→host sync")
                elif call.func.attr == "block_until_ready":
                    yield self.finding(
                        ctx, call,
                        f".block_until_ready() {where} blocks on the device "
                        "— sync at the dispatch boundary instead")
            return
        last = chain[-1]
        if last == "item" and len(chain) > 1:
            yield self.finding(ctx, call,
                               f".item() {where} forces a device→host sync")
        elif last == "block_until_ready" and len(chain) > 1:
            yield self.finding(
                ctx, call, f".block_until_ready() {where} blocks on the "
                "device — sync at the dispatch boundary instead")
        elif chain[:2] == ["jax", "device_get"]:
            yield self.finding(ctx, call,
                               f"jax.device_get() {where} is a host transfer")
        elif len(chain) == 2 and chain[0] in ("np", "numpy") and \
                last in _NP_TRANSFER:
            arg = call.args[0] if call.args else None
            host_const = arg is not None and (
                _is_literalish(arg)
                or (isinstance(arg, ast.Name) and arg.id in host_lists))
            if arg is not None and not host_const:
                yield self.finding(
                    ctx, call,
                    f"np.{last}() of a non-literal {where} pulls the value "
                    "to host (use jnp, or hoist to the host boundary)")
        elif len(chain) == 1 and last in _CAST_FUNCS and call.args:
            arg = call.args[0]
            if not _is_literalish(arg) and not _is_static_metadata(arg):
                yield self.finding(
                    ctx, call,
                    f"{last}() of a non-literal {where} forces a host sync "
                    "(shape/dtype metadata is exempt; traced values are not)")
