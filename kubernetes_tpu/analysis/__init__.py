"""kube-verify: repo-native static analysis for the control plane.

The reference keeps 900k LoC of concurrent Go honest with a `hack/verify-*`
battery plus `go test -race`; this package is our equivalent, specialized to
the bug classes THIS codebase has actually shipped (round-5 ADVICE):

- ``lock-held-across-io``    a ``with <lock>:`` body that performs blocking
                             I/O (RESTClient verbs, sockets, subprocess,
                             ``time.sleep``, device syncs) — the exact
                             volume-manager bug
- ``informer-cache-mutation``  mutating an object obtained from an informer
                             store/lister without ``deep_copy``
- ``host-sync-in-kernel``    host/device sync points (``.item()``,
                             ``np.asarray``, traced-value branching) inside
                             the jitted kernel call graph of any
                             jax-importing module (``ops/`` in practice)
- ``swallowed-exception``    bare/overbroad ``except`` that silently
                             discards errors
- ``monotonic-duration``     ``time.time()`` used for durations instead of
                             ``time.monotonic()``
- ``nondaemon-thread``       threads created without explicit ``daemon=``

Run it: ``python -m kubernetes_tpu.analysis kubernetes_tpu/``
Suppress a finding in place: ``# kube-verify: disable=<check>`` (same line),
``# kube-verify: disable-next-line=<check>``, or a file-level
``# kube-verify: disable-file=<check>``.
Grandfathered findings live in ``analysis/baseline.json`` (see
``--write-baseline``); the self-hosting gate in tests/test_static_analysis.py
keeps the package itself at zero non-baselined findings.

The runtime half — the lock-order tracker and checked informer store that
tests run under (our ``go test -race`` stand-in) — is in
``kubernetes_tpu.analysis.runtime``.
"""

from kubernetes_tpu.analysis.core import (  # noqa: F401
    Baseline,
    Checker,
    Finding,
    all_checkers,
    analyze_paths,
    analyze_source,
    default_baseline_path,
)
