"""lock-held-across-io: a ``with <lock>:`` body that performs blocking I/O.

The volume manager shipped exactly this bug (round-5 ADVICE): PVC
resolution — an apiserver HTTP round-trip — ran under the manager-wide
lock, so one slow claim stalled every pod's volume lifecycle on the
kubelet. The checker encodes the pattern syntactically: a with-statement
whose context expression *names a lock*, whose body (same scope only —
nested defs execute later) *calls a known-blocking operation*.

Known-blocking (each with its rationale):
- ``time.sleep``                      the classic
- ``subprocess.*`` / ``socket.*``     process spawn / network syscalls
- ``requests.*`` / ``urllib.*`` / ``urlopen``  HTTP libraries
- HTTP connection verbs (``.request``/``.getresponse`` on a *conn*)
- RESTClient verbs on a receiver that names a client/resolver —
  ``self.client.get(...)`` is an apiserver round-trip, not a dict lookup
- ``.block_until_ready()``            device sync (seconds under load)
- ``X.wait(...)`` where X is NOT the held lock — ``Condition.wait`` on the
  held lock releases it (fine); ``Event.wait`` under someone else's lock
  sleeps while holding it (not fine)
- ``X.join(...)`` where X names a thread

Indirect blocking (``with lock: self._helper()`` where the helper does the
I/O) is out of scope for the AST pass — the runtime lock-order tracker and
review cover that; this checker exists to make the *obvious* version
impossible to ship again.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from kubernetes_tpu.analysis.core import (
    Checker,
    FileContext,
    Finding,
    chain_text,
    dotted_chain,
    walk_same_scope,
)

_LOCK_WORDS = ("lock", "mutex")
_LOCK_EXACT = {"lk", "mu"}

_REST_VERBS = {
    "get", "create", "update", "update_status", "patch", "delete", "list",
    "watch", "bind", "get_scale", "update_scale", "request", "get_json",
}


def _is_rest_receiver(receiver_last: str) -> bool:
    """'client'/'self.client'/'pv_resolver' yes; 'clients' (a dict of
    clients) and 'restart_counts' (substring trap) no."""
    return receiver_last.endswith("client") or receiver_last == "resolver" \
        or receiver_last.endswith("_resolver")

_SOCKET_BLOCKING = {
    "create_connection", "connect", "accept", "recv", "recv_into", "send",
    "sendall", "sendto", "getaddrinfo", "gethostbyname",
}


def is_lock_expr(node: ast.AST) -> bool:
    """Does this with-context expression name a lock? Terminal-segment
    heuristic: ``self._lock``, ``self._deleted_lock``, ``lk``..."""
    chain = dotted_chain(node)
    if not chain:
        return False
    last = chain[-1].lower()
    return last in _LOCK_EXACT or any(w in last for w in _LOCK_WORDS)


def blocking_reason(call: ast.Call, held_lock_text: str) -> Optional[str]:
    chain = dotted_chain(call.func)
    if not chain:
        # method on a computed receiver, e.g. kernel(x).block_until_ready()
        if isinstance(call.func, ast.Attribute):
            if call.func.attr == "block_until_ready":
                return ".block_until_ready() syncs with the device"
            if call.func.attr == "getresponse":
                return ".getresponse() does HTTP I/O"
        return None
    head, last = chain[0], chain[-1]
    receiver = ".".join(chain[:-1])
    rlow = receiver.lower()
    if head == "time" and last == "sleep":
        return "time.sleep() sleeps"
    if head == "subprocess":
        return f"subprocess.{last}() spawns a process"
    if head == "socket" and (last in _SOCKET_BLOCKING or last == "socket"):
        return f"socket.{last}() does network I/O"
    if head in ("requests", "urllib") or last == "urlopen":
        return f"{'.'.join(chain)}() does HTTP I/O"
    if last == "block_until_ready":
        return ".block_until_ready() syncs with the device"
    if last in _SOCKET_BLOCKING and any(
            w in rlow for w in ("sock", "conn")):
        return f"{receiver}.{last}() does network I/O"
    if last in ("getresponse", "putrequest") or (
            last == "request" and "conn" in rlow):
        return f"{receiver}.{last}() does HTTP I/O"
    if last in _REST_VERBS and chain[:-1] and \
            _is_rest_receiver(chain[-2].lower()):
        return f"{receiver}.{last}() is an apiserver round-trip"
    if last == "wait" and receiver and receiver != held_lock_text:
        return (f"{receiver}.wait() sleeps while the lock is held "
                "(only waiting on the held lock itself releases it)")
    if last == "join" and "thread" in rlow:
        return f"{receiver}.join() blocks on another thread"
    return None


class LockHeldAcrossIOChecker(Checker):
    name = "lock-held-across-io"
    description = ("blocking I/O (REST verbs, sockets, subprocess, sleep, "
                   "device sync) inside a `with <lock>:` body")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            for item in node.items:
                lock_expr = item.context_expr
                # `with lock.acquire():` style — unwrap call AND the
                # .acquire so the receiver is what the name heuristic sees
                if isinstance(lock_expr, ast.Call):
                    lock_expr = lock_expr.func
                    if isinstance(lock_expr, ast.Attribute) and \
                            lock_expr.attr in ("acquire", "acquire_read",
                                               "acquire_write"):
                        lock_expr = lock_expr.value
                if not is_lock_expr(lock_expr):
                    continue
                lock_text = chain_text(lock_expr)
                for inner in self._body_nodes(node):
                    if not isinstance(inner, ast.Call):
                        continue
                    reason = blocking_reason(inner, lock_text)
                    if reason:
                        yield self.finding(
                            ctx, inner,
                            f"{reason} while holding {lock_text or 'a lock'}"
                            " — move the blocking call outside the lock")

    @staticmethod
    def _body_nodes(with_node):
        for stmt in with_node.body:
            yield stmt
            if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                yield from walk_same_scope(stmt)
