"""replication-lock-io: replication traffic or fsync under a store lock.

The replicated control plane's write pipeline is only safe for readers
because its split is STRUCTURAL: mutations stage under the store/member
lock, but the replication round-trip (transport sends to other members)
and every durability syscall (fsync) happen outside it, serialized by
writer batons (`_commit_gate` / `_ship_gate`) that readers and watchers
never touch. Collapse that split — ship or fsync while holding a lock —
and one slow follower or one slow disk stalls every read, watch
delivery, and CAS loop in the process: the same bug class as the round-5
volume manager (PVC resolution under the manager-wide lock), one layer
lower where it is strictly worse.

This checker makes the obvious regression impossible to ship:

- a call to a replication RPC (``append_entries``, ``request_vote``,
  ``install_snapshot``, ``read_log_tail``, or any method on a receiver
  naming a transport/peer) inside a ``with <lock>:`` body
- ``os.fsync`` / ``os.fdatasync`` inside a ``with <lock>:`` body

"Lock" uses the same terminal-name heuristic as lock-held-across-io
(``self._lock``, ``mu``, ...): the batons are deliberately NOT locks by
that heuristic — holding a writer baton across the round-trip is the
design, holding the reader-visible lock across it is the bug.

Like its sibling, this is a lexical same-scope pass: indirect flows
(``with lock: self._helper()`` where the helper ships) are the runtime
lock-order tracker's and review's job. The one legitimate
fsync-near-lock in the repo — DurableStore's WAL append, where fsync
must precede publish under the single-store lock by contract — is a
function *called with* the lock held, not a ``with`` body, and so stays
out of scope by the same rule. Baseline: empty, and it stays empty.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from kubernetes_tpu.analysis.core import (
    Checker,
    FileContext,
    Finding,
    chain_text,
    dotted_chain,
)
from kubernetes_tpu.analysis.locks import LockHeldAcrossIOChecker, is_lock_expr

# the replication RPC surface: anything on this list is a send to (or a
# durable read on behalf of) another member — never under a lock
_REPL_VERBS = {
    "append_entries", "request_vote", "install_snapshot", "read_log_tail",
    "catch_up", "replicate", "ship", "send_entries", "heartbeat",
}

_SYNC_CALLS = {"fsync", "fdatasync"}


def _replication_reason(call: ast.Call) -> Optional[str]:
    chain = dotted_chain(call.func)
    if not chain:
        return None
    head, last = chain[0], chain[-1]
    receiver = ".".join(chain[:-1])
    if head == "os" and last in _SYNC_CALLS:
        return (f"os.{last}() is a durability syscall (milliseconds to "
                "seconds on a loaded disk)")
    if last in _REPL_VERBS:
        return f"{receiver + '.' if receiver else ''}{last}() is replication traffic"
    if chain[:-1] and any(w in chain[-2].lower()
                          for w in ("transport", "peer")):
        return f"{receiver}.{last}() goes through the member transport"
    return None


class ReplicationLockIOChecker(Checker):
    name = "replication-lock-io"
    description = ("replication sends (append_entries/request_vote/"
                   "install_snapshot/transport.*) or fsync inside a "
                   "`with <lock>:` body — stage under the lock, ship and "
                   "sync outside it")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            for item in node.items:
                lock_expr = item.context_expr
                if isinstance(lock_expr, ast.Call):
                    lock_expr = lock_expr.func
                    if isinstance(lock_expr, ast.Attribute) and \
                            lock_expr.attr in ("acquire", "acquire_read",
                                               "acquire_write"):
                        lock_expr = lock_expr.value
                if not is_lock_expr(lock_expr):
                    continue
                lock_text = chain_text(lock_expr)
                for inner in LockHeldAcrossIOChecker._body_nodes(node):
                    if not isinstance(inner, ast.Call):
                        continue
                    reason = _replication_reason(inner)
                    if reason:
                        yield self.finding(
                            ctx, inner,
                            f"{reason} while holding "
                            f"{lock_text or 'a lock'} — the rotate-under-"
                            "lock/ship-outside-lock split must be "
                            "structural")
