"""Text and JSON reporters for kube-verify findings."""

from __future__ import annotations

import json
from typing import Dict, List, TextIO

from kubernetes_tpu.analysis.core import Finding


def render_text(results: Dict[str, List[Finding]], out: TextIO,
                verbose_baselined: bool = False) -> None:
    new, baselined = results["new"], results["baselined"]
    for f in sorted(new, key=lambda f: (f.path, f.line, f.col)):
        out.write(f"{f.path}:{f.line}:{f.col + 1}: [{f.check}] "
                  f"{f.message}\n")
        if f.snippet:
            out.write(f"    {f.snippet}\n")
    if verbose_baselined:
        for f in sorted(baselined, key=lambda f: (f.path, f.line)):
            out.write(f"{f.path}:{f.line}:{f.col + 1}: [baselined:"
                      f"{f.check}] {f.message}\n")
    by_check: Dict[str, int] = {}
    for f in new:
        by_check[f.check] = by_check.get(f.check, 0) + 1
    summary = ", ".join(f"{k}={v}" for k, v in sorted(by_check.items()))
    out.write(f"kube-verify: {len(new)} finding(s)"
              f"{' (' + summary + ')' if summary else ''}, "
              f"{len(baselined)} baselined\n")


def render_json(results: Dict[str, List[Finding]], out: TextIO) -> None:
    payload = {
        "findings": [f.to_dict() for f in
                     sorted(results["new"],
                            key=lambda f: (f.path, f.line, f.col))],
        "baselined": [f.to_dict() for f in
                      sorted(results["baselined"],
                             key=lambda f: (f.path, f.line, f.col))],
        "summary": {
            "new": len(results["new"]),
            "baselined": len(results["baselined"]),
        },
    }
    json.dump(payload, out, indent=2)
    out.write("\n")
