"""Runtime race detection for tests — the ``go test -race`` stand-in.

Two instruments, both switched on by tests/conftest.py so the whole tier-1
suite runs under them:

**Lock-order tracker.** ``install_lock_order_tracker()`` patches
``threading.Lock``/``threading.RLock`` with factories that wrap locks
*created from kubernetes_tpu code* (caller-module check at creation time —
stdlib and pytest internals keep real locks). Each wrapped lock belongs to
an order class keyed by its creation site (file:line — all per-pod locks
minted by one line are one class, like lockdep). Acquiring B while holding
A records the edge A→B in a global acquisition graph; an edge that closes
a cycle (the classic A→B vs B→A inversion) records a LockOrderViolation.
Violations are *recorded*, not raised — a detector that crashes arbitrary
victim threads hides the report; tests/conftest fails the responsible test
from its teardown hook instead.

**Checked informer store.** ``enable_checked_store()`` makes every
``ThreadSafeStore`` fingerprint objects on insert (stable serialization of
the dataclass) and re-fingerprint on read; a mismatch means some reader
mutated the shared cache object in place — the runtime complement of the
``informer-cache-mutation`` static check, and it sees through helper-call
indirection the AST pass cannot. Reads are verified in full for small
stores and sampled above ``VERIFY_FULL_LIMIT`` so the 30k-pod scale test
keeps its throughput SLO.

Both report into a module-global violation list: ``drain_violations()``
returns-and-clears it (the conftest teardown hook asserts it is empty
after every test; seeded-violation tests drain it themselves).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import sys
import threading
from typing import Dict, List, Optional, Set, Tuple

_real_Lock = threading.Lock
_real_RLock = threading.RLock

# -- shared violation sink -----------------------------------------------------

_violations: List[str] = []
_violations_lock = _real_Lock()


def record_violation(message: str) -> None:
    with _violations_lock:
        _violations.append(message)


def drain_violations() -> List[str]:
    """Return and clear all recorded race violations."""
    with _violations_lock:
        out = list(_violations)
        _violations.clear()
    return out


def peek_violations() -> List[str]:
    with _violations_lock:
        return list(_violations)


# -- lock-order tracking -------------------------------------------------------

class LockOrderTracker:
    """Acquisition-order graph over lock classes (creation sites)."""

    def __init__(self):
        self._lock = _real_Lock()
        self._graph: Dict[str, Set[str]] = {}   # site -> sites acquired under
        self._edges: Set[Tuple[str, str]] = set()
        self._reported: Set[Tuple[str, str]] = set()
        self._held = threading.local()          # [(site, lock_id, count)]
        self.violations: List[str] = []

    def _held_list(self) -> list:
        held = getattr(self._held, "stack", None)
        if held is None:
            held = self._held.stack = []
        return held

    def note_acquired(self, site: str, lock_id: int) -> None:
        held = self._held_list()
        for entry in held:
            if entry[1] == lock_id:     # RLock re-entry: no new ordering
                entry[2] += 1
                return
        new_edges = [(h_site, site) for h_site, _, _ in held
                     if h_site != site
                     and (h_site, site) not in self._edges]
        held.append([site, lock_id, 1])
        if not new_edges:
            return
        with self._lock:
            for edge in new_edges:
                if edge in self._edges:
                    continue
                self._edges.add(edge)
                self._graph.setdefault(edge[0], set()).add(edge[1])
                cycle = self._find_cycle(edge)
                if cycle:
                    self._report(cycle)

    def note_released(self, lock_id: int) -> None:
        held = self._held_list()
        for i in range(len(held) - 1, -1, -1):
            if held[i][1] == lock_id:
                held[i][2] -= 1
                if held[i][2] <= 0:
                    del held[i]
                return

    def _find_cycle(self, new_edge: Tuple[str, str]) -> Optional[List[str]]:
        """Adding src→dst closes a cycle iff dst already reaches src."""
        src, dst = new_edge
        parent = {dst: None}
        stack = [dst]
        while stack:
            node = stack.pop()
            for nxt in self._graph.get(node, ()):
                if nxt == src:
                    # cycle: src -> dst -> ... -> node -> src
                    path = [node]
                    while parent[path[-1]] is not None:
                        path.append(parent[path[-1]])
                    return [src] + list(reversed(path)) + [src]
                if nxt not in parent:
                    parent[nxt] = node
                    stack.append(nxt)
        return None

    def _report(self, cycle: List[str]) -> None:
        key = (cycle[0], cycle[1])
        if key in self._reported:
            return
        self._reported.add(key)
        msg = ("lock-order inversion (potential deadlock): "
               + " -> ".join(cycle)
               + f" [thread {threading.current_thread().name}]")
        self.violations.append(msg)
        record_violation(msg)


class InstrumentedLock:
    """Wraps a real Lock/RLock; reports acquire/release to the tracker.
    Exposes the Condition protocol (_release_save etc.) by delegating to
    the real lock — during Condition.wait the thread is blocked, so the
    held-set staying 'as if held' is exactly right."""

    def __init__(self, real, site: str, tracker: LockOrderTracker):
        self._real = real
        self._site = site
        self._tracker = tracker

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._real.acquire(blocking, timeout)
        if got:
            self._tracker.note_acquired(self._site, id(self))
        return got

    def release(self):
        self._tracker.note_released(id(self))
        self._real.release()

    def locked(self):
        return self._real.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # Condition(lock) protocol — present only on RLock; getattr keeps the
    # plain-Lock wrapper working (Condition falls back to acquire/release)
    def __getattr__(self, name):
        if name in ("_release_save", "_acquire_restore", "_is_owned",
                    "_at_fork_reinit"):
            return getattr(self._real, name)
        raise AttributeError(name)

    def __repr__(self):
        return f"<InstrumentedLock site={self._site} {self._real!r}>"


_installed_tracker: Optional[LockOrderTracker] = None


def install_lock_order_tracker(module_prefix: str = "kubernetes_tpu",
                               ) -> LockOrderTracker:
    """Patch threading.Lock/RLock to mint instrumented locks for code in
    `module_prefix`. Idempotent; returns the active tracker."""
    global _installed_tracker
    if _installed_tracker is not None:
        return _installed_tracker
    tracker = LockOrderTracker()

    def _site_of(frame) -> str:
        return f"{frame.f_code.co_filename}:{frame.f_lineno}"

    def _wants_instrumentation(frame) -> bool:
        mod = frame.f_globals.get("__name__", "")
        return mod == module_prefix or mod.startswith(module_prefix + ".")

    def make_lock():
        frame = sys._getframe(1)
        real = _real_Lock()
        if _wants_instrumentation(frame):
            return InstrumentedLock(real, _site_of(frame), tracker)
        return real

    def make_rlock():
        frame = sys._getframe(1)
        real = _real_RLock()
        if _wants_instrumentation(frame):
            return InstrumentedLock(real, _site_of(frame), tracker)
        return real

    threading.Lock = make_lock
    threading.RLock = make_rlock
    _installed_tracker = tracker
    return tracker


def uninstall_lock_order_tracker() -> None:
    global _installed_tracker
    threading.Lock = _real_Lock
    threading.RLock = _real_RLock
    _installed_tracker = None


# -- checked informer store ----------------------------------------------------

# above this many tracked objects, reads verify a deterministic sample so
# scale tests (30k pods) keep their throughput SLOs
VERIFY_FULL_LIMIT = 1024
SAMPLE_STRIDE = 64


def fingerprint(obj) -> str:
    """Stable content hash of an API object (dataclass) or plain value."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        from kubernetes_tpu.api.serialization import to_dict
        payload = to_dict(obj)
    else:
        payload = obj
    try:
        raw = json.dumps(payload, sort_keys=True, default=repr)
    except (TypeError, ValueError):
        raw = repr(payload)
    return hashlib.sha1(raw.encode()).hexdigest()


class StoreChecker:
    """Per-store mutation detector: fingerprint on write, verify on read.
    Reports each mutated key once (a hot loop re-reading the same mutated
    pod must not flood the report)."""

    def __init__(self, name: str = ""):
        self.name = name
        self._fp: Dict[str, str] = {}
        self._flagged: Set[str] = set()
        self._lock = _real_Lock()

    def on_write(self, key: str, obj) -> None:
        with self._lock:
            self._fp[key] = fingerprint(obj)
            self._flagged.discard(key)

    def on_delete(self, key: str) -> None:
        with self._lock:
            self._fp.pop(key, None)
            self._flagged.discard(key)

    def on_replace(self, items: Dict[str, object]) -> None:
        with self._lock:
            self._fp = {k: fingerprint(v) for k, v in items.items()}
            self._flagged = set()

    def verify(self, key: str, obj) -> None:
        with self._lock:
            want = self._fp.get(key)
            if want is None or key in self._flagged:
                return
            if fingerprint(obj) != want:
                self._flagged.add(key)
                msg = (f"informer-cache mutation detected: object {key!r} "
                       f"in store {self.name or id(self)} changed while "
                       "cached — some reader mutated it in place instead "
                       "of deep_copy()ing")
                record_violation(msg)

    def verify_many(self, items) -> None:
        """items: iterable of (key, obj). Samples above VERIFY_FULL_LIMIT."""
        with self._lock:
            tracked = len(self._fp)
        if tracked <= VERIFY_FULL_LIMIT:
            for key, obj in items:
                self.verify(key, obj)
        else:
            for i, (key, obj) in enumerate(items):
                if i % SAMPLE_STRIDE == 0:
                    self.verify(key, obj)


_checked_store_enabled = False


def enable_checked_store() -> None:
    global _checked_store_enabled
    _checked_store_enabled = True


def disable_checked_store() -> None:
    global _checked_store_enabled
    _checked_store_enabled = False


def checked_store_enabled() -> bool:
    return _checked_store_enabled


def new_store_checker(name: str = "") -> Optional[StoreChecker]:
    """Factory used by client.cache.ThreadSafeStore — None when the mode is
    off, so the store's fast path stays branch-on-None cheap."""
    return StoreChecker(name) if _checked_store_enabled else None
