"""Controller hygiene: swallowed exceptions, wall-clock durations, threads.

Three small checkers that encode review rules this codebase keeps
re-learning:

- ``swallowed-exception``: a bare/``Exception``/``BaseException`` handler
  whose body does NOTHING (only ``pass``/``continue``/``break``/constants)
  silently discards errors. The sync-loop version of this bug hides a
  controller that has been failing for hours. Handlers that log, raise,
  assign a fallback, return a value, or call anything are fine — the rule
  targets pure swallows.

- ``monotonic-duration``: ``time.time()`` in duration arithmetic
  (``time.time() - start``, ``deadline > time.time()``) or as a
  ``clock=time.time`` default jumps with NTP steps — leader leases and
  eviction timers misfire on clock skew. ``time.monotonic()`` is the
  duration clock; wall clock is ONLY for timestamps serialized into API
  objects (suppress those sites with a justification).

- ``nondaemon-thread``: ``threading.Thread(...)`` without an explicit
  ``daemon=`` keyword. A forgotten non-daemon worker turns every process
  exit into a hang; writing the choice down is the point.
"""

from __future__ import annotations

import ast
from typing import Iterable

from kubernetes_tpu.analysis.core import (
    Checker,
    FileContext,
    Finding,
    dotted_chain,
)

_BROAD = {"Exception", "BaseException"}


def _handler_is_broad(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True
    chain = dotted_chain(handler.type)
    return bool(chain) and chain[-1] in _BROAD


def _stmt_is_inert(stmt: ast.stmt) -> bool:
    if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
        return True
    if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
        return True  # docstring / ellipsis
    return False


class SwallowedExceptionChecker(Checker):
    name = "swallowed-exception"
    description = ("bare/overbroad except whose body silently discards the "
                   "error (no log, no raise, no handling)")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _handler_is_broad(node):
                continue
            if all(_stmt_is_inert(s) for s in node.body):
                what = ("bare except" if node.type is None else
                        f"except {dotted_chain(node.type)[-1]}")
                yield self.finding(
                    ctx, node,
                    f"{what} swallows the error silently — log it, narrow "
                    "the exception type, or handle it")


class MonotonicDurationChecker(Checker):
    name = "monotonic-duration"
    description = ("time.time() used for durations/deadlines — "
                   "time.monotonic() is immune to wall-clock steps")

    @staticmethod
    def _is_wallclock_call(node: ast.AST) -> bool:
        return (isinstance(node, ast.Call)
                and dotted_chain(node.func) == ["time", "time"])

    @staticmethod
    def _is_wallclock_ref(node: ast.AST) -> bool:
        return dotted_chain(node) == ["time", "time"]

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(tree):
            if isinstance(node, ast.BinOp) and \
                    isinstance(node.op, (ast.Add, ast.Sub)):
                if self._is_wallclock_call(node.left) or \
                        self._is_wallclock_call(node.right):
                    yield self.finding(
                        ctx, node,
                        "time.time() in duration arithmetic — use "
                        "time.monotonic() (wall clock only for serialized "
                        "API timestamps; suppress with a justification)")
            elif isinstance(node, ast.Compare):
                operands = [node.left] + list(node.comparators)
                if any(self._is_wallclock_call(o) for o in operands):
                    yield self.finding(
                        ctx, node,
                        "time.time() compared against a deadline — "
                        "monotonic deadlines don't jump with NTP")
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                args = node.args
                pos = list(args.args)
                defaults = list(args.defaults)
                for arg, default in zip(pos[len(pos) - len(defaults):],
                                        defaults):
                    if arg.arg == "clock" and self._is_wallclock_ref(default):
                        yield self.finding(
                            ctx, default,
                            "clock=time.time default — components measuring "
                            "durations should default to time.monotonic "
                            "(keep wall clock only where values are "
                            "serialized into API objects)")
                for arg, default in zip(args.kwonlyargs, args.kw_defaults):
                    if default is not None and arg.arg == "clock" and \
                            self._is_wallclock_ref(default):
                        yield self.finding(
                            ctx, default,
                            "clock=time.time default — use time.monotonic "
                            "for duration clocks")


class NonDaemonThreadChecker(Checker):
    name = "nondaemon-thread"
    description = ("threading.Thread(...) without an explicit daemon= — "
                   "undeclared thread lifetime blocks process exit")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            chain = dotted_chain(node.func)
            if not chain or chain[-1] != "Thread":
                continue
            if len(chain) > 1 and chain[-2] != "threading":
                continue
            if not any(kw.arg == "daemon" for kw in node.keywords):
                yield self.finding(
                    ctx, node,
                    "Thread created without daemon= — declare its lifetime "
                    "(daemon=True, or daemon=False plus join ownership)")
