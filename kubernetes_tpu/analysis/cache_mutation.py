"""informer-cache-mutation: in-place mutation of shared informer-cache objects.

Informer stores hand out *references* — every controller, the scheduler,
and kubectl printers see the same object the watch delivered. A controller
that does ``pod.status = ...`` on a store-read object corrupts every other
reader's view (and the next relist diff). The reference enforces this by
convention plus the race detector; here the convention is checkable:

    node = self.node_informer.store.get(name)      # tainted
    node.status = ...                              # FINDING
    fresh = deep_copy(node)                        # fresh is clean
    fresh.status = ...                             # fine

Function-local taint tracking, statement order as control-flow proxy:

- taint sources: ``.get/.list/.list_all/.by_index/.get_pod_*`` calls whose
  receiver text names a store/lister/informer
- propagation: aliasing (``x = tainted``), sub-object access
  (``st = pod.status``), iteration (``for p in tainted_list``),
  comprehensions and ``list()/sorted()`` over tainted collections
- sanitizers: ``deep_copy``/``deepcopy`` (any dotted spelling)
- violations: attribute/subscript assignment or augmented assignment
  through a tainted name, and mutating-method calls
  (``.append/.update/...``) on a tainted name's sub-objects

Mutations routed through helper calls (``self._mutate(pod)``) are invisible
to this pass — the checked-store mode in analysis.runtime catches those at
test time.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional, Set

from kubernetes_tpu.analysis.core import (
    Checker,
    FileContext,
    Finding,
    dotted_chain,
)

_READ_METHODS = {"get", "list", "list_all", "by_index", "get_pod_services",
                 "get_pod_controllers", "get_pod_replica_sets"}
_SOURCE_WORDS = ("store", "lister", "informer")
_SANITIZERS = {"deep_copy", "deepcopy"}
_MUTATORS = {"append", "extend", "insert", "remove", "pop", "clear",
             "update", "setdefault", "sort", "reverse", "add", "discard",
             "popitem"}
# list()/sorted() copy the container, not the elements — taint flows through
_CONTAINER_COPIES = {"list", "sorted", "tuple", "reversed"}


def _is_store_read(call: ast.Call) -> bool:
    chain = dotted_chain(call.func)
    if not chain or len(chain) < 2 or chain[-1] not in _READ_METHODS:
        return False
    receiver = ".".join(chain[:-1]).lower()
    return any(w in receiver for w in _SOURCE_WORDS)


def _is_sanitizer(call: ast.Call) -> bool:
    chain = dotted_chain(call.func)
    return bool(chain) and chain[-1] in _SANITIZERS


def _root_name(node: ast.AST) -> Optional[str]:
    """Leftmost Name of an attribute/subscript chain, if any."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


class _FunctionPass:
    """One linear pass over a function body (statement order ≈ execution
    order — good enough for a heuristic that prefers false negatives)."""

    def __init__(self, checker: "CacheMutationChecker", ctx: FileContext):
        self.checker = checker
        self.ctx = ctx
        self.tainted: Set[str] = set()        # names bound to cache objects
        self.collections: Set[str] = set()    # names bound to lists of them
        self.findings = []

    # --- taint classification of an expression --------------------------------

    def _value_taint(self, value: ast.AST) -> str:
        """'' | 'object' | 'collection' for the value being bound."""
        if isinstance(value, ast.Call):
            if _is_sanitizer(value):
                return ""
            if _is_store_read(value):
                chain = dotted_chain(value.func)
                return "object" if chain[-1] == "get" else "collection"
            chain = dotted_chain(value.func)
            if chain and len(chain) == 1 and chain[0] in _CONTAINER_COPIES \
                    and value.args:
                inner = self._value_taint(value.args[0])
                return "collection" if inner else ""
            return ""
        if isinstance(value, ast.Name):
            if value.id in self.tainted:
                return "object"
            if value.id in self.collections:
                return "collection"
            return ""
        if isinstance(value, ast.Attribute):
            root = _root_name(value)
            # sub-objects of a tainted object are tainted (pod.status);
            # their list-valued fields are shared collections
            return "object" if root in self.tainted else ""
        if isinstance(value, (ast.ListComp, ast.GeneratorExp)):
            for gen in value.generators:
                if self._value_taint(gen.iter) == "collection" and \
                        isinstance(value.elt, ast.Name):
                    return "collection"
            return ""
        if isinstance(value, ast.BoolOp):
            # `x = maybe_tainted or default`
            return ("object" if any(self._value_taint(v) == "object"
                                    for v in value.values) else "")
        if isinstance(value, ast.IfExp):
            if any(self._value_taint(v) for v in (value.body, value.orelse)):
                return self._value_taint(value.body) or \
                    self._value_taint(value.orelse)
            return ""
        return ""

    def _bind(self, target: ast.AST, taint: str):
        if not isinstance(target, ast.Name):
            return
        self.tainted.discard(target.id)
        self.collections.discard(target.id)
        if taint == "object":
            self.tainted.add(target.id)
        elif taint == "collection":
            self.collections.add(target.id)

    # --- statement walk -------------------------------------------------------

    def run(self, fn: ast.FunctionDef):
        self._visit_body(fn.body)
        return self.findings

    def _visit_body(self, body):
        for stmt in body:
            self._visit_stmt(stmt)

    def _visit_stmt(self, stmt: ast.stmt):
        if isinstance(stmt, ast.Assign):
            taint = self._value_taint(stmt.value)
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name):
                    self._bind(tgt, taint)
                else:
                    self._flag_mutation(tgt, stmt)
            self._scan_calls(stmt.value)
        elif isinstance(stmt, ast.AugAssign):
            if isinstance(stmt.target, ast.Name):
                pass  # rebinding a local, not mutating a cache object
            else:
                self._flag_mutation(stmt.target, stmt)
        elif isinstance(stmt, ast.For):
            taint = self._value_taint(stmt.iter)
            self._bind(stmt.target,
                       "object" if taint == "collection" else "")
            self._visit_body(stmt.body)
            self._visit_body(stmt.orelse)
        elif isinstance(stmt, (ast.If, ast.While)):
            self._visit_body(stmt.body)
            self._visit_body(stmt.orelse)
        elif isinstance(stmt, ast.With):
            self._visit_body(stmt.body)
        elif isinstance(stmt, ast.Try):
            self._visit_body(stmt.body)
            for h in stmt.handlers:
                self._visit_body(h.body)
            self._visit_body(stmt.orelse)
            self._visit_body(stmt.finalbody)
        elif isinstance(stmt, ast.Expr):
            self._scan_calls(stmt.value)
        # nested defs get their own pass from the checker's top-level walk

    def _flag_mutation(self, target: ast.AST, stmt: ast.stmt):
        root = _root_name(target)
        if root in self.tainted:
            self.findings.append(self.checker.finding(
                self.ctx, stmt,
                f"'{root}' was read from an informer store/lister and is "
                "mutated in place — deep_copy() it first (shared cache "
                "object; every other reader sees this write)"))

    def _scan_calls(self, expr: ast.AST):
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            chain = dotted_chain(node.func)
            if not chain or len(chain) < 2 or chain[-1] not in _MUTATORS:
                continue
            root = chain[0]
            # require a sub-object hop (pod.metadata.labels.update) or a
            # direct mutator on a tainted object; a mutator on a tainted
            # COLLECTION (pods.append) touches our copy of the list, not
            # the cached objects
            if root in self.tainted:
                self.findings.append(self.checker.finding(
                    self.ctx, node,
                    f"'{'.'.join(chain[:-1])}' belongs to a cache object "
                    f"read from an informer store/lister; .{chain[-1]}() "
                    "mutates shared state — deep_copy() the object first"))


class CacheMutationChecker(Checker):
    name = "informer-cache-mutation"
    description = ("in-place mutation of an object read from an informer "
                   "store/lister without deep_copy")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from _FunctionPass(self, ctx).run(node)
