"""Checker framework: findings, suppressions, baseline, and the runner.

Stdlib-only by design (``ast`` + ``re``): the analyzer must run in any
environment the code itself runs in, including CI images with nothing but
the interpreter.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set


@dataclass(frozen=True)
class Finding:
    check: str        # checker id, e.g. "lock-held-across-io"
    path: str         # path as given to the runner (repo-relative in CI)
    line: int         # 1-based
    col: int          # 0-based, ast convention
    message: str
    snippet: str = ""  # stripped source line — the baseline fingerprint input

    def fingerprint(self) -> str:
        """Stable identity that survives unrelated edits: the line NUMBER is
        deliberately excluded so code moving around doesn't churn the
        baseline; the normalized source line is included so the baseline
        entry dies with the code it grandfathered. The path contributes its
        last two components — stable across absolute/relative invocation
        styles, while same-named files in different packages (every
        __init__.py) don't collide."""
        tail = "/".join(self.path.replace("\\", "/").split("/")[-2:])
        raw = f"{self.check}|{tail}|{' '.join(self.snippet.split())}"
        return hashlib.sha1(raw.encode()).hexdigest()[:16]

    def to_dict(self) -> dict:
        return {"check": self.check, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "fingerprint": self.fingerprint()}


class Checker:
    """Base checker: subclasses set ``name``/``description`` and implement
    ``check(tree, ctx)`` yielding Findings. Register by listing the class in
    ``all_checkers()`` — the CLI, the self-hosting gate, and ``--list-checks``
    all read from there."""

    name = ""
    description = ""

    def check(self, tree: ast.Module, ctx: "FileContext") -> Iterable[Finding]:
        raise NotImplementedError

    def finding(self, ctx: "FileContext", node: ast.AST,
                message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        return Finding(check=self.name, path=ctx.path, line=line,
                       col=getattr(node, "col_offset", 0), message=message,
                       snippet=ctx.line(line))


@dataclass
class FileContext:
    path: str
    source: str
    lines: List[str] = field(default_factory=list)

    def __post_init__(self):
        if not self.lines:
            self.lines = self.source.splitlines()

    def line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""


# --- suppressions -------------------------------------------------------------

_DIRECTIVE = re.compile(
    r"#\s*kube-verify:\s*(disable|disable-next-line|disable-file)"
    r"\s*=\s*([\w,\- ]+)")


class Suppressions:
    """Per-file suppression directives parsed from comments."""

    def __init__(self, source: str):
        self.by_line: Dict[int, Set[str]] = {}
        self.file_wide: Set[str] = set()
        for i, text in enumerate(source.splitlines(), start=1):
            m = _DIRECTIVE.search(text)
            if not m:
                continue
            kind, checks = m.group(1), {
                c.strip() for c in m.group(2).split(",") if c.strip()}
            if kind == "disable":
                self.by_line.setdefault(i, set()).update(checks)
            elif kind == "disable-next-line":
                self.by_line.setdefault(i + 1, set()).update(checks)
            else:
                self.file_wide.update(checks)

    def suppressed(self, finding: Finding) -> bool:
        if finding.check in self.file_wide or "all" in self.file_wide:
            return True
        checks = self.by_line.get(finding.line, ())
        return finding.check in checks or "all" in checks


# --- baseline -----------------------------------------------------------------

class Baseline:
    """Checked-in ledger of grandfathered findings. A finding whose
    fingerprint appears here is reported as baselined (not a failure);
    fixing the code removes the line, and ``--write-baseline`` regenerates
    the file. New code should never grow the baseline — fix or suppress
    with an in-line justification instead."""

    def __init__(self, entries: Optional[List[dict]] = None):
        self.entries = entries or []
        self._fps = {e["fingerprint"] for e in self.entries}

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls()
        with open(path) as f:
            data = json.load(f)
        return cls(data.get("findings", []))

    def contains(self, finding: Finding) -> bool:
        return finding.fingerprint() in self._fps

    @staticmethod
    def write(path: str, findings: Sequence[Finding]) -> None:
        data = {
            "version": 1,
            "comment": "grandfathered kube-verify findings; regenerate with "
                       "`python -m kubernetes_tpu.analysis --write-baseline`",
            "findings": [{
                "check": f.check, "path": f.path,
                "fingerprint": f.fingerprint(),
                "snippet": f.snippet,
            } for f in sorted(findings, key=lambda f: (f.path, f.line))],
        }
        with open(path, "w") as fh:
            json.dump(data, fh, indent=2, sort_keys=True)
            fh.write("\n")


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


# --- runner -------------------------------------------------------------------

def all_checkers() -> List[Checker]:
    # imported here, not at module top: each checker module imports core
    from kubernetes_tpu.analysis.cache_mutation import CacheMutationChecker
    from kubernetes_tpu.analysis.hostsync import HostSyncChecker
    from kubernetes_tpu.analysis.hygiene import (
        MonotonicDurationChecker,
        NonDaemonThreadChecker,
        SwallowedExceptionChecker,
    )
    from kubernetes_tpu.analysis.locks import LockHeldAcrossIOChecker
    from kubernetes_tpu.analysis.replication_io import (
        ReplicationLockIOChecker,
    )
    from kubernetes_tpu.analysis.spans import LeakedSpanChecker
    return [
        LockHeldAcrossIOChecker(),
        ReplicationLockIOChecker(),
        CacheMutationChecker(),
        HostSyncChecker(),
        SwallowedExceptionChecker(),
        MonotonicDurationChecker(),
        NonDaemonThreadChecker(),
        LeakedSpanChecker(),
    ]


def analyze_source(source: str, path: str = "<string>",
                   checkers: Optional[Sequence[Checker]] = None,
                   ) -> List[Finding]:
    """Run checkers over one source blob; suppressions applied, baseline not
    (the baseline is a repo-level concern, see analyze_paths)."""
    checkers = list(checkers) if checkers is not None else all_checkers()
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Finding(check="parse-error", path=path, line=e.lineno or 1,
                        col=e.offset or 0, message=f"syntax error: {e.msg}",
                        snippet="")]
    ctx = FileContext(path=path, source=source)
    sup = Suppressions(source)
    out: List[Finding] = []
    for checker in checkers:
        for f in checker.check(tree, ctx):
            if not sup.suppressed(f):
                out.append(f)
    out.sort(key=lambda f: (f.line, f.col, f.check))
    return out


def iter_python_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            yield p
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d != "__pycache__"
                                 and not d.startswith("."))
                for name in sorted(files):
                    if name.endswith(".py"):
                        yield os.path.join(root, name)


def analyze_paths(paths: Sequence[str],
                  checkers: Optional[Sequence[Checker]] = None,
                  baseline: Optional[Baseline] = None,
                  ) -> Dict[str, List[Finding]]:
    """Analyze files/trees. Returns {"new": [...], "baselined": [...]}."""
    baseline = baseline or Baseline()
    new: List[Finding] = []
    old: List[Finding] = []
    for fp in iter_python_files(paths):
        try:
            with open(fp, encoding="utf-8") as f:
                source = f.read()
        except OSError as e:
            new.append(Finding(check="read-error", path=fp, line=1, col=0,
                               message=str(e)))
            continue
        for finding in analyze_source(source, path=fp, checkers=checkers):
            (old if baseline.contains(finding) else new).append(finding)
    return {"new": new, "baselined": old}


# --- shared AST helpers used by several checkers ------------------------------

def dotted_chain(node: ast.AST) -> Optional[List[str]]:
    """['self', 'client', 'get'] for self.client.get — None if the
    expression isn't a plain dotted name."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return None


def chain_text(node: ast.AST) -> str:
    chain = dotted_chain(node)
    return ".".join(chain) if chain else ""


_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                ast.ClassDef)


def walk_same_scope(node: ast.AST) -> Iterable[ast.AST]:
    """Like ast.walk but does not descend into nested function/class scopes
    (a lock held here says nothing about code that merely gets DEFINED
    here)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        yield child
        if not isinstance(child, _SCOPE_NODES):
            stack.extend(ast.iter_child_nodes(child))
