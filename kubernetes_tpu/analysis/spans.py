"""leaked-span: a Span must provably reach finish() (or change owners).

A `utils/trace.Span` that is constructed but never finished is invisible —
it never lands in the recent-spans ring, never exports its SLI histogram,
and silently holds its subtree open. The classic shape is

    sp = Span("work")
    do_things()      # raises -> finish() below never runs
    sp.finish()

which is exactly the swallowed-exception class of bug transplanted to
tracing; this checker mirrors that checker's plumbing (pure-AST, per-scope
scan, suppressible with ``# kube-verify: disable``).

Flagged:

- a bare ``Span(...)`` expression statement — created, unreferenceable,
  unfinishable;
- ``x = Span(...)`` where, within the same function scope, ``x`` is
  neither ``.finish()``ed inside some ``finally:`` block nor handed off.

"Handed off" (ownership moves, the creator is not responsible for
finishing) means: returned or yielded, stored into an attribute /
subscript / container, or woven into another binding's value. A plain
straight-line ``x.finish()`` does NOT count as safe — any statement
between creation and that call can raise and skip it; putting the finish
in a ``finally`` is the fix the checker is steering toward.
"""

from __future__ import annotations

import ast
from typing import Iterable, Iterator, List, Set

from kubernetes_tpu.analysis.core import (
    Checker,
    FileContext,
    Finding,
    dotted_chain,
    walk_same_scope,
)


def _is_span_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    chain = dotted_chain(node.func)
    return bool(chain) and chain[-1] == "Span"


def _walk_shallow(stmts: List[ast.stmt]) -> Iterator[ast.AST]:
    """Walk statement bodies without descending into nested scopes (the
    same containment rule as walk_same_scope, over an explicit body)."""
    stack: List[ast.AST] = list(stmts)
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
            stack.extend(ast.iter_child_nodes(node))


def _handoff_names(node: ast.AST) -> Set[str]:
    """Names whose OBJECT is woven into this expression — i.e. the bare
    name appears, not merely an attribute read off it. `sp` in `[sp, None]`
    or `other = sp` hands the span over; `tid = sp.trace_id` only reads a
    field and must NOT suppress the leak check."""
    out: Set[str] = set()
    stack: List[ast.AST] = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, ast.Attribute) and isinstance(n.value, ast.Name):
            continue  # plain attribute read: the object itself stays put
        if isinstance(n, ast.Name):
            out.add(n.id)
            continue
        stack.extend(ast.iter_child_nodes(n))
    return out


class LeakedSpanChecker(Checker):
    name = "leaked-span"
    description = ("Span created without a finally-guarded finish() or an "
                   "ownership hand-off — an exception on the way leaks the "
                   "span (no ring entry, no SLI export)")

    def check(self, tree: ast.Module,
              ctx: FileContext) -> Iterable[Finding]:
        scopes: List[ast.AST] = [tree]
        scopes += [n for n in ast.walk(tree)
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for scope in scopes:
            yield from self._check_scope(scope, ctx)

    def _check_scope(self, scope: ast.AST,
                     ctx: FileContext) -> Iterable[Finding]:
        created = {}  # local name -> the creating Assign's value node
        for node in walk_same_scope(scope):
            if isinstance(node, ast.Expr) and _is_span_ctor(node.value):
                yield self.finding(
                    ctx, node,
                    "Span created and immediately discarded — it can never "
                    "be finished; bind it and finish in a finally")
            elif isinstance(node, ast.Assign) and _is_span_ctor(node.value):
                if len(node.targets) == 1 and \
                        isinstance(node.targets[0], ast.Name):
                    created[node.targets[0].id] = node
        if not created:
            return

        finished_in_finally: Set[str] = set()
        escaped: Set[str] = set()
        for node in walk_same_scope(scope):
            if isinstance(node, ast.Try):
                for sub in _walk_shallow(node.finalbody):
                    if isinstance(sub, ast.Call) and \
                            isinstance(sub.func, ast.Attribute) and \
                            sub.func.attr == "finish" and \
                            isinstance(sub.func.value, ast.Name):
                        finished_in_finally.add(sub.func.value.id)
            elif isinstance(node, (ast.Return, ast.Yield, ast.YieldFrom)):
                if node.value is not None:
                    escaped |= _handoff_names(node.value)
            elif isinstance(node, ast.Assign):
                # storing the span anywhere but a plain rebind of itself
                # moves ownership: self.sp = sp / live[key] = [sp, None] /
                # other = sp
                if any(not isinstance(t, ast.Name) for t in node.targets) \
                        or node is not created.get(
                            getattr(node.targets[0], "id", None)):
                    if not _is_span_ctor(node.value):
                        escaped |= _handoff_names(node.value)

        for name, node in created.items():
            if name in finished_in_finally or name in escaped:
                continue
            yield self.finding(
                ctx, node,
                f"Span {name!r} has no finally-guarded finish() and never "
                "changes owner — an exception between creation and its "
                "finish() leaks it; wrap in try/finally")
