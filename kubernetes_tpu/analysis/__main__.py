"""CLI: python -m kubernetes_tpu.analysis [paths...]

Exit codes (stable — tools/verify.sh and CI key off them):
  0  clean (no non-baselined findings)
  1  findings
  2  usage / IO error
"""

from __future__ import annotations

import argparse
import sys

from kubernetes_tpu.analysis.core import (
    Baseline,
    all_checkers,
    analyze_paths,
    default_baseline_path,
)
from kubernetes_tpu.analysis.report import render_json, render_text


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m kubernetes_tpu.analysis",
        description="kube-verify: repo-native static analysis")
    parser.add_argument("paths", nargs="*", default=["kubernetes_tpu"],
                        help="files or directories (default: kubernetes_tpu)")
    parser.add_argument("--json", action="store_true",
                        help="JSON report instead of text")
    parser.add_argument("--baseline", default=None,
                        help="baseline file (default: the checked-in "
                             "analysis/baseline.json)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report grandfathered findings as failures too")
    parser.add_argument("--write-baseline", action="store_true",
                        help="grandfather all current findings into the "
                             "baseline file and exit 0")
    parser.add_argument("--select", default=None,
                        help="comma-separated checker names to run")
    parser.add_argument("--disable", default=None,
                        help="comma-separated checker names to skip")
    parser.add_argument("--list-checks", action="store_true")
    parser.add_argument("--show-baselined", action="store_true",
                        help="include baselined findings in the text report")
    args = parser.parse_args(argv)

    checkers = all_checkers()
    if args.list_checks:
        for c in checkers:
            print(f"{c.name}: {c.description}")
        return 0
    if args.select:
        wanted = {s.strip() for s in args.select.split(",")}
        unknown = wanted - {c.name for c in checkers}
        if unknown:
            print(f"unknown checker(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        checkers = [c for c in checkers if c.name in wanted]
    if args.disable:
        skip = {s.strip() for s in args.disable.split(",")}
        checkers = [c for c in checkers if c.name not in skip]

    import os
    missing = [p for p in args.paths if not os.path.exists(p)]
    if missing:
        for p in missing:
            print(f"no such file or directory: {p}", file=sys.stderr)
        return 2

    baseline_path = args.baseline or default_baseline_path()
    baseline = Baseline() if args.no_baseline else Baseline.load(baseline_path)

    results = analyze_paths(args.paths, checkers=checkers, baseline=baseline)

    io_errors = [f for f in results["new"] if f.check == "read-error"]
    if io_errors:
        for f in io_errors:
            print(f"{f.path}: {f.message}", file=sys.stderr)
        return 2  # IO error, per the documented exit-code contract

    if args.write_baseline:
        Baseline.write(baseline_path,
                       results["new"] + results["baselined"])
        print(f"wrote {len(results['new']) + len(results['baselined'])} "
              f"finding(s) to {baseline_path}")
        return 0

    if args.json:
        render_json(results, sys.stdout)
    else:
        render_text(results, sys.stdout,
                    verbose_baselined=args.show_baselined)
    return 1 if results["new"] else 0


if __name__ == "__main__":
    sys.exit(main())
