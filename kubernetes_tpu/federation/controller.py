"""Federation controllers.

Parity target: reference federation/pkg/federation-controller —
cluster controller (health probes -> Cluster Ready condition,
cluster-controller/clustercontroller.go) and the per-resource federation
sync pattern: an object created at the federation control plane is
created in every ready member cluster, updated on drift, deleted
everywhere when it goes away, and its status is aggregated back
(replicaset federation sums member readyReplicas).

The sync set covers the namespaced workload + config kinds a v1.3-era
federation carried; additional kinds are one entry in SYNCED_RESOURCES.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, Optional

from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.serialization import camel, deep_copy, scheme
from kubernetes_tpu.apis import federation as fedapi
from kubernetes_tpu.client import Informer, ListWatch, RESTClient
from kubernetes_tpu.client.rest import ApiError
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.utils.nethost import parse_host_port
from kubernetes_tpu.utils.timeutil import now_iso

log = logging.getLogger("federation")

# resource -> aggregate status fields summed across members (None = none)
SYNCED_RESOURCES = {
    "replicationcontrollers": ("replicas",),
    "replicasets": ("replicas", "ready_replicas"),
    "secrets": None,
    "configmaps": None,
    "services": None,
}

ANN_FEDERATED_BY = "federation.kubernetes.io/managed-by"


def _member_client(cluster: fedapi.Cluster) -> RESTClient:
    host, port = parse_host_port(
        cluster.spec.server_address if cluster.spec else "")
    return RESTClient(host=host, port=port, user_agent="federation-sync")


def _is_ready(cluster: fedapi.Cluster) -> bool:
    for c in (cluster.status.conditions or []) if cluster.status else []:
        if c.type == fedapi.CLUSTER_READY:
            return c.status == api.CONDITION_TRUE
    return False


class ClusterHealthController(Controller):
    """Probes member /healthz and maintains the Ready condition
    (cluster-controller UpdateClusterStatus)."""

    name = "federation-cluster"

    def __init__(self, fed_client: RESTClient, probe_period: float = 5.0,
                 workers: int = 1):
        super().__init__(workers)
        self.fed = fed_client
        self.probe_period = probe_period
        self.cluster_informer = Informer(ListWatch(fed_client, "clusters"))
        self.cluster_informer.add_event_handler(
            on_add=lambda c: self.enqueue(c.metadata.name),
            on_update=self._cluster_changed,
            on_delete=lambda c: self.disarm_resync(c.metadata.name))

    def _cluster_changed(self, old, new):
        """Enqueue only on SPEC change. Our own status patches come back as
        update events; re-probing on them made the loop self-sustaining —
        every probe's write triggered the next probe immediately, bypassing
        probe_period entirely (round-5 ADVICE: 115 probes in 5 s). The
        periodic re-probe is arm_resync's job."""
        old_spec = scheme.encode(old).get("spec")
        new_spec = scheme.encode(new).get("spec")
        if old_spec != new_spec:
            self.enqueue(new.metadata.name)

    def sync(self, key: str) -> None:
        cluster = self.cluster_informer.store.get(key)
        if cluster is None:
            return
        ready = False
        reason = "ProbeFailed"
        try:
            import http.client as hc
            host, port = parse_host_port(
                cluster.spec.server_address if cluster.spec else "")
            conn = hc.HTTPConnection(host, port, timeout=3)
            try:
                conn.request("GET", "/healthz")
                resp = conn.getresponse()
                resp.read()
                ready = resp.status == 200
                reason = "ClusterReady" if ready \
                    else f"ProbeFailed: HTTP {resp.status}"
            finally:
                conn.close()
        except Exception as e:
            reason = f"ProbeFailed: {type(e).__name__}"
        cond = fedapi.ClusterCondition(
            type=fedapi.CLUSTER_READY,
            status=api.CONDITION_TRUE if ready else api.CONDITION_FALSE,
            reason=reason, last_probe_time=now_iso())
        # every probe refreshes the condition (the reference updates
        # lastProbeTime each cycle — a stale timestamp is indistinguishable
        # from a dead controller)
        enc = scheme.encode(fedapi.Cluster(
            status=fedapi.ClusterStatus(conditions=[cond])))
        try:
            self.fed.patch("clusters", key, {"status": enc.get("status")},
                           patch_type=self.fed.MERGE_PATCH)
        except ApiError as e:
            if not e.is_not_found:
                raise
        # periodic re-probe regardless of events
        self.arm_resync(key, self.probe_period)

    def start(self):
        self.cluster_informer.run()
        self.cluster_informer.wait_for_sync()
        for c in self.cluster_informer.store.list():
            self.enqueue(c.metadata.name)
        return self.run()

    def stop(self):
        super().stop()
        self.cluster_informer.stop()


class FederationSyncController(Controller):
    """Propagates federated objects to every ready member cluster and
    aggregates status back (the per-kind federation controllers of the
    reference, collapsed onto one sync loop keyed resource/ns/name)."""

    name = "federation-sync"

    def __init__(self, fed_client: RESTClient, workers: int = 2,
                 resources: Optional[dict] = None,
                 resync_period: float = 2.0):
        super().__init__(workers)
        self.fed = fed_client
        self.resources = dict(resources or SYNCED_RESOURCES)
        # member-cluster changes (status, drift) have no watch into this
        # plane (the reference runs an informer per member cluster); the
        # periodic per-object re-sync is the compact reconcile analog
        self.resync_period = resync_period
        self.cluster_informer = Informer(ListWatch(fed_client, "clusters"))
        self.cluster_informer.add_event_handler(
            on_add=lambda c: self._resync_all(),
            on_update=self._cluster_updated,
            on_delete=lambda c: None)
        self.informers: Dict[str, Informer] = {}
        for resource in self.resources:
            inf = Informer(ListWatch(fed_client, resource))
            self.informers[resource] = inf
            inf.add_event_handler(
                on_add=lambda o, r=resource: self.enqueue(self._key(r, o)),
                on_update=lambda o, n, r=resource: self.enqueue(
                    self._key(r, n)),
                on_delete=lambda o, r=resource: self.enqueue(
                    self._key(r, o)))
        self._clients_lock = threading.Lock()
        # keyed by (cluster name, address): a re-registered cluster on a
        # new port must not keep dialing the dead one
        self._clients: Dict[tuple, RESTClient] = {}
        self._delete_retries: Dict[str, int] = {}

    @staticmethod
    def _key(resource: str, obj) -> str:
        return f"{resource}|{obj.metadata.namespace or ''}|{obj.metadata.name}"

    def _cluster_updated(self, old, new):
        if _is_ready(old) != _is_ready(new):
            self._resync_all()

    def _resync_all(self):
        for resource, inf in self.informers.items():
            for obj in inf.store.list():
                self.enqueue(self._key(resource, obj))

    def _ready_members(self):
        out = []
        for cluster in self.cluster_informer.store.list():
            if not _is_ready(cluster):
                continue
            name = cluster.metadata.name
            addr = cluster.spec.server_address if cluster.spec else ""
            ckey = (name, addr)
            with self._clients_lock:
                client = self._clients.get(ckey)
                if client is None:
                    try:
                        client = _member_client(cluster)
                    except Exception as e:
                        log.warning("cluster %s: bad address %r: %s",
                                    name, addr, e)
                        continue
                    # drop stale clients for this cluster's old addresses
                    for old in [k for k in self._clients if k[0] == name]:
                        del self._clients[old]
                    self._clients[ckey] = client
            out.append((name, client))
        return out

    def _any_unready(self) -> bool:
        return any(not _is_ready(c)
                   for c in self.cluster_informer.store.list())

    def sync(self, key: str) -> None:
        resource, ns, name = key.split("|", 2)
        store_key = f"{ns}/{name}" if ns else name
        fed_obj = self.informers[resource].store.get(store_key)
        members = self._ready_members()
        if fed_obj is None:
            # deleted at the federation: delete everywhere (cascading,
            # like the reference's federated deletion helper)
            for cname, client in members:
                try:
                    existing = client.get(resource, name, ns)
                except ApiError as e:
                    if e.is_not_found:
                        continue
                    raise
                if (existing.metadata.annotations or {}).get(
                        ANN_FEDERATED_BY):
                    client.delete(resource, name, ns)
                    log.info("federation: deleted %s %s from %s",
                             resource, store_key, cname)
            if self._any_unready():
                # an unready member may still hold a copy: retry for a
                # bounded window (a permanently-dead registered cluster
                # must not pin every deleted key's timer forever)
                tries = self._delete_retries.get(key, 0) + 1
                if tries <= 30:
                    self._delete_retries[key] = tries
                    self.arm_resync(key, self.resync_period)
                else:
                    log.warning("federation: giving up delete sweep of %s "
                                "(unready member remains)", key)
                    self._delete_retries.pop(key, None)
            else:
                self._delete_retries.pop(key, None)
            return
        desired = self._desired(fed_obj)
        agg = self.resources.get(resource)
        totals = [0] * len(agg or ())
        seen_members = 0
        for cname, client in members:
            try:
                existing = client.get(resource, name, ns)
            except ApiError as e:
                if not e.is_not_found:
                    raise
                created = deep_copy(desired)
                client.create(resource, created, ns)
                log.info("federation: created %s %s in %s",
                         resource, store_key, cname)
                continue
            if not (existing.metadata.annotations or {}).get(
                    ANN_FEDERATED_BY):
                # a member-local object owns this name: never adopt or
                # clobber it (the delete path honors the same guard)
                log.warning("federation: %s %s in %s is member-local; "
                            "skipping", resource, store_key, cname)
                continue
            if not self._specs_match(resource, desired, existing):
                merged = deep_copy(desired)
                merged.metadata.resource_version = \
                    existing.metadata.resource_version
                if hasattr(merged, "status"):
                    # reconcile the SPEC; the member's status is its own
                    merged.status = existing.status
                client.update(resource, merged, ns)
                log.info("federation: updated %s %s in %s",
                         resource, store_key, cname)
            if agg and existing.status is not None:
                seen_members += 1
                for i, field in enumerate(agg):
                    totals[i] += int(getattr(existing.status, field, 0) or 0)
        if agg and seen_members:
            self._aggregate_status(resource, fed_obj, agg, totals)
        self.arm_resync(key, self.resync_period)

    def _desired(self, fed_obj):
        d = deep_copy(fed_obj)
        d.metadata = api.ObjectMeta(
            name=d.metadata.name, namespace=d.metadata.namespace,
            labels=dict(d.metadata.labels or {}) or None,
            annotations=dict(d.metadata.annotations or {}))
        d.metadata.annotations[ANN_FEDERATED_BY] = "kubernetes-tpu"
        d.status = None
        if hasattr(d, "spec") and d.spec is not None \
                and hasattr(d.spec, "cluster_ip"):
            # member clusters allocate their own service IPs
            d.spec.cluster_ip = ""
        return d

    def _specs_match(self, resource, desired, existing) -> bool:
        # compare the full propagated payload, not just .spec — Secrets and
        # ConfigMaps carry their state in `data`, and a rotated federated
        # secret MUST reach members
        def payload(obj):
            enc = scheme.encode(obj)
            return {k: v for k, v in enc.items()
                    if k not in ("metadata", "status", "kind", "apiVersion")}
        enc_d, enc_e = payload(desired), payload(existing)
        if resource == "services":
            for enc in (enc_d, enc_e):
                if isinstance(enc.get("spec"), dict):
                    enc["spec"] = dict(enc["spec"])
                    enc["spec"].pop("clusterIP", None)
        return enc_d == enc_e

    def _aggregate_status(self, resource, fed_obj, agg, totals) -> None:
        cur = [int(getattr(fed_obj.status, f, 0) or 0)
               if fed_obj.status is not None else 0 for f in agg]
        if cur == totals:
            return
        patch_fields = {camel(f): total for f, total in zip(agg, totals)}
        try:
            self.fed.patch(resource, fed_obj.metadata.name,
                           {"status": patch_fields},
                           fed_obj.metadata.namespace or "default",
                           subresource="status",
                           patch_type=self.fed.MERGE_PATCH)
        except ApiError as e:
            if not e.is_not_found:
                raise

    def start(self):
        self.cluster_informer.run()
        for inf in self.informers.values():
            inf.run()
        self.cluster_informer.wait_for_sync()
        for inf in self.informers.values():
            inf.wait_for_sync()
        self._resync_all()
        return self.run()

    def stop(self):
        super().stop()
        self.cluster_informer.stop()
        for inf in self.informers.values():
            inf.stop()
