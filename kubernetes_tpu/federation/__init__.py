"""Federation (ubernetes): one control plane fronting member clusters.

Parity target: reference federation/ — the federated apiserver + cluster
registry + federation controller (federation/cmd/*,
federation/pkg/federation-controller). The federation control plane here
IS a normal APIServer (it serves the same resource map plus the
federation group's Cluster registry); the FederationSyncController does
the ubernetes work: health-checks member clusters, propagates federated
objects to every ready member, reconciles drift and deletions, and
aggregates member status back up.
"""

from kubernetes_tpu.federation.controller import (
    ClusterHealthController, FederationSyncController,
)

__all__ = ["ClusterHealthController", "FederationSyncController"]
