"""Federation entrypoint: python -m kubernetes_tpu.federation

The federated control plane (reference federation/cmd): a full APIServer
(same resource map + the federation group's Cluster registry) plus the
cluster-health and federation-sync controllers.
"""

from __future__ import annotations

import argparse
import signal
import threading

from kubernetes_tpu.apiserver import APIServer
from kubernetes_tpu.client import RESTClient
from kubernetes_tpu.federation import (
    ClusterHealthController, FederationSyncController,
)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="federation-apiserver")
    p.add_argument("--bind-address", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    a = p.parse_args(argv)

    server = APIServer(host=a.bind_address, port=a.port).start()
    print(f"federation apiserver listening on "
          f"http://{a.bind_address}:{server.port}", flush=True)
    client = RESTClient.for_server(server, user_agent="federation")
    health = ClusterHealthController(client)
    health.start()
    sync = FederationSyncController(client)
    sync.start()

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a_: stop.set())
    signal.signal(signal.SIGINT, lambda *a_: stop.set())
    stop.wait()
    sync.stop()
    health.stop()
    server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
