"""The API server.

Route shapes (reference pkg/apiserver/api_installer.go:169
registerResourceHandlers):

  GET    /api/v1/{resource}                       cluster list / all-ns list
  GET    /api/v1/{resource}?watch=true            watch stream (NDJSON frames)
  GET    /api/v1/namespaces/{ns}/{resource}
  POST   /api/v1/namespaces/{ns}/{resource}
  GET    /api/v1/namespaces/{ns}/{resource}/{name}
  PUT    /api/v1/namespaces/{ns}/{resource}/{name}
  PATCH  /api/v1/namespaces/{ns}/{resource}/{name}   (strategic / merge)
  DELETE /api/v1/namespaces/{ns}/{resource}/{name}
  PUT    /api/v1/namespaces/{ns}/pods/{name}/status
  PATCH  /api/v1/namespaces/{ns}/pods/{name}/status
  POST   /api/v1/namespaces/{ns}/bindings         (+ pods/{name}/binding)
  GET    /healthz, /version, /metrics

Watch responses stream newline-delimited JSON `{"type": ..., "object": ...}`
frames over chunked transfer encoding, exactly the reference's
watchjson format (pkg/apiserver/watch.go:64 serveWatch); `410 Gone` when the
requested resourceVersion predates the store's retained window, which tells
the Reflector to re-LIST (reflector.go:252).

Built on ThreadingHTTPServer: one thread per connection, which is the
idiomatic Python analogue of the reference's goroutine-per-request model.
"""

from __future__ import annotations

import contextlib
import json
import logging
import re
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from kubernetes_tpu.api import binary_codec
from kubernetes_tpu.api import fields as fieldsel
from kubernetes_tpu.api import labels as labelsel
from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.serialization import scheme, to_dict
from kubernetes_tpu.registry.generic import (
    RESOURCES, Registry, RegistryError, bad_request,
)
from kubernetes_tpu.observability.audit import (
    AUDIT, AuditRecord, now_iso, render_auditz,
)
from kubernetes_tpu.storage import NoQuorum, TooOldResourceVersion
from kubernetes_tpu.storage import store as store_mod
from kubernetes_tpu.utils import trace
from kubernetes_tpu.utils.metrics import REGISTRY as METRICS

_PATH = re.compile(
    r"^(?:/api/(?P<cver>v[0-9][a-z0-9]*)"
    r"|/apis/(?P<group>[a-z0-9.-]+)/(?P<gversion>v[a-z0-9]+))"
    r"(?:/namespaces/(?P<ns>[a-z0-9-]+))?"
    r"/(?P<resource>[a-z]+)"
    r"(?:/(?P<name>[A-Za-z0-9._-]+))?"
    r"(?:/(?P<sub>status|binding|scale|rollback))?$"
)


class _V1Codec:
    """The native encoding: internal types ARE the v1 wire types."""

    @staticmethod
    def decode_into(cls, data):
        return scheme.decode_into(cls, data)

    @staticmethod
    def encode(obj):
        return scheme.encode(obj)

    @staticmethod
    def encode_item(obj):
        return to_dict(obj)


_V1CODEC = _V1Codec()


class APIServer:
    """In-process API server wrapping a Registry. `start()` binds a real
    socket (port 0 = ephemeral); tests may also call `handle_*` style methods
    through the Registry directly."""

    def __init__(self, registry: Optional[Registry] = None, host: str = "127.0.0.1",
                 port: int = 0, admission_control: Optional[list] = None,
                 authenticator=None, authorizer=None,
                 max_in_flight: int = 400,
                 tls_cert_file: str = "", tls_key_file: str = "",
                 client_ca_file: str = "", audit_log_path: str = ""):
        self.registry = registry or Registry()
        # audit sink: the in-memory ring is always on (the AUDIT singleton,
        # served at /auditz); a path (or KTPU_AUDIT_LOG) adds the rotating
        # on-disk JSON-lines trail (reference --audit-log-path + maxsize).
        # The sink is process-wide (last open wins, like the metrics
        # registry); a server that opened it closes it again in stop() so a
        # stopped server's file handle doesn't capture later servers' traffic
        self._audit_sink_path = audit_log_path
        if audit_log_path:
            AUDIT.open(audit_log_path)
        self._host = host
        self._port = port
        # secure serving (reference genericapiserver.go:638 secure port +
        # --tls-cert-file/--tls-private-key-file/--client-ca-file): TLS when
        # a server keypair is given; with a client CA, verified client certs
        # become identities via the x509 authenticator (CERT_OPTIONAL — the
        # token/basic chain still serves certless clients)
        self.tls_cert_file = tls_cert_file
        self.tls_key_file = tls_key_file
        self.client_ca_file = client_ca_file
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        # server-side flow control (reference MaxInFlightLimit,
        # pkg/apiserver/handlers.go): non-long-running requests beyond the
        # cap get 429 instead of queueing unboundedly. Watches are exempt
        # (long-running, like the reference's longRunningRequestCheck).
        self.max_in_flight = max_in_flight
        self._inflight = threading.BoundedSemaphore(max_in_flight) \
            if max_in_flight else None
        # /configz registry (pkg/util/configz): entrypoints mount their
        # componentconfig objects here
        self.configz: dict = {}
        # admission chain (reference --admission-control flag; the chain runs
        # between decode and storage, cmd/kube-apiserver/app/server.go)
        self.admission = None
        if admission_control:
            from kubernetes_tpu.admission import AdmissionChain, new_chain
            if isinstance(admission_control, AdmissionChain):
                self.admission = admission_control
            else:
                self.admission = new_chain(admission_control, registry=self.registry)
        # authn/authz chain (reference authn→authz filters before dispatch)
        self.authenticator = authenticator
        self.authorizer = authorizer

    # --- lifecycle -----------------------------------------------------------

    @property
    def port(self) -> int:
        assert self._httpd is not None, "server not started"
        return self._httpd.server_address[1]

    @property
    def secure(self) -> bool:
        return bool(self.tls_cert_file)

    @property
    def base_url(self) -> str:
        scheme = "https" if self.secure else "http"
        return f"{scheme}://{self._host}:{self.port}"

    def start(self):
        registry = self.registry
        outer = self

        class Handler(_Handler):
            pass

        class Server(ThreadingHTTPServer):
            # many clients open connections in the same instant (informer
            # fan-out, burst creates); the http.server default backlog of 5
            # RSTs the overflow
            request_queue_size = 128

        Handler.registry = registry
        Handler.server_ref = outer
        self._httpd = Server((self._host, self._port), Handler)
        if self.client_ca_file and not self.secure:
            raise ValueError(
                "--client-ca-file requires --tls-cert-file: client certs "
                "can only be verified on a TLS listener")
        if self.secure:
            import ssl
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(self.tls_cert_file, self.tls_key_file)
            if self.client_ca_file:
                ctx.load_verify_locations(self.client_ca_file)
                ctx.verify_mode = ssl.CERT_OPTIONAL
            # handshake deferred to the per-connection worker thread: done
            # on the listening socket it would run inside the single accept
            # loop, letting one stalled client freeze all new connections
            self._httpd.socket = ctx.wrap_socket(
                self._httpd.socket, server_side=True,
                do_handshake_on_connect=False)
            # and a trickling handshake must not pin a worker forever
            Handler.timeout = 65
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="apiserver", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._audit_sink_path:
            # release only OUR sink: a newer server may have re-pointed the
            # process-wide log since, and its trail must keep flowing
            AUDIT.close_if(self._audit_sink_path)
            self._audit_sink_path = ""


class _Handler(BaseHTTPRequestHandler):
    registry: Registry = None  # set per-server subclass
    server_ref: APIServer = None
    protocol_version = "HTTP/1.1"
    # Nagle off: a delayed-ACK peer otherwise costs ~40ms per small
    # response (watch frames, Status bodies) — see utils/nethost.py
    disable_nagle_algorithm = True

    # silence per-request stderr logging
    def log_message(self, fmt, *args):
        pass

    # --- helpers -------------------------------------------------------------

    def _wants_binary(self) -> bool:
        return binary_codec.CONTENT_TYPE in (self.headers.get("Accept") or "")

    def _send_json(self, code: int, payload: dict):
        # content negotiation (reference negotiateOutputSerializer): clients
        # accepting the binary type get the magic-prefixed wire form
        if self._wants_binary():
            body = binary_codec.encode_dict(payload)
            ctype = binary_codec.CONTENT_TYPE
        else:
            body = json.dumps(payload, separators=(",", ":")).encode()
            ctype = "application/json"
        self._status = code
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_status(self, code: int, reason: str, message: str):
        self._send_json(code, {
            "kind": "Status", "apiVersion": "v1",
            "status": "Failure" if code >= 400 else "Success",
            "reason": reason, "message": message, "code": code,
        })

    def _send_obj(self, obj, code: int = 200):
        codec = getattr(self, "_codec", _V1CODEC)
        self._send_json(code, codec.encode(obj))

    def _read_body(self) -> dict:
        length = int(self.headers.get("Content-Length", 0))
        raw = self.rfile.read(length) if length else b"{}"
        ctype = self.headers.get("Content-Type") or ""
        if binary_codec.CONTENT_TYPE in ctype or binary_codec.is_binary(raw):
            try:
                return binary_codec.decode_dict(raw)
            except binary_codec.BinaryCodecError as e:
                raise bad_request(f"invalid binary body: {e}") from None
        try:
            return json.loads(raw)
        except json.JSONDecodeError as e:
            raise bad_request(f"invalid JSON body: {e}") from None

    # --- dispatch ------------------------------------------------------------

    def _route(self, method: str):
        # per-request trace context: adopt the client's traceparent (same
        # trace id, client span as remote parent) or mint a root trace —
        # either way every audit record carries a usable trace id. The
        # CAS-retry counter is request-scoped and read back at audit time.
        # reset per request: the HTTP/1.1 keep-alive handler instance is
        # reused, and a stale _user from the previous request would be
        # attributed to one that never authenticated (a lying audit trail)
        self._user = None
        if self.path.startswith("/healthz"):
            # liveness probes get neither a span nor an audit record: a
            # hollow fleet's probe traffic would flood both rings with noise
            self._span = None
            self._status = 0
            return self._route_guarded(method)
        t0 = time.perf_counter()
        parsed_tp = trace.parse_traceparent(
            self.headers.get(trace.TRACEPARENT_HEADER))
        self._span = trace.Span(
            "apiserver_request",
            trace_id=parsed_tp[0] if parsed_tp else None,
            parent_id=parsed_tp[1] if parsed_tp else "",
            verb=method, path=self.path)
        self._status = 0
        self._audited = False
        self._t0 = t0
        trace.reset_cas_retries()
        try:
            self._route_guarded(method)
        finally:
            self._finish_audit(method, t0)

    def _route_guarded(self, method: str):
        # watch streams live for hours; timing them as requests would poison
        # the latency histogram (they have their own counter), and they are
        # exempt from the in-flight cap (longRunningRequestCheck)
        q = parse_qs(urlparse(self.path).query)
        is_watch = q.get("watch", ["false"])[0] in ("true", "1")
        sem = None if is_watch else self.server_ref._inflight
        if sem is not None and not sem.acquire(blocking=False):
            METRICS.inc("apiserver_dropped_requests", verb=method)
            try:
                # drain the unread body first or the keep-alive stream
                # desyncs (the next request would parse the leftover bytes)
                length = int(self.headers.get("Content-Length", 0))
                if length:
                    self.rfile.read(length)
                self._send_status(429, "TooManyRequests",
                                  "too many requests in flight; retry")
            except OSError:
                pass
            return
        timer = (contextlib.nullcontext() if is_watch
                 else METRICS.time("apiserver_request_seconds", verb=method))
        try:
            with timer:
                try:
                    with trace.use_span(self._span):
                        self._route_inner(method)
                except RegistryError as e:
                    self._send_status(e.code, e.reason, e.message)
                except TooOldResourceVersion as e:
                    self._send_status(410, "Expired", str(e))
                except NoQuorum as e:
                    # the replicated store could not reach a durable
                    # majority: outcome unknown — clients re-read + retry,
                    # exactly the reference's etcd-timeout surface
                    METRICS.inc("apiserver_storage_noquorum", verb=method)
                    self._send_status(503, "ServiceUnavailable", str(e))
                except BrokenPipeError:
                    pass
                except Exception as e:  # HandleCrash equivalent
                    import traceback
                    traceback.print_exc()
                    try:
                        self._send_status(500, "InternalError",
                                          f"{type(e).__name__}: {e}")
                    except (OSError, ValueError):
                        # client hung up / headers already sent — the
                        # original crash is already on stderr above
                        pass
        finally:
            if sem is not None:
                sem.release()

    def _finish_audit(self, method: str, t0: float):
        """Close the request span and emit the audit record (health probes
        never get here — _route skips them). Long-running watch streams
        audit at stream START instead (_serve_watch) — their audit record
        must not wait hours for the connection to die."""
        span = self._span
        span.attrs["status"] = self._status
        span.finish()
        if self._audited:
            return
        self._audited = True
        self._emit_audit(method, self._status, t0)

    def _emit_audit(self, verb: str, status: int, t0: float):
        """Build + record one AuditRecord from the request's span/headers —
        the single constructor both the request path and the watch-open
        path use, so the two record shapes cannot drift."""
        user = getattr(self, "_user", None)
        try:
            retries = int(self.headers.get(trace.RETRY_HEADER, 0) or 0)
        except ValueError:
            retries = 0
        AUDIT.record(AuditRecord(
            ts=now_iso(), verb=verb, path=self.path,
            component=self.headers.get("User-Agent") or "",
            user=user.name if user is not None else "",
            status=status,
            latency_seconds=round(time.perf_counter() - t0, 6),
            trace_id=self._span.trace_id, span_id=self._span.span_id,
            parent_id=self._span.parent_id,
            cas_retries=trace.cas_retries(), retries=retries))

    def _route_inner(self, method: str):
        url = urlparse(self.path)
        q = {k: v[0] for k, v in parse_qs(url.query).items()}

        if url.path in ("/healthz", "/healthz/ping"):
            # health probes stay unauthenticated (reference serves /healthz on
            # the insecure port for liveness checks)
            return self._send_plain(200, b"ok")
        if url.path in ("/version", "/metrics", "/api", "/apis", "/auditz"):
            if not self._auth_nonresource(url.path):
                return
        if url.path == "/auditz":
            # tail of the audit ring (newest last); ?n= bounds the slice
            return self._send_json(200, render_auditz(AUDIT, q.get("n")))
        if url.path == "/version":
            return self._send_json(200, {"major": "0", "minor": "1",
                                         "gitVersion": "kubernetes-tpu-0.1"})
        if url.path == "/metrics":
            return self._send_plain(200, METRICS.render().encode())
        if url.path == "/configz":
            # live component configuration (pkg/util/configz)
            from kubernetes_tpu.utils.debugserver import render_configz
            return self._send_json(200,
                                   render_configz(self.server_ref.configz))

        if url.path == "/api":
            return self._send_json(200, {"kind": "APIVersions",
                                         "versions": ["v1", "v2"]})
        if url.path == "/apis":
            from kubernetes_tpu.apis import GROUPS
            return self._send_json(200, {
                "kind": "APIGroupList",
                "groups": [{"name": g, "preferredVersion":
                            {"groupVersion": gv}} for g, gv in GROUPS.items()]})

        m = _PATH.match(url.path)
        if not m:
            return self._send_status(404, "NotFound", f"unknown path {url.path}")
        ns = m.group("ns") or ""
        resource = m.group("resource")
        name = m.group("name")
        sub = m.group("sub")
        group = m.group("group")
        gversion = m.group("gversion")
        cver = m.group("cver") or ""

        # /api/v1/namespaces/{name}/status parses as ns + resource="status":
        # reinterpret as the namespaces status subresource (must happen before
        # authz, which would otherwise see resource="status" ns=<name>)
        if ns and resource == "status" and not name:
            resource, name, sub, ns = "namespaces", ns, "status", ""

        # pick the wire codec: v1 is native; other core versions translate at
        # the boundary (conversion + defaulting; storage stays internal)
        self._codec = _V1CODEC
        if cver and cver != "v1":
            from kubernetes_tpu.apis import v2 as v2api
            if cver != v2api.API_VERSION:
                return self._send_status(404, "NotFound",
                                         f"unknown API version {cver!r}")
            if resource != "bindings":  # bindings are version-neutral
                codec = v2api.codec_for(resource)
                if codec is None:
                    return self._send_status(
                        404, "NotFound",
                        f"resource {resource!r} is not served at {cver!r}")
                self._codec = codec

        # a group resource must be addressed under its own group prefix and
        # vice versa (reference: per-group route install, master.go:215)
        if resource in RESOURCES:
            want = RESOURCES[resource].api_version
            got = f"{group}/{gversion}" if group else "v1"
            if want != got:
                return self._send_status(
                    404, "NotFound",
                    f"resource {resource!r} is served at {want!r}, not {got!r}")

        # authn -> authz filters (reference pkg/apiserver/handlers.go chain;
        # the insecure handler — no authenticator configured — skips both)
        if not self._auth_filter(method, resource, name, ns, q,
                                 group or "", sub or ""):
            return

        # "bindings" is a virtual write-only resource backed by the pod
        # registry (reference BindingREST)
        if resource == "bindings" and method == "POST":
            return self._serve_binding(ns)
        if resource not in RESOURCES:
            return self._send_status(404, "NotFound", f"unknown resource {resource!r}")

        if sub == "scale":
            from kubernetes_tpu.apis import extensions as ext
            if method == "GET":
                return self._send_obj(self.registry.get_scale(resource, name, ns))
            if method == "PUT":
                sc = scheme.decode_into(ext.Scale, self._read_body())
                self._admit("UPDATE", resource, ns, name=name, obj=sc,
                            sub="scale")
                return self._send_obj(
                    self.registry.update_scale(resource, name, ns, sc))
            return self._send_status(405, "MethodNotAllowed",
                                     f"{method} not supported on scale")
        if sub == "rollback":
            if method == "POST" and resource == "deployments":
                from kubernetes_tpu.apis import extensions as ext
                rb = scheme.decode_into(ext.DeploymentRollback, self._read_body())
                self._admit("UPDATE", resource, ns, name=name, obj=rb,
                            sub="rollback")
                self.registry.rollback_deployment(name, ns, rb)
                return self._send_json(200, {"kind": "Status", "status": "Success",
                                             "message": "rollback request recorded"})
            return self._send_status(405, "MethodNotAllowed",
                                     f"{method} {resource} rollback not supported")

        if method == "GET" and not name:
            if q.get("watch") in ("true", "1"):
                return self._serve_watch(resource, ns, q)
            return self._serve_list(resource, ns, q)
        if method == "GET":
            return self._send_obj(self.registry.get(resource, name, ns))
        if method == "POST" and not name:
            obj = self._codec.decode_into(RESOURCES[resource].cls,
                                          self._read_body())
            self._admit("CREATE", resource, ns, obj=obj)
            try:
                created = self.registry.create(resource, obj, namespace=ns)
            except RegistryError:
                # a create that fails after admission must not strand side
                # effects booked by mutating plugins (quota charges)
                self._admit_release(resource, ns, obj)
                raise
            return self._send_obj(created, 201)
        if method == "POST" and sub == "binding":
            return self._serve_binding(ns, pod_name=name)
        if method == "PUT" and name:
            obj = self._codec.decode_into(RESOURCES[resource].cls,
                                          self._read_body())
            self._check_body_matches_url(obj, name, ns)
            if not sub:
                # subresource writes (status) skip admission, matching the
                # reference (admission only guards main-resource mutations;
                # kubelet status PATCHes must not be subject to LimitRanger)
                self._admit("UPDATE", resource, ns, name=name, obj=obj)
            if sub == "status":
                return self._send_obj(self.registry.update_status(resource, obj, ns))
            return self._send_obj(self.registry.update(resource, obj, namespace=ns))
        if method == "PATCH" and name:
            return self._serve_patch(resource, name, ns, sub)
        if method == "DELETE" and name:
            self._admit("DELETE", resource, ns, name=name)
            return self._send_obj(self.registry.delete(resource, name, ns))
        return self._send_status(405, "MethodNotAllowed",
                                 f"{method} not supported here")

    def _peer_cert(self):
        """Verified TLS client certificate (ssl dict form) or None — the
        x509 authenticator's input; the TLS handshake already chain-checked
        it against the client CA."""
        getpeercert = getattr(self.connection, "getpeercert", None)
        if getpeercert is None:
            return None
        try:
            return getpeercert() or None
        except Exception:
            return None

    def _auth_nonresource(self, path: str) -> bool:
        """Authn/authz for non-resource debug endpoints (/metrics, /api,
        /apis, /version). ABAC nonResourcePath and RBAC nonResourceURLs rules
        apply. Returns False after sending an error response."""
        outer = self.server_ref
        self._user = None
        if outer is None or outer.authenticator is None:
            return True
        from kubernetes_tpu.auth import AuthenticationError, AuthzAttributes
        try:
            self._user = outer.authenticator.authenticate(
                self.headers, peer_cert=self._peer_cert())
        except AuthenticationError as e:
            self._send_status(401, "Unauthorized", str(e))
            return False
        if self._user is None:
            self._send_status(401, "Unauthorized", "authentication required")
            return False
        if outer.authorizer is None:
            return True
        attrs = AuthzAttributes(user=self._user, verb="get",
                                resource_request=False, path=path)
        if not outer.authorizer.authorize(attrs):
            self._send_status(403, "Forbidden",
                              f'user {self._user.name!r} cannot get {path}')
            return False
        return True

    def _auth_filter(self, method: str, resource: str, name, ns: str,
                     q: dict, api_group: str, subresource: str = "") -> bool:
        """Authenticate then authorize; returns False after sending an error
        response. No-op when the server has no authenticator (insecure port)."""
        outer = self.server_ref
        self._user = None
        if outer is None or outer.authenticator is None:
            return True
        from kubernetes_tpu.auth import AuthenticationError, AuthzAttributes
        try:
            self._user = outer.authenticator.authenticate(
                self.headers, peer_cert=self._peer_cert())
        except AuthenticationError as e:
            self._send_status(401, "Unauthorized", str(e))
            return False
        if self._user is None:
            # no authenticator recognized the request (and no anonymous
            # fallback was configured in the chain)
            self._send_status(401, "Unauthorized", "authentication required")
            return False
        if outer.authorizer is None:
            return True
        if method == "GET":
            verb = ("watch" if q.get("watch") in ("true", "1")
                    else ("get" if name else "list"))
        else:
            verb = {"POST": "create", "PUT": "update", "PATCH": "patch",
                    "DELETE": "delete"}.get(method, method.lower())
        attrs = AuthzAttributes(user=self._user, verb=verb, resource=resource,
                                subresource=subresource, namespace=ns,
                                api_group=api_group, name=name or "")
        if not outer.authorizer.authorize(attrs):
            uname = self._user.name if self._user else "<anonymous>"
            what = f"{resource}/{subresource}" if subresource else resource
            self._send_status(403, "Forbidden",
                              f'user {uname!r} cannot {verb} {what} '
                              f'in namespace {ns!r}')
            return False
        return True

    def _admit(self, op: str, resource: str, ns: str, name: str = "",
               obj=None, sub: str = ""):
        """Run the admission chain; rejections surface as HTTP errors
        (reference resthandler wraps plugin errors in Forbidden)."""
        adm = self.server_ref.admission if self.server_ref else None
        if adm is None:
            return
        from kubernetes_tpu.admission import AdmissionError, Attributes
        if not name and obj is not None and getattr(obj, "metadata", None):
            name = obj.metadata.name
        attrs = Attributes(resource=resource, subresource=sub, name=name,
                           namespace=ns, operation=op, obj=obj,
                           kind=type(obj).__name__ if obj is not None else "",
                           user=getattr(self, "_user", None))
        try:
            adm.admit(attrs)
        except AdmissionError as e:
            raise RegistryError(e.code, "Forbidden", str(e)) from None

    def _admit_release(self, resource: str, ns: str, obj):
        """Undo admission side effects after a failed create: plugins exposing
        release_create (ResourceQuota) get the rejected object back."""
        adm = self.server_ref.admission if self.server_ref else None
        if adm is None:
            return
        from kubernetes_tpu.admission import Attributes
        attrs = Attributes(resource=resource, namespace=ns, operation="CREATE",
                           obj=obj)
        for p in adm.plugins:
            release = getattr(p, "release_create", None)
            if release is not None:
                try:
                    release(attrs)
                except Exception:
                    # best-effort; periodic recalc is the backstop — but a
                    # plugin that can't release quota leaks it until then
                    logging.getLogger("apiserver").exception(
                        "admission release_create failed for %s/%s",
                        ns, resource)

    def _check_body_matches_url(self, obj, name: str, ns: str):
        """The reference apiserver rejects name/namespace mismatches between
        the URL and body metadata with 400 (resthandler.go update path)."""
        meta = getattr(obj, "metadata", None)
        body_name = meta.name if meta else ""
        body_ns = meta.namespace if meta else ""
        if body_name and body_name != name:
            raise bad_request(f"metadata.name {body_name!r} does not match URL name {name!r}")
        if ns and body_ns and body_ns != ns:
            raise bad_request(f"metadata.namespace {body_ns!r} does not match URL namespace {ns!r}")
        if meta:
            meta.name = meta.name or name
            meta.namespace = meta.namespace or ns

    def _send_plain(self, code: int, body: bytes):
        self._status = code
        self.send_response(code)
        self.send_header("Content-Type", "text/plain")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    # --- collection handlers -------------------------------------------------

    def _selectors(self, q, kind: Optional[str] = None):
        try:
            lsel = labelsel.parse_selector(q.get("labelSelector"))
            fsel = fieldsel.parse_field_selector(q.get("fieldSelector"))
        except (labelsel.SelectorError, fieldsel.FieldSelectorError) as e:
            raise bad_request(str(e)) from None
        if kind is not None:
            allowed = api.supported_fields(kind)
            for r in fsel.requirements:
                if r.key not in allowed:
                    raise bad_request(f"field label not supported: {r.key}")
        return lsel, fsel

    def _serve_list(self, resource, ns, q):
        lsel, fsel = self._selectors(q, kind=RESOURCES[resource].kind)
        items, rv = self.registry.list(resource, ns, lsel, fsel)
        rd = RESOURCES[resource]
        codec = getattr(self, "_codec", _V1CODEC)
        version = (rd.api_version if codec is _V1CODEC
                   else getattr(codec, "api_version", "v2"))
        self._send_json(200, {
            "kind": rd.list_kind, "apiVersion": version,
            "metadata": {"resourceVersion": str(rv)},
            "items": [codec.encode_item(o) for o in items],
        })

    # patch content types (reference api.StrategicMergePatchType /
    # MergePatchType, resthandler.go:503-615)
    from kubernetes_tpu.utils.strategicpatch import (
        MERGE_PATCH_TYPE as MERGE_PATCH,
        STRATEGIC_PATCH_TYPE as STRATEGIC_PATCH,
    )

    def _serve_patch(self, resource, name, ns, sub):
        """Server-side PATCH: read-modify-write under optimistic concurrency.

        The merged object carries the read's resourceVersion, so a
        concurrent writer between our GET and UPDATE surfaces as 409 and we
        re-get + re-apply — the reference's patchResource retry
        (resthandler.go:562-615). This is what lets concurrent label and
        status patches of one pod both land without a lost update."""
        from kubernetes_tpu.utils.strategicpatch import (
            apply_patch, json_merge_patch,
        )
        if sub not in ("", None, "status"):
            self._read_body()  # drain: keep-alive must not desync
            return self._send_status(
                405, "MethodNotAllowed", f"PATCH not supported on {sub}")
        ctype = (self.headers.get("Content-Type") or "").split(";")[0].strip()
        if ctype in (self.STRATEGIC_PATCH, "", "application/json",
                     binary_codec.CONTENT_TYPE):
            merge = apply_patch
        elif ctype == self.MERGE_PATCH:
            merge = json_merge_patch
        else:
            self._read_body()  # drain: keep-alive must not desync
            return self._send_status(
                415, "UnsupportedMediaType",
                f"unsupported patch type {ctype!r}; use "
                f"{self.STRATEGIC_PATCH} or {self.MERGE_PATCH}")
        patch = self._read_body()
        if not isinstance(patch, dict):
            raise bad_request(
                f"patch body must be a JSON object, got {type(patch).__name__}")
        if "resourceVersion" in (patch.get("metadata") or {}):
            raise bad_request("metadata.resourceVersion may not be patched")
        rd = RESOURCES[resource]
        codec = getattr(self, "_codec", _V1CODEC)
        last = None
        for attempt in range(50):
            if attempt:
                # jittered backoff: N racing patchers otherwise re-collide
                # in lockstep and exhaust any fixed retry budget
                import random
                import time as _time
                trace.note_cas_retry()  # audited: how contended this PATCH was
                _time.sleep(random.uniform(0, 0.002 * min(attempt, 10)))
            current = self.registry.get(resource, name, ns)
            merged = merge(codec.encode(current), patch)
            obj = codec.decode_into(rd.cls, merged)
            self._check_body_matches_url(obj, name, ns)
            # CAS token: the patch applies to the state we read
            obj.metadata.resource_version = current.metadata.resource_version
            if not sub:
                self._admit("UPDATE", resource, ns, name=name, obj=obj)
            try:
                if sub == "status":
                    return self._send_obj(
                        self.registry.update_status(resource, obj, ns))
                return self._send_obj(
                    self.registry.update(resource, obj, namespace=ns))
            except RegistryError as e:
                if e.code != 409:
                    raise
                last = e
        raise last

    def _serve_binding(self, ns, pod_name: Optional[str] = None):
        body = self._read_body()
        binding = scheme.decode_into(api.Binding, body)
        if pod_name and (binding.metadata is None or not binding.metadata.name):
            binding.metadata = binding.metadata or api.ObjectMeta()
            binding.metadata.name = pod_name
        self.registry.bind_pod(binding, ns or "default")
        self._send_status(201, "Created", "binding created")

    def _serve_watch(self, resource, ns, q):
        lsel, fsel = self._selectors(q, kind=RESOURCES[resource].kind)
        since = q.get("resourceVersion")
        try:
            since_rv = int(since) if since not in (None, "") else None
        except ValueError:
            raise bad_request(f"invalid resourceVersion: {since!r}") from None
        watcher = self.registry.watch(resource, ns, since_rv=since_rv)
        rd = RESOURCES[resource]
        binary = self._wants_binary()
        METRICS.inc("apiserver_watch_streams", resource=resource)
        self._status = 200
        # audit the stream at OPEN (latency = time-to-accept): a watch can
        # live for hours and its audit record must not wait for that
        self._audited = True
        self._emit_audit("GET", 200, self._t0)
        self.send_response(200)
        self.send_header("Content-Type",
                         binary_codec.CONTENT_TYPE if binary
                         else "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        try:
            while True:
                ev = watcher.next(timeout=30.0)
                if ev is None:
                    if watcher.stopped:
                        break  # dropped/cancelled: end the stream
                    # heartbeat: blank line (JSON) / zero-length frame
                    # (binary) so a dead TCP peer raises BrokenPipe and we
                    # reclaim thread + watcher
                    self._write_chunk(b"\x00\x00\x00\x00" if binary
                                      else b"\n")
                    continue
                if ev.type == store_mod.ERROR:
                    # slow-watcher drop (cacher.go:73): terminal ERROR frame,
                    # then close; the Reflector answers with a re-list
                    METRICS.inc("apiserver_watch_drops", resource=resource)
                    payload = {"type": "ERROR", "object": ev.obj}
                    if binary:
                        body = binary_codec.encode_dict(payload)
                        self._write_chunk(len(body).to_bytes(4, "big") + body)
                    else:
                        self._write_chunk(json.dumps(
                            payload, separators=(",", ":")).encode() + b"\n")
                    break
                out = self._transform_for_selectors(rd, ev, lsel, fsel)
                if out is None:
                    continue
                etype, obj = out
                codec = getattr(self, "_codec", _V1CODEC)
                if binary:
                    # length-delimited binary event frames (reference
                    # protobuf watch framing, pkg/runtime/serializer/
                    # protobuf + util/framer LengthDelimitedFramer)
                    payload = binary_codec.encode_dict(
                        {"type": etype, "object": codec.encode(obj)})
                    frame = len(payload).to_bytes(4, "big") + payload
                else:
                    frame = json.dumps({"type": etype,
                                        "object": codec.encode(obj)},
                                       separators=(",", ":")).encode() + b"\n"
                self._write_chunk(frame)
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            watcher.stop()
            try:
                self._write_chunk(b"")  # terminal chunk
            except OSError:
                pass

    def _transform_for_selectors(self, rd, ev, lsel, fsel):
        """Selector-filtered watch must tell clients when an object *leaves*
        the selected set (else their caches go permanently stale): an event
        whose object no longer matches but whose previous state did becomes
        DELETED; one entering the set becomes ADDED (reference etcd_watcher /
        cacher transform). Returns (type, obj) or None to drop."""
        obj = self.registry._decode(rd, ev.obj, ev.rv)
        if (lsel is None or lsel.empty()) and (fsel is None or fsel.empty()):
            return ev.type, obj
        cur = Registry._matches(obj, lsel, fsel)
        prev_match = False
        if ev.prev_obj is not None:
            prev = self.registry._decode(rd, ev.prev_obj, None)
            prev_match = Registry._matches(prev, lsel, fsel)
        if ev.type == "DELETED":
            return ("DELETED", obj) if (cur or prev_match) else None
        if cur and not prev_match:
            return "ADDED", obj
        if cur and prev_match:
            return ev.type, obj
        if not cur and prev_match:
            return "DELETED", obj
        return None

    def _write_chunk(self, data: bytes):
        if data:
            self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
        else:
            self.wfile.write(b"0\r\n\r\n")
        self.wfile.flush()

    # --- HTTP verbs ----------------------------------------------------------

    def do_GET(self):
        self._route("GET")

    def do_POST(self):
        self._route("POST")

    def do_PUT(self):
        self._route("PUT")

    def do_PATCH(self):
        self._route("PATCH")

    def do_DELETE(self):
        self._route("DELETE")
