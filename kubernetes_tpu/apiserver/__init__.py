"""L2 API server: REST over HTTP with LIST/WATCH streaming.

Parity target: reference pkg/apiserver (api_installer.go route generation,
resthandler.go, watch.go chunked streaming) + pkg/genericapiserver (serving
stack) + pkg/master (resource composition).
"""

from kubernetes_tpu.apiserver.server import APIServer
