"""kube-apiserver entrypoint: python -m kubernetes_tpu.apiserver

Flags bind to the versioned APIServerConfiguration (componentconfig), which
is served live at /configz (reference cmd/kube-apiserver/app/server.go:79-281
pattern: flags -> versioned config -> component)."""

from __future__ import annotations

import argparse
import signal
import sys
import threading

from kubernetes_tpu.apis.componentconfig import APIServerConfiguration
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.registry.generic import Registry


def build_config(argv=None) -> APIServerConfiguration:
    p = argparse.ArgumentParser(prog="kube-apiserver")
    p.add_argument("--bind-address", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--data-dir", default="",
                   help="WAL+snapshot directory; empty = memory-only")
    p.add_argument("--max-requests-inflight", type=int, default=400,
                   dest="max_in_flight")
    p.add_argument("--watcher-queue", type=int, default=4096)
    p.add_argument("--admission-control", default="")
    p.add_argument("--tls-cert-file", default="")
    p.add_argument("--tls-private-key-file", default="")
    p.add_argument("--client-ca-file", default="")
    p.add_argument("--token-auth-file", default="")
    p.add_argument("--authorization-mode", default="")
    p.add_argument("--authorization-policy-file", default="")
    a = p.parse_args(argv)
    return APIServerConfiguration(
        bind_address=a.bind_address, port=a.port, data_dir=a.data_dir,
        max_in_flight=a.max_in_flight, watcher_queue=a.watcher_queue,
        admission_control=a.admission_control,
        tls_cert_file=a.tls_cert_file,
        tls_private_key_file=a.tls_private_key_file,
        client_ca_file=a.client_ca_file,
        token_auth_file=a.token_auth_file,
        authorization_mode=a.authorization_mode,
        authorization_policy_file=a.authorization_policy_file)


def build_server(cfg: APIServerConfiguration) -> APIServer:
    if cfg.data_dir:
        from kubernetes_tpu.storage.durable import DurableStore
        store = DurableStore(cfg.data_dir, watcher_queue=cfg.watcher_queue)
    else:
        from kubernetes_tpu.storage.store import MemStore
        store = MemStore(watcher_queue=cfg.watcher_queue)
    admission = ([s for s in cfg.admission_control.split(",") if s]
                 or None)
    authenticator = authorizer = None
    if cfg.client_ca_file or cfg.token_auth_file:
        from kubernetes_tpu.auth import (
            TokenAuthenticator, UnionAuthenticator, X509Authenticator,
        )
        chain = []
        if cfg.client_ca_file:
            chain.append(X509Authenticator())
        if cfg.token_auth_file:
            with open(cfg.token_auth_file) as f:
                chain.append(TokenAuthenticator.from_csv(f.read()))
        authenticator = UnionAuthenticator(chain)
    if cfg.authorization_mode == "RBAC":
        from kubernetes_tpu.auth import RBACAuthorizer
        authorizer = RBACAuthorizer(Registry(store))
    elif cfg.authorization_mode == "ABAC":
        from kubernetes_tpu.auth import ABACAuthorizer
        with open(cfg.authorization_policy_file) as f:
            authorizer = ABACAuthorizer.from_file_text(f.read())
    elif cfg.authorization_mode in ("AlwaysAllow", ""):
        authorizer = None
    else:
        # fail closed at startup: a typo'd mode must not silently allow all
        raise SystemExit(
            f"unknown --authorization-mode {cfg.authorization_mode!r} "
            "(supported: RBAC, ABAC, AlwaysAllow)")
    server = APIServer(Registry(store), host=cfg.bind_address, port=cfg.port,
                       admission_control=admission,
                       max_in_flight=cfg.max_in_flight,
                       authenticator=authenticator, authorizer=authorizer,
                       tls_cert_file=cfg.tls_cert_file,
                       tls_key_file=cfg.tls_private_key_file,
                       client_ca_file=cfg.client_ca_file)
    server.configz["apiserver"] = cfg
    return server


def main(argv=None) -> int:
    cfg = build_config(argv)
    server = build_server(cfg).start()
    # parseable by wrappers (localup) even with --port 0
    scheme = "https" if server.secure else "http"
    print(f"apiserver listening on {scheme}://{cfg.bind_address}:{server.port}",
          flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    store = server.registry.store
    server.stop()
    if hasattr(store, "close"):
        store.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
