"""kube-apiserver entrypoint: python -m kubernetes_tpu.apiserver

Flags bind to the versioned APIServerConfiguration (componentconfig), which
is served live at /configz (reference cmd/kube-apiserver/app/server.go:79-281
pattern: flags -> versioned config -> component)."""

from __future__ import annotations

import argparse
import signal
import sys
import threading

from kubernetes_tpu.apis.componentconfig import APIServerConfiguration
from kubernetes_tpu.apiserver.server import APIServer
from kubernetes_tpu.registry.generic import Registry


def build_config(argv=None) -> APIServerConfiguration:
    p = argparse.ArgumentParser(prog="kube-apiserver")
    p.add_argument("--bind-address", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--data-dir", default="",
                   help="WAL+snapshot directory; empty = memory-only")
    p.add_argument("--max-requests-inflight", type=int, default=400,
                   dest="max_in_flight")
    p.add_argument("--watcher-queue", type=int, default=4096)
    p.add_argument("--admission-control", default="")
    a = p.parse_args(argv)
    return APIServerConfiguration(
        bind_address=a.bind_address, port=a.port, data_dir=a.data_dir,
        max_in_flight=a.max_in_flight, watcher_queue=a.watcher_queue,
        admission_control=a.admission_control)


def build_server(cfg: APIServerConfiguration) -> APIServer:
    if cfg.data_dir:
        from kubernetes_tpu.storage.durable import DurableStore
        store = DurableStore(cfg.data_dir, watcher_queue=cfg.watcher_queue)
    else:
        from kubernetes_tpu.storage.store import MemStore
        store = MemStore(watcher_queue=cfg.watcher_queue)
    admission = ([s for s in cfg.admission_control.split(",") if s]
                 or None)
    server = APIServer(Registry(store), host=cfg.bind_address, port=cfg.port,
                       admission_control=admission,
                       max_in_flight=cfg.max_in_flight)
    server.configz["apiserver"] = cfg
    return server


def main(argv=None) -> int:
    cfg = build_config(argv)
    server = build_server(cfg).start()
    # parseable by wrappers (localup) even with --port 0
    print(f"apiserver listening on http://{cfg.bind_address}:{server.port}",
          flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    stop.wait()
    store = server.registry.store
    server.stop()
    if hasattr(store, "close"):
        store.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
