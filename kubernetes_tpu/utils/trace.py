"""Step tracing: named multi-step traces logged only when over threshold.

Parity target: reference pkg/util/trace.go:32-67 — the scheduler wraps every
Schedule() in a trace with steps "Computing predicates"/"Prioritizing"/
"Selecting host" and logs it only if the decision exceeded 20ms
(generic_scheduler.go:71-77).
"""

from __future__ import annotations

import logging
import time
from contextlib import contextmanager
from typing import List, Tuple

log = logging.getLogger("trace")


class Trace:
    def __init__(self, name: str, **fields):
        self.name = name
        self.fields = fields
        self.start = time.perf_counter()
        self.steps: List[Tuple[str, float]] = []

    @contextmanager
    def step(self, name: str):
        try:
            yield
        finally:
            self.steps.append((name, time.perf_counter()))

    def total_seconds(self) -> float:
        return time.perf_counter() - self.start

    def log_if_slow(self, threshold_seconds: float):
        total = self.total_seconds()
        if total < threshold_seconds:
            return
        parts = [f'"{self.name}" {self.fields}: total {total * 1000:.1f}ms']
        prev = self.start
        for name, at in self.steps:
            parts.append(f"  {name}: +{(at - prev) * 1000:.1f}ms")
            prev = at
        log.info("\n".join(parts))
