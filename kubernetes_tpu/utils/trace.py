"""Spans + step tracing for the scheduling pipeline.

Two layers:

- `Trace` — the original threshold-logged step trace (reference
  pkg/util/trace.go:32-67; the sequential scheduler wraps Schedule() in one
  and logs it only past 20ms).
- `Span` / `SpanTracker` — correlated spans with trace/span IDs and parent
  links, carried from pod arrival (informer delivery) through queue wait,
  the kernel pipeline stages (tensorize / upload / solve), and bind.  A
  span's `finish(metric=...)` exports its duration straight into the
  metrics registry, so the span structure and the SLI histograms
  (`scheduler_pod_queue_wait_seconds`, `scheduler_stage_seconds`, ...) are
  one measurement, not two.  Finished spans land in a bounded ring
  (`recent_spans`) for tests and postmortems — the compact stand-in for a
  span exporter.
"""

from __future__ import annotations

import itertools
import logging
import os
import threading
import time
from collections import OrderedDict, deque
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.utils.metrics import REGISTRY as METRICS

log = logging.getLogger("trace")

_ID_PREFIX = os.urandom(4).hex()  # per-process uniqueness
_ID_COUNTER = itertools.count(1)


def new_id() -> str:
    return f"{_ID_PREFIX}-{next(_ID_COUNTER):x}"


class Span:
    """One timed operation. Children share the trace_id and point at their
    parent via parent_id; `finish` stamps the end and (optionally) records
    the duration into a registry histogram."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start", "end",
                 "attrs", "children")

    def __init__(self, name: str, trace_id: Optional[str] = None,
                 parent: Optional["Span"] = None, **attrs):
        self.name = name
        self.trace_id = trace_id or (parent.trace_id if parent else new_id())
        self.span_id = new_id()
        self.parent_id = parent.span_id if parent else ""
        self.start = time.perf_counter()
        self.end: Optional[float] = None
        self.attrs: Dict[str, object] = dict(attrs)
        self.children: List[Span] = []
        if parent is not None:
            parent.children.append(self)

    def child(self, name: str, **attrs) -> "Span":
        return Span(name, parent=self, **attrs)

    @property
    def duration(self) -> float:
        return (self.end if self.end is not None
                else time.perf_counter()) - self.start

    def finish(self, metric: Optional[str] = None, registry=None,
               **labels) -> float:
        if self.end is None:
            self.end = time.perf_counter()
            _record_span(self)
        d = self.end - self.start
        if metric:
            (registry or METRICS).observe(metric, d, **labels)
        return d

    @contextmanager
    def timed(self, name: str, metric: Optional[str] = None, **labels):
        c = self.child(name)
        try:
            yield c
        finally:
            c.finish(metric=metric, **labels)

    def tree_lines(self, indent: str = "") -> List[str]:
        lines = [f"{indent}{self.name} [{self.span_id}"
                 f"{' <- ' + self.parent_id if self.parent_id else ''}]"
                 f" {self.duration * 1000:.1f}ms {self.attrs or ''}"]
        for c in self.children:
            lines.extend(c.tree_lines(indent + "  "))
        return lines

    def __repr__(self):
        return (f"Span({self.name!r}, trace={self.trace_id},"
                f" id={self.span_id}, parent={self.parent_id or None})")


# bounded exporter ring: tests and postmortems read finished spans here
_RECENT: "deque[Span]" = deque(maxlen=4096)
_RECENT_LOCK = threading.Lock()


def _record_span(span: Span):
    with _RECENT_LOCK:
        _RECENT.append(span)


def recent_spans(name: Optional[str] = None,
                 trace_id: Optional[str] = None) -> List[Span]:
    with _RECENT_LOCK:
        out = list(_RECENT)
    if name is not None:
        out = [s for s in out if s.name == name]
    if trace_id is not None:
        out = [s for s in out if s.trace_id == trace_id]
    return out


def clear_recent():
    with _RECENT_LOCK:
        _RECENT.clear()


class SpanTracker:
    """Bounded key -> live-root-span map: the correlation table the
    scheduler uses to carry one span per pending pod across threads
    (informer dispatch -> batch loop -> bind pool). At most one open child
    ("stage") per key."""

    def __init__(self, cap: int = 65536, slow_log_seconds: float = 0.0):
        self._cap = cap
        self._slow = slow_log_seconds
        self._lock = threading.Lock()
        # key -> (root span, open stage child or None)
        self._live: "OrderedDict[str, list]" = OrderedDict()

    def start(self, key: str, name: str, **attrs) -> Span:
        sp = Span(name, **attrs)
        with self._lock:
            self._live[key] = [sp, None]
            self._live.move_to_end(key)
            while len(self._live) > self._cap:
                self._live.popitem(last=False)
        return sp

    def current(self, key: str) -> Optional[Span]:
        with self._lock:
            rec = self._live.get(key)
            return rec[0] if rec else None

    def annotate(self, key: str, **attrs):
        with self._lock:
            rec = self._live.get(key)
            if rec:
                rec[0].attrs.update(attrs)

    def stage(self, key: str, name: str, **attrs) -> Optional[Span]:
        """Open a named child of the key's root, closing any open stage;
        idempotent when the open stage already has this name."""
        with self._lock:
            rec = self._live.get(key)
            if rec is None:
                return None
            root, open_stage = rec
            if open_stage is not None:
                if open_stage.name == name:
                    return open_stage
                open_stage.finish()
            child = root.child(name, **attrs)
            rec[1] = child
            return child

    def stage_if_idle(self, key: str, name: str, **attrs) -> Optional[Span]:
        """Open a named child only when no OTHER stage is open — a pod
        mid-bind must not have its live stage clobbered by a watch-echo
        re-enqueue."""
        with self._lock:
            rec = self._live.get(key)
            if rec is None:
                return None
            root, open_stage = rec
            if open_stage is not None:
                return open_stage if open_stage.name == name else None
            child = root.child(name, **attrs)
            rec[1] = child
            return child

    def end_stage(self, key: str, metric: Optional[str] = None,
                  name: Optional[str] = None, **labels) -> Optional[Span]:
        """Close the open stage; with `name` given, only if it matches —
        the metric must never be fed some other stage's duration."""
        with self._lock:
            rec = self._live.get(key)
            if rec is None or rec[1] is None:
                return None
            child = rec[1]
            if name is not None and child.name != name:
                return None
            rec[1] = None
        child.finish(metric=metric, **labels)
        return child

    def finish(self, key: str, metric: Optional[str] = None,
               error: Optional[str] = None, **labels) -> Optional[Span]:
        with self._lock:
            rec = self._live.pop(key, None)
        if rec is None:
            return None
        root, open_stage = rec
        if open_stage is not None:
            open_stage.finish()
        if error is not None:
            root.attrs["error"] = error
        root.finish(metric=metric, **labels)
        if self._slow and root.duration >= self._slow:
            log.info("slow span %s:\n%s", key, "\n".join(root.tree_lines()))
        return root

    def discard(self, key: str):
        with self._lock:
            self._live.pop(key, None)


class Trace:
    """Named multi-step trace logged only when over threshold
    (generic_scheduler.go:71-77 semantics)."""

    def __init__(self, name: str, **fields):
        self.name = name
        self.fields = fields
        self.start = time.perf_counter()
        self.steps: List[Tuple[str, float]] = []

    @contextmanager
    def step(self, name: str):
        try:
            yield
        finally:
            self.steps.append((name, time.perf_counter()))

    def total_seconds(self) -> float:
        return time.perf_counter() - self.start

    def log_if_slow(self, threshold_seconds: float):
        total = self.total_seconds()
        if total < threshold_seconds:
            return
        parts = [f'"{self.name}" {self.fields}: total {total * 1000:.1f}ms']
        prev = self.start
        for name, at in self.steps:
            parts.append(f"  {name}: +{(at - prev) * 1000:.1f}ms")
            prev = at
        log.info("\n".join(parts))
