"""Spans + step tracing for the scheduling pipeline.

Two layers:

- `Trace` — the original threshold-logged step trace (reference
  pkg/util/trace.go:32-67; the sequential scheduler wraps Schedule() in one
  and logs it only past 20ms).
- `Span` / `SpanTracker` — correlated spans with trace/span IDs and parent
  links, carried from pod arrival (informer delivery) through queue wait,
  the kernel pipeline stages (tensorize / upload / solve), and bind.  A
  span's `finish(metric=...)` exports its duration straight into the
  metrics registry, so the span structure and the SLI histograms
  (`scheduler_pod_queue_wait_seconds`, `scheduler_stage_seconds`, ...) are
  one measurement, not two.  Finished spans land in a bounded ring
  (`recent_spans`) for tests and postmortems — the compact stand-in for a
  span exporter.
- the cross-process layer: a contextvar holding the "current" span
  (`use_span` / `current_span`), a W3C-style `traceparent` header carried
  by `client/rest.py` and parsed by `apiserver/server.py`
  (`format_traceparent` / `parse_traceparent`), and a request-scoped
  CAS-retry counter (`note_cas_retry`) that `storage/store.py` ticks and
  the apiserver's audit log reads — one trace id from a controller span
  through its apiserver request span down to the storage retry loop.
"""

from __future__ import annotations

import contextvars
import itertools
import logging
import os
import re
import threading
import time
from collections import OrderedDict, deque
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.utils.metrics import REGISTRY as METRICS

log = logging.getLogger("trace")

_ID_PREFIX = os.urandom(4).hex()  # per-process uniqueness
_ID_COUNTER = itertools.count(1)


def new_trace_id() -> str:
    """32 lowercase hex chars (W3C trace-id shape): process prefix +
    counter, so ids parse back out of a `traceparent` header unambiguously."""
    return f"{_ID_PREFIX}{next(_ID_COUNTER):024x}"


def new_span_id() -> str:
    """16 lowercase hex chars (W3C parent-id shape)."""
    return f"{_ID_PREFIX}{next(_ID_COUNTER):08x}"


class Span:
    """One timed operation. Children share the trace_id and point at their
    parent via parent_id; `finish` stamps the end and (optionally) records
    the duration into a registry histogram."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start", "end",
                 "attrs", "children")

    def __init__(self, name: str, trace_id: Optional[str] = None,
                 parent: Optional["Span"] = None, parent_id: str = "",
                 **attrs):
        self.name = name
        self.trace_id = trace_id or (parent.trace_id if parent
                                     else new_trace_id())
        self.span_id = new_span_id()
        # `parent_id` covers the cross-process case: the remote parent is a
        # header, not a Span object we could link children into
        self.parent_id = parent.span_id if parent else parent_id
        self.start = time.perf_counter()
        self.end: Optional[float] = None
        self.attrs: Dict[str, object] = dict(attrs)
        self.children: List[Span] = []
        if parent is not None:
            parent.children.append(self)

    def child(self, name: str, **attrs) -> "Span":
        return Span(name, parent=self, **attrs)

    @property
    def duration(self) -> float:
        return (self.end if self.end is not None
                else time.perf_counter()) - self.start

    def finish(self, metric: Optional[str] = None, registry=None,
               **labels) -> float:
        # first-write-wins under a lock: the watchdog force-finishes a
        # timed-out stage's span while the (no longer hung) worker may be
        # racing its own finally-finish — without the lock both could pass
        # the end-is-None check and double-record into the ring
        with _FINISH_LOCK:
            first = self.end is None
            if first:
                self.end = time.perf_counter()
        if first:
            _record_span(self)
        d = self.end - self.start
        if metric:
            (registry or METRICS).observe(metric, d, **labels)
        return d

    @contextmanager
    def timed(self, name: str, metric: Optional[str] = None, **labels):
        c = self.child(name)
        try:
            yield c
        finally:
            c.finish(metric=metric, **labels)

    def tree_lines(self, indent: str = "") -> List[str]:
        lines = [f"{indent}{self.name} [{self.span_id}"
                 f"{' <- ' + self.parent_id if self.parent_id else ''}]"
                 f" {self.duration * 1000:.1f}ms {self.attrs or ''}"]
        for c in self.children:
            lines.extend(c.tree_lines(indent + "  "))
        return lines

    def __repr__(self):
        return (f"Span({self.name!r}, trace={self.trace_id},"
                f" id={self.span_id}, parent={self.parent_id or None})")


# bounded exporter ring: tests and postmortems read finished spans here
_RECENT: "deque[Span]" = deque(maxlen=4096)
_RECENT_LOCK = threading.Lock()
# serializes the end-stamp transition in Span.finish (distinct from
# _RECENT_LOCK, which _record_span takes after the transition)
_FINISH_LOCK = threading.Lock()


def _record_span(span: Span):
    with _RECENT_LOCK:
        _RECENT.append(span)


def recent_spans(name: Optional[str] = None,
                 trace_id: Optional[str] = None) -> List[Span]:
    with _RECENT_LOCK:
        out = list(_RECENT)
    if name is not None:
        out = [s for s in out if s.name == name]
    if trace_id is not None:
        out = [s for s in out if s.trace_id == trace_id]
    return out


def clear_recent():
    with _RECENT_LOCK:
        _RECENT.clear()


def spans_for_trace(trace_id: str) -> List[Span]:
    """Every finished span on one trace, oldest first — the per-trace
    lookup tests and the flight recorder use instead of scanning the ring."""
    with _RECENT_LOCK:
        return [s for s in _RECENT if s.trace_id == trace_id]


# --- cross-process context ----------------------------------------------------
#
# The current span rides a contextvar, NOT a threading.local: handler threads
# are per-request, worker threads run one logical operation at a time, and a
# contextvar composes with any future async port for free.  Threads do not
# inherit it — a component handing work to another thread re-establishes the
# context with `use_span(span)` around the calls it wants correlated (see
# Scheduler._bind).

_CURRENT: "contextvars.ContextVar[Optional[Span]]" = contextvars.ContextVar(
    "ktpu_current_span", default=None)

TRACEPARENT_HEADER = "traceparent"
RETRY_HEADER = "x-ktpu-retries"

_TRACEPARENT = re.compile(
    r"^00-([0-9a-f]{16,32})-([0-9a-f]{8,16})-([0-9a-f]{2})$")


def current_span() -> Optional[Span]:
    return _CURRENT.get()


@contextmanager
def use_span(span: Optional[Span]):
    """Make `span` the current trace context for the duration of the block.
    None is accepted and is a no-op, so call sites can pass an optional
    span straight through without branching."""
    if span is None:
        yield None
        return
    token = _CURRENT.set(span)
    try:
        yield span
    finally:
        _CURRENT.reset(token)


def format_traceparent(span: Span) -> str:
    """W3C-style `00-<trace-id>-<span-id>-01` header value."""
    return f"00-{span.trace_id}-{span.span_id}-01"


def parse_traceparent(value: Optional[str]) -> Optional[Tuple[str, str]]:
    """(trace_id, parent_span_id) from a traceparent header, or None for a
    missing/garbled header — a bad header must degrade to "new trace",
    never to a 400."""
    if not value:
        return None
    m = _TRACEPARENT.match(value.strip())
    if not m:
        return None
    return m.group(1), m.group(2)


# request-scoped CAS-retry counter: the apiserver handler resets it per
# request, storage's guaranteed_update and the PATCH retry loop tick it, and
# the audit record reads the total — how contended this request's write was.
_CAS_RETRIES: "contextvars.ContextVar[int]" = contextvars.ContextVar(
    "ktpu_cas_retries", default=0)


def reset_cas_retries() -> None:
    _CAS_RETRIES.set(0)


def note_cas_retry(n: int = 1) -> None:
    _CAS_RETRIES.set(_CAS_RETRIES.get() + n)


def cas_retries() -> int:
    return _CAS_RETRIES.get()


class SpanTracker:
    """Bounded key -> live-root-span map: the correlation table the
    scheduler uses to carry one span per pending pod across threads
    (informer dispatch -> batch loop -> bind pool). At most one open child
    ("stage") per key."""

    def __init__(self, cap: int = 65536, slow_log_seconds: float = 0.0):
        self._cap = cap
        self._slow = slow_log_seconds
        self._lock = threading.Lock()
        # key -> (root span, open stage child or None)
        self._live: "OrderedDict[str, list]" = OrderedDict()

    def start(self, key: str, name: str, **attrs) -> Span:
        sp = Span(name, **attrs)
        with self._lock:
            self._live[key] = [sp, None]
            self._live.move_to_end(key)
            while len(self._live) > self._cap:
                self._live.popitem(last=False)
        return sp

    def current(self, key: str) -> Optional[Span]:
        with self._lock:
            rec = self._live.get(key)
            return rec[0] if rec else None

    def annotate(self, key: str, **attrs):
        with self._lock:
            rec = self._live.get(key)
            if rec:
                rec[0].attrs.update(attrs)

    def stage(self, key: str, name: str, **attrs) -> Optional[Span]:
        """Open a named child of the key's root, closing any open stage;
        idempotent when the open stage already has this name."""
        with self._lock:
            rec = self._live.get(key)
            if rec is None:
                return None
            root, open_stage = rec
            if open_stage is not None:
                if open_stage.name == name:
                    return open_stage
                open_stage.finish()
            child = root.child(name, **attrs)
            rec[1] = child
            return child

    def stage_if_idle(self, key: str, name: str, **attrs) -> Optional[Span]:
        """Open a named child only when no OTHER stage is open — a pod
        mid-bind must not have its live stage clobbered by a watch-echo
        re-enqueue."""
        with self._lock:
            rec = self._live.get(key)
            if rec is None:
                return None
            root, open_stage = rec
            if open_stage is not None:
                return open_stage if open_stage.name == name else None
            child = root.child(name, **attrs)
            rec[1] = child
            return child

    def end_stage(self, key: str, metric: Optional[str] = None,
                  name: Optional[str] = None, **labels) -> Optional[Span]:
        """Close the open stage; with `name` given, only if it matches —
        the metric must never be fed some other stage's duration."""
        with self._lock:
            rec = self._live.get(key)
            if rec is None or rec[1] is None:
                return None
            child = rec[1]
            if name is not None and child.name != name:
                return None
            rec[1] = None
        child.finish(metric=metric, **labels)
        return child

    def finish(self, key: str, metric: Optional[str] = None,
               error: Optional[str] = None, **labels) -> Optional[Span]:
        with self._lock:
            rec = self._live.pop(key, None)
        if rec is None:
            return None
        root, open_stage = rec
        if open_stage is not None:
            open_stage.finish()
        if error is not None:
            root.attrs["error"] = error
        root.finish(metric=metric, **labels)
        if self._slow and root.duration >= self._slow:
            log.info("slow span %s:\n%s", key, "\n".join(root.tree_lines()))
        return root

    def discard(self, key: str):
        with self._lock:
            self._live.pop(key, None)


class Trace:
    """Named multi-step trace logged only when over threshold
    (generic_scheduler.go:71-77 semantics)."""

    def __init__(self, name: str, **fields):
        self.name = name
        self.fields = fields
        self.start = time.perf_counter()
        self.steps: List[Tuple[str, float]] = []

    @contextmanager
    def step(self, name: str):
        try:
            yield
        finally:
            self.steps.append((name, time.perf_counter()))

    def total_seconds(self) -> float:
        return time.perf_counter() - self.start

    def log_if_slow(self, threshold_seconds: float):
        total = self.total_seconds()
        if total < threshold_seconds:
            return
        parts = [f'"{self.name}" {self.fields}: total {total * 1000:.1f}ms']
        prev = self.start
        for name, at in self.steps:
            parts.append(f"  {name}: +{(at - prev) * 1000:.1f}ms")
            prev = at
        log.info("\n".join(parts))
