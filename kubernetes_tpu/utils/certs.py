"""Certificate plumbing for the secure serving stack.

Parity target: the reference's cert utilities behind --tls-cert-file /
--client-ca-file (pkg/genericapiserver, pkg/util/crypto): a minimal CA +
issuance helper used by tests, localup, and --tls-self-signed bring-up.
Identity convention matches the reference x509 authenticator
(plugin/pkg/auth/authenticator/request/x509): subject CN = user name,
subject O = group memberships.
"""

from __future__ import annotations

import datetime
import ipaddress
import os
from typing import List, Optional, Tuple


def _crypto():
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    return x509, hashes, serialization, ec


class CertAuthority:
    """An in-memory CA that can issue server and client certificates."""

    def __init__(self, common_name: str = "kubernetes-tpu-ca"):
        x509, hashes, serialization, ec = _crypto()
        self._x509 = x509
        self._hashes = hashes
        self._ser = serialization
        self.key = ec.generate_private_key(ec.SECP256R1())
        name = x509.Name([x509.NameAttribute(
            x509.oid.NameOID.COMMON_NAME, common_name)])
        now = datetime.datetime.now(datetime.timezone.utc)
        self.cert = (
            x509.CertificateBuilder()
            .subject_name(name).issuer_name(name)
            .public_key(self.key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=365))
            .add_extension(x509.BasicConstraints(ca=True, path_length=None),
                           critical=True)
            .sign(self.key, hashes.SHA256()))

    # -- issuance --------------------------------------------------------------

    def issue(self, common_name: str, organizations: Optional[List[str]] = None,
              dns_names: Optional[List[str]] = None,
              ips: Optional[List[str]] = None,
              server: bool = False) -> Tuple[bytes, bytes]:
        """(cert PEM, key PEM) with CN=common_name, O=organizations."""
        x509, hashes, serialization, ec = _crypto()
        key = ec.generate_private_key(ec.SECP256R1())
        attrs = [x509.NameAttribute(x509.oid.NameOID.COMMON_NAME, common_name)]
        for org in organizations or []:
            attrs.append(x509.NameAttribute(
                x509.oid.NameOID.ORGANIZATION_NAME, org))
        now = datetime.datetime.now(datetime.timezone.utc)
        builder = (
            x509.CertificateBuilder()
            .subject_name(x509.Name(attrs))
            .issuer_name(self.cert.subject)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=365))
            .add_extension(x509.BasicConstraints(ca=False, path_length=None),
                           critical=True)
            .add_extension(x509.ExtendedKeyUsage(
                [x509.oid.ExtendedKeyUsageOID.SERVER_AUTH] if server
                else [x509.oid.ExtendedKeyUsageOID.CLIENT_AUTH]),
                critical=False))
        sans = [x509.DNSName(d) for d in (dns_names or [])]
        sans += [x509.IPAddress(ipaddress.ip_address(ip))
                 for ip in (ips or [])]
        if sans:
            builder = builder.add_extension(
                x509.SubjectAlternativeName(sans), critical=False)
        cert = builder.sign(self.key, hashes.SHA256())
        return (cert.public_bytes(serialization.Encoding.PEM),
                key.private_bytes(
                    serialization.Encoding.PEM,
                    serialization.PrivateFormat.PKCS8,
                    serialization.NoEncryption()))

    def ca_pem(self) -> bytes:
        return self.cert.public_bytes(self._ser.Encoding.PEM)

    # -- file helpers ----------------------------------------------------------

    def write_bundle(self, directory: str, name: str, common_name: str,
                     organizations: Optional[List[str]] = None,
                     server: bool = False,
                     ips: Optional[List[str]] = None) -> dict:
        """Issue + write {name}.crt/.key and ca.crt under directory; returns
        the paths."""
        os.makedirs(directory, exist_ok=True)
        cert_pem, key_pem = self.issue(
            common_name, organizations,
            dns_names=["localhost"] if server else None,
            ips=ips or (["127.0.0.1"] if server else None), server=server)
        paths = {
            "cert": os.path.join(directory, f"{name}.crt"),
            "key": os.path.join(directory, f"{name}.key"),
            "ca": os.path.join(directory, "ca.crt"),
        }
        with open(paths["cert"], "wb") as f:
            f.write(cert_pem)
        with open(paths["key"], "wb") as f:
            f.write(key_pem)
        with open(paths["ca"], "wb") as f:
            f.write(self.ca_pem())
        return paths
