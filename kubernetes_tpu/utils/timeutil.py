"""Shared timestamp helpers (one format for server- and client-stamped
metadata/events)."""

import time


def now_iso() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
