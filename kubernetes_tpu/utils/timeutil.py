"""Shared timestamp helpers (one format for server- and client-stamped
metadata/events)."""

import calendar
import time
from typing import Optional


def now_iso() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def parse_iso(ts: Optional[str]) -> Optional[float]:
    """Inverse of now_iso: RFC3339 'Z' timestamp -> unix seconds (None on
    missing/unparseable input)."""
    if not ts:
        return None
    try:
        return calendar.timegm(time.strptime(ts, "%Y-%m-%dT%H:%M:%SZ"))
    except ValueError:
        return None
