"""Cross-cutting utilities.

Parity target: reference pkg/util — workqueue (+Parallelize), flowcontrol
(token bucket + backoff), wait (Until/Poll), clock injection, trace, metrics.
"""
