"""Client-side flow control.

Parity target: reference pkg/util/flowcontrol — the QPS+burst token bucket
every RESTClient passes requests through (restclient/config.go:96-103,
throttle.go) and the per-item exponential Backoff used by the scheduler's
pod requeue path (factory.go:503-539) and node controller.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional


class TokenBucket:
    """QPS rate limiter with burst. `accept()` blocks until a token is
    available (reference RateLimiter.Accept)."""

    def __init__(self, qps: float, burst: int, clock=time.monotonic):
        assert qps > 0 and burst >= 1
        self.qps = qps
        self.burst = burst
        self._clock = clock
        self._tokens = float(burst)
        self._last = clock()
        self._lock = threading.Lock()

    def _refill(self):
        now = self._clock()
        self._tokens = min(self.burst, self._tokens + (now - self._last) * self.qps)
        self._last = now

    def try_accept(self) -> bool:
        with self._lock:
            self._refill()
            if self._tokens >= 1:
                self._tokens -= 1
                return True
            return False

    def accept(self):
        while True:
            with self._lock:
                self._refill()
                if self._tokens >= 1:
                    self._tokens -= 1
                    return
                need = (1 - self._tokens) / self.qps
            time.sleep(min(need, 0.1))


class Backoff:
    """Per-key exponential backoff with a cap and idle reset
    (reference flowcontrol.Backoff; scheduler podBackoff uses
    initial=1s max=60s, factory.go:100)."""

    def __init__(self, initial: float = 1.0, maximum: float = 60.0,
                 clock=time.monotonic):
        self.initial = initial
        self.maximum = maximum
        self._clock = clock
        self._lock = threading.Lock()
        self._entries: Dict[str, tuple] = {}  # key -> (duration, last_update)

    def next(self, key: str) -> float:
        """Bump and return the backoff duration for key."""
        with self._lock:
            now = self._clock()
            dur, last = self._entries.get(key, (0.0, now))
            # idle longer than 2*max resets the entry (gc_expired analogue)
            if now - last > 2 * self.maximum:
                dur = 0.0
            dur = self.initial if dur == 0 else min(dur * 2, self.maximum)
            self._entries[key] = (dur, now)
            return dur

    def reset(self, key: str):
        with self._lock:
            self._entries.pop(key, None)

    def gc(self):
        with self._lock:
            now = self._clock()
            stale = [k for k, (_, last) in self._entries.items()
                     if now - last > 2 * self.maximum]
            for k in stale:
                del self._entries[k]
