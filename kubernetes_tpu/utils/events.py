"""Event recording: correlation, aggregation, and spam filtering.

Parity target: reference pkg/client/record — EventRecorder/EventBroadcaster
(event.go:96,112) plus the full events_cache.go correlation stack:

- **logger dedup** (events_cache.go:69-75): an exact repeat of the same
  (object, source, type, reason, message) becomes a count bump via PUT
  instead of a new Event object;
- **aggregation** (EventAggregator): more than `max_similar` events that
  differ ONLY in message within `similar_interval` collapse into one
  "(combined from similar events)" Event whose count keeps climbing — the
  control that keeps a crash-looping container from minting a distinct
  Event per iteration;
- **spam filtering** (EventSourceObjectSpamFilter): a token bucket per
  (source, object) drops events beyond `spam_burst` with a slow refill,
  so not even aggregated PUTs can melt the API server during a 5k-node
  "FailedScheduling" storm. Drops are visible as the
  `events_discarded_total` counter, emissions as `events_emitted_total`.

Every component (scheduler, kubelet, node/replication controllers) posts
through one of these recorders; `kubectl get events` / `describe` read the
result back from the apiserver.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from collections import OrderedDict, deque
from typing import Optional, Tuple

from kubernetes_tpu.api import types as api
from kubernetes_tpu.client.rest import ApiError, RESTClient
from kubernetes_tpu.utils.metrics import REGISTRY as METRICS
from kubernetes_tpu.utils.timeutil import now_iso as _now_iso

log = logging.getLogger("events")

# correlation cache caps (the reference's events_cache LRU analogues)
MAX_AGGREGATION_ENTRIES = 4096

# aggregation: > this many similar-but-for-message events inside the
# interval collapse onto one aggregate Event (events_cache.go maxEvents=10)
DEFAULT_MAX_SIMILAR = 10
DEFAULT_SIMILAR_INTERVAL = 600.0

# spam filter token bucket per (source, object): burst 25, one token back
# every 5 minutes (events_cache.go defaultSpamBurst/defaultSpamQPS)
DEFAULT_SPAM_BURST = 25
DEFAULT_SPAM_QPS = 1.0 / 300.0

AGGREGATED_PREFIX = "(combined from similar events): "

# local black-box ring of emitted events (post-correlation), independent of
# whether the API post succeeds — the flight recorder reads THIS, because a
# wedged control plane is exactly when reading Events back via the API fails
_RECENT_EVENTS: "deque[dict]" = deque(maxlen=1024)
_RECENT_EVENTS_LOCK = threading.Lock()


def _note_recent_event(component: str, kind: str, namespace: str, name: str,
                       etype: str, reason: str, message: str) -> None:
    with _RECENT_EVENTS_LOCK:
        _RECENT_EVENTS.append({
            "ts": _now_iso(), "component": component, "kind": kind,
            "namespace": namespace, "name": name, "type": etype,
            "reason": reason, "message": message})


def recent_events(n: int = 256) -> list:
    """Newest-last tail of locally emitted events (dicts, JSON-ready)."""
    with _RECENT_EVENTS_LOCK:
        return list(_RECENT_EVENTS)[-n:]


class EventCorrelator:
    """Decides, for each observed event, whether it should be dropped
    (spam), aggregated (similar storm), or recorded as-is — and under which
    dedup identity repeats bump a count instead of minting a new Event."""

    def __init__(self, clock=time.monotonic,
                 max_similar: int = DEFAULT_MAX_SIMILAR,
                 similar_interval: float = DEFAULT_SIMILAR_INTERVAL,
                 spam_burst: int = DEFAULT_SPAM_BURST,
                 spam_qps: float = DEFAULT_SPAM_QPS,
                 cache_size: int = MAX_AGGREGATION_ENTRIES):
        self._clock = clock
        self._max_similar = max_similar
        self._similar_interval = similar_interval
        self._spam_burst = spam_burst
        self._spam_qps = spam_qps
        self._cache_size = cache_size
        self._lock = threading.Lock()
        # (source, object) -> [tokens, last refill time]
        self._spam: "OrderedDict[Tuple, list]" = OrderedDict()
        # similarity key (everything but message) -> [distinct message set,
        # window start] — the reference aggregator's localKeys: only
        # DISTINCT messages advance toward aggregation, exact repeats are
        # the logger-dedup path's job
        self._similar: "OrderedDict[Tuple, list]" = OrderedDict()

    def _cap(self, cache: OrderedDict):
        while len(cache) > self._cache_size:
            cache.popitem(last=False)

    def correlate(self, source_key: Tuple, similarity_key: Tuple,
                  message: str,
                  signature: Optional[Tuple] = None
                  ) -> Optional[Tuple[Tuple, str, bool]]:
        """Returns (dedup key, message to record, aggregated?) — or None when
        the spam filter drops the event.

        `signature` (optional) replaces the raw message in BOTH the dedup
        identity and the distinct-variant count: events whose messages
        differ but share a signature (e.g. the scheduler's per-predicate
        elimination histogram SHAPE, whose counts drift as the cluster
        churns) bump one Event's count instead of minting new objects —
        richer ledger-derived messages must not defeat the storm dedup."""
        now = self._clock()
        variant = signature if signature is not None else message
        with self._lock:
            tokens, last = self._spam.get(source_key, (self._spam_burst, now))
            tokens = min(self._spam_burst,
                         tokens + (now - last) * self._spam_qps)
            if tokens < 1.0:
                self._spam[source_key] = [tokens, now]
                self._spam.move_to_end(source_key)
                return None
            self._spam[source_key] = [tokens - 1.0, now]
            self._spam.move_to_end(source_key)
            self._cap(self._spam)

            rec = self._similar.get(similarity_key)
            if rec is None or now - rec[1] > self._similar_interval:
                rec = [set(), now]
            if len(rec[0]) <= self._max_similar:
                rec[0].add(variant)
            self._similar[similarity_key] = rec
            self._similar.move_to_end(similarity_key)
            self._cap(self._similar)
            if len(rec[0]) > self._max_similar:
                # storm of similar events: they all collapse onto ONE
                # aggregate identity regardless of message
                return similarity_key, AGGREGATED_PREFIX + message, True
            return similarity_key + (variant,), message, False


class EventRecorder:
    """`event(obj, type, reason, message)` — async fire-and-forget like the
    reference broadcaster (a blocked event sink must never stall the
    scheduler loop)."""

    def __init__(self, client: RESTClient, source_component: str,
                 source_host: str = "",
                 correlator: Optional[EventCorrelator] = None):
        self.client = client
        self.source = api.EventSource(component=source_component,
                                      host=source_host)
        self.correlator = correlator or EventCorrelator()
        # dedup key -> (event name, count); LRU-capped so long-running
        # components don't grow without bound
        self._seen: "OrderedDict[Tuple, Tuple[str, int]]" = OrderedDict()
        self._q: "queue.Queue" = queue.Queue()
        self._thread = threading.Thread(target=self._pump,
                                        name="event-recorder", daemon=True)
        self._started = False
        self._lock = threading.Lock()

    def event(self, obj, etype: str, reason: str, message: str,
              signature: Optional[Tuple] = None):
        with self._lock:
            if not self._started:
                self._thread.start()
                self._started = True
        self._q.put((obj, etype, reason, message, signature))

    def flush(self, timeout: float = 5.0):
        """Best-effort wait for queued events to be posted (tests)."""
        deadline = time.monotonic() + timeout
        while not self._q.empty() and time.monotonic() < deadline:
            time.sleep(0.01)

    def _pump(self):
        while True:
            obj, etype, reason, message, signature = self._q.get()
            try:
                self._record(obj, etype, reason, message, signature)
            except Exception as e:
                log.warning("event post failed: %s", e)

    def _record(self, obj, etype: str, reason: str, message: str,
                signature: Optional[Tuple] = None):
        meta = obj.metadata
        ref = api.ObjectReference(
            kind=type(obj).__name__, namespace=meta.namespace, name=meta.name,
            uid=meta.uid, resource_version=meta.resource_version)
        source_key = (self.source.component, self.source.host,
                      ref.kind, ref.namespace, ref.name, ref.uid)
        similarity_key = (ref.kind, ref.namespace, ref.name, etype, reason)
        hit = self.correlator.correlate(source_key, similarity_key, message,
                                        signature=signature)
        if hit is None:
            METRICS.inc("events_discarded_total",
                        component=self.source.component)
            return
        dedup_key, message, _aggregated = hit
        METRICS.inc("events_emitted_total", component=self.source.component)
        _note_recent_event(self.source.component, ref.kind,
                           ref.namespace or "", ref.name or "",
                           etype, reason, message)
        ns = meta.namespace or "default"
        existing = self._seen.get(dedup_key)
        if existing is not None:
            name, count = existing
            try:
                ev = self.client.get("events", name, ns)
                ev.count = count + 1
                ev.last_timestamp = _now_iso()
                if message != ev.message:
                    ev.message = message
                self.client.update("events", ev, ns)
                self._seen[dedup_key] = (name, count + 1)
                self._seen.move_to_end(dedup_key)
                return
            except ApiError:
                pass  # fall through to create
        now = _now_iso()
        name = f"{meta.name}.{int(time.time() * 1e6):x}"
        ev = api.Event(
            metadata=api.ObjectMeta(name=name, namespace=ns),
            involved_object=ref, reason=reason, message=message,
            source=self.source, type=etype,
            first_timestamp=now, last_timestamp=now, count=1)
        self.client.create("events", ev, ns)
        self._seen[dedup_key] = (name, 1)
        self._seen.move_to_end(dedup_key)
        while len(self._seen) > MAX_AGGREGATION_ENTRIES:
            self._seen.popitem(last=False)
