"""Minimal cron schedule parser for the scheduled-job controller.

Parity target: the cron syntax the reference's scheduledjob controller accepts
via github.com/robfig/cron (5 fields: minute hour day-of-month month
day-of-week; each a '*', '*/step', value, range 'a-b', or comma list).
"""

from __future__ import annotations

import calendar
import time
from typing import Set, Tuple

_FIELD_RANGES: Tuple[Tuple[int, int], ...] = (
    (0, 59),   # minute
    (0, 23),   # hour
    (1, 31),   # day of month
    (1, 12),   # month
    (0, 6),    # day of week (0=Sunday)
)


class CronParseError(ValueError):
    pass


def _parse_field(expr: str, lo: int, hi: int) -> Set[int]:
    out: Set[int] = set()
    for part in expr.split(","):
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            try:
                step = int(step_s)
            except ValueError:
                raise CronParseError(f"bad step {step_s!r}")
            if step <= 0:
                raise CronParseError(f"step must be positive: {step}")
        if part == "*":
            start, end = lo, hi
        elif part == "":
            raise CronParseError("empty field part")
        elif "-" in part:
            a, b = part.split("-", 1)
            try:
                start, end = int(a), int(b)
            except ValueError:
                raise CronParseError(f"bad range {part!r}")
        else:
            try:
                start = end = int(part)
            except ValueError:
                raise CronParseError(f"bad value {part!r}")
        if not (lo <= start <= hi and lo <= end <= hi and start <= end):
            raise CronParseError(f"value out of range [{lo},{hi}]: {part!r}")
        out.update(range(start, end + 1, step))
    return out


class Schedule:
    def __init__(self, spec: str):
        fields = spec.split()
        if len(fields) != 5:
            raise CronParseError(
                f"expected 5 cron fields, got {len(fields)}: {spec!r}")
        (self.minutes, self.hours, self.dom, self.months, self.dow) = (
            _parse_field(f, lo, hi)
            for f, (lo, hi) in zip(fields, _FIELD_RANGES))
        # day fields beginning with '*' (including '*/n') carry the star bit:
        # standard (robfig) cron ORs dom/dow only when BOTH lack it
        self.dom_star = fields[2].startswith("*")
        self.dow_star = fields[4].startswith("*")

    def _day_matches(self, tm: time.struct_time) -> bool:
        dom_ok = tm.tm_mday in self.dom
        dow_ok = ((tm.tm_wday + 1) % 7) in self.dow  # struct_time: Mon=0
        # robfig/cron: day fields combine with OR only when BOTH are
        # restricted (no star bit); otherwise both must match — a pure '*'
        # matches every day anyway, while '*/2' still restricts
        if self.dom_star or self.dow_star:
            return dom_ok and dow_ok
        return dom_ok or dow_ok

    def matches(self, epoch: float) -> bool:
        tm = time.gmtime(epoch)
        return (tm.tm_min in self.minutes and tm.tm_hour in self.hours
                and tm.tm_mon in self.months and self._day_matches(tm))

    def next_after(self, epoch: float, horizon_days: int = 366 * 2) -> float:
        """First matching minute strictly after `epoch` (UTC). Raises if none
        within the horizon (e.g. Feb 30). Skips by day/hour when those fields
        don't match, so the scan is cheap even for sparse schedules."""
        t = (int(epoch) // 60 + 1) * 60  # next minute boundary
        deadline = t + horizon_days * 86400
        while t < deadline:
            tm = time.gmtime(t)
            if not (tm.tm_mon in self.months and self._day_matches(tm)):
                t = (int(t) // 86400 + 1) * 86400  # next midnight
                continue
            if tm.tm_hour not in self.hours:
                t = (int(t) // 3600 + 1) * 3600  # next hour
                continue
            if tm.tm_min in self.minutes:
                return float(t)
            t += 60
        raise CronParseError("no matching time within horizon")


def parse(spec: str) -> Schedule:
    return Schedule(spec)


def timegm(tm) -> float:
    return float(calendar.timegm(tm))
