"""Work queues for controllers.

Parity target: reference pkg/util/workqueue — the deduplicating Type
(queue.go: an item re-added while processing is re-queued, not duplicated),
DelayingQueue (delaying_queue.go), RateLimitingQueue
(rate_limitting_queue.go with the default exponential per-item +
overall-token-bucket limiter, default_rate_limiters.go), and
Parallelize (parallelizer.go:17-48) — the 16-way helper the scheduler's
filter stage used, re-expressed on-device in ops/ but kept here for host-side
controller fan-out.
"""

from __future__ import annotations

import heapq
import threading
import time
from typing import Callable, Optional

from kubernetes_tpu.utils.flowcontrol import Backoff


class WorkQueue:
    """Deduplicating FIFO of hashable items with in-flight tracking:
    `add` while an item is processing marks it dirty for reprocessing after
    `done` (reference workqueue.Type semantics).

    A named queue exports the reference's workqueue SLIs
    (prometheus adapter of workqueue.go): `workqueue_depth{queue}`,
    `workqueue_queue_latency_seconds{queue}` (add -> get) and
    `workqueue_work_duration_seconds{queue}` (get -> done)."""

    def __init__(self, name: str = ""):
        self._cond = threading.Condition()
        self._queue: list = []
        self._queued: set = set()
        self._processing: set = set()
        self._dirty: set = set()
        self._shutdown = False
        self.name = name
        self._added_at: dict = {}
        self._started_at: dict = {}

    def _set_depth(self):
        if self.name:
            from kubernetes_tpu.utils.metrics import REGISTRY
            REGISTRY.set_gauge("workqueue_depth", len(self._queue),
                               queue=self.name)

    def add(self, item):
        with self._cond:
            if self._shutdown or item in self._queued:
                return
            if item in self._processing:
                self._dirty.add(item)
                return
            self._queued.add(item)
            self._queue.append(item)
            if self.name:
                self._added_at.setdefault(item, time.monotonic())
                self._set_depth()
            self._cond.notify()

    def get(self, timeout: Optional[float] = None):
        """Block for the next item; None on shutdown/timeout. Caller must
        call done(item)."""
        with self._cond:
            while not self._queue and not self._shutdown:
                if not self._cond.wait(timeout=timeout):
                    return None
            if not self._queue:
                return None
            item = self._queue.pop(0)
            self._queued.discard(item)
            self._processing.add(item)
            if self.name:
                from kubernetes_tpu.utils.metrics import REGISTRY
                now = time.monotonic()
                added = self._added_at.pop(item, None)
                if added is not None:
                    REGISTRY.observe("workqueue_queue_latency_seconds",
                                     now - added, queue=self.name)
                self._started_at[item] = now
                self._set_depth()
            return item

    def done(self, item):
        with self._cond:
            self._processing.discard(item)
            if self.name:
                started = self._started_at.pop(item, None)
                if started is not None:
                    from kubernetes_tpu.utils.metrics import REGISTRY
                    REGISTRY.observe("workqueue_work_duration_seconds",
                                     time.monotonic() - started,
                                     queue=self.name)
            if item in self._dirty:
                self._dirty.discard(item)
                self._queued.add(item)
                self._queue.append(item)
                if self.name:
                    self._added_at.setdefault(item, time.monotonic())
                    self._set_depth()
                self._cond.notify()

    def shutdown(self):
        with self._cond:
            self._shutdown = True
            self._cond.notify_all()

    def __len__(self):
        with self._cond:
            return len(self._queue)


class DelayingQueue(WorkQueue):
    """add_after(item, delay): deliver after delay via a waiting thread and
    a heap (reference delaying_queue.go)."""

    def __init__(self, clock=time.monotonic, name: str = ""):
        super().__init__(name=name)
        self._clock = clock
        self._heap: list = []
        self._heap_cond = threading.Condition()
        self._seq = 0
        self._waiter = threading.Thread(target=self._wait_loop,
                                        name="delaying-queue", daemon=True)
        self._waiter_started = False

    def add_after(self, item, delay: float):
        if delay <= 0:
            self.add(item)
            return
        with self._heap_cond:
            if not self._waiter_started:
                self._waiter.start()
                self._waiter_started = True
            self._seq += 1
            heapq.heappush(self._heap, (self._clock() + delay, self._seq, item))
            self._heap_cond.notify()

    def _wait_loop(self):
        while True:
            with self._heap_cond:
                while not self._heap:
                    self._heap_cond.wait()
                at, _, item = self._heap[0]
                now = self._clock()
                if at > now:
                    self._heap_cond.wait(timeout=at - now)
                    continue
                heapq.heappop(self._heap)
            self.add(item)


class RateLimitingQueue(DelayingQueue):
    """add_rate_limited(item) delays by a per-item exponential backoff;
    forget(item) resets it (reference rate_limitting_queue.go with the
    ItemExponentialFailureRateLimiter)."""

    def __init__(self, base_delay: float = 0.005, max_delay: float = 1000.0,
                 clock=time.monotonic, name: str = ""):
        super().__init__(clock=clock, name=name)
        self._backoff = Backoff(initial=base_delay, maximum=max_delay, clock=clock)

    def add_rate_limited(self, item):
        self.add_after(item, self._backoff.next(_key(item)))

    def forget(self, item):
        self._backoff.reset(_key(item))


def _key(item) -> str:
    return str(item)


def parallelize(workers: int, pieces: int, do_piece: Callable[[int], None]):
    """Run do_piece(0..pieces-1) on `workers` threads
    (reference parallelizer.go:29)."""
    if pieces <= 0:
        return
    it = iter(range(pieces))
    lock = threading.Lock()

    def worker():
        while True:
            with lock:
                i = next(it, None)
            if i is None:
                return
            do_piece(i)

    threads = [threading.Thread(target=worker, daemon=True)
               for _ in range(min(workers, pieces))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
