"""Strategic merge patch (dict form).

Parity target: reference pkg/util/strategicpatch/patch.go — the three-way
merge `kubectl apply` performs. Semantics implemented:

  - maps merge recursively; a key set to None in the patch deletes it
  - lists of maps that carry a merge key (containers/ports/volumes/env -> by
    name; no struct tags here, so the well-known merge keys are a table)
    merge element-wise by key; other lists REPLACE wholesale
  - three-way: changes = diff(original, modified) plus deletions for keys in
    original missing from modified; then patch applied to current
"""

from __future__ import annotations

import copy
from typing import Any, Dict, List, Optional

# patch MIME types (reference pkg/api/types.go PatchType) — the one
# definition both the apiserver handler and the REST client import
STRATEGIC_PATCH_TYPE = "application/strategic-merge-patch+json"
MERGE_PATCH_TYPE = "application/merge-patch+json"

# field name -> merge key (reference struct tags patchMergeKey)
MERGE_KEYS = {
    "containers": "name",
    "volumes": "name",
    "env": "name",
    "ports": "containerPort",
    "volumeMounts": "mountPath",
    "subsets": None,  # replace
}


def create_two_way_merge_patch(original: Dict, modified: Dict) -> Dict:
    """Patch that turns original into modified."""
    patch: Dict[str, Any] = {}
    for k, mv in modified.items():
        ov = original.get(k)
        if k not in original:
            patch[k] = copy.deepcopy(mv)
        elif isinstance(ov, dict) and isinstance(mv, dict):
            sub = create_two_way_merge_patch(ov, mv)
            if sub:
                patch[k] = sub
        elif (isinstance(ov, list) and isinstance(mv, list)
              and _mergeable(k, ov + mv)):
            sub_list = _list_diff(ov, mv, _merge_key_for(k))
            if sub_list:
                patch[k] = sub_list
        elif ov != mv:
            patch[k] = copy.deepcopy(mv)
    for k in original:
        if k not in modified:
            patch[k] = None  # deletion directive
    return patch


def _list_diff(original: List[Dict], modified: List[Dict],
               key: str) -> List[Dict]:
    """Element-wise patch for a merge-keyed list: changed/new elements plus
    `{"$patch": "delete", key: v}` directives for removed ones (reference
    patch.go diffLists)."""
    out: List[Dict] = []
    orig_by_key = {e.get(key): e for e in original if e.get(key) is not None}
    mod_keys = {e.get(key) for e in modified}
    for me in modified:
        mk = me.get(key)
        oe = orig_by_key.get(mk)
        if oe is None:
            out.append(copy.deepcopy(me))
            continue
        sub = create_two_way_merge_patch(oe, me)
        if sub:
            sub[key] = mk  # the merge key always rides along
            out.append(sub)
    for ok in orig_by_key:
        if ok not in mod_keys:
            out.append({"$patch": "delete", key: ok})
    return out


def apply_patch(current: Dict, patch: Dict) -> Dict:
    out = copy.deepcopy(current)
    for k, pv in patch.items():
        if pv is None:
            out.pop(k, None)
            continue
        cv = out.get(k)
        if isinstance(pv, dict) and isinstance(cv, dict):
            out[k] = apply_patch(cv, pv)
        elif isinstance(pv, list) and isinstance(cv, list) and \
                _mergeable(k, cv + pv):
            out[k] = _merge_lists(cv, pv, _merge_key_for(k))
        else:
            # target key absent (or scalar): the patch subtree becomes the
            # value, minus its deletion directives — a {k: null} delete of a
            # key inside an absent map must not store a literal null, and a
            # $patch:delete element must not survive as data
            out[k] = _strip_directives(pv)
    return out


def _strip_directives(v):
    """Deep-copy a patch subtree with deletion directives executed against
    nothing: null map values drop, $patch-delete list elements drop."""
    if isinstance(v, dict):
        return {k: _strip_directives(sv) for k, sv in v.items()
                if sv is not None}
    if isinstance(v, list):
        return [_strip_directives(e) for e in v
                if not (isinstance(e, dict) and e.get("$patch") == "delete")]
    return copy.deepcopy(v)


def three_way_merge(original: Dict, modified: Dict, current: Dict) -> Dict:
    """What `kubectl apply` computes: apply (original->modified) changes on
    top of current, preserving fields others set on current."""
    patch = create_two_way_merge_patch(original, modified)
    return apply_patch(current, patch)


def json_merge_patch(target, patch):
    """RFC 7386 merge patch (reference application/merge-patch+json,
    resthandler.go:503 JSONPatchType switch): recursive map merge, null
    deletes, everything else — including lists — replaces wholesale."""
    if not isinstance(patch, dict):
        return copy.deepcopy(patch)
    out = dict(target) if isinstance(target, dict) else {}
    for k, v in patch.items():
        if v is None:
            out.pop(k, None)
        else:
            out[k] = json_merge_patch(out.get(k), v)
    return out


def _merge_key_for(field: str) -> Optional[str]:
    return MERGE_KEYS.get(field)


def _mergeable(field: str, elements: List) -> bool:
    """Merge-by-key applies only when EVERY element is a dict carrying the
    key — e.g. Service ports have 'port' not 'containerPort', so a
    same-named 'ports' field without the key falls back to whole-list
    replacement instead of appending duplicates."""
    key = _merge_key_for(field)
    return bool(key) and all(
        isinstance(e, dict) and e.get(key) is not None
        or (isinstance(e, dict) and e.get("$patch") == "delete")
        for e in elements)


def _merge_lists(current: List, patch: List, key: str) -> List:
    """Element-wise merge of lists of dicts by merge key; patch order wins
    for new elements, current order preserved for existing ones."""
    if not all(isinstance(e, dict) for e in list(current) + list(patch)):
        return copy.deepcopy(patch)
    out = copy.deepcopy(current)
    for pe in patch:
        pk = pe.get(key)
        if pe.get("$patch") == "delete":
            out = [e for e in out if e.get(key) != pk]
            continue
        idx = next((i for i, e in enumerate(out)
                    if pk is not None and e.get(key) == pk), None)
        if idx is not None:
            out[idx] = apply_patch(out[idx], pe)
        else:
            out.append(copy.deepcopy(pe))
    return out
