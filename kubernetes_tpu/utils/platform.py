"""Backend platform helpers.

The environment's axon sitecustomize force-registers the TPU platform at
interpreter startup whenever PALLAS_AXON_POOL_IPS is set, and its
jax.config.update beats the JAX_PLATFORMS env var — so forcing CPU requires
updating the live config AND dropping any initialized backends. Every
CPU-only entrypoint (tests/conftest.py, bench.py's fallback, direct drives)
shares this dance here instead of hand-copying it.
"""

import hashlib
import logging
import os
import re

# live persistent-cache state (set by enable_persistent_compilation_cache);
# the compile-cache hit/miss/rejection accounting reads it
_CACHE_STATE = {"dir": "", "fingerprint": ""}

_MACHINE_MARKER = "MACHINE_FEATURES"


def _label_safe(s: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.-]+", "_", s) or "unknown"


def machine_fingerprint(include_device: bool = False) -> str:
    """Device + host machine-feature fingerprint keying AOT compile-cache
    entries (the round-5 failure mode: an artifact compiled for different
    machine features loaded and wedged the CPU fallback for 600 s).

    Host features only by default — computing the fingerprint must never
    initialize a jax backend (backend init is itself a hang risk). Pass
    include_device=True only when a backend is known-live (e.g. right after
    a successful dispatch) to refine the label with the device kind.
    """
    import platform as _p

    feats = [_p.machine(), _p.system(),
             os.environ.get("JAX_PLATFORMS", ""),
             "axon" if os.environ.get("PALLAS_AXON_POOL_IPS") else "host"]
    device = ""
    try:
        import jax
        feats.append(jax.__version__)
        if include_device:
            d = jax.devices()[0]
            device = getattr(d, "device_kind", "") or d.platform
            feats.append(device)
    except Exception as e:
        # fingerprint degrades to host features only — say which import or
        # device probe failed so a wrong-platform cache key is explainable
        logging.getLogger("platform").debug(
            "machine fingerprint: jax features unavailable: %s", e)
    tag = _label_safe("-".join(
        t for t in (_p.machine(),
                    os.environ.get("JAX_PLATFORMS") or "auto", device) if t))
    return f"{tag}-{hashlib.sha1('|'.join(feats).encode()).hexdigest()[:8]}"


def compile_cache_dir() -> str:
    return _CACHE_STATE["dir"]


def compile_cache_snapshot():
    """Entry listing of the live persistent cache dir (None when disabled) —
    the 'before' side of record_compile_cache_event."""
    d = _CACHE_STATE["dir"]
    if not d:
        return None
    try:
        return frozenset(os.listdir(d))
    except OSError:
        return None


def record_compile_cache_event(before, registry=None) -> str:
    """Classify the compile that just ran against the persistent cache and
    tick `compile_cache_events_total{event,fingerprint}`. A dispatch that
    persisted a new entry is a miss; one that wrote nothing against a
    NON-EMPTY cache was (almost certainly — a sub-threshold compile is
    indistinguishable) served from it: hit; nothing written against an
    EMPTY cache cannot be a hit and is "uncached" (compile below the
    persistence threshold); no cache dir means disabled. Returns the
    event label."""
    if registry is None:
        from kubernetes_tpu.utils.metrics import REGISTRY as registry
    after = compile_cache_snapshot()
    if before is None or after is None:
        event = "disabled"
    elif after - before:
        event = "miss"
    elif any(e != _MACHINE_MARKER for e in before):
        event = "hit"
    else:
        event = "uncached"
    # label with the fingerprint that KEYS the live cache (the marker file /
    # directory name), so hit/miss/rejected series for one cache identity
    # join on one label value
    fp = _CACHE_STATE["fingerprint"] or machine_fingerprint()
    registry.inc("compile_cache_events_total", event=event, fingerprint=fp)
    return event


def clear_backends_compat():
    try:
        from jax.extend.backend import clear_backends
    except ImportError:  # older jax layouts
        from jax._src.api import clear_backends  # type: ignore
    clear_backends()


def enable_persistent_compilation_cache(path: str = "") -> str:
    """Point XLA's persistent compilation cache at a durable directory so a
    scheduler restart reuses the compiled 30k-step scan instead of paying
    the ~30s cold compile again (round-4 verdict #4: restart-to-first-
    binding must be seconds, not the compile time).

    The cache key includes program HLO + compile options + backend, so a
    kernel/feature/shape change misses cleanly. Entries are additionally
    keyed by the HOST machine-feature fingerprint: each fingerprint gets its
    own subdirectory, so an AOT artifact compiled on different machine
    features can never be loaded here (the round-5 0.0-pods/s failure), and
    a marker file validates the directory on every enable — a mismatch is
    counted as `compile_cache_events_total{event="rejected"}` and the stale
    entries are dropped. Returns the directory."""
    import shutil

    import jax

    from kubernetes_tpu.utils.metrics import REGISTRY as _METRICS

    root = (path or os.environ.get("KTPU_XLA_CACHE")
            or os.path.join(os.path.expanduser("~"), ".cache",
                            "kubernetes-tpu-xla"))
    fp = machine_fingerprint()
    os.makedirs(root, exist_ok=True)
    # pre-fingerprint layouts put artifacts directly in the root; they can't
    # be validated against machine features, so they are rejected — never
    # loaded (jax is pointed at the fingerprint subdir) and left in place:
    # the root may be a user-chosen shared directory (KTPU_XLA_CACHE), and
    # deleting files there that we didn't write would be data loss
    legacy = [e for e in os.listdir(root)
              if not os.path.isdir(os.path.join(root, e))]
    if legacy:
        _METRICS.inc("compile_cache_events_total",
                     event="rejected", fingerprint=fp)
        import logging
        logging.getLogger("platform").warning(
            "compile cache root %s holds %d unvalidated pre-fingerprint "
            "entries; ignoring them (rejected)", root, len(legacy))
    cache_dir = os.path.join(root, fp)
    os.makedirs(cache_dir, exist_ok=True)
    marker = os.path.join(cache_dir, _MACHINE_MARKER)
    stamped = ""
    if os.path.exists(marker):
        with open(marker) as f:
            stamped = f.read().strip()
    if stamped and stamped != fp:
        # a foreign-machine cache under our fingerprint path (copied dirs,
        # changed env): reject and start clean rather than load it
        _METRICS.inc("compile_cache_events_total", event="rejected",
                     fingerprint=fp)
        shutil.rmtree(cache_dir, ignore_errors=True)
        os.makedirs(cache_dir, exist_ok=True)
    with open(marker, "w") as f:
        f.write(fp + "\n")
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_enable_compilation_cache", True)
    # the scan kernel is the whole point: cache anything non-trivial
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    _CACHE_STATE["dir"] = cache_dir
    _CACHE_STATE["fingerprint"] = fp
    return cache_dir


def force_cpu(device_count: int = 0):
    """Pin jax to the host CPU platform, optionally with N virtual devices.
    Safe to call before or after jax's first import; must run before the
    first device op."""
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    if device_count:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags +
                f" --xla_force_host_platform_device_count={device_count}"
            ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    clear_backends_compat()
    return jax
