"""Backend platform helpers.

The environment's axon sitecustomize force-registers the TPU platform at
interpreter startup whenever PALLAS_AXON_POOL_IPS is set, and its
jax.config.update beats the JAX_PLATFORMS env var — so forcing CPU requires
updating the live config AND dropping any initialized backends. Every
CPU-only entrypoint (tests/conftest.py, bench.py's fallback, direct drives)
shares this dance here instead of hand-copying it.
"""

import os


def clear_backends_compat():
    try:
        from jax.extend.backend import clear_backends
    except ImportError:  # older jax layouts
        from jax._src.api import clear_backends  # type: ignore
    clear_backends()


def enable_persistent_compilation_cache(path: str = "") -> str:
    """Point XLA's persistent compilation cache at a durable directory so a
    scheduler restart reuses the compiled 30k-step scan instead of paying
    the ~30s cold compile again (round-4 verdict #4: restart-to-first-
    binding must be seconds, not the compile time).

    The cache key includes program HLO + compile options + backend, so a
    kernel/feature/shape change misses cleanly. Returns the directory."""
    import jax

    cache_dir = (path or os.environ.get("KTPU_XLA_CACHE")
                 or os.path.join(os.path.expanduser("~"), ".cache",
                                 "kubernetes-tpu-xla"))
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_enable_compilation_cache", True)
    # the scan kernel is the whole point: cache anything non-trivial
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    return cache_dir


def force_cpu(device_count: int = 0):
    """Pin jax to the host CPU platform, optionally with N virtual devices.
    Safe to call before or after jax's first import; must run before the
    first device op."""
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    if device_count:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags +
                f" --xla_force_host_platform_device_count={device_count}"
            ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    clear_backends_compat()
    return jax
