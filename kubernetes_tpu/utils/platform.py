"""Backend platform helpers.

The environment's axon sitecustomize force-registers the TPU platform at
interpreter startup whenever PALLAS_AXON_POOL_IPS is set, and its
jax.config.update beats the JAX_PLATFORMS env var — so forcing CPU requires
updating the live config AND dropping any initialized backends. Every
CPU-only entrypoint (tests/conftest.py, bench.py's fallback, direct drives)
shares this dance here instead of hand-copying it.
"""

import os


def clear_backends_compat():
    try:
        from jax.extend.backend import clear_backends
    except ImportError:  # older jax layouts
        from jax._src.api import clear_backends  # type: ignore
    clear_backends()


def force_cpu(device_count: int = 0):
    """Pin jax to the host CPU platform, optionally with N virtual devices.
    Safe to call before or after jax's first import; must run before the
    first device op."""
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    if device_count:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags +
                f" --xla_force_host_platform_device_count={device_count}"
            ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    clear_backends_compat()
    return jax
