"""Per-component debug mux: /healthz, /metrics, /configz, /profilez.

Every reference component serves this trio on its own port (scheduler on
:10251 — plugin/cmd/kube-scheduler/app/server.go:92-108; /configz from
pkg/util/configz exposes the component's live versioned configuration).
The component entrypoints (__main__ modules) mount their componentconfig
object here, closing the round-3 finding that the config types were
consumed by nothing.

/profilez (the pprof-endpoint analogue, backed by jax.profiler via
observability/profiling.py) opens/closes a device trace window on the
LIVE component: GET /profilez for status, /profilez/start?dir=... to open,
/profilez/stop to close and learn where the trace landed.
"""

from __future__ import annotations

import json
import logging
import threading
from dataclasses import asdict, is_dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

from kubernetes_tpu.utils.metrics import REGISTRY as METRICS


def render_configz(configz: Dict[str, object]) -> dict:
    """JSON-ready /configz payload (shared with the apiserver's route)."""
    return {name: (asdict(o) if is_dataclass(o) else o)
            for name, o in configz.items()}


def debug_route(path: str, healthz: Callable[[], bool],
                configz: Dict[str, object]):
    """Shared /healthz /metrics /configz /profilez handling for every
    component server (DebugServer + the kubelet node server). Returns
    (code, body bytes, content-type) or None when the path isn't a debug
    route."""
    from urllib.parse import parse_qs, urlsplit

    parts = urlsplit(path)
    query = parse_qs(parts.query)
    path = parts.path
    if path == "/profilez" or path.startswith("/profilez/"):
        return _profilez(path, query)
    if path in ("/healthz", "/healthz/ping"):
        ok = False
        try:
            ok = healthz()
        except Exception:
            # a crashing health callback IS unhealthy, but the probe reply
            # must not hide why
            logging.getLogger("debugserver").exception(
                "healthz callback raised; reporting unhealthy")
        return (200 if ok else 500, b"ok" if ok else b"unhealthy",
                "text/plain")
    if path == "/metrics":
        return 200, METRICS.render().encode(), "text/plain"
    if path == "/configz":
        return (200, json.dumps(render_configz(configz)).encode(),
                "application/json")
    if path == "/auditz":
        # tail of the process-wide audit ring (the apiserver writes it;
        # every component's mux can serve it, mirroring /metrics)
        from kubernetes_tpu.observability.audit import AUDIT, render_auditz
        n = (query.get("n") or [None])[0]
        return (200, json.dumps(render_auditz(AUDIT, n)).encode(),
                "application/json")
    if path == "/explainz":
        # the scheduler decision ledger: per-pod why/why-not provenance
        # (?pod=ns/name for one pod's latest decision, ?n= for the tail)
        from kubernetes_tpu.observability.explain import (
            LEDGER, render_explainz,
        )
        pod = (query.get("pod") or [None])[0]
        n = (query.get("n") or [None])[0]
        return (200, json.dumps(render_explainz(LEDGER, pod=pod, n=n)).encode(),
                "application/json")
    return None


def _profilez(path: str, query: Dict[str, list]):
    """Open/close/inspect a live jax profiler trace window."""
    from kubernetes_tpu.observability import profiling

    action = path[len("/profilez"):].strip("/") or "status"
    try:
        if action == "status":
            body = profiling.profile_status()
        elif action == "start":
            body = profiling.start_profile(
                (query.get("dir") or [""])[0])
        elif action == "stop":
            body = profiling.stop_profile()
        else:
            return (404, json.dumps(
                {"error": f"unknown profilez action {action!r}"}).encode(),
                "application/json")
    except RuntimeError as e:
        # start-while-open / stop-while-idle: caller error, not a crash
        return (409, json.dumps({"error": str(e)}).encode(),
                "application/json")
    except Exception as e:
        logging.getLogger("debugserver").exception("profilez %s failed",
                                                   action)
        return (500, json.dumps({"error": repr(e)}).encode(),
                "application/json")
    return 200, json.dumps(body).encode(), "application/json"


class DebugServer:
    """healthz/metrics/configz endpoint bundle for a component process."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 healthz: Optional[Callable[[], bool]] = None,
                 configz: Optional[Dict[str, object]] = None):
        self._host = host
        self._port = port
        self.healthz = healthz or (lambda: True)
        self.configz: Dict[str, object] = dict(configz or {})
        self._httpd = None
        self._thread = None

    def register_config(self, name: str, obj) -> None:
        self.configz[name] = obj

    @property
    def port(self) -> int:
        assert self._httpd is not None, "not started"
        return self._httpd.server_address[1]

    def start(self) -> "DebugServer":
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            disable_nagle_algorithm = True  # see utils/nethost.py

            def log_message(self, fmt, *args):
                pass

            def _send(self, code, body, ctype="text/plain"):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                hit = debug_route(self.path, outer.healthz, outer.configz)
                if hit is not None:
                    return self._send(*hit[:2], hit[2])
                self._send(404, b"not found")

        self._httpd = ThreadingHTTPServer((self._host, self._port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="debug-server", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None


def client_from_url(url: str, **kw):
    """RESTClient from a --master URL like http://127.0.0.1:8080."""
    from urllib.parse import urlparse

    from kubernetes_tpu.client import RESTClient
    u = urlparse(url if "//" in url else f"http://{url}")
    return RESTClient(host=u.hostname or "127.0.0.1", port=u.port or 8080,
                      **kw)
