"""Minimal JSONPath for -o jsonpath= output.

Parity target: the subset of reference pkg/util/jsonpath used by kubectl
one-liners: `{.path.to[0].field}`, `{.items[*].metadata.name}`, `{range
.items[*]}...{end}` is NOT supported — multiple `{...}` templates are joined
with the literal text between them."""

from __future__ import annotations

import re
from typing import Any, List


class JSONPathError(ValueError):
    pass


_SEGMENT = re.compile(r"\.([A-Za-z0-9_\-]+)|\[(\*|-?\d+)\]")


def _walk(value: Any, path: str) -> List[Any]:
    """Evaluate one {.a.b[*].c} body against value; returns matches."""
    values = [value]
    pos = 0
    while pos < len(path):
        m = _SEGMENT.match(path, pos)
        if not m:
            raise JSONPathError(f"unrecognized path at {path[pos:]!r}")
        pos = m.end()
        field, index = m.group(1), m.group(2)
        nxt: List[Any] = []
        for v in values:
            if field is not None:
                if isinstance(v, dict) and field in v:
                    nxt.append(v[field])
            elif index == "*":
                if isinstance(v, list):
                    nxt.extend(v)
            else:
                i = int(index)
                if isinstance(v, list) and -len(v) <= i < len(v):
                    nxt.append(v[i])
        values = nxt
    return values


def evaluate(template: str, data: Any) -> str:
    """Expand a jsonpath template: text outside {} is literal, each {.path}
    is replaced by its matches joined with spaces."""
    out = []
    pos = 0
    while pos < len(template):
        start = template.find("{", pos)
        if start < 0:
            out.append(template[pos:])
            break
        out.append(template[pos:start])
        end = template.find("}", start)
        if end < 0:
            raise JSONPathError("unclosed '{' in jsonpath template")
        body = template[start + 1:end].strip()
        if not body.startswith("."):
            raise JSONPathError(f"path must start with '.': {body!r}")
        matches = _walk(data, body)
        out.append(" ".join(_fmt(m) for m in matches))
        pos = end + 1
    return "".join(out)


def _fmt(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    if v is None:
        return ""
    if isinstance(v, (dict, list)):
        import json
        return json.dumps(v, separators=(",", ":"))
    return str(v)
