"""Prometheus-style metrics: counters, gauges, histograms, text exposition.

Parity target: reference's per-component prometheus registries
(plugin/pkg/scheduler/metrics/metrics.go, pkg/apiserver/metrics) — exponential
histogram buckets 1ms*2^k mirroring the scheduler latency histograms.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Dict, Tuple

# 1ms * 2^k for k in 0..14 — the scheduler histogram bucket layout
# (reference metrics.go:31-54)
SCHEDULER_BUCKETS = tuple(0.001 * 2**k for k in range(15))


def _label_key(labels: dict) -> Tuple:
    return tuple(sorted(labels.items()))


class Histogram:
    def __init__(self, name: str, buckets=SCHEDULER_BUCKETS):
        self.name = name
        self.buckets = tuple(sorted(buckets))
        self._counts: Dict[Tuple, list] = defaultdict(lambda: [0] * (len(self.buckets) + 1))
        self._sums: Dict[Tuple, float] = defaultdict(float)
        self._totals: Dict[Tuple, int] = defaultdict(int)

    def observe(self, value: float, **labels):
        k = _label_key(labels)
        counts = self._counts[k]
        for i, b in enumerate(self.buckets):
            if value <= b:
                counts[i] += 1
                break
        else:
            counts[-1] += 1
        self._sums[k] += value
        self._totals[k] += 1

    def quantile(self, q: float, **labels) -> float:
        """Estimated quantile from bucket counts (upper bound of the bucket
        containing the q-th observation)."""
        k = _label_key(labels)
        counts = self._counts.get(k)
        total = self._totals.get(k, 0)
        if not counts or not total:
            return 0.0
        target = q * total
        seen = 0
        for i, c in enumerate(counts[:-1]):
            seen += c
            if seen >= target:
                return self.buckets[i]
        return float("inf")


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Dict[Tuple, float]] = defaultdict(lambda: defaultdict(float))
        self._gauges: Dict[str, Dict[Tuple, float]] = defaultdict(dict)
        self._histograms: Dict[str, Histogram] = {}

    def inc(self, name: str, value: float = 1.0, **labels):
        with self._lock:
            self._counters[name][_label_key(labels)] += value

    def set_gauge(self, name: str, value: float, **labels):
        with self._lock:
            self._gauges[name][_label_key(labels)] = value

    def observe(self, name: str, value: float, buckets=SCHEDULER_BUCKETS, **labels):
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(name, buckets)
            h.observe(value, **labels)

    def histogram(self, name: str) -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(name)
            return h

    @contextmanager
    def time(self, name: str, **labels):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(name, time.perf_counter() - t0, **labels)

    def counter_value(self, name: str, **labels) -> float:
        with self._lock:
            return self._counters.get(name, {}).get(_label_key(labels), 0.0)

    def hist_total(self, name: str) -> int:
        """Locked sum of a histogram's observation counts across labels."""
        with self._lock:
            h = self._histograms.get(name)
            return sum(h._totals.values()) if h is not None else 0

    def counter_series(self, name: str) -> Dict[Tuple, float]:
        """Locked snapshot of one counter family: {label tuple: value}."""
        with self._lock:
            return dict(self._counters.get(name, {}))

    def hist_stats(self, name: str) -> Dict[Tuple, Tuple[int, float]]:
        """Locked snapshot of one histogram family:
        {label tuple: (observation count, sum of values)} — the source the
        bench's per-stage breakdown renders from."""
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                return {}
            return {lk: (h._totals[lk], h._sums[lk]) for lk in h._totals}

    def hist_snapshot(self, name: str):
        """Locked copy of a histogram's (counts, totals) — the 'before' side
        of delta_quantile (SLO windows scoped to one phase, the way the
        density suite scopes its latency asserts)."""
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(name)
            return ({k: list(v) for k, v in h._counts.items()},
                    dict(h._totals))

    def delta_quantile(self, name: str, snap, q: float, **labels) -> float:
        """Quantile over observations made AFTER the snapshot (upper bound
        of the bucket containing the q-th observation)."""
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                return 0.0
            before_counts, before_totals = snap
            k = _label_key(labels)
            zero = [0] * (len(h.buckets) + 1)
            counts = [a - b for a, b in zip(h._counts.get(k, zero),
                                            before_counts.get(k, zero))]
            total = h._totals.get(k, 0) - before_totals.get(k, 0)
        if total <= 0:
            return 0.0
        seen, target = 0, q * total
        for i, c in enumerate(counts[:-1]):
            seen += c
            if seen >= target:
                return h.buckets[i]
        return float("inf")

    def render(self) -> str:
        """Prometheus text exposition format."""
        out = []
        with self._lock:
            for name, series in sorted(self._counters.items()):
                out.append(f"# TYPE {name} counter")
                for lk, v in sorted(series.items()):
                    out.append(f"{name}{_fmt_labels(lk)} {v}")
            for name, series in sorted(self._gauges.items()):
                out.append(f"# TYPE {name} gauge")
                for lk, v in sorted(series.items()):
                    out.append(f"{name}{_fmt_labels(lk)} {v}")
            for name, h in sorted(self._histograms.items()):
                out.append(f"# TYPE {name} histogram")
                for lk in h._totals:
                    cum = 0
                    for i, b in enumerate(h.buckets):
                        cum += h._counts[lk][i]
                        out.append(f'{name}_bucket{_fmt_labels(lk, le=b)} {cum}')
                    out.append(f'{name}_bucket{_fmt_labels(lk, le="+Inf")} {h._totals[lk]}')
                    out.append(f"{name}_sum{_fmt_labels(lk)} {h._sums[lk]}")
                    out.append(f"{name}_count{_fmt_labels(lk)} {h._totals[lk]}")
        return "\n".join(out) + "\n"


def _fmt_labels(lk: Tuple, **extra) -> str:
    pairs = list(lk) + sorted(extra.items())
    if not pairs:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in pairs)
    return "{" + inner + "}"


REGISTRY = MetricsRegistry()
