"""Prometheus-style metrics: counters, gauges, histograms, text exposition.

Parity target: reference's per-component prometheus registries
(plugin/pkg/scheduler/metrics/metrics.go, pkg/apiserver/metrics) — exponential
histogram buckets 1ms*2^k mirroring the scheduler latency histograms.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from contextlib import contextmanager
from typing import Dict, Tuple

# 1ms * 2^k for k in 0..14 — the scheduler histogram bucket layout
# (reference metrics.go:31-54)
SCHEDULER_BUCKETS = tuple(0.001 * 2**k for k in range(15))

# optional # HELP text per metric family, keyed by family name (mutate
# directly: HELP["my_total"] = "..."); families without an entry render a
# placeholder so the exposition stays parseable by strict readers
# (observability/scrape.py round-trips it)
HELP: Dict[str, str] = {}


def finite_round(v, ndigits: int = 4):
    """JSON-report formatter for SLI values: a finite number rounds, NaN
    ("no samples") and inf (beyond bucket range) become None — a missing
    measurement must never serialize as a plausible number. Ints (counts)
    pass through unrounded."""
    if isinstance(v, bool) or v is None:
        return None
    if isinstance(v, int):
        return v
    import math
    return round(v, ndigits) if math.isfinite(v) else None


def _label_key(labels: dict) -> Tuple:
    return tuple(sorted(labels.items()))


class Histogram:
    def __init__(self, name: str, buckets=SCHEDULER_BUCKETS):
        self.name = name
        self.buckets = tuple(sorted(buckets))
        self._counts: Dict[Tuple, list] = defaultdict(lambda: [0] * (len(self.buckets) + 1))
        self._sums: Dict[Tuple, float] = defaultdict(float)
        self._totals: Dict[Tuple, int] = defaultdict(int)

    def observe(self, value: float, **labels):
        k = _label_key(labels)
        counts = self._counts[k]
        for i, b in enumerate(self.buckets):
            if value <= b:
                counts[i] += 1
                break
        else:
            counts[-1] += 1
        self._sums[k] += value
        self._totals[k] += 1

    def quantile(self, q: float, **labels) -> float:
        """Estimated quantile from bucket counts (upper bound of the bucket
        containing the q-th observation). An EMPTY series returns NaN —
        "no samples" must be distinguishable from a genuine zero latency
        (bench._finite and the SLO evaluator both branch on it)."""
        k = _label_key(labels)
        counts = self._counts.get(k)
        total = self._totals.get(k, 0)
        if not counts or not total:
            return float("nan")
        target = q * total
        seen = 0
        for i, c in enumerate(counts[:-1]):
            seen += c
            if seen >= target:
                return self.buckets[i]
        return float("inf")


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Dict[Tuple, float]] = defaultdict(lambda: defaultdict(float))
        self._gauges: Dict[str, Dict[Tuple, float]] = defaultdict(dict)
        self._histograms: Dict[str, Histogram] = {}

    def inc(self, name: str, value: float = 1.0, **labels):
        with self._lock:
            self._counters[name][_label_key(labels)] += value

    def set_gauge(self, name: str, value: float, **labels):
        with self._lock:
            self._gauges[name][_label_key(labels)] = value

    def observe(self, name: str, value: float, buckets=SCHEDULER_BUCKETS, **labels):
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(name, buckets)
            h.observe(value, **labels)

    def histogram(self, name: str) -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(name)
            return h

    @contextmanager
    def time(self, name: str, **labels):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(name, time.perf_counter() - t0, **labels)

    def counter_value(self, name: str, **labels) -> float:
        with self._lock:
            return self._counters.get(name, {}).get(_label_key(labels), 0.0)

    def hist_total(self, name: str) -> int:
        """Locked sum of a histogram's observation counts across labels."""
        with self._lock:
            h = self._histograms.get(name)
            return sum(h._totals.values()) if h is not None else 0

    def counter_series(self, name: str) -> Dict[Tuple, float]:
        """Locked snapshot of one counter family: {label tuple: value}."""
        with self._lock:
            return dict(self._counters.get(name, {}))

    def counter_totals(self) -> Dict[str, float]:
        """Locked snapshot of every counter family summed across labels —
        the flight recorder's delta baseline."""
        with self._lock:
            return {name: sum(series.values())
                    for name, series in self._counters.items()}

    def hist_stats(self, name: str) -> Dict[Tuple, Tuple[int, float]]:
        """Locked snapshot of one histogram family:
        {label tuple: (observation count, sum of values)} — the source the
        bench's per-stage breakdown renders from."""
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                return {}
            return {lk: (h._totals[lk], h._sums[lk]) for lk in h._totals}

    def hist_snapshot(self, name: str):
        """Locked copy of a histogram's (counts, totals) — the 'before' side
        of delta_quantile (SLO windows scoped to one phase, the way the
        density suite scopes its latency asserts)."""
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(name)
            return ({k: list(v) for k, v in h._counts.items()},
                    dict(h._totals))

    def delta_quantile(self, name: str, snap, q: float, **labels) -> float:
        """Quantile over observations made AFTER the snapshot (upper bound
        of the bucket containing the q-th observation). NaN when the window
        holds no samples (same contract as Histogram.quantile)."""
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                return float("nan")
            before_counts, before_totals = snap
            k = _label_key(labels)
            zero = [0] * (len(h.buckets) + 1)
            counts = [a - b for a, b in zip(h._counts.get(k, zero),
                                            before_counts.get(k, zero))]
            total = h._totals.get(k, 0) - before_totals.get(k, 0)
        if total <= 0:
            return float("nan")
        seen, target = 0, q * total
        for i, c in enumerate(counts[:-1]):
            seen += c
            if seen >= target:
                return h.buckets[i]
        return float("inf")

    def render(self) -> str:
        """Prometheus text exposition format: # HELP + # TYPE per family,
        label values escaped (backslash, quote, newline), `le` bucket bounds
        formatted through the one shared formatter — a strict parser (the
        observability scraper included) must round-trip this output."""
        out = []
        with self._lock:
            for name, series in sorted(self._counters.items()):
                _family_header(out, name, "counter")
                for lk, v in sorted(series.items()):
                    out.append(f"{name}{_fmt_labels(lk)} {_fmt_value(v)}")
            for name, series in sorted(self._gauges.items()):
                _family_header(out, name, "gauge")
                for lk, v in sorted(series.items()):
                    out.append(f"{name}{_fmt_labels(lk)} {_fmt_value(v)}")
            for name, h in sorted(self._histograms.items()):
                _family_header(out, name, "histogram")
                for lk in h._totals:
                    cum = 0
                    for i, b in enumerate(h.buckets):
                        cum += h._counts[lk][i]
                        out.append(f'{name}_bucket'
                                   f'{_fmt_labels(lk, le=_fmt_value(b))} {cum}')
                    out.append(f'{name}_bucket{_fmt_labels(lk, le="+Inf")} '
                               f'{h._totals[lk]}')
                    out.append(f"{name}_sum{_fmt_labels(lk)} "
                               f"{_fmt_value(h._sums[lk])}")
                    out.append(f"{name}_count{_fmt_labels(lk)} {h._totals[lk]}")
        return "\n".join(out) + "\n"


def _family_header(out: list, name: str, mtype: str) -> None:
    help_text = HELP.get(name, f"{name} ({mtype})")
    # HELP escaping per the format spec: backslash and newline only
    help_text = help_text.replace("\\", "\\\\").replace("\n", "\\n")
    out.append(f"# HELP {name} {help_text}")
    out.append(f"# TYPE {name} {mtype}")


def _escape_label_value(v) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_value(v: float) -> str:
    """One canonical float rendering for sample values AND `le` bounds, so
    a bound compares equal whether read from a bucket line or recomputed
    from SCHEDULER_BUCKETS (0.016 must never render as 0.016000000000000001
    on one line and 0.016 on another)."""
    if v != v:
        return "NaN"  # a NaN sample must never crash every /metrics scrape
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    # repr = shortest round-tripping decimal (0.016 stays "0.016", never
    # "0.016000000000000001"); integral values drop the trailing ".0"
    return str(int(v)) if v == int(v) else repr(float(v))


def _fmt_labels(lk: Tuple, **extra) -> str:
    pairs = list(lk) + sorted(extra.items())
    if not pairs:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"' for k, v in pairs)
    return "{" + inner + "}"


REGISTRY = MetricsRegistry()

# core SLI families (components observe these without registering help)
HELP.update({
    "scheduler_e2e_scheduling_latency_seconds":
        "Pod queue pop to CAS-accepted binding, per pod",
    "scheduler_binding_latency_seconds": "The bind POST round-trip",
    "scheduler_pod_queue_wait_seconds": "Informer delivery to FIFO pop",
    "scheduler_informer_delivery_seconds":
        "Pod creation to first scheduler informer delivery",
    "scheduler_scheduling_algorithm_latency_seconds":
        "Kernel (or oracle) solve per batch",
    "scheduler_stage_seconds":
        "Kernel pipeline stage wall time (tensorize/upload/compile/solve)",
    "scheduler_stage_timeout_total":
        "Watchdog conversions of kernel stage hangs",
    "scheduler_kernel_device_seconds":
        "Kernel stage time split into host dispatch vs device execution",
    "scheduler_kernel_health": "1 ok / 0.5 degraded / 0 failed",
    "kubelet_pod_startup_latency_seconds":
        "Pod creation to containers started",
    "informer_watch_lag_seconds": "Store apply to handler dispatch",
    "workqueue_depth": "Controller workqueue depth",
    "compile_cache_events_total":
        "Persistent XLA cache hit/miss/rejected/disabled, by fingerprint",
    "scheduler_preemptions_total":
        "Preemption victim evictions, by outcome (evicted/evict-error)",
    "scheduler_preemption_victims":
        "Victims per preemption nomination",
    "scheduler_gang_placements_total":
        "Gang scheduling verdicts (placed/rejected)",
})
