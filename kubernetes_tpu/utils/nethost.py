"""host:port parsing shared by everything that dials a configured address
(federation member clients, cluster health probes, the discovery proxy).
One tolerant parse instead of three divergent hand-rolled ones.

Also the TCP_NODELAY connection classes every in-repo HTTP hop uses: the
stdlib leaves Nagle ON, and a small POST (headers then body in separate
segments) against a delayed-ACK peer costs a flat ~40 ms per request —
a 20x request-rate floor that made the chaos soak's churn back up behind
the kill. The reference's Go net/http sets TCP_NODELAY on every conn by
default; these classes are that default for http.client, and the HTTP
servers set disable_nagle_algorithm for the other direction."""

from __future__ import annotations

import http.client
import socket
from typing import Tuple


def set_nodelay(sock) -> None:
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except (OSError, AttributeError):
        pass  # non-TCP transports (unix sockets, mocks) simply don't care


class NoDelayHTTPConnection(http.client.HTTPConnection):
    def connect(self):
        super().connect()
        set_nodelay(self.sock)


class NoDelayHTTPSConnection(http.client.HTTPSConnection):
    def connect(self):
        super().connect()
        # SSLSocket proxies setsockopt to the wrapped TCP socket
        set_nodelay(self.sock)


def parse_host_port(address: str, default_port: int = 8080) -> Tuple[str, int]:
    """"host:port" -> (host, port); a bare host (or empty/garbage port)
    gets the default port; an empty host becomes loopback. Scheme prefixes
    (http://) are tolerated and stripped."""
    addr = address or ""
    if "//" in addr:
        addr = addr.split("//", 1)[1]
    addr = addr.rstrip("/")
    host, _, port = addr.rpartition(":")
    if not port.isdigit():
        host, port = addr, str(default_port)
    return host or "127.0.0.1", int(port)
