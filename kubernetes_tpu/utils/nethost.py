"""host:port parsing shared by everything that dials a configured address
(federation member clients, cluster health probes, the discovery proxy).
One tolerant parse instead of three divergent hand-rolled ones."""

from __future__ import annotations

from typing import Tuple


def parse_host_port(address: str, default_port: int = 8080) -> Tuple[str, int]:
    """"host:port" -> (host, port); a bare host (or empty/garbage port)
    gets the default port; an empty host becomes loopback. Scheme prefixes
    (http://) are tolerated and stripped."""
    addr = address or ""
    if "//" in addr:
        addr = addr.split("//", 1)[1]
    addr = addr.rstrip("/")
    host, _, port = addr.rpartition(":")
    if not port.isdigit():
        host, port = addr, str(default_port)
    return host or "127.0.0.1", int(port)
