"""Controller expectations: suppress re-sync until our own writes are seen.

Parity target: reference pkg/controller/controller_utils.go (ControllerExpectations,
ExpectationsTimeout 5m) — a controller that just created/deleted N pods must not
act again for the same key until the informer cache has delivered those N events
(or the expectation expired), otherwise cache lag causes double-creates.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

EXPECTATIONS_TIMEOUT = 5 * 60.0


class ControllerExpectations:
    def __init__(self, clock=time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        # key -> [adds_pending, dels_pending, set_time]
        self._exp: Dict[str, list] = {}

    def satisfied_expectations(self, key: str) -> bool:
        with self._lock:
            e = self._exp.get(key)
            if e is None:
                return True
            adds, dels, t = e
            if adds <= 0 and dels <= 0:
                return True
            if self._clock() - t > EXPECTATIONS_TIMEOUT:
                return True  # expired: self-heal by allowing a fresh sync
            return False

    def expect_creations(self, key: str, n: int) -> None:
        self._set(key, adds=n, dels=0)

    def expect_deletions(self, key: str, n: int) -> None:
        self._set(key, adds=0, dels=n)

    def _set(self, key: str, adds: int, dels: int) -> None:
        with self._lock:
            self._exp[key] = [adds, dels, self._clock()]

    def creation_observed(self, key: str) -> None:
        self._lower(key, 0)

    def deletion_observed(self, key: str) -> None:
        self._lower(key, 1)

    def _lower(self, key: str, idx: int) -> None:
        with self._lock:
            e = self._exp.get(key)
            if e is not None and e[idx] > 0:
                e[idx] -= 1

    def delete_expectations(self, key: str) -> None:
        with self._lock:
            self._exp.pop(key, None)

    def get(self, key: str) -> Optional[Tuple[int, int]]:
        with self._lock:
            e = self._exp.get(key)
            return (e[0], e[1]) if e else None
