"""Controller base: informer feed -> keyed workqueue -> reconcile workers.

Parity target: the shared shape of every reference controller
(pkg/controller/*/: informer handlers enqueue keys, N workers pop and sync,
errors re-enqueue rate-limited; see expectations.py for the
controller_utils.go expectations pattern used by pod-creating controllers)."""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, List, Optional

from kubernetes_tpu.utils.metrics import REGISTRY as METRICS
from kubernetes_tpu.utils.workqueue import RateLimitingQueue

log = logging.getLogger("controller")


class Controller:
    """Subclasses implement sync(key) -> None (raise to retry)."""

    name = "controller"

    def __init__(self, workers: int = 2):
        # named queue -> workqueue depth/latency SLIs land per controller
        self.queue = RateLimitingQueue(name=self.name)
        self.workers = workers
        self._threads: List[threading.Thread] = []
        self._stop = threading.Event()
        self._armed: Dict[str, float] = {}
        self._armed_lock = threading.Lock()

    def enqueue(self, key: str):
        self.queue.add(key)

    def enqueue_after(self, key: str, delay: float):
        self.queue.add_after(key, delay)

    def arm_resync(self, key: str, delay: float):
        """Schedule a delayed re-sync, at most ONE outstanding per key.
        Event-driven syncs calling this repeatedly must not each spawn a new
        delayed entry — the DelayingQueue heap doesn't dedup future entries,
        so unconditional re-arming grows without bound."""
        now = time.monotonic()
        with self._armed_lock:
            if self._armed.get(key, 0.0) > now:
                return  # a timer is already pending for this key
            self._armed[key] = now + delay
        self.queue.add_after(key, delay)

    def disarm_resync(self, key: str):
        with self._armed_lock:
            self._armed.pop(key, None)

    def sync(self, key: str) -> None:
        raise NotImplementedError

    def run(self):
        for i in range(self.workers):
            t = threading.Thread(target=self._worker, name=f"{self.name}-{i}",
                                 daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def _worker(self):
        while not self._stop.is_set():
            key = self.queue.get(timeout=0.5)
            if key is None:
                continue
            try:
                self.sync(key)
                self.queue.forget(key)
            except Exception as e:
                # a sync loop that fails quietly for hours is the bug class
                # the swallowed-exception checker exists for: every failure
                # is logged at warning WITH the error, counted, and offered
                # to the subclass's recorder before the rate-limited requeue
                log.warning("%s: sync %s failed: %s: %s; requeueing",
                            self.name, key, type(e).__name__, e)
                METRICS.inc("controller_sync_errors_total",
                            controller=self.name)
                try:
                    self.on_sync_error(key, e)
                except Exception:
                    log.exception("%s: on_sync_error hook failed", self.name)
                self.queue.add_rate_limited(key)
            finally:
                self.queue.done(key)

    def on_sync_error(self, key: str, err: Exception) -> None:
        """Subclass hook: controllers with an EventRecorder post a Warning
        Event for the object behind `key` here (utils/events.py handles
        dedup/aggregation, so a crash-looping sync can't melt the
        apiserver). Default: counted + logged by the worker, nothing more."""

    def stop(self):
        self._stop.set()
        self.queue.shutdown()
        for t in self._threads:
            t.join(timeout=2)
