"""Namespace controller: cascading deletion.

Parity target: reference pkg/controller/namespace — a namespace with a
deletionTimestamp is drained: every namespaced resource inside it is deleted,
then the namespace itself is removed once empty."""

from __future__ import annotations

import logging

from kubernetes_tpu.api import types as api
from kubernetes_tpu.client import Informer, ListWatch, RESTClient
from kubernetes_tpu.client.rest import ApiError
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.registry.generic import RESOURCES

log = logging.getLogger("namespace-controller")


class NamespaceController(Controller):
    name = "namespace"

    def __init__(self, client: RESTClient, workers: int = 1):
        super().__init__(workers)
        self.client = client
        self.informer = Informer(ListWatch(client, "namespaces"))
        self.informer.add_event_handler(
            on_add=self._changed,
            on_update=lambda o, n: self._changed(n))

    def _changed(self, ns: api.Namespace):
        if ns.metadata.deletion_timestamp is not None or (
                ns.status and ns.status.phase == "Terminating"):
            self.enqueue(ns.metadata.name)

    def sync(self, key: str) -> None:
        remaining = 0
        for rname, rd in RESOURCES.items():
            if not rd.namespaced:
                continue
            items, _ = self.client.list(rname, key)
            for obj in items:
                remaining += 1
                try:
                    self.client.delete(rname, obj.metadata.name, key)
                except ApiError as e:
                    if not e.is_not_found:
                        raise
        if remaining == 0:
            try:
                self.client.delete("namespaces", key)
            except ApiError as e:
                if not e.is_not_found:
                    raise
        else:
            raise RuntimeError(f"namespace {key}: {remaining} objects drained; re-check")

    def start(self):
        self.informer.run()
        self.informer.wait_for_sync()
        return self.run()

    def stop(self):
        super().stop()
        self.informer.stop()
