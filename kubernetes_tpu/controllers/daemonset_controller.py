"""DaemonSet controller: one pod per eligible node.

Parity target: reference pkg/controller/daemon/controller.go — for every node,
decide nodeShouldRunDaemonPod (node ready, nodeSelector/nodeName match, taints
tolerated, room per GeneralPredicates), create daemon pods with spec.nodeName
set directly (this era's daemon pods bypass the scheduler,
controller.go createPodsOnNode), delete pods from nodes that no longer
qualify, and keep status {desired,current,misscheduled}NumberScheduled."""

from __future__ import annotations

import logging
from typing import Dict, List

from kubernetes_tpu.api import labels as labelsel
from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.serialization import deep_copy
from kubernetes_tpu.apis import extensions as ext
from kubernetes_tpu.client import Informer, ListWatch, RESTClient
from kubernetes_tpu.client.rest import ApiError
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.controllers.expectations import ControllerExpectations
from kubernetes_tpu.controllers.pod_control import (
    is_pod_active, pod_from_template, selector_for,
)
from kubernetes_tpu.scheduler.cache import NodeInfo
from kubernetes_tpu.scheduler.predicates import (
    PredicateFailure, pod_matches_node_selector, pod_tolerates_node_taints,
)

log = logging.getLogger("daemonset-controller")


class DaemonSetController(Controller):
    name = "daemonset"

    def __init__(self, client: RESTClient, workers: int = 2):
        super().__init__(workers)
        self.client = client
        self.ds_informer = Informer(ListWatch(client, "daemonsets"))
        self.pod_informer = Informer(ListWatch(client, "pods"))
        self.node_informer = Informer(ListWatch(client, "nodes"))
        self.expectations = ControllerExpectations()
        self.ds_informer.add_event_handler(
            on_add=lambda ds: self.enqueue(_key(ds)),
            on_update=lambda old, new: self.enqueue(_key(new)),
            on_delete=self._ds_deleted)
        self.pod_informer.add_event_handler(
            on_add=self._pod_added,
            on_update=lambda old, new: self._pod_changed(new),
            on_delete=self._pod_deleted)
        # any node change can flip eligibility for every daemon set
        self.node_informer.add_event_handler(
            on_add=lambda n: self._all_dirty(),
            on_update=lambda old, new: self._all_dirty(),
            on_delete=lambda n: self._all_dirty())

    def _all_dirty(self):
        for ds in self.ds_informer.store.list():
            self.enqueue(_key(ds))

    def _ds_deleted(self, ds):
        self.expectations.delete_expectations(_key(ds))
        self.enqueue(_key(ds))

    def _pod_added(self, pod):
        for ds in self._owners_of(pod):
            self.expectations.creation_observed(_key(ds))
            self.enqueue(_key(ds))

    def _pod_deleted(self, pod):
        for ds in self._owners_of(pod):
            self.expectations.deletion_observed(_key(ds))
            self.enqueue(_key(ds))

    def _pod_changed(self, pod):
        for ds in self._owners_of(pod):
            self.enqueue(_key(ds))

    def _owners_of(self, pod) -> List[ext.DaemonSet]:
        lbls = pod.metadata.labels or {}
        return [ds for ds in self.ds_informer.store.list()
                if ds.metadata.namespace == pod.metadata.namespace
                and _selector(ds).matches(lbls)]

    # --- eligibility ---------------------------------------------------------

    @staticmethod
    def node_should_run(ds: ext.DaemonSet, node: api.Node) -> bool:
        """nodeShouldRunDaemonPod: readiness + nodeName/nodeSelector/affinity
        + taint toleration (resource fit is delegated to kubelet admission)."""
        for c in ((node.status.conditions or []) if node.status else []):
            if c.type == api.NODE_READY and c.status != api.CONDITION_TRUE:
                return False
        if node.spec and node.spec.unschedulable:
            return False
        tpl = ds.spec.template if ds.spec else None
        spec = tpl.spec if tpl else None
        probe = api.Pod(metadata=api.ObjectMeta(), spec=spec or api.PodSpec())
        if spec and spec.node_name and spec.node_name != node.metadata.name:
            return False
        info = NodeInfo(node)
        try:
            pod_matches_node_selector(probe, info)
            pod_tolerates_node_taints(probe, info)
        except PredicateFailure:
            return False
        return True

    # --- reconcile -----------------------------------------------------------

    def sync(self, key: str) -> None:
        ns, _ = key.split("/", 1)
        ds = self.ds_informer.store.get(key)
        if ds is None:
            return
        sel = _selector(ds)
        nodes = self.node_informer.store.list()
        # daemon pods by node
        by_node: Dict[str, List[api.Pod]] = {}
        for p in self.pod_informer.store.list():
            if (p.metadata.namespace == ns and is_pod_active(p)
                    and sel.matches(p.metadata.labels or {})):
                nn = p.spec.node_name if p.spec else ""
                by_node.setdefault(nn, []).append(p)

        should_run = {n.metadata.name: self.node_should_run(ds, n)
                      for n in nodes}
        to_create, to_delete = [], []
        for node in nodes:
            name = node.metadata.name
            have = by_node.get(name, [])
            if should_run[name] and not have:
                to_create.append(name)
            elif not should_run[name] and have:
                to_delete.extend(have)
            elif should_run[name] and len(have) > 1:
                # more than one daemon pod on a node: keep the oldest
                extras = sorted(have,
                                key=lambda p: p.metadata.creation_timestamp or "")
                to_delete.extend(extras[1:])

        if self.expectations.satisfied_expectations(key):
            self._apply(key, ds, to_create, to_delete)
        self._update_status(ds, should_run, by_node)

    def _apply(self, key, ds, to_create: List[str], to_delete: List[api.Pod]):
        if to_create:
            self.expectations.expect_creations(key, len(to_create))
            done = 0
            try:
                for node_name in to_create:
                    pod = pod_from_template(
                        "DaemonSet", ds,
                        (ds.spec.template if ds.spec else None)
                        or api.PodTemplateSpec(),
                        node_name=node_name)
                    self.client.create("pods", pod, ds.metadata.namespace)
                    done += 1
            except ApiError:
                for _ in range(len(to_create) - done):
                    self.expectations.creation_observed(key)
                raise
        if to_delete:
            self.expectations.expect_deletions(key, len(to_delete))
            for i, p in enumerate(to_delete):
                try:
                    self.client.delete("pods", p.metadata.name,
                                       ds.metadata.namespace)
                except ApiError as e:
                    if e.is_not_found:
                        self.expectations.deletion_observed(key)
                        continue
                    for _ in range(len(to_delete) - i):
                        self.expectations.deletion_observed(key)
                    raise

    def _update_status(self, ds, should_run, by_node):
        desired = sum(1 for v in should_run.values() if v)
        current = 0
        mis = 0
        for name, should in should_run.items():
            have = bool(by_node.get(name))
            if should:
                current += 1 if have else 0
            elif have:
                mis += 1
        st = ds.status
        if (st and st.desired_number_scheduled == desired
                and st.current_number_scheduled == current
                and st.number_misscheduled == mis):
            return
        fresh = deep_copy(ds)
        fresh.status = ext.DaemonSetStatus(
            current_number_scheduled=current,
            number_misscheduled=mis,
            desired_number_scheduled=desired)
        try:
            self.client.update_status("daemonsets", fresh)
        except ApiError as e:
            if not (e.is_not_found or e.is_conflict):
                raise

    # --- lifecycle -----------------------------------------------------------

    def start(self):
        for inf in (self.ds_informer, self.pod_informer, self.node_informer):
            inf.run()
        for inf in (self.ds_informer, self.pod_informer, self.node_informer):
            inf.wait_for_sync()
        return self.run()

    def stop(self):
        super().stop()
        for inf in (self.ds_informer, self.pod_informer, self.node_informer):
            inf.stop()


def _selector(ds: ext.DaemonSet) -> labelsel.Selector:
    return selector_for(ds)


def _key(obj) -> str:
    return f"{obj.metadata.namespace}/{obj.metadata.name}"
