"""ReplicaSet controller: next-gen replication with set-based selectors.

Parity target: reference pkg/controller/replicaset/replica_set.go — identical
reconcile shape to the replication controller but selecting pods with the
structured LabelSelector {matchLabels, matchExpressions}. Deployments manage
replicas through these (see deployment_controller.py)."""

from __future__ import annotations

import logging
from typing import List

from kubernetes_tpu.api import labels as labelsel
from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.serialization import deep_copy
from kubernetes_tpu.apis import extensions as ext  # noqa: F401  (group home of ReplicaSet routes)
from kubernetes_tpu.client import Informer, ListWatch, RESTClient
from kubernetes_tpu.client.rest import ApiError
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.controllers.expectations import ControllerExpectations
from kubernetes_tpu.controllers.pod_control import (
    deletion_rank, is_pod_active, is_pod_ready, pod_from_template, selector_for,
)

log = logging.getLogger("replicaset-controller")


def _selector(rs: api.ReplicaSet) -> labelsel.Selector:
    return selector_for(rs)


class ReplicaSetController(Controller):
    name = "replicaset"

    def __init__(self, client: RESTClient, workers: int = 2,
                 burst_replicas: int = 500):
        super().__init__(workers)
        self.client = client
        self.burst = burst_replicas
        self.rs_informer = Informer(ListWatch(client, "replicasets"))
        self.pod_informer = Informer(ListWatch(client, "pods"))
        self.expectations = ControllerExpectations()
        self.rs_informer.add_event_handler(
            on_add=lambda rs: self.enqueue(_key(rs)),
            on_update=lambda old, new: self.enqueue(_key(new)),
            on_delete=self._rs_deleted)
        self.pod_informer.add_event_handler(
            on_add=self._pod_added,
            on_update=lambda old, new: self._pod_changed(new),
            on_delete=self._pod_deleted)

    def _rs_deleted(self, rs):
        self.expectations.delete_expectations(_key(rs))
        self.enqueue(_key(rs))

    def _pod_added(self, pod):
        for rs in self._owners_of(pod):
            self.expectations.creation_observed(_key(rs))
            self.enqueue(_key(rs))

    def _pod_deleted(self, pod):
        for rs in self._owners_of(pod):
            self.expectations.deletion_observed(_key(rs))
            self.enqueue(_key(rs))

    def _pod_changed(self, pod):
        for rs in self._owners_of(pod):
            self.enqueue(_key(rs))

    def _owners_of(self, pod: api.Pod) -> List[api.ReplicaSet]:
        lbls = pod.metadata.labels or {}
        return [rs for rs in self.rs_informer.store.list()
                if rs.metadata.namespace == pod.metadata.namespace
                and _selector(rs).matches(lbls)]

    # --- reconcile -----------------------------------------------------------

    def sync(self, key: str) -> None:
        ns, _ = key.split("/", 1)
        rs = self.rs_informer.store.get(key)
        if rs is None:
            return
        sel = _selector(rs)
        pods = [p for p in self.pod_informer.store.list()
                if p.metadata.namespace == ns and is_pod_active(p)
                and sel.matches(p.metadata.labels or {})]
        if self.expectations.satisfied_expectations(key):
            self._manage_replicas(key, rs, pods)
        self._update_status(rs, pods)

    def _manage_replicas(self, key: str, rs, pods: list) -> None:
        diff = (rs.spec.replicas or 0) - len(pods)
        if diff > 0:
            n = min(diff, self.burst)
            self.expectations.expect_creations(key, n)
            created = 0
            try:
                for _ in range(n):
                    pod = pod_from_template("ReplicaSet", rs, rs.spec.template
                                            or api.PodTemplateSpec())
                    self.client.create("pods", pod, rs.metadata.namespace)
                    created += 1
            except ApiError:
                for _ in range(n - created):
                    self.expectations.creation_observed(key)
                raise
        elif diff < 0:
            victims = sorted(pods, key=deletion_rank)[: min(-diff, self.burst)]
            self.expectations.expect_deletions(key, len(victims))
            for i, p in enumerate(victims):
                try:
                    self.client.delete("pods", p.metadata.name,
                                       rs.metadata.namespace)
                except ApiError as e:
                    if e.is_not_found:
                        self.expectations.deletion_observed(key)
                        continue
                    for _ in range(len(victims) - i):
                        self.expectations.deletion_observed(key)
                    raise

    def _update_status(self, rs, pods: list):
        n, ready = len(pods), sum(1 for p in pods if is_pod_ready(p))
        st = rs.status
        if st and st.replicas == n and getattr(st, "ready_replicas", 0) == ready:
            return
        fresh = deep_copy(rs)
        fresh.status = api.ReplicaSetStatus(replicas=n, ready_replicas=ready)
        try:
            self.client.update_status("replicasets", fresh)
        except ApiError as e:
            if not (e.is_not_found or e.is_conflict):
                raise

    # --- lifecycle -----------------------------------------------------------

    def start(self):
        self.rs_informer.run()
        self.pod_informer.run()
        self.rs_informer.wait_for_sync()
        self.pod_informer.wait_for_sync()
        return self.run()

    def stop(self):
        super().stop()
        self.rs_informer.stop()
        self.pod_informer.stop()


def _key(obj) -> str:
    return f"{obj.metadata.namespace}/{obj.metadata.name}"
