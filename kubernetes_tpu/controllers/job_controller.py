"""Job controller: run pods to completion.

Parity target: reference pkg/controller/job/jobcontroller.go — count
active/succeeded/failed pods per job; create up to parallelism (capped by
remaining completions), delete surplus actives on scale-down; completions
reached (or nil completions + any success) sets the Complete condition and
stamps completionTime; activeDeadlineSeconds exceeded kills actives and sets
Failed (syncJob / manageJob)."""

from __future__ import annotations

import logging
import time
from typing import List

from kubernetes_tpu.api import labels as labelsel
from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.serialization import deep_copy
from kubernetes_tpu.apis import batch
from kubernetes_tpu.client import Informer, ListWatch, RESTClient
from kubernetes_tpu.client.rest import ApiError
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.controllers.expectations import ControllerExpectations
from kubernetes_tpu.controllers.pod_control import (
    deletion_rank, pod_from_template, selector_for,
)
from kubernetes_tpu.utils.timeutil import now_iso, parse_iso

log = logging.getLogger("job-controller")


class JobController(Controller):
    name = "job"

    def __init__(self, client: RESTClient, workers: int = 2):
        super().__init__(workers)
        self.client = client
        self.job_informer = Informer(ListWatch(client, "jobs"))
        self.pod_informer = Informer(ListWatch(client, "pods"))
        self.expectations = ControllerExpectations()
        self.job_informer.add_event_handler(
            on_add=lambda j: self.enqueue(_key(j)),
            on_update=lambda old, new: self.enqueue(_key(new)),
            on_delete=self._job_deleted)
        self.pod_informer.add_event_handler(
            on_add=self._pod_added,
            on_update=lambda old, new: self._pod_changed(new),
            on_delete=self._pod_deleted)

    def _job_deleted(self, j):
        self.expectations.delete_expectations(_key(j))
        self.enqueue(_key(j))

    def _pod_added(self, pod):
        for j in self._owners_of(pod):
            self.expectations.creation_observed(_key(j))
            self.enqueue(_key(j))

    def _pod_deleted(self, pod):
        for j in self._owners_of(pod):
            self.expectations.deletion_observed(_key(j))
            self.enqueue(_key(j))

    def _pod_changed(self, pod):
        for j in self._owners_of(pod):
            self.enqueue(_key(j))

    def _owners_of(self, pod) -> List[batch.Job]:
        lbls = pod.metadata.labels or {}
        return [j for j in self.job_informer.store.list()
                if j.metadata.namespace == pod.metadata.namespace
                and _selector(j).matches(lbls)]

    # --- reconcile -----------------------------------------------------------

    def sync(self, key: str) -> None:
        ns, _ = key.split("/", 1)
        job = self.job_informer.store.get(key)
        if job is None:
            return
        if _finished(job):
            return
        sel = _selector(job)
        pods = [p for p in self.pod_informer.store.list()
                if p.metadata.namespace == ns
                and p.metadata.deletion_timestamp is None
                and sel.matches(p.metadata.labels or {})]
        active = [p for p in pods if _phase(p) not in
                  (api.POD_SUCCEEDED, api.POD_FAILED)]
        succeeded = sum(1 for p in pods if _phase(p) == api.POD_SUCCEEDED)
        failed = sum(1 for p in pods if _phase(p) == api.POD_FAILED)

        start_time = (job.status.start_time if job.status else None) or now_iso()
        deadline_exceeded = self._past_deadline(job, start_time)
        if not deadline_exceeded:
            # nothing else requeues us at the deadline — schedule the wake-up
            # ourselves (the reference relies on its 30s resync period)
            limit = job.spec.active_deadline_seconds if job.spec else None
            started = parse_iso(start_time)
            if limit is not None and started is not None:
                # wall vs the SERIALIZED job start timestamp — monotonic has
                # no epoch to compare against it
                # kube-verify: disable-next-line=monotonic-duration
                self.enqueue_after(key, max(0.0, started + limit - time.time()))

        condition = None
        if deadline_exceeded:
            # kill remaining actives, mark Failed
            for p in active:
                try:
                    self.client.delete("pods", p.metadata.name, ns)
                except ApiError as e:
                    if not e.is_not_found:
                        raise
            active = []
            condition = batch.JobCondition(
                type=batch.JOB_FAILED, status=api.CONDITION_TRUE,
                reason="DeadlineExceeded",
                message="Job was active longer than specified deadline",
                last_transition_time=now_iso())
        else:
            completions = job.spec.completions if job.spec else None
            complete = (succeeded >= completions if completions is not None
                        else succeeded > 0 and not active)
            if complete:
                condition = batch.JobCondition(
                    type=batch.JOB_COMPLETE, status=api.CONDITION_TRUE,
                    last_transition_time=now_iso())
            elif self.expectations.satisfied_expectations(key):
                active = self._manage(key, job, active, succeeded)

        self._update_status(job, len(active), succeeded, failed, start_time,
                            condition)

    def _past_deadline(self, job, start_time: str) -> bool:
        limit = job.spec.active_deadline_seconds if job.spec else None
        if limit is None:
            return False
        started = parse_iso(start_time)
        # wall vs serialized start timestamp (see _past_deadline caller)
        # kube-verify: disable-next-line=monotonic-duration
        return started is not None and (time.time() - started) >= limit

    def _manage(self, key, job, active: list, succeeded: int) -> list:
        parallelism = job.spec.parallelism if job.spec and \
            job.spec.parallelism is not None else 1
        completions = job.spec.completions if job.spec else None
        if completions is not None:
            want_active = min(parallelism, max(0, completions - succeeded))
        else:
            want_active = parallelism
        diff = want_active - len(active)
        if diff > 0:
            self.expectations.expect_creations(key, diff)
            done = 0
            try:
                for _ in range(diff):
                    pod = pod_from_template(
                        "Job", job,
                        (job.spec.template if job.spec else None)
                        or api.PodTemplateSpec())
                    self.client.create("pods", pod, job.metadata.namespace)
                    done += 1
            except ApiError:
                for _ in range(diff - done):
                    self.expectations.creation_observed(key)
                raise
        elif diff < 0:
            victims = sorted(active, key=deletion_rank)[: -diff]
            self.expectations.expect_deletions(key, len(victims))
            remaining = [p for p in active if p not in victims]
            for i, p in enumerate(victims):
                try:
                    self.client.delete("pods", p.metadata.name,
                                       job.metadata.namespace)
                except ApiError as e:
                    if e.is_not_found:
                        self.expectations.deletion_observed(key)
                        continue
                    for _ in range(len(victims) - i):
                        self.expectations.deletion_observed(key)
                    raise
            return remaining
        return active

    def _update_status(self, job, active: int, succeeded: int, failed: int,
                       start_time: str, condition) -> None:
        st = job.status or batch.JobStatus()
        changed = (st.active != active or st.succeeded != succeeded
                   or st.failed != failed or st.start_time != start_time
                   or condition is not None)
        if not changed:
            return
        fresh = deep_copy(job)
        conditions = list((st.conditions or []))
        if condition is not None:
            conditions.append(condition)
        fresh.status = batch.JobStatus(
            conditions=conditions or None, start_time=start_time,
            completion_time=(now_iso() if condition is not None
                             and condition.type == batch.JOB_COMPLETE
                             else st.completion_time),
            active=active, succeeded=succeeded, failed=failed)
        try:
            self.client.update_status("jobs", fresh)
        except ApiError as e:
            if not (e.is_not_found or e.is_conflict):
                raise

    # --- lifecycle -----------------------------------------------------------

    def start(self):
        self.job_informer.run()
        self.pod_informer.run()
        self.job_informer.wait_for_sync()
        self.pod_informer.wait_for_sync()
        return self.run()

    def stop(self):
        super().stop()
        self.job_informer.stop()
        self.pod_informer.stop()


def _selector(job: batch.Job) -> labelsel.Selector:
    return selector_for(job)


def _finished(job: batch.Job) -> bool:
    for c in ((job.status.conditions or []) if job.status else []):
        if c.type in (batch.JOB_COMPLETE, batch.JOB_FAILED) and \
                c.status == api.CONDITION_TRUE:
            return True
    return False


def _phase(pod: api.Pod) -> str:
    return pod.status.phase if pod.status else ""


def _key(obj) -> str:
    return f"{obj.metadata.namespace}/{obj.metadata.name}"
