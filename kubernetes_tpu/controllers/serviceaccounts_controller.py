"""ServiceAccount + token controllers.

Parity targets:
  - ServiceAccountsController (reference pkg/controller/serviceaccount/
    serviceaccounts_controller.go): ensure every active namespace has the
    "default" ServiceAccount; recreate it if deleted.
  - TokensController (reference pkg/controller/serviceaccount/
    tokens_controller.go): every ServiceAccount gets a
    kubernetes.io/service-account-token Secret carrying a signed token,
    referenced from sa.secrets; secrets of deleted SAs are cleaned up.
    Token generation mirrors the JWT layout the reference produces via
    pkg/serviceaccount/jwt.go, HMAC-signed here instead of RSA."""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import logging

from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.serialization import deep_copy
from kubernetes_tpu.client import Informer, ListWatch, RESTClient
from kubernetes_tpu.client.rest import ApiError
from kubernetes_tpu.controllers.base import Controller

log = logging.getLogger("serviceaccount-controller")

DEFAULT_SA = "default"


class ServiceAccountsController(Controller):
    """Namespace -> ensure the managed service accounts exist."""

    name = "serviceaccount"

    def __init__(self, client: RESTClient, workers: int = 1,
                 names=(DEFAULT_SA,)):
        super().__init__(workers)
        self.client = client
        self.names = tuple(names)
        self.ns_informer = Informer(ListWatch(client, "namespaces"))
        self.sa_informer = Informer(ListWatch(client, "serviceaccounts"))
        self.ns_informer.add_event_handler(
            on_add=lambda ns: self.enqueue(ns.metadata.name),
            on_update=lambda old, new: self.enqueue(new.metadata.name))
        self.sa_informer.add_event_handler(
            on_delete=lambda sa: self.enqueue(sa.metadata.namespace))

    def sync(self, key: str) -> None:
        ns = self.ns_informer.store.get(key)
        if ns is None:
            return
        if ns.status and ns.status.phase == "Terminating":
            return
        for name in self.names:
            if self.sa_informer.store.get(f"{key}/{name}") is not None:
                continue
            try:
                self.client.create("serviceaccounts", api.ServiceAccount(
                    metadata=api.ObjectMeta(name=name, namespace=key)), key)
            except ApiError as e:
                if not e.is_conflict:  # already exists: informer lag
                    raise

    def start(self):
        self.ns_informer.run()
        self.sa_informer.run()
        self.ns_informer.wait_for_sync()
        self.sa_informer.wait_for_sync()
        return self.run()

    def stop(self):
        super().stop()
        self.ns_informer.stop()
        self.sa_informer.stop()


def generate_token(signing_key: bytes, namespace: str, sa_name: str,
                   sa_uid: str, secret_name: str) -> str:
    """Compact JWT (header.claims.signature), HMAC-SHA256 signed. Claims match
    the reference's legacy service-account claims (pkg/serviceaccount/jwt.go:
    iss kubernetes/serviceaccount + namespace/name/uid/secret-name)."""
    def b64(obj) -> str:
        raw = json.dumps(obj, separators=(",", ":"), sort_keys=True).encode()
        return base64.urlsafe_b64encode(raw).rstrip(b"=").decode()

    header = {"alg": "HS256", "typ": "JWT"}
    claims = {
        "iss": "kubernetes/serviceaccount",
        "kubernetes.io/serviceaccount/namespace": namespace,
        "kubernetes.io/serviceaccount/secret.name": secret_name,
        "kubernetes.io/serviceaccount/service-account.name": sa_name,
        "kubernetes.io/serviceaccount/service-account.uid": sa_uid,
        "sub": f"system:serviceaccount:{namespace}:{sa_name}",
    }
    signing_input = f"{b64(header)}.{b64(claims)}"
    sig = hmac.new(signing_key, signing_input.encode(), hashlib.sha256).digest()
    return f"{signing_input}." + \
        base64.urlsafe_b64encode(sig).rstrip(b"=").decode()


class TokensController(Controller):
    name = "serviceaccount-tokens"

    def __init__(self, client: RESTClient, signing_key: bytes = b"dev-signing-key",
                 workers: int = 1):
        super().__init__(workers)
        self.client = client
        self.signing_key = signing_key
        self.sa_informer = Informer(ListWatch(client, "serviceaccounts"))
        self.secret_informer = Informer(ListWatch(client, "secrets"))
        self.sa_informer.add_event_handler(
            on_add=lambda sa: self.enqueue(_key(sa)),
            on_update=lambda old, new: self.enqueue(_key(new)),
            on_delete=self._sa_deleted)
        self.secret_informer.add_event_handler(
            on_delete=self._secret_deleted)

    def _sa_deleted(self, sa):
        # hand cleanup to the workqueue: informer handlers must not block on
        # API calls, and the queue gives us retry on transient failures
        self.enqueue(f"cleanup|{_key(sa)}")

    def _secret_deleted(self, secret):
        ann = (secret.metadata.annotations or {})
        sa_name = ann.get(api.ANN_SERVICE_ACCOUNT_NAME)
        if sa_name:
            self.enqueue(f"{secret.metadata.namespace}/{sa_name}")

    def _token_secrets_of(self, sa):
        out = []
        for s in self.secret_informer.store.list():
            if s.metadata.namespace != sa.metadata.namespace:
                continue
            if s.type != api.SECRET_TYPE_SERVICE_ACCOUNT_TOKEN:
                continue
            ann = s.metadata.annotations or {}
            if ann.get(api.ANN_SERVICE_ACCOUNT_NAME) == sa.metadata.name:
                out.append(s)
        return out

    def sync(self, key: str) -> None:
        if key.startswith("cleanup|"):
            self._cleanup_tokens(key.split("|", 1)[1])
            return
        sa = self.sa_informer.store.get(key)
        if sa is None:
            return
        ns = sa.metadata.namespace
        secret_name = f"{sa.metadata.name}-token"
        if not self._token_secrets_of(sa):
            token = generate_token(self.signing_key, ns, sa.metadata.name,
                                   sa.metadata.uid, secret_name)
            secret = api.Secret(
                metadata=api.ObjectMeta(
                    name=secret_name, namespace=ns,
                    annotations={
                        api.ANN_SERVICE_ACCOUNT_NAME: sa.metadata.name,
                        api.ANN_SERVICE_ACCOUNT_UID: sa.metadata.uid}),
                type=api.SECRET_TYPE_SERVICE_ACCOUNT_TOKEN,
                data={"token": base64.b64encode(token.encode()).decode()})
            try:
                self.client.create("secrets", secret, ns)
            except ApiError as e:
                if not e.is_conflict:
                    raise
        # link the secret from the service account even when the secret was
        # created by an earlier sync whose update step failed (conflicts
        # propagate so the rate-limited requeue retries the link)
        if not any(r.name == secret_name for r in (sa.secrets or [])):
            try:
                fresh = deep_copy(self.client.get("serviceaccounts",
                                                  sa.metadata.name, ns))
                refs = list(fresh.secrets or [])
                if not any(r.name == secret_name for r in refs):
                    refs.append(api.ObjectReference(
                        kind="Secret", namespace=ns, name=secret_name))
                    fresh.secrets = refs
                    self.client.update("serviceaccounts", fresh, ns)
            except ApiError as e:
                if e.is_not_found:
                    return  # SA vanished; cleanup path handles the secret
                raise  # incl. conflicts: requeue retries the link

    def _cleanup_tokens(self, nn: str) -> None:
        ns, name = nn.split("/", 1)
        for s in self.secret_informer.store.list():
            if s.metadata.namespace != ns:
                continue
            if s.type != api.SECRET_TYPE_SERVICE_ACCOUNT_TOKEN:
                continue
            if (s.metadata.annotations or {}).get(
                    api.ANN_SERVICE_ACCOUNT_NAME) != name:
                continue
            try:
                self.client.delete("secrets", s.metadata.name, ns)
            except ApiError as e:
                if not e.is_not_found:
                    raise

    def start(self):
        self.sa_informer.run()
        self.secret_informer.run()
        self.sa_informer.wait_for_sync()
        self.secret_informer.wait_for_sync()
        return self.run()

    def stop(self):
        super().stop()
        self.sa_informer.stop()
        self.secret_informer.stop()


def _key(obj) -> str:
    return f"{obj.metadata.namespace}/{obj.metadata.name}"
