"""Replication controller: converge pod count to spec.replicas.

Parity target: reference pkg/controller/replication/replication_controller.go
(615 ln core) — watch RCs + pods; per RC key, diff matching active pods vs
desired replicas; create from template / delete surplus. Pod churn enqueues
the owning RC. The created-by annotation records provenance
(kubernetes.io/created-by)."""

from __future__ import annotations

import logging
from typing import List

from kubernetes_tpu.api import labels as labelsel
from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.serialization import deep_copy
from kubernetes_tpu.client import Informer, ListWatch, RESTClient
from kubernetes_tpu.client.record import EventRecorder
from kubernetes_tpu.client.rest import ApiError
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.controllers.expectations import ControllerExpectations
from kubernetes_tpu.controllers.pod_control import (
    deletion_rank, is_pod_active, pod_from_template,
)

log = logging.getLogger("rc-controller")


class ReplicationManager(Controller):
    name = "replication"

    def __init__(self, client: RESTClient, workers: int = 2,
                 burst_replicas: int = 500):
        super().__init__(workers)
        self.client = client
        self.burst = burst_replicas
        self.recorder = EventRecorder(client, "replication-controller")
        self.rc_informer = Informer(ListWatch(client, "replicationcontrollers"))
        self.pod_informer = Informer(ListWatch(client, "pods"))
        self.expectations = ControllerExpectations()
        self.rc_informer.add_event_handler(
            on_add=lambda rc: self.enqueue(_key(rc)),
            on_update=lambda old, new: self.enqueue(_key(new)),
            on_delete=self._rc_deleted)
        self.pod_informer.add_event_handler(
            on_add=self._pod_added,
            on_update=lambda old, new: self._pod_changed(new),
            on_delete=self._pod_deleted)

    def _rc_deleted(self, rc: api.ReplicationController):
        self.expectations.delete_expectations(_key(rc))
        self.enqueue(_key(rc))

    def on_sync_error(self, key: str, err: Exception) -> None:
        """Failed syncs surface as Warning Events on the RC (the base
        worker already logged + counted them) — the correlator dedups a
        crash-looping sync into one climbing count."""
        rc = self.rc_informer.store.get(key)
        if rc is not None:
            self.recorder.event(rc, "Warning", "FailedSync",
                                f"Error syncing: {type(err).__name__}: {err}")

    def _pod_added(self, pod: api.Pod):
        for rc in self._controllers_for(pod):
            self.expectations.creation_observed(_key(rc))
            self.enqueue(_key(rc))

    def _pod_deleted(self, pod: api.Pod):
        for rc in self._controllers_for(pod):
            self.expectations.deletion_observed(_key(rc))
            self.enqueue(_key(rc))

    def _pod_changed(self, pod: api.Pod):
        for rc in self._controllers_for(pod):
            self.enqueue(_key(rc))

    def _controllers_for(self, pod: api.Pod) -> List[api.ReplicationController]:
        out = []
        lbls = (pod.metadata.labels or {})
        for rc in self.rc_informer.store.list():
            if rc.metadata.namespace != pod.metadata.namespace:
                continue
            sel = rc.spec.selector if rc.spec else None
            if sel and labelsel.selector_from_map(sel).matches(lbls):
                out.append(rc)
        return out

    # --- reconcile -----------------------------------------------------------

    def sync(self, key: str) -> None:
        ns, name = key.split("/", 1)
        rc = self.rc_informer.store.get(key)
        if rc is None:
            return  # deleted; pods are left to the GC / cascade path
        sel = labelsel.selector_from_map(rc.spec.selector)
        pods = [p for p in self.pod_informer.store.list()
                if p.metadata.namespace == ns
                and is_pod_active(p)
                and sel.matches(p.metadata.labels or {})]
        if self.expectations.satisfied_expectations(key):
            self._manage_replicas(key, rc, pods)
        self._update_status(rc, pods)

    def _manage_replicas(self, key: str, rc: api.ReplicationController,
                         pods: list) -> None:
        ns = rc.metadata.namespace
        diff = (rc.spec.replicas or 0) - len(pods)
        if diff > 0:
            n = min(diff, self.burst)
            self.expectations.expect_creations(key, n)
            created = 0
            try:
                for _ in range(n):
                    self._create_pod(rc)
                    created += 1
            except ApiError as e:
                # the watch will never deliver the failed + untried pods;
                # un-expect all of them so the requeued sync isn't blocked
                # for the full expectations timeout
                self.recorder.event(rc, "Warning", "FailedCreate",
                                    f"Error creating: {e}")
                for _ in range(n - created):
                    self.expectations.creation_observed(key)
                raise
        elif diff < 0:
            # delete surplus: prefer unassigned, then unready (the reference
            # sorts by activePods ranking)
            victims = sorted(pods, key=deletion_rank)[: min(-diff, self.burst)]
            self.expectations.expect_deletions(key, len(victims))
            for i, p in enumerate(victims):
                try:
                    self.client.delete("pods", p.metadata.name, ns)
                    self.recorder.event(
                        rc, "Normal", "SuccessfulDelete",
                        f"Deleted pod: {p.metadata.name}")
                except ApiError as e:
                    if e.is_not_found:
                        self.expectations.deletion_observed(key)
                        continue
                    # un-expect the failed + untried deletions before the
                    # requeue, same reasoning as the create path
                    for _ in range(len(victims) - i):
                        self.expectations.deletion_observed(key)
                    raise

    def _create_pod(self, rc: api.ReplicationController):
        pod = pod_from_template("ReplicationController", rc,
                                rc.spec.template or api.PodTemplateSpec())
        created = self.client.create("pods", pod, rc.metadata.namespace)
        self.recorder.event(rc, "Normal", "SuccessfulCreate",
                            f"Created pod: {created.metadata.name}")

    def _update_status(self, rc: api.ReplicationController, pods: list):
        desired_status = len(pods)
        if rc.status and rc.status.replicas == desired_status:
            return
        fresh = deep_copy(rc)
        fresh.status = api.ReplicationControllerStatus(replicas=desired_status)
        try:
            self.client.update_status("replicationcontrollers", fresh)
        except ApiError as e:
            if not (e.is_not_found or e.is_conflict):
                raise

    # --- lifecycle -----------------------------------------------------------

    def start(self):
        self.rc_informer.run()
        self.pod_informer.run()
        self.rc_informer.wait_for_sync()
        self.pod_informer.wait_for_sync()
        return self.run()

    def stop(self):
        super().stop()
        self.rc_informer.stop()
        self.pod_informer.stop()


def _key(obj) -> str:
    return f"{obj.metadata.namespace}/{obj.metadata.name}"
