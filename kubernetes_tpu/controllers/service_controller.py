"""Service load-balancer controller.

Parity target: reference pkg/controller/service/servicecontroller.go —
for every Service of type LoadBalancer, ensure a cloud LB fronting the
ready nodes and publish its ingress IP in status.loadBalancer; tear the
LB down when the service is deleted or its type changes away. Node
readiness changes re-target every LB (the reference's nodeSyncLoop).
"""

from __future__ import annotations

import logging

from kubernetes_tpu.api import types as api
from kubernetes_tpu.client import Informer, ListWatch, RESTClient
from kubernetes_tpu.client.rest import ApiError
from kubernetes_tpu.controllers.base import Controller

log = logging.getLogger("service-controller")


def _key(obj) -> str:
    return f"{obj.metadata.namespace}/{obj.metadata.name}"


def _lb_name(key: str) -> str:
    return "lb-" + key.replace("/", "-")


def _node_ready(node: api.Node) -> bool:
    for c in (node.status.conditions or []) if node.status else []:
        if c.type == api.NODE_READY:
            return c.status == api.CONDITION_TRUE
    return False


class ServiceController(Controller):
    name = "service-lb"

    def __init__(self, client: RESTClient, cloud, workers: int = 2):
        super().__init__(workers)
        self.client = client
        self.cloud = cloud
        self.svc_informer = Informer(ListWatch(client, "services"))
        self.node_informer = Informer(ListWatch(client, "nodes"))
        self.svc_informer.add_event_handler(
            on_add=lambda s: self.enqueue(_key(s)),
            on_update=lambda o, n: self.enqueue(_key(n)),
            on_delete=lambda s: self.enqueue(_key(s)))
        # node membership changes re-target every LB (nodeSyncLoop)
        self.node_informer.add_event_handler(
            on_add=lambda n: self._resync_all(),
            on_update=self._node_updated,
            on_delete=lambda n: self._resync_all())

    def _node_updated(self, old: api.Node, new: api.Node):
        if _node_ready(old) != _node_ready(new):
            self._resync_all()

    def _resync_all(self):
        for svc in self.svc_informer.store.list():
            if svc.spec and svc.spec.type == "LoadBalancer":
                self.enqueue(_key(svc))

    def _ready_node_names(self):
        return sorted(n.metadata.name for n in self.node_informer.store.list()
                      if _node_ready(n))

    def sync(self, key: str) -> None:
        svc = self.svc_informer.store.get(key)
        if svc is None or svc.spec is None \
                or svc.spec.type != "LoadBalancer":
            # deleted or no longer LB-typed: the cloud resource must go
            if self.cloud.get_load_balancer(_lb_name(key)) is not None:
                self.cloud.delete_load_balancer(_lb_name(key))
                log.info("deleted load balancer for %s", key)
            if svc is not None and svc.status \
                    and svc.status.load_balancer \
                    and svc.status.load_balancer.ingress:
                self._patch_status(svc, None)
            return
        ports = [p.port for p in (svc.spec.ports or [])]
        ip = self.cloud.ensure_load_balancer(
            _lb_name(key), ports, self._ready_node_names())
        cur = ""
        if svc.status and svc.status.load_balancer \
                and svc.status.load_balancer.ingress:
            cur = svc.status.load_balancer.ingress[0].ip
        if cur != ip:
            self._patch_status(
                svc, api.LoadBalancerStatus(
                    ingress=[api.LoadBalancerIngress(ip=ip)]))
            log.info("service %s load balancer at %s", key, ip)

    def _patch_status(self, svc: api.Service, lb) -> None:
        from kubernetes_tpu.api.serialization import scheme
        enc = (scheme.encode(api.Service(status=api.ServiceStatus(
            load_balancer=lb))).get("status") or {})
        try:
            self.client.patch(
                "services", svc.metadata.name,
                {"status": {"loadBalancer": enc.get("loadBalancer")}},
                svc.metadata.namespace or "default",
                patch_type=self.client.MERGE_PATCH)
        except ApiError as e:
            if not e.is_not_found:
                raise

    def start(self):
        self.svc_informer.run()
        self.node_informer.run()
        self.svc_informer.wait_for_sync()
        self.node_informer.wait_for_sync()
        return self.run()

    def stop(self):
        super().stop()
        self.svc_informer.stop()
        self.node_informer.stop()
