"""Horizontal pod autoscaler controller.

Parity target: reference pkg/controller/podautoscaler/horizontal.go —
periodically, for each HPA: read the target's scale subresource, compute the
pods' average CPU utilization vs the target percentage, and set

    desired = ceil(current * currentUtilization / targetUtilization)

within a 10% tolerance band, clamped to [minReplicas, maxReplicas]
(computeReplicasForCPUUtilization). The reference pulls utilization from
heapster (metrics_client.go); here the metrics source is pluggable, with the
default reading the per-pod cpu-utilization annotation that hollow kubelets
(kubemark) publish."""

from __future__ import annotations

import logging
import math
from typing import List, Optional

from kubernetes_tpu.api import labels as labelsel
from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.serialization import deep_copy
from kubernetes_tpu.apis import autoscaling
from kubernetes_tpu.client import Informer, ListWatch, RESTClient
from kubernetes_tpu.client.rest import ApiError
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.controllers.pod_control import is_pod_active
from kubernetes_tpu.utils.timeutil import now_iso

log = logging.getLogger("hpa-controller")

# annotation a node agent (or test) publishes per pod: integer percent of
# the pod's cpu request currently used
ANN_CPU_UTILIZATION = "metrics.alpha.kubernetes.io/cpu-utilization"

TOLERANCE = 0.1  # reference defaultTolerance
DEFAULT_TARGET_UTILIZATION = 80

KIND_TO_RESOURCE = {
    "ReplicationController": "replicationcontrollers",
    "ReplicaSet": "replicasets",
    "Deployment": "deployments",
}


class AnnotationMetricsClient:
    """Average the per-pod utilization annotations (stand-in for heapster)."""

    def cpu_utilization(self, pods: List[api.Pod]) -> Optional[int]:
        vals = []
        for p in pods:
            raw = (p.metadata.annotations or {}).get(ANN_CPU_UTILIZATION)
            if raw is None:
                continue
            try:
                vals.append(int(raw))
            except ValueError:
                continue
        if not vals:
            return None
        return int(round(sum(vals) / len(vals)))


class HorizontalController(Controller):
    name = "horizontalpodautoscaler"

    def __init__(self, client: RESTClient, metrics_client=None,
                 sync_seconds: float = 15.0, workers: int = 1):
        super().__init__(workers)
        self.client = client
        self.metrics = metrics_client or AnnotationMetricsClient()
        self.sync_seconds = sync_seconds
        self.hpa_informer = Informer(ListWatch(client, "horizontalpodautoscalers"))
        self.pod_informer = Informer(ListWatch(client, "pods"))
        self.hpa_informer.add_event_handler(
            on_add=lambda h: self.enqueue(_key(h)),
            on_update=lambda old, new: self.enqueue(_key(new)))

    # --- reconcile -----------------------------------------------------------

    def sync(self, key: str) -> None:
        hpa = self.hpa_informer.store.get(key)
        if hpa is None or hpa.spec is None:
            self.disarm_resync(key)
            return
        try:
            self._reconcile(hpa)
        finally:
            self.arm_resync(key, self.sync_seconds)  # periodic resync

    def _reconcile(self, hpa: autoscaling.HorizontalPodAutoscaler) -> None:
        ref = hpa.spec.scale_target_ref
        resource = KIND_TO_RESOURCE.get(ref.kind if ref else "")
        if resource is None:
            log.info("hpa %s: unsupported target kind %r", _key(hpa),
                     ref.kind if ref else None)
            return
        ns = hpa.metadata.namespace
        try:
            scale = self.client.get_scale(resource, ref.name, ns)
        except ApiError as e:
            if e.is_not_found:
                return
            raise
        current = scale.status.replicas if scale.status else 0
        if current == 0:
            # replicas==0 means autoscaling is deliberately disabled
            # (reference horizontal.go: never scale a 0-replica target)
            self._update_status(hpa, 0, 0, None, scaled=False)
            return
        selector = scale.status.selector if scale.status else None
        if not selector:
            # no selector -> we cannot attribute pods to the target; a nil
            # map would otherwise match every pod in the namespace
            log.info("hpa %s: target has no selector; skipping", _key(hpa))
            return
        target_util = (hpa.spec.target_cpu_utilization_percentage
                       or DEFAULT_TARGET_UTILIZATION)

        desired = current
        sel = labelsel.selector_from_map(selector)
        pods = [p for p in self.pod_informer.store.list()
                if p.metadata.namespace == ns and is_pod_active(p)
                and sel.matches(p.metadata.labels or {})]
        current_util = self.metrics.cpu_utilization(pods)
        if current_util is not None:
            ratio = current_util / target_util
            if abs(ratio - 1.0) > TOLERANCE:
                desired = int(math.ceil(ratio * current))

        min_r = hpa.spec.min_replicas or 1
        desired = max(min_r, min(hpa.spec.max_replicas or desired, desired))

        if desired != current:
            sc = deep_copy(scale)
            sc.spec.replicas = desired
            try:
                self.client.update_scale(resource, ref.name, ns, sc)
            except ApiError as e:
                if not e.is_conflict:
                    raise
                return  # retry at next resync on fresh state
        self._update_status(hpa, current, desired, current_util,
                            scaled=desired != current)

    def _update_status(self, hpa, current: int, desired: int,
                       current_util: Optional[int], scaled: bool) -> None:
        st = hpa.status
        if (st and st.current_replicas == current
                and st.desired_replicas == desired
                and st.current_cpu_utilization_percentage == current_util
                and not scaled):
            return
        fresh = deep_copy(hpa)
        fresh.status = autoscaling.HorizontalPodAutoscalerStatus(
            current_replicas=current, desired_replicas=desired,
            current_cpu_utilization_percentage=current_util,
            last_scale_time=now_iso() if scaled
            else (st.last_scale_time if st else None))
        try:
            self.client.update_status("horizontalpodautoscalers", fresh)
        except ApiError as e:
            if not (e.is_not_found or e.is_conflict):
                raise

    # --- lifecycle -----------------------------------------------------------

    def start(self):
        self.hpa_informer.run()
        self.pod_informer.run()
        self.hpa_informer.wait_for_sync()
        self.pod_informer.wait_for_sync()
        return self.run()

    def stop(self):
        super().stop()
        self.hpa_informer.stop()
        self.pod_informer.stop()


def _key(obj) -> str:
    return f"{obj.metadata.namespace}/{obj.metadata.name}"
