"""Deployment controller: declarative rollouts over replica sets.

Parity target: reference pkg/controller/deployment/deployment_controller.go
(1,288 ln) + pkg/util/deployment/deployment.go. Reconcile shape:

  - the deployment's pod template is hashed; the replica set named
    {deployment}-{hash} (labeled pod-template-hash={hash}) is "new", every
    other matching RS is "old" (GetNewReplicaSet / GetOldReplicaSets)
  - Recreate: scale all old RSes to 0, wait for their pods to exit, then
    scale the new RS up to spec.replicas
  - RollingUpdate: scale the new RS up bounded by maxSurge, scale old RSes
    down bounded by maxUnavailable against the count of available pods
    (reconcileNewReplicaSet / reconcileOldReplicaSets)
  - each new template revision bumps deployment.kubernetes.io/revision on
    the new RS; rollback (spec.rollbackTo) copies an old RS's template back
    into the deployment spec and clears rollbackTo (rollback in
    deployment_controller.go:480-530)
  - old RSes at 0 replicas beyond revisionHistoryLimit are deleted
"""

from __future__ import annotations

import logging
from typing import List, Optional, Tuple

from kubernetes_tpu.api import labels as labelsel
from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.serialization import deep_copy, to_dict
from kubernetes_tpu.apis import extensions as ext
from kubernetes_tpu.client import Informer, ListWatch, RESTClient
from kubernetes_tpu.client.rest import ApiError
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.controllers.pod_control import (
    is_pod_active, is_pod_available, pod_template_hash, selector_for,
)

log = logging.getLogger("deployment-controller")

HASH_LABEL = "pod-template-hash"


def resolve_fenceposts(strategy: Optional[ext.DeploymentStrategy],
                       replicas: int) -> Tuple[int, int]:
    """(maxSurge, maxUnavailable) as absolute counts; percents round
    surge up, unavailable down; both zero resolves to unavailable=1
    (reference deployment.ResolveFenceposts)."""
    ru = strategy.rolling_update if strategy and strategy.rolling_update else None
    surge = _int_or_percent(ru.max_surge if ru else None, replicas, round_up=True,
                            default=1)
    unavail = _int_or_percent(ru.max_unavailable if ru else None, replicas,
                              round_up=False, default=1)
    if surge == 0 and unavail == 0:
        unavail = 1
    return surge, unavail


def _int_or_percent(v, total: int, round_up: bool, default: int) -> int:
    if v is None:
        return default
    if isinstance(v, str) and v.endswith("%"):
        pct = int(v[:-1])
        exact = total * pct / 100.0
        return int(-(-exact // 1)) if round_up else int(exact)
    return int(v)


def _template_equal(a: Optional[api.PodTemplateSpec],
                    b: Optional[api.PodTemplateSpec]) -> bool:
    """Compare templates ignoring the pod-template-hash label the controller
    itself injects (reference equalIgnoreHash)."""
    def strip(t):
        if t is None:
            return {}
        d = to_dict(deep_copy(t))
        meta = d.get("metadata") or {}
        (meta.get("labels") or {}).pop(HASH_LABEL, None)
        return d
    return strip(a) == strip(b)


class DeploymentController(Controller):
    name = "deployment"

    def __init__(self, client: RESTClient, workers: int = 2):
        super().__init__(workers)
        self.client = client
        self.d_informer = Informer(ListWatch(client, "deployments"))
        self.rs_informer = Informer(ListWatch(client, "replicasets"))
        self.pod_informer = Informer(ListWatch(client, "pods"))
        self.d_informer.add_event_handler(
            on_add=lambda d: self.enqueue(_key(d)),
            on_update=lambda old, new: self.enqueue(_key(new)),
            on_delete=lambda d: self.enqueue(_key(d)))
        self.rs_informer.add_event_handler(
            on_add=self._rs_changed,
            on_update=lambda old, new: self._rs_changed(new),
            on_delete=self._rs_changed)
        self.pod_informer.add_event_handler(
            on_update=lambda old, new: self._pod_changed(new),
            on_delete=self._pod_changed)

    def _rs_changed(self, rs):
        for d in self.d_informer.store.list():
            if d.metadata.namespace != rs.metadata.namespace:
                continue
            if self._selector(d).matches(rs.metadata.labels
                                         or _tpl_labels(rs)):
                self.enqueue(_key(d))

    def _pod_changed(self, pod):
        for d in self.d_informer.store.list():
            if (d.metadata.namespace == pod.metadata.namespace
                    and self._selector(d).matches(pod.metadata.labels or {})):
                self.enqueue(_key(d))

    @staticmethod
    def _selector(d: ext.Deployment) -> labelsel.Selector:
        return selector_for(d)

    # --- reconcile -----------------------------------------------------------

    def sync(self, key: str) -> None:
        d = self.d_informer.store.get(key)
        if d is None or d.spec is None:
            return
        if d.spec.rollback_to is not None:
            self._rollback(d)
            return
        if d.spec.paused:
            self._sync_status(d)
            return
        new_rs, old_rses = self._get_or_create_new_rs(d)
        if (d.spec.strategy and d.spec.strategy.type == ext.RECREATE):
            self._recreate(d, new_rs, old_rses)
        else:
            self._rolling(d, new_rs, old_rses)
        self._cleanup_history(d, new_rs, old_rses)
        self._sync_status(d)

    # replica sets ------------------------------------------------------------

    def _matching_rses(self, d) -> List[api.ReplicaSet]:
        sel = self._selector(d)
        return [rs for rs in self.rs_informer.store.list()
                if rs.metadata.namespace == d.metadata.namespace
                and sel.matches(rs.metadata.labels or _tpl_labels(rs))]

    def _get_or_create_new_rs(self, d):
        tpl_hash = pod_template_hash(d.spec.template or api.PodTemplateSpec())
        rses = self._matching_rses(d)
        new_rs = None
        old_rses = []
        for rs in rses:
            if _template_equal(rs.spec.template if rs.spec else None,
                               d.spec.template):
                new_rs = rs
            else:
                old_rses.append(rs)
        if new_rs is not None:
            return new_rs, old_rses

        # next revision = max(old revisions) + 1
        max_rev = 0
        for rs in old_rses:
            try:
                max_rev = max(max_rev, int(
                    (rs.metadata.annotations or {}).get(ext.ANN_REVISION, "0")))
            except ValueError:
                pass
        tpl = deep_copy(d.spec.template) if d.spec.template else \
            api.PodTemplateSpec()
        if tpl.metadata is None:
            tpl.metadata = api.ObjectMeta()
        tpl.metadata.labels = dict(tpl.metadata.labels or {})
        tpl.metadata.labels[HASH_LABEL] = tpl_hash
        sel = deep_copy(d.spec.selector) if d.spec.selector else \
            api.LabelSelector(match_labels=dict(tpl.metadata.labels))
        if sel.match_labels is None:
            sel.match_labels = {}
        sel.match_labels[HASH_LABEL] = tpl_hash
        rs = api.ReplicaSet(
            metadata=api.ObjectMeta(
                name=f"{d.metadata.name}-{tpl_hash}",
                namespace=d.metadata.namespace,
                labels=dict(tpl.metadata.labels),
                annotations={ext.ANN_REVISION: str(max_rev + 1)},
                owner_references=[api.OwnerReference(
                    kind="Deployment", name=d.metadata.name,
                    uid=d.metadata.uid, controller=True)]),
            spec=api.ReplicaSetSpec(replicas=0, selector=sel, template=tpl))
        try:
            created = self.client.create("replicasets", rs,
                                         d.metadata.namespace)
        except ApiError as e:
            if not e.is_conflict:
                raise
            created = self.client.get("replicasets", rs.metadata.name,
                                      d.metadata.namespace)
        return created, old_rses

    def _scale_rs(self, rs, replicas: int):
        if (rs.spec.replicas or 0) == replicas:
            return rs
        fresh = deep_copy(rs)
        fresh.spec.replicas = replicas
        # conflicts propagate: the rate-limited requeue retries on fresh state
        return self.client.update("replicasets", fresh, rs.metadata.namespace)

    # strategies --------------------------------------------------------------

    def _pods_of(self, d, sel=None) -> List[api.Pod]:
        sel = sel or self._selector(d)
        return [p for p in self.pod_informer.store.list()
                if p.metadata.namespace == d.metadata.namespace
                and sel.matches(p.metadata.labels or {})]

    def _recreate(self, d, new_rs, old_rses):
        scaled_down = False
        for rs in old_rses:
            if (rs.spec.replicas or 0) != 0:
                self._scale_rs(rs, 0)
                scaled_down = True
        if scaled_down:
            raise RuntimeError("recreate: waiting for old replica sets to scale down")
        # any old pod still active -> wait (watch events requeue us)
        old_hashes = {(_tpl_labels(rs) or {}).get(HASH_LABEL) for rs in old_rses}
        for p in self._pods_of(d):
            if (is_pod_active(p)
                    and (p.metadata.labels or {}).get(HASH_LABEL) in old_hashes):
                raise RuntimeError("recreate: old pods still terminating")
        self._scale_rs(new_rs, d.spec.replicas or 0)

    def _rolling(self, d, new_rs, old_rses):
        replicas = d.spec.replicas or 0
        surge, max_unavail = resolve_fenceposts(d.spec.strategy, replicas)
        old_total = sum((rs.spec.replicas or 0) for rs in old_rses)
        new_count = new_rs.spec.replicas or 0

        # deployment scaled down below what the new RS already runs
        # (reconcileNewReplicaSet's rsSize > deployment size branch)
        if new_count > replicas:
            self._scale_rs(new_rs, replicas)
            return

        # scale up new RS bounded by maxSurge (reconcileNewReplicaSet)
        if new_count < replicas:
            allowed = replicas + surge - old_total
            target = max(new_count, min(replicas, allowed))
            if target != new_count:
                new_rs = self._scale_rs(new_rs, target)
                return  # wait for pods; watch requeues

        if old_total == 0:
            return
        sel = self._selector(d)
        pods = self._pods_of(d, sel)
        available_by_hash = {}
        for p in pods:
            if is_pod_available(p):
                h = (p.metadata.labels or {}).get(HASH_LABEL, "")
                available_by_hash[h] = available_by_hash.get(h, 0) + 1

        # first scale down UNHEALTHY old replicas — killing a not-available
        # pod can't violate maxUnavailable (cleanupUnhealthyReplicas); without
        # this, crash-looping old pods + maxSurge=0 deadlocks the rollout
        progressed = False
        for rs in sorted(old_rses, key=_revision):
            cur = rs.spec.replicas or 0
            if cur == 0:
                continue
            rs_hash = (_tpl_labels(rs) or {}).get(HASH_LABEL, "")
            healthy = available_by_hash.get(rs_hash, 0)
            if cur > healthy:
                self._scale_rs(rs, healthy)
                progressed = True
        if progressed:
            return  # recompute totals on the requeue the scale-down triggers

        # then scale down healthy old RSes bounded by maxUnavailable against
        # AVAILABLE pods (reconcileOldReplicaSets: never dip below
        # replicas - maxUnavailable available pods)
        available = sum(available_by_hash.values())
        min_available = replicas - max_unavail
        cleanup_budget = available - min_available
        if cleanup_budget <= 0:
            return  # not enough ready pods to make progress yet
        for rs in sorted(old_rses, key=_revision, reverse=True):
            if cleanup_budget <= 0:
                break
            cur = rs.spec.replicas or 0
            if cur == 0:
                continue
            down = min(cur, cleanup_budget)
            self._scale_rs(rs, cur - down)
            cleanup_budget -= down

    def _cleanup_history(self, d, new_rs, old_rses):
        limit = d.spec.revision_history_limit
        if limit is None:
            return
        dead = sorted([rs for rs in old_rses if (rs.spec.replicas or 0) == 0
                       and (rs.status is None or rs.status.replicas == 0)],
                      key=_revision)
        for rs in dead[: max(0, len(dead) - limit)]:
            try:
                self.client.delete("replicasets", rs.metadata.name,
                                   rs.metadata.namespace)
            except ApiError as e:
                if not e.is_not_found:
                    raise

    # rollback ----------------------------------------------------------------

    def _rollback(self, d):
        target_rev = d.spec.rollback_to.revision
        rses = self._matching_rses(d)
        if target_rev == 0:  # revision 0 = previous revision
            revs = sorted((_revision(rs) for rs in rses), reverse=True)
            target_rev = revs[1] if len(revs) > 1 else 0
        target = next((rs for rs in rses if _revision(rs) == target_rev), None)
        fresh = deep_copy(self.client.get("deployments", d.metadata.name,
                                          d.metadata.namespace))
        if target is not None and target.spec and target.spec.template:
            tpl = deep_copy(target.spec.template)
            if tpl.metadata and tpl.metadata.labels:
                tpl.metadata.labels.pop(HASH_LABEL, None)
            fresh.spec.template = tpl
        # clear rollbackTo whether or not the revision was found (reference
        # emits RollbackRevisionNotFound and clears)
        fresh.spec.rollback_to = None
        try:
            self.client.update("deployments", fresh, d.metadata.namespace)
        except ApiError as e:
            if not e.is_conflict:
                raise

    # status ------------------------------------------------------------------

    def _sync_status(self, d):
        sel = self._selector(d)
        pods = [p for p in self._pods_of(d, sel) if is_pod_active(p)]
        tpl_hash = pod_template_hash(d.spec.template or api.PodTemplateSpec())
        total = len(pods)
        updated = sum(1 for p in pods
                      if (p.metadata.labels or {}).get(HASH_LABEL) == tpl_hash)
        available = sum(1 for p in pods if is_pod_available(p))
        st = d.status
        if (st and st.replicas == total and st.updated_replicas == updated
                and st.available_replicas == available):
            return
        fresh = deep_copy(d)
        fresh.status = ext.DeploymentStatus(
            replicas=total, updated_replicas=updated,
            available_replicas=available,
            unavailable_replicas=max(0, (d.spec.replicas or 0) - available))
        try:
            self.client.update_status("deployments", fresh)
        except ApiError as e:
            if not (e.is_not_found or e.is_conflict):
                raise

    # lifecycle ---------------------------------------------------------------

    def start(self):
        for inf in (self.d_informer, self.rs_informer, self.pod_informer):
            inf.run()
        for inf in (self.d_informer, self.rs_informer, self.pod_informer):
            inf.wait_for_sync()
        return self.run()

    def stop(self):
        super().stop()
        for inf in (self.d_informer, self.rs_informer, self.pod_informer):
            inf.stop()


def _key(obj) -> str:
    return f"{obj.metadata.namespace}/{obj.metadata.name}"


def _revision(rs) -> int:
    try:
        return int((rs.metadata.annotations or {}).get(ext.ANN_REVISION, "0"))
    except ValueError:
        return 0


def _tpl_labels(rs) -> dict:
    tpl = rs.spec.template if rs.spec else None
    return (tpl.metadata.labels if tpl and tpl.metadata else None) or {}
