"""L6 reconciliation controllers.

Parity target: reference pkg/controller (35.9k LoC) +
cmd/kube-controller-manager — the informer + workqueue + reconcile pattern:
watch desired state, compare to observed, converge. Inventory here:
replication (replication_controller.py), endpoints (endpoints_controller.py),
node lifecycle (node_controller.py), namespace cascade (namespace_controller.py),
all composed by ControllerManager (manager.py) under leader election.
"""

from kubernetes_tpu.controllers.manager import ControllerManager
