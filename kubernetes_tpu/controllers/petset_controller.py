"""PetSet controller: stateful pods with stable, ordinal identity.

Parity target: reference pkg/controller/petset (pet_set.go, pet.go,
identity_mappers.go) — pods named {set}-0 … {set}-{N-1}; creation strictly in
ordinal order, each pet gated on its predecessor being Running+Ready; scale
down removes the highest ordinal first; each volumeClaimTemplate yields a
per-pet PVC named {template}-{pet} that the pet mounts; pet hostname/subdomain
come from the governing service (spec.serviceName)."""

from __future__ import annotations

import logging
import re
from typing import Dict, List

from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.serialization import deep_copy
from kubernetes_tpu.apis import apps
from kubernetes_tpu.client import Informer, ListWatch, RESTClient
from kubernetes_tpu.client.rest import ApiError
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.controllers.pod_control import (
    is_pod_active, is_pod_ready, selector_for,
)

log = logging.getLogger("petset-controller")

ANN_POD_NAME = "pod.alpha.kubernetes.io/name"
ANN_SUBDOMAIN = "pod.alpha.kubernetes.io/subdomain"


def pet_name(ps: apps.PetSet, ordinal: int) -> str:
    return f"{ps.metadata.name}-{ordinal}"


def pet_ordinal(ps: apps.PetSet, pod: api.Pod) -> int:
    m = re.fullmatch(re.escape(ps.metadata.name) + r"-(\d+)",
                     pod.metadata.name)
    return int(m.group(1)) if m else -1


class PetSetController(Controller):
    name = "petset"

    def __init__(self, client: RESTClient, workers: int = 1):
        super().__init__(workers)
        self.client = client
        self.ps_informer = Informer(ListWatch(client, "petsets"))
        self.pod_informer = Informer(ListWatch(client, "pods"))
        self.ps_informer.add_event_handler(
            on_add=lambda ps: self.enqueue(_key(ps)),
            on_update=lambda old, new: self.enqueue(_key(new)))
        self.pod_informer.add_event_handler(
            on_add=self._pod_changed,
            on_update=lambda old, new: self._pod_changed(new),
            on_delete=self._pod_changed)

    def _pod_changed(self, pod):
        lbls = pod.metadata.labels or {}
        for ps in self.ps_informer.store.list():
            if (ps.metadata.namespace == pod.metadata.namespace
                    and selector_for(ps).matches(lbls)):
                self.enqueue(_key(ps))

    # --- reconcile -----------------------------------------------------------

    def sync(self, key: str) -> None:
        ns, _ = key.split("/", 1)
        ps = self.ps_informer.store.get(key)
        if ps is None:
            return
        sel = selector_for(ps)
        pets: Dict[int, api.Pod] = {}
        for p in self.pod_informer.store.list():
            if (p.metadata.namespace != ns
                    or not sel.matches(p.metadata.labels or {})):
                continue
            o = pet_ordinal(ps, p)
            if o < 0:
                continue
            if not is_pod_active(p):
                # a terminated pet still occupies its ordinal name; delete it
                # so the recreate below isn't a perpetual 409 (reference
                # pet_set.go replaces failed pets)
                if p.metadata.deletion_timestamp is None:
                    try:
                        self.client.delete("pods", p.metadata.name, ns)
                    except ApiError as e:
                        if not e.is_not_found:
                            raise
                continue
            pets[o] = p
        want = ps.spec.replicas or 0

        # scale up: create the FIRST missing ordinal, but only if every lower
        # ordinal is Running+Ready (sequential bring-up, pet_set.go syncPetSet)
        for i in range(want):
            pod = pets.get(i)
            if pod is None:
                self._create_pet(ps, i)
                break
            if not (_running(pod) and is_pod_ready(pod)):
                break  # wait for this pet before creating successors
        else:
            # scale down: highest ordinal first, one at a time
            extra = sorted((o for o in pets if o >= want), reverse=True)
            if extra:
                victim = pets[extra[0]]
                try:
                    self.client.delete("pods", victim.metadata.name, ns)
                except ApiError as e:
                    if not e.is_not_found:
                        raise
        self._update_status(ps, len([o for o in pets if o < want]))

    def _create_pet(self, ps: apps.PetSet, ordinal: int) -> None:
        ns = ps.metadata.namespace
        name = pet_name(ps, ordinal)
        tpl = ps.spec.template or api.PodTemplateSpec()
        spec = deep_copy(tpl.spec) if tpl.spec else api.PodSpec(
            containers=[api.Container(name="c", image="pause")])

        # per-pet claims from volumeClaimTemplates; the pet's volumes point at
        # them by the {template}-{pet} naming contract
        volumes = list(spec.volumes or [])
        for ct in ps.spec.volume_claim_templates or []:
            claim_name = f"{ct.metadata.name}-{name}"
            self._ensure_claim(ns, claim_name, ct)
            volumes = [v for v in volumes if v.name != ct.metadata.name]
            volumes.append(api.Volume(
                name=ct.metadata.name,
                persistent_volume_claim=api.PersistentVolumeClaimVolumeSource(
                    claim_name=claim_name)))
        spec.volumes = volumes or None

        pod = api.Pod(
            metadata=api.ObjectMeta(
                name=name, namespace=ns,
                labels=dict((tpl.metadata.labels if tpl.metadata else None)
                            or {}),
                annotations={ANN_POD_NAME: name,
                             ANN_SUBDOMAIN: ps.spec.service_name or ""},
                owner_references=[api.OwnerReference(
                    kind="PetSet", name=ps.metadata.name,
                    uid=ps.metadata.uid, controller=True)]),
            spec=spec)
        try:
            self.client.create("pods", pod, ns)
        except ApiError as e:
            if not e.is_conflict:  # already exists: informer lag
                raise

    def _ensure_claim(self, ns: str, claim_name: str,
                      template: api.PersistentVolumeClaim) -> None:
        try:
            self.client.get("persistentvolumeclaims", claim_name, ns)
            return
        except ApiError as e:
            if not e.is_not_found:
                raise
        pvc = api.PersistentVolumeClaim(
            metadata=api.ObjectMeta(name=claim_name, namespace=ns),
            spec=deep_copy(template.spec) if template.spec else
            api.PersistentVolumeClaimSpec())
        try:
            self.client.create("persistentvolumeclaims", pvc, ns)
        except ApiError as e:
            if not e.is_conflict:
                raise

    def _update_status(self, ps, replicas: int) -> None:
        if ps.status and ps.status.replicas == replicas:
            return
        fresh = deep_copy(ps)
        fresh.status = apps.PetSetStatus(replicas=replicas)
        try:
            self.client.update_status("petsets", fresh)
        except ApiError as e:
            if not (e.is_not_found or e.is_conflict):
                raise

    # --- lifecycle -----------------------------------------------------------

    def start(self):
        self.ps_informer.run()
        self.pod_informer.run()
        self.ps_informer.wait_for_sync()
        self.pod_informer.wait_for_sync()
        return self.run()

    def stop(self):
        super().stop()
        self.ps_informer.stop()
        self.pod_informer.stop()


def _running(pod: api.Pod) -> bool:
    return (pod.status.phase if pod.status else "") == api.POD_RUNNING


def _key(obj) -> str:
    return f"{obj.metadata.namespace}/{obj.metadata.name}"
