"""ScheduledJob (cron job) controller.

Parity target: reference pkg/controller/scheduledjob (controller.go, utils.go)
— every sync period, for each ScheduledJob: skip if suspended; find the most
recent schedule time due since the last run (cron semantics via utils/cron);
honor startingDeadlineSeconds; apply the concurrency policy (Allow runs
alongside, Forbid skips while active, Replace deletes actives first); create
the Job from spec.jobTemplate named {sj}-{scheduledEpochMinutes}; track it in
status.active and prune finished jobs from that list."""

from __future__ import annotations

import logging
import time
from typing import List

from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.serialization import deep_copy
from kubernetes_tpu.apis import batch
from kubernetes_tpu.client import Informer, ListWatch, RESTClient
from kubernetes_tpu.client.rest import ApiError
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.utils import cron
from kubernetes_tpu.utils.timeutil import parse_iso

log = logging.getLogger("scheduledjob-controller")


def job_name_for(sj: batch.ScheduledJob, scheduled_epoch: float) -> str:
    # deterministic name: re-creating the same scheduled run is a 409, which
    # is how double-fires are deduped (reference getJobName)
    return f"{sj.metadata.name}-{int(scheduled_epoch) // 60}"


class ScheduledJobController(Controller):
    name = "scheduledjob"

    def __init__(self, client: RESTClient, workers: int = 1,
                 sync_seconds: float = 10.0,
                 # cron schedules fire at WALL times ("0 3 * * *" means 3am,
                 # not 3h-of-monotonic)
                 # kube-verify: disable-next-line=monotonic-duration
                 clock=time.time):
        super().__init__(workers)
        self.client = client
        self.sync_seconds = sync_seconds
        self.clock = clock
        self.sj_informer = Informer(ListWatch(client, "scheduledjobs"))
        self.job_informer = Informer(ListWatch(client, "jobs"))
        self.sj_informer.add_event_handler(
            on_add=lambda sj: self.enqueue(_key(sj)),
            on_update=lambda old, new: self.enqueue(_key(new)))
        self.job_informer.add_event_handler(
            on_update=lambda old, new: self._job_changed(new),
            on_delete=self._job_changed)

    def _job_changed(self, job):
        refs = job.metadata.owner_references or []
        for r in refs:
            if r.kind == "ScheduledJob":
                self.enqueue(f"{job.metadata.namespace}/{r.name}")

    # --- reconcile -----------------------------------------------------------

    def sync(self, key: str) -> None:
        sj = self.sj_informer.store.get(key)
        if sj is None:
            self.disarm_resync(key)
            return
        try:
            self._reconcile(sj)
        finally:
            self.arm_resync(key, self.sync_seconds)

    def _reconcile(self, sj: batch.ScheduledJob) -> None:
        ns = sj.metadata.namespace
        active = self._prune_active(sj)
        if sj.spec is None or sj.spec.suspend:
            return
        try:
            sched = cron.parse(sj.spec.schedule)
        except cron.CronParseError as e:
            log.info("scheduledjob %s: bad schedule %r: %s", _key(sj),
                     sj.spec.schedule, e)
            return
        now = self.clock()
        last = parse_iso(sj.status.last_schedule_time
                         if sj.status else None)
        since = last if last is not None else \
            parse_iso(sj.metadata.creation_timestamp) or (now - 60)
        try:
            due = sched.next_after(since)
        except cron.CronParseError:
            return
        if due > now:
            return
        # most recent missed time wins (skip intermediate misses, as the
        # reference does when too many are outstanding)
        latest = due
        while True:
            try:
                nxt = sched.next_after(latest)
            except cron.CronParseError:
                break
            if nxt > now:
                break
            latest = nxt
        deadline = sj.spec.starting_deadline_seconds
        if deadline is not None and now - latest > deadline:
            self._record_schedule(sj, latest)  # missed for good
            return

        policy = sj.spec.concurrency_policy or batch.ALLOW_CONCURRENT
        if active and policy == batch.FORBID_CONCURRENT:
            return
        if active and policy == batch.REPLACE_CONCURRENT:
            for ref in active:
                try:
                    self.client.delete("jobs", ref.name, ns)
                except ApiError as e:
                    if not e.is_not_found:
                        raise

        job = self._job_from_template(sj, latest)
        try:
            created = self.client.create("jobs", job, ns)
        except ApiError as e:
            if not e.is_conflict:
                raise
            created = None  # this scheduled run already fired
        self._record_schedule(sj, latest, created)

    def _prune_active(self, sj) -> List[api.ObjectReference]:
        """Drop finished/vanished jobs from status.active; return live ones."""
        refs = (sj.status.active if sj.status else None) or []
        live = []
        for r in refs:
            job = self.job_informer.store.get(
                f"{sj.metadata.namespace}/{r.name}")
            if job is None:
                # informer may simply lag behind our own create — confirm
                # with the API before declaring the job gone, or Forbid
                # concurrency would launch an overlapping run
                try:
                    job = self.client.get("jobs", r.name,
                                          sj.metadata.namespace)
                except ApiError as e:
                    if not e.is_not_found:
                        raise
                    continue
            if any(c.type in (batch.JOB_COMPLETE, batch.JOB_FAILED)
                   and c.status == api.CONDITION_TRUE
                   for c in ((job.status.conditions or [])
                             if job.status else [])):
                continue
            live.append(r)
        if len(live) != len(refs):
            fresh = deep_copy(sj)
            if fresh.status is None:
                fresh.status = batch.ScheduledJobStatus()
            fresh.status.active = live or None
            try:
                self.client.update_status("scheduledjobs", fresh)
            except ApiError as e:
                if not (e.is_not_found or e.is_conflict):
                    raise
        return live

    def _job_from_template(self, sj, scheduled_epoch: float) -> batch.Job:
        tpl = sj.spec.job_template or batch.JobTemplateSpec()
        meta = tpl.metadata or api.ObjectMeta()
        return batch.Job(
            metadata=api.ObjectMeta(
                name=job_name_for(sj, scheduled_epoch),
                namespace=sj.metadata.namespace,
                labels=dict(meta.labels or {}),
                annotations=dict(meta.annotations or {}),
                owner_references=[api.OwnerReference(
                    kind="ScheduledJob", name=sj.metadata.name,
                    uid=sj.metadata.uid, controller=True)]),
            spec=deep_copy(tpl.spec) if tpl.spec else batch.JobSpec())

    def _record_schedule(self, sj, scheduled_epoch: float,
                         created_job=None) -> None:
        # read-modify-write against the LIVE object: _prune_active may have
        # bumped the resourceVersion this same sync, and silently losing this
        # write would hide the new job from the concurrency-policy check
        for _ in range(5):
            try:
                fresh = deep_copy(self.client.get(
                    "scheduledjobs", sj.metadata.name, sj.metadata.namespace))
            except ApiError as e:
                if e.is_not_found:
                    return
                raise
            if fresh.status is None:
                fresh.status = batch.ScheduledJobStatus()
            fresh.status.last_schedule_time = time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime(scheduled_epoch))
            if created_job is not None:
                refs = [r for r in (fresh.status.active or [])
                        if r.name != created_job.metadata.name]
                refs.append(api.ObjectReference(
                    kind="Job", namespace=created_job.metadata.namespace,
                    name=created_job.metadata.name,
                    uid=created_job.metadata.uid))
                fresh.status.active = refs
            try:
                self.client.update_status("scheduledjobs", fresh)
                return
            except ApiError as e:
                if e.is_not_found:
                    return
                if not e.is_conflict:
                    raise

    # --- lifecycle -----------------------------------------------------------

    def start(self):
        self.sj_informer.run()
        self.job_informer.run()
        self.sj_informer.wait_for_sync()
        self.job_informer.wait_for_sync()
        return self.run()

    def stop(self):
        super().stop()
        self.sj_informer.stop()
        self.job_informer.stop()


def _key(obj) -> str:
    return f"{obj.metadata.namespace}/{obj.metadata.name}"
