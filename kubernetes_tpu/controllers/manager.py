"""Controller manager: compose + run all controllers under leader election.

Parity target: reference cmd/kube-controller-manager/app/controllermanager.go
:198-477 (start each controller with its worker count) and :157 (leader
election gate)."""

from __future__ import annotations

import logging
from typing import List, Optional

from kubernetes_tpu.client import RESTClient
from kubernetes_tpu.client.leaderelection import LeaderElectionConfig, LeaderElector
from kubernetes_tpu.controllers.daemonset_controller import DaemonSetController
from kubernetes_tpu.controllers.deployment_controller import DeploymentController
from kubernetes_tpu.controllers.endpoints_controller import EndpointsController
from kubernetes_tpu.controllers.garbagecollector import (
    GarbageCollector, PodGCController,
)
from kubernetes_tpu.controllers.job_controller import JobController
from kubernetes_tpu.controllers.namespace_controller import NamespaceController
from kubernetes_tpu.controllers.node_controller import NodeController
from kubernetes_tpu.controllers.persistentvolume_controller import (
    PersistentVolumeController,
)
from kubernetes_tpu.controllers.petset_controller import PetSetController
from kubernetes_tpu.controllers.podautoscaler import HorizontalController
from kubernetes_tpu.controllers.replicaset_controller import ReplicaSetController
from kubernetes_tpu.controllers.replication_controller import ReplicationManager
from kubernetes_tpu.controllers.resourcequota_controller import (
    ResourceQuotaController,
)
from kubernetes_tpu.controllers.scheduledjob_controller import (
    ScheduledJobController,
)
from kubernetes_tpu.controllers.serviceaccounts_controller import (
    ServiceAccountsController, TokensController,
)

log = logging.getLogger("controller-manager")


class ControllerManager:
    def __init__(self, client: RESTClient, leader_elect: bool = False,
                 identity: str = "controller-manager", cloud=None,
                 allocate_node_cidrs: bool = False):
        self.client = client
        self.leader_elect = leader_elect
        self.identity = identity
        # cloud provider seam (servicecontroller + routecontroller start
        # only when a cloud is configured, controllermanager.go:362-399)
        self.cloud = cloud
        self.allocate_node_cidrs = allocate_node_cidrs
        self.controllers: List = []
        self._elector: Optional[LeaderElector] = None
        self._started = False

    def _start_controllers(self):
        if self._started:
            return
        self._started = True
        self.controllers = [
            ReplicationManager(self.client),
            ReplicaSetController(self.client),
            DeploymentController(self.client),
            DaemonSetController(self.client),
            JobController(self.client),
            EndpointsController(self.client),
            NodeController(self.client),
            NamespaceController(self.client),
            ResourceQuotaController(self.client),
            ServiceAccountsController(self.client),
            TokensController(self.client),
            GarbageCollector(self.client),
            PodGCController(self.client),
            HorizontalController(self.client),
            PersistentVolumeController(self.client),
            PetSetController(self.client),
            ScheduledJobController(self.client),
        ]
        if self.cloud is not None:
            from kubernetes_tpu.controllers.route_controller import (
                RouteController,
            )
            from kubernetes_tpu.controllers.service_controller import (
                ServiceController,
            )
            self.controllers.append(ServiceController(self.client, self.cloud))
            if self.allocate_node_cidrs:
                self.controllers.append(
                    RouteController(self.client, self.cloud))
        for c in self.controllers:
            c.start()
        log.info("controller-manager: %d controllers running",
                 len(self.controllers))

    def _stop_controllers(self):
        """Leadership lost: stop reconciling immediately, or we'd run split-
        brain against the new leader (the reference exits the process in
        OnStoppedLeading; we stop and allow re-election)."""
        controllers, self.controllers = self.controllers, []
        self._started = False
        for c in controllers:
            c.stop()

    def start(self):
        if not self.leader_elect:
            self._start_controllers()
            return self
        self._elector = LeaderElector(
            self.client,
            LeaderElectionConfig(lock_name="kube-controller-manager",
                                 identity=self.identity),
            on_started_leading=self._start_controllers,
            on_stopped_leading=self._stop_controllers).run()
        return self

    def stop(self):
        for c in self.controllers:
            c.stop()
        if self._elector:
            self._elector.stop()
