"""kube-controller-manager entrypoint:
python -m kubernetes_tpu.controllers

Flags bind to ControllerManagerConfiguration, served at /configz next to
/healthz and /metrics (reference cmd/kube-controller-manager/app/
controllermanager.go:198-477 + leader election at :157)."""

from __future__ import annotations

import argparse
import signal
import sys
import threading

from kubernetes_tpu.apis.componentconfig import ControllerManagerConfiguration
from kubernetes_tpu.controllers.manager import ControllerManager
from kubernetes_tpu.utils.debugserver import DebugServer, client_from_url


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="kube-controller-manager")
    p.add_argument("--master", default="http://127.0.0.1:8080")
    p.add_argument("--port", type=int, default=10252)
    p.add_argument("--leader-elect", action="store_true")
    p.add_argument("--cloud-provider", default="",
                   choices=("", "fake"),
                   help="enables the service-LB + route controllers")
    p.add_argument("--allocate-node-cidrs", action="store_true")
    a = p.parse_args(argv)
    cfg = ControllerManagerConfiguration(port=a.port,
                                         leader_elect=a.leader_elect)

    client = client_from_url(a.master, qps=1000, burst=1000)
    cloud = None
    if a.cloud_provider == "fake":
        from kubernetes_tpu.cloudprovider import FakeCloud
        cloud = FakeCloud()
    mgr = ControllerManager(client, leader_elect=cfg.leader_elect,
                            cloud=cloud,
                            allocate_node_cidrs=a.allocate_node_cidrs)
    mgr.start()
    debug = DebugServer(port=cfg.port,
                        configz={"componentconfig": cfg}).start()
    print(f"controller-manager debug on http://127.0.0.1:{debug.port}",
          flush=True)

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a_: stop.set())
    signal.signal(signal.SIGINT, lambda *a_: stop.set())
    stop.wait()
    mgr.stop()
    debug.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
