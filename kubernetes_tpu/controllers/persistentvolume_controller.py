"""PersistentVolume binder/reclaimer/provisioner.

Parity target: reference pkg/controller/persistentvolume (binder +
recycler/deleter + provisioner split across controllers in 1.3):

  - bind: a Pending claim is matched to the smallest Available volume whose
    capacity and accessModes satisfy the request (or an exact
    spec.volumeName); both sides record the bind (pv.spec.claimRef /
    pvc.spec.volumeName) and go phase Bound
  - reclaim: when the bound claim disappears the volume goes Released, then
    per persistentVolumeReclaimPolicy: Retain keeps it Released, Recycle
    scrubs the claimRef and returns it to Available, Delete removes it
  - provision: a claim carrying the alpha storage-class annotation gets a
    volume created on demand when nothing matches (pluggable provisioner)
"""

from __future__ import annotations

import logging
from typing import Callable, List, Optional

from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.quantity import parse_quantity
from kubernetes_tpu.api.serialization import deep_copy
from kubernetes_tpu.client import Informer, ListWatch, RESTClient
from kubernetes_tpu.client.rest import ApiError
from kubernetes_tpu.controllers.base import Controller

log = logging.getLogger("pv-controller")

# phases (reference pkg/api/types.go PersistentVolumePhase / ClaimPhase)
VOLUME_AVAILABLE = "Available"
VOLUME_BOUND = "Bound"
VOLUME_RELEASED = "Released"
VOLUME_FAILED = "Failed"
CLAIM_PENDING = "Pending"
CLAIM_BOUND = "Bound"

RECLAIM_RETAIN = "Retain"
RECLAIM_RECYCLE = "Recycle"
RECLAIM_DELETE = "Delete"

ANN_STORAGE_CLASS = "volume.alpha.kubernetes.io/storage-class"


def claim_request_bytes(pvc: api.PersistentVolumeClaim) -> int:
    req = (pvc.spec.resources.requests
           if pvc.spec and pvc.spec.resources else None) or {}
    return parse_quantity(req.get("storage", "0"))


def volume_capacity_bytes(pv: api.PersistentVolume) -> int:
    cap = (pv.spec.capacity if pv.spec else None) or {}
    return parse_quantity(cap.get("storage", "0"))


def access_modes_satisfy(pv: api.PersistentVolume,
                         pvc: api.PersistentVolumeClaim) -> bool:
    want = set((pvc.spec.access_modes if pvc.spec else None) or [])
    have = set((pv.spec.access_modes if pv.spec else None) or [])
    return want <= have


class PersistentVolumeController(Controller):
    """One workqueue for both kinds: keys are "pv|name" / "pvc|ns/name"."""

    name = "persistentvolume"

    def __init__(self, client: RESTClient, workers: int = 1,
                 provisioner: Optional[Callable] = None):
        super().__init__(workers)
        self.client = client
        self.provisioner = provisioner
        self.pv_informer = Informer(ListWatch(client, "persistentvolumes"))
        self.pvc_informer = Informer(ListWatch(client, "persistentvolumeclaims"))
        self.pv_informer.add_event_handler(
            on_add=lambda pv: self.enqueue(f"pv|{pv.metadata.name}"),
            on_update=lambda o, n: self.enqueue(f"pv|{n.metadata.name}"),
            on_delete=lambda pv: self._requeue_pending_claims())
        self.pvc_informer.add_event_handler(
            on_add=lambda c: self.enqueue(f"pvc|{_nn(c)}"),
            on_update=lambda o, n: self.enqueue(f"pvc|{_nn(n)}"),
            on_delete=self._claim_deleted)

    def _requeue_pending_claims(self):
        for c in self.pvc_informer.store.list():
            if (c.status.phase if c.status else "") != CLAIM_BOUND:
                self.enqueue(f"pvc|{_nn(c)}")

    def _claim_deleted(self, pvc):
        # release the volume this claim was bound to; ALSO sweep volumes
        # whose claimRef names this claim — a bind interrupted between the
        # PV and PVC writes leaves the volume pointing at a claim that never
        # recorded volume_name
        vol_name = pvc.spec.volume_name if pvc.spec else ""
        if vol_name:
            self.enqueue(f"pv|{vol_name}")
        ns, name = pvc.metadata.namespace, pvc.metadata.name
        for pv in self.pv_informer.store.list():
            ref = pv.spec.claim_ref if pv.spec else None
            if ref is not None and ref.namespace == ns and ref.name == name:
                self.enqueue(f"pv|{pv.metadata.name}")

    # --- reconcile -----------------------------------------------------------

    def sync(self, key: str) -> None:
        kind, rest = key.split("|", 1)
        if kind == "pvc":
            self._sync_claim(rest)
        else:
            self._sync_volume(rest)

    # claims ------------------------------------------------------------------

    def _sync_claim(self, nn: str) -> None:
        pvc = self.pvc_informer.store.get(nn)
        if pvc is None:
            return
        phase = pvc.status.phase if pvc.status else ""
        if phase == CLAIM_BOUND:
            return
        match = self._find_match(pvc)
        if match is None and self.provisioner is not None and \
                (pvc.metadata.annotations or {}).get(ANN_STORAGE_CLASS):
            pv = self.provisioner(pvc)
            if pv is not None:
                try:
                    match = self.client.create("persistentvolumes", pv)
                except ApiError as e:
                    if not e.is_conflict:
                        raise
                    match = self.client.get("persistentvolumes",
                                            pv.metadata.name)
        if match is None:
            # stay Pending; new volumes requeue us
            if phase != CLAIM_PENDING:
                self._set_claim_phase(pvc, CLAIM_PENDING)
            return
        self._bind(match, pvc)

    def _find_match(self, pvc) -> Optional[api.PersistentVolume]:
        want_name = pvc.spec.volume_name if pvc.spec else ""
        want_bytes = claim_request_bytes(pvc)
        candidates: List[api.PersistentVolume] = []
        for pv in self.pv_informer.store.list():
            phase = pv.status.phase if pv.status else ""
            claim_ref = pv.spec.claim_ref if pv.spec else None
            if claim_ref is not None:
                # pre-bound volume: only its designated claim may take it —
                # and only the SAME claim instance (uid match), else a
                # recreated claim would inherit a retained volume's data
                if (claim_ref.namespace == pvc.metadata.namespace
                        and claim_ref.name == pvc.metadata.name
                        and (not claim_ref.uid
                             or claim_ref.uid == pvc.metadata.uid)):
                    return pv
                continue
            if phase not in ("", VOLUME_AVAILABLE):
                continue
            if want_name and pv.metadata.name != want_name:
                continue
            if not access_modes_satisfy(pv, pvc):
                continue
            if volume_capacity_bytes(pv) < want_bytes:
                continue
            candidates.append(pv)
        if not candidates:
            return None
        # smallest satisfying volume wins (reference matchVolume sort)
        return min(candidates, key=volume_capacity_bytes)

    def _bind(self, pv, pvc) -> None:
        fresh_pv = deep_copy(pv)
        fresh_pv.spec.claim_ref = api.ObjectReference(
            kind="PersistentVolumeClaim",
            namespace=pvc.metadata.namespace, name=pvc.metadata.name,
            uid=pvc.metadata.uid)
        fresh_pv.status = api.PersistentVolumeStatus(phase=VOLUME_BOUND)
        # conflicts propagate: the requeue re-matches on fresh state
        self.client.update("persistentvolumes", fresh_pv)
        fresh_pvc = deep_copy(pvc)
        fresh_pvc.spec.volume_name = pv.metadata.name
        fresh_pvc.status = api.PersistentVolumeClaimStatus(phase=CLAIM_BOUND)
        try:
            self.client.update("persistentvolumeclaims", fresh_pvc,
                               pvc.metadata.namespace)
        except ApiError as e:
            if not e.is_not_found:
                raise
            # claim vanished mid-bind: the volume sync will release it
        log.info("pv: bound %s -> %s/%s", pv.metadata.name,
                 pvc.metadata.namespace, pvc.metadata.name)

    def _set_claim_phase(self, pvc, phase: str) -> None:
        fresh = deep_copy(pvc)
        fresh.status = api.PersistentVolumeClaimStatus(phase=phase)
        try:
            self.client.update("persistentvolumeclaims", fresh,
                               pvc.metadata.namespace)
        except ApiError as e:
            if not (e.is_not_found or e.is_conflict):
                raise

    # volumes -----------------------------------------------------------------

    def _sync_volume(self, name: str) -> None:
        pv = self.pv_informer.store.get(name)
        if pv is None:
            return
        claim_ref = pv.spec.claim_ref if pv.spec else None
        phase = pv.status.phase if pv.status else ""
        if claim_ref is None:
            if phase not in (VOLUME_AVAILABLE,):
                self._set_volume_phase(pv, VOLUME_AVAILABLE)
                self._requeue_pending_claims()
            return
        # bound (or pre-bound): does the claim still exist?
        claim = self.pvc_informer.store.get(
            f"{claim_ref.namespace}/{claim_ref.name}")
        if claim is not None and (not claim_ref.uid
                                  or claim.metadata.uid == claim_ref.uid):
            if phase != VOLUME_BOUND and (claim.spec and
                                          claim.spec.volume_name == name):
                self._set_volume_phase(pv, VOLUME_BOUND)
            return
        # claim is gone -> reclaim
        policy = (pv.spec.persistent_volume_reclaim_policy
                  if pv.spec else "") or RECLAIM_RETAIN
        if policy == RECLAIM_DELETE:
            try:
                self.client.delete("persistentvolumes", name)
            except ApiError as e:
                if not e.is_not_found:
                    raise
        elif policy == RECLAIM_RECYCLE:
            fresh = deep_copy(pv)
            fresh.spec.claim_ref = None
            fresh.status = api.PersistentVolumeStatus(phase=VOLUME_AVAILABLE)
            self.client.update("persistentvolumes", fresh)
            self._requeue_pending_claims()
        else:  # Retain
            if phase != VOLUME_RELEASED:
                self._set_volume_phase(pv, VOLUME_RELEASED)

    def _set_volume_phase(self, pv, phase: str) -> None:
        fresh = deep_copy(pv)
        fresh.status = api.PersistentVolumeStatus(phase=phase)
        try:
            self.client.update("persistentvolumes", fresh)
        except ApiError as e:
            if not (e.is_not_found or e.is_conflict):
                raise

    # --- lifecycle -----------------------------------------------------------

    def start(self):
        self.pv_informer.run()
        self.pvc_informer.run()
        self.pv_informer.wait_for_sync()
        self.pvc_informer.wait_for_sync()
        return self.run()

    def stop(self):
        super().stop()
        self.pv_informer.stop()
        self.pvc_informer.stop()


def _nn(obj) -> str:
    return f"{obj.metadata.namespace}/{obj.metadata.name}"
