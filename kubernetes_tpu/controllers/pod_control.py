"""Shared pod create/delete helpers for pod-managing controllers.

Parity target: reference pkg/controller/controller_utils.go PodControlInterface
(RealPodControl.CreatePods / CreatePodsOnNode / DeletePod) and the activePods
deletion ranking used by replicaset/replication controllers."""

from __future__ import annotations

import hashlib
import json
from typing import Optional

from kubernetes_tpu.api import labels as labelsel
from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.serialization import deep_copy, to_dict


def created_by_annotation(kind: str, owner) -> str:
    return json.dumps({"kind": kind,
                       "namespace": owner.metadata.namespace,
                       "name": owner.metadata.name,
                       "uid": owner.metadata.uid})


def pod_from_template(kind: str, owner, template: api.PodTemplateSpec,
                      extra_labels: Optional[dict] = None,
                      node_name: str = "") -> api.Pod:
    """Build (not create) a pod from a controller's template, stamped with the
    created-by annotation (reference controller_utils.go GetPodFromTemplate)."""
    labels = dict((template.metadata.labels if template.metadata else None) or {})
    if extra_labels:
        labels.update(extra_labels)
    spec = deep_copy(template.spec) if template.spec else api.PodSpec(
        containers=[api.Container(name="c", image="pause")])
    if node_name:
        spec.node_name = node_name
    return api.Pod(
        metadata=api.ObjectMeta(
            generate_name=f"{owner.metadata.name}-",
            namespace=owner.metadata.namespace,
            labels=labels,
            annotations={api.ANN_CREATED_BY: created_by_annotation(kind, owner)},
            owner_references=[api.OwnerReference(
                kind=kind, name=owner.metadata.name, uid=owner.metadata.uid,
                controller=True)]),
        spec=spec)


def pod_template_hash(template: api.PodTemplateSpec) -> str:
    """Deterministic hash of a pod template, used to name/label the replica
    set a deployment owns (reference pkg/util/deployment GetPodTemplateSpecHash
    via fnv; we hash the canonical JSON encoding instead)."""
    canon = json.dumps(to_dict(template), sort_keys=True)
    return hashlib.sha1(canon.encode()).hexdigest()[:10]


def is_pod_active(pod: api.Pod) -> bool:
    phase = pod.status.phase if pod.status else ""
    return (pod.metadata.deletion_timestamp is None
            and phase not in (api.POD_SUCCEEDED, api.POD_FAILED))


def is_pod_ready(pod: api.Pod) -> bool:
    for c in ((pod.status.conditions or []) if pod.status else []):
        if c.type == api.POD_READY:
            return c.status == api.CONDITION_TRUE
    return False


def is_pod_available(pod: api.Pod) -> bool:
    """Running + Ready (minReadySeconds elided; reference
    pkg/util/deployment.IsPodAvailable)."""
    return (is_pod_active(pod)
            and (pod.status.phase if pod.status else "") == api.POD_RUNNING
            and is_pod_ready(pod))


def selector_for(obj) -> labelsel.Selector:
    """Structured spec.selector, defaulting to the pod template's labels when
    absent (the server-side selector defaulting every workload strategy in the
    reference applies; shared by RC/RS/Deployment/DaemonSet/Job controllers)."""
    sel = obj.spec.selector if obj.spec else None
    if sel is None:
        tpl = getattr(obj.spec, "template", None) if obj.spec else None
        return labelsel.selector_from_map(
            (tpl.metadata.labels if tpl and tpl.metadata else None) or {})
    if isinstance(sel, dict):  # RC's map-form selector
        return labelsel.selector_from_map(sel)
    if isinstance(sel, api.LabelSelector):
        return labelsel.selector_from_label_selector(sel)
    return labelsel.selector_from_map(sel or {})


def deletion_rank(pod: api.Pod):
    """Sort key: unassigned first, then not-running, then unready — the pods
    cheapest to kill go first (reference controller_utils.go ActivePods.Less)."""
    assigned = bool(pod.spec and pod.spec.node_name)
    phase = pod.status.phase if pod.status else ""
    return (assigned, phase == api.POD_RUNNING, is_pod_ready(pod))
