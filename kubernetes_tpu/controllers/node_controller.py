"""Node lifecycle controller: heartbeat monitoring + pod eviction.

Parity target: reference pkg/controller/node/nodecontroller.go (1,077 ln) —
monitor node heartbeats (NodeCondition Ready lastHeartbeatTime); after a
grace period mark the node NotReady/Unknown; after the pod-eviction timeout,
evict its pods through a rate-limited queue so a zone-wide blip doesn't mass-
delete the cluster (zone-aware eviction limiting)."""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, Optional

from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.serialization import deep_copy
from kubernetes_tpu.client import Informer, ListWatch, RESTClient
from kubernetes_tpu.client.record import EventRecorder
from kubernetes_tpu.client.rest import ApiError
from kubernetes_tpu.utils.flowcontrol import TokenBucket
from kubernetes_tpu.utils.timeutil import now_iso

log = logging.getLogger("node-controller")


class NodeController:
    def __init__(self, client: RESTClient,
                 monitor_period: float = 5.0,
                 grace_period: float = 40.0,
                 pod_eviction_timeout: float = 60.0,
                 eviction_qps: float = 0.1,
                 clock=time.monotonic):
        self.client = client
        self.monitor_period = monitor_period
        self.grace_period = grace_period
        self.pod_eviction_timeout = pod_eviction_timeout
        self.eviction_limiter = TokenBucket(qps=eviction_qps, burst=1)
        self._clock = clock
        self.recorder = EventRecorder(client, "node-controller")
        self.node_informer = Informer(ListWatch(client, "nodes"))
        self.pod_informer = Informer(ListWatch(client, "pods"))
        self._last_heartbeat: Dict[str, float] = {}
        self._last_seen: Dict[str, float] = {}
        self._not_ready_since: Dict[str, float] = {}
        self._deleted_nodes: Dict[str, float] = {}  # name -> deletion time
        self._deleted_lock = threading.Lock()
        self.node_informer.add_event_handler(on_delete=self._node_deleted)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _node_deleted(self, node: api.Node):
        """A deleted Node leaves its bound pods orphaned — queue them for
        eviction on the next monitor tick (reference evicts on node deletion)
        and drop the per-node tracking state."""
        name = node.metadata.name
        with self._deleted_lock:
            self._deleted_nodes[name] = self._clock()
            self._last_heartbeat.pop(name, None)
            self._last_seen.pop(name, None)
            self._not_ready_since.pop(name, None)

    # --- monitor loop --------------------------------------------------------

    def monitor_once(self, now: Optional[float] = None):
        now = now if now is not None else self._clock()
        with self._deleted_lock:
            deleted = list(self._deleted_nodes.items())
        for name, when in deleted:
            if self.node_informer.store.get(name) is not None:
                # node re-registered under the same name: its pods are live
                # again — stop treating it as deleted
                with self._deleted_lock:
                    self._deleted_nodes.pop(name, None)
                continue
            # keep re-scanning for the eviction-timeout window: the pod
            # informer may deliver pods bound to this node after the node
            # delete event arrived (cache lag), and a dropped entry would
            # orphan them forever
            done = self._evict_pods(name)
            if done and now - when >= self.pod_eviction_timeout:
                with self._deleted_lock:
                    self._deleted_nodes.pop(name, None)
        for node in self.node_informer.store.list():
            name = node.metadata.name
            hb = _heartbeat_of(node)
            ready = _is_ready(node)
            with self._deleted_lock:
                if name in self._deleted_nodes:
                    continue  # deleted concurrently; tracking state dropped
                prev = self._last_heartbeat.get(name)
                if hb != prev:
                    self._last_heartbeat[name] = hb
                    self._last_seen[name] = now
                last_seen = self._last_seen.get(name, now)
                if ready and now - last_seen <= self.grace_period:
                    self._not_ready_since.pop(name, None)
                    continue
                # stale heartbeat or explicitly NotReady
                since = self._not_ready_since.setdefault(name, now)
                stale = now - last_seen > self.grace_period
            if stale and ready:
                self._mark_unknown(node)
            if now - since >= self.pod_eviction_timeout:
                self._evict_pods(name)

    def _mark_unknown(self, node: api.Node):
        fresh = deep_copy(node)
        conds = list((fresh.status.conditions or []) if fresh.status else [])
        for i, c in enumerate(conds):
            if c.type == api.NODE_READY:
                conds[i] = api.NodeCondition(
                    type=api.NODE_READY, status=api.CONDITION_UNKNOWN,
                    reason="NodeStatusUnknown",
                    message="Kubelet stopped posting node status.",
                    last_heartbeat_time=c.last_heartbeat_time,
                    last_transition_time=now_iso())
                break
        if fresh.status is None:
            fresh.status = api.NodeStatus()
        fresh.status.conditions = conds
        try:
            # deliberately a resourceVersion-checked PUT, not a PATCH: the
            # Ready=Unknown flip is only valid against the exact heartbeat
            # state the controller judged stale — a server-retried PATCH
            # would clobber a fresh kubelet heartbeat that landed in between,
            # while the CAS update 409s (swallowed; re-judged next tick)
            self.client.update_status("nodes", fresh)
        except ApiError:
            return  # flip lost the race: no event for a node that's alive
        self.recorder.event(
            node, "Normal", "NodeNotReady",
            f"Node {node.metadata.name} status is now: NodeNotReady")

    def _evict_pods(self, node_name: str) -> bool:
        """Returns True when no pods remain bound to node_name."""
        pods = [p for p in self.pod_informer.store.list()
                if p.spec and p.spec.node_name == node_name]
        ok = True
        for pod in pods:
            if not self.eviction_limiter.try_accept():
                return False  # rate limited: resume next tick
            try:
                self.client.delete("pods", pod.metadata.name,
                                   pod.metadata.namespace)
                self.recorder.event(
                    pod, "Normal", "NodeControllerEviction",
                    f"Marking for deletion Pod {pod.metadata.name} from "
                    f"Node {node_name}")
                log.info("evicted pod %s/%s from dead node %s",
                         pod.metadata.namespace, pod.metadata.name, node_name)
            except ApiError as e:
                if not e.is_not_found:
                    log.warning("evicting %s failed: %s", pod.metadata.name, e)
                    ok = False
        return ok

    # --- lifecycle -----------------------------------------------------------

    def start(self):
        self.node_informer.run()
        self.pod_informer.run()
        self.node_informer.wait_for_sync()
        self.pod_informer.wait_for_sync()
        self._thread = threading.Thread(target=self._loop, name="node-controller",
                                        daemon=True)
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.monitor_period):
            try:
                self.monitor_once()
            except Exception:
                log.exception("node monitor tick failed")

    def stop(self):
        self._stop.set()
        self.node_informer.stop()
        self.pod_informer.stop()
        if self._thread:
            self._thread.join(timeout=2)


def _heartbeat_of(node: api.Node) -> str:
    for c in ((node.status.conditions or []) if node.status else []):
        if c.type == api.NODE_READY:
            return c.last_heartbeat_time or ""
    return ""


def _is_ready(node: api.Node) -> bool:
    for c in ((node.status.conditions or []) if node.status else []):
        if c.type == api.NODE_READY:
            return c.status == api.CONDITION_TRUE
    return False
