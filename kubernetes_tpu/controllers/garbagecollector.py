"""Garbage collection: ownerReference cascade + terminated-pod GC.

Parity targets:
  - GarbageCollector (reference pkg/controller/garbagecollector/
    garbagecollector.go): watches a set of resources, maintains a uid->object
    ownership graph, and deletes any dependent whose owners have ALL been
    deleted. This is what makes deleting a Deployment cascade to its
    ReplicaSets and their pods (each stamped with ownerReferences by the
    controllers that created them).
  - PodGCController (reference pkg/controller/gc/gc_controller.go): when the
    cluster's terminated (Succeeded/Failed) pod count exceeds a threshold,
    deletes the oldest terminated pods down to the threshold.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional

from kubernetes_tpu.api import types as api
from kubernetes_tpu.client import Informer, ListWatch, RESTClient
from kubernetes_tpu.client.rest import ApiError
from kubernetes_tpu.controllers.base import Controller

log = logging.getLogger("garbage-collector")

# resources the collector watches, and the kind an ownerReference names
DEFAULT_MONITORED = ("pods", "replicasets", "replicationcontrollers",
                     "deployments", "jobs", "daemonsets", "petsets",
                     "scheduledjobs")
KIND_TO_RESOURCE = {
    "Pod": "pods",
    "ReplicaSet": "replicasets",
    "ReplicationController": "replicationcontrollers",
    "Deployment": "deployments",
    "Job": "jobs",
    "DaemonSet": "daemonsets",
    "PetSet": "petsets",
    "ScheduledJob": "scheduledjobs",
}


class GarbageCollector(Controller):
    """Deletes dependents whose owners are all gone. Keys are
    "resource|namespace/name" so one workqueue serves every monitored type."""

    name = "garbagecollector"

    def __init__(self, client: RESTClient, workers: int = 2,
                 monitored=DEFAULT_MONITORED):
        super().__init__(workers)
        self.client = client
        self.monitored = tuple(monitored)
        self.informers: Dict[str, Informer] = {}
        # ownership graph (reference uidToNode): the live-uid set plus an
        # owner-uid -> dependent-keys index so a delete event fans out in
        # O(dependents), not a full store scan
        self._live_uids: Dict[str, bool] = {}
        self._dependents: Dict[str, set] = {}
        self._uids_lock = threading.Lock()
        for res in self.monitored:
            inf = Informer(ListWatch(client, res))
            self.informers[res] = inf
            inf.add_event_handler(
                on_add=lambda obj, r=res: self._observe(r, obj),
                on_update=lambda old, new, r=res: self._observe(r, new),
                on_delete=lambda obj, r=res: self._owner_deleted(r, obj))

    # --- graph maintenance ---------------------------------------------------

    def _observe(self, resource: str, obj):
        meta = obj.metadata
        uid = meta.uid if meta else ""
        key = f"{resource}|{_nn(obj)}"
        with self._uids_lock:
            if uid:
                self._live_uids[uid] = True
            for ref in (meta.owner_references if meta else None) or []:
                self._dependents.setdefault(ref.uid, set()).add(key)
        if meta and meta.owner_references:
            self.enqueue(key)

    def _owner_deleted(self, resource: str, obj):
        meta = obj.metadata
        uid = meta.uid if meta else ""
        with self._uids_lock:
            if uid:
                self._live_uids.pop(uid, None)
            dependents = self._dependents.pop(uid, set()) if uid else set()
            # drop this object from any dependent index it appears in
            key = f"{resource}|{_nn(obj)}"
            for ref in (meta.owner_references if meta else None) or []:
                deps = self._dependents.get(ref.uid)
                if deps:
                    deps.discard(key)
        for dep_key in dependents:
            self.enqueue(dep_key)

    def _owner_alive(self, ns: str, ref: api.OwnerReference) -> bool:
        with self._uids_lock:
            if ref.uid in self._live_uids:
                return True
        # informer may lag: confirm with the API before condemning (the
        # reference does an apiserver GET in attemptToDeleteItem too)
        res = KIND_TO_RESOURCE.get(ref.kind)
        if res is None:
            return True  # unknown owner kinds never orphan their dependents
        try:
            obj = self.client.get(res, ref.name,
                                  ns if _is_namespaced(res) else "")
        except ApiError as e:
            if e.is_not_found:
                return False
            raise
        return (obj.metadata.uid == ref.uid) if ref.uid else True

    # --- reconcile -----------------------------------------------------------

    def sync(self, key: str) -> None:
        resource, nn = key.split("|", 1)
        ns, name = nn.split("/", 1) if "/" in nn else ("", nn)
        obj = self.informers[resource].store.get(nn)
        if obj is None:
            return
        refs = obj.metadata.owner_references if obj.metadata else None
        if not refs:
            return
        if any(self._owner_alive(ns, r) for r in refs):
            return
        log.info("gc: deleting orphaned %s %s", resource, nn)
        try:
            self.client.delete(resource, name, ns)
        except ApiError as e:
            if not e.is_not_found:
                raise

    # --- lifecycle -----------------------------------------------------------

    def start(self):
        for inf in self.informers.values():
            inf.run()
        for inf in self.informers.values():
            inf.wait_for_sync()
        return self.run()

    def stop(self):
        super().stop()
        for inf in self.informers.values():
            inf.stop()


class PodGCController(Controller):
    """Bounds the number of terminated pods kept around (reference
    gc_controller.go: threshold via --terminated-pod-gc-threshold, oldest
    deleted first)."""

    name = "pod-gc"
    KEY = "gc"

    def __init__(self, client: RESTClient, threshold: int = 100):
        super().__init__(workers=1)
        self.client = client
        self.threshold = threshold
        self.pod_informer = Informer(ListWatch(client, "pods"))
        self.pod_informer.add_event_handler(
            on_add=lambda p: self._maybe_enqueue(p),
            on_update=lambda old, new: self._maybe_enqueue(new))

    def _maybe_enqueue(self, pod):
        phase = pod.status.phase if pod.status else ""
        if phase in (api.POD_SUCCEEDED, api.POD_FAILED):
            self.enqueue(self.KEY)

    def sync(self, key: str) -> None:
        terminated = [p for p in self.pod_informer.store.list()
                      if (p.status.phase if p.status else "") in
                      (api.POD_SUCCEEDED, api.POD_FAILED)
                      and p.metadata.deletion_timestamp is None]
        excess = len(terminated) - self.threshold
        if excess <= 0:
            return
        terminated.sort(key=lambda p: p.metadata.creation_timestamp or "")
        for p in terminated[:excess]:
            try:
                self.client.delete("pods", p.metadata.name,
                                   p.metadata.namespace)
            except ApiError as e:
                if not e.is_not_found:
                    raise

    def start(self):
        self.pod_informer.run()
        self.pod_informer.wait_for_sync()
        return self.run()

    def stop(self):
        super().stop()
        self.pod_informer.stop()


def _nn(obj) -> str:
    m = obj.metadata
    return f"{m.namespace}/{m.name}" if m.namespace else m.name


def _is_namespaced(resource: str) -> bool:
    from kubernetes_tpu.registry.generic import RESOURCES
    rd = RESOURCES.get(resource)
    return rd.namespaced if rd else True
