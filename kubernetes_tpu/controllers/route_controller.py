"""Route controller + node pod-CIDR allocation.

Parity target: reference pkg/controller/route/routecontroller.go (one
cloud route per node's podCIDR, orphaned routes removed) plus the
controller-manager's --allocate-node-cidrs path: nodes without a
spec.podCIDR get one carved out of the cluster CIDR here, since there is
no separate nodeipam controller in this tree.
"""

from __future__ import annotations

import ipaddress
import logging
import threading

from kubernetes_tpu.api import types as api
from kubernetes_tpu.client import Informer, ListWatch, RESTClient
from kubernetes_tpu.client.rest import ApiError
from kubernetes_tpu.controllers.base import Controller

log = logging.getLogger("route-controller")


class RouteController(Controller):
    name = "routes"

    def __init__(self, client: RESTClient, cloud,
                 cluster_cidr: str = "10.244.0.0/16", node_mask: int = 24,
                 workers: int = 1):
        super().__init__(workers)
        self.client = client
        self.cloud = cloud
        self.net = ipaddress.ip_network(cluster_cidr)
        self.node_mask = node_mask
        self._cidr_lock = threading.Lock()
        # CIDRs handed out but possibly not yet visible in the informer
        # store: without this, two back-to-back node syncs both read the
        # stale store and collide on the same subnet
        self._issued: set = set()
        self.node_informer = Informer(ListWatch(client, "nodes"))
        self.node_informer.add_event_handler(
            on_add=lambda n: self.enqueue(n.metadata.name),
            on_update=lambda o, n: self.enqueue(n.metadata.name),
            on_delete=lambda n: self.enqueue(n.metadata.name))

    # -- pod CIDR allocation ---------------------------------------------------

    def _used_cidrs(self):
        return {n.spec.pod_cidr for n in self.node_informer.store.list()
                if n.spec and n.spec.pod_cidr}

    def _allocate_cidr(self) -> str:
        with self._cidr_lock:
            used = self._used_cidrs() | self._issued
            for subnet in self.net.subnets(new_prefix=self.node_mask):
                s = str(subnet)
                if s not in used:
                    self._issued.add(s)
                    return s
        raise RuntimeError(f"cluster CIDR {self.net} exhausted")

    def sync(self, key: str) -> None:
        node = self.node_informer.store.get(key)
        if node is None:
            # node gone: its route must go too (routecontroller.go reconcile)
            if key in self.cloud.list_routes():
                self.cloud.delete_route(key)
                log.info("deleted route for departed node %s", key)
            return
        cidr = node.spec.pod_cidr if node.spec else ""
        if not cidr:
            cidr = self._allocate_cidr()
            try:
                self.client.patch("nodes", key,
                                  {"spec": {"podCIDR": cidr}})
            except ApiError as e:
                if e.is_not_found:
                    return
                raise
            log.info("allocated podCIDR %s to node %s", cidr, key)
        if self.cloud.list_routes().get(key) != cidr:
            self.cloud.create_route(key, cidr)
            log.info("route %s -> %s", key, cidr)

    def start(self):
        self.node_informer.run()
        self.node_informer.wait_for_sync()
        return self.run()

    def stop(self):
        super().stop()
        self.node_informer.stop()
