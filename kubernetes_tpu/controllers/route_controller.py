"""Route controller + node pod-CIDR allocation.

Parity target: reference pkg/controller/route/routecontroller.go (one
cloud route per node's podCIDR, orphaned routes removed) plus the
controller-manager's --allocate-node-cidrs path: nodes without a
spec.podCIDR get one carved out of the cluster CIDR here, since there is
no separate nodeipam controller in this tree.
"""

from __future__ import annotations

import ipaddress
import logging
import threading

from kubernetes_tpu.api import types as api
from kubernetes_tpu.client import Informer, ListWatch, RESTClient
from kubernetes_tpu.client.rest import ApiError
from kubernetes_tpu.controllers.base import Controller

log = logging.getLogger("route-controller")


class RouteController(Controller):
    name = "routes"

    def __init__(self, client: RESTClient, cloud,
                 cluster_cidr: str = "10.244.0.0/16", node_mask: int = 24,
                 workers: int = 1):
        super().__init__(workers)
        self.client = client
        self.cloud = cloud
        self.net = ipaddress.ip_network(cluster_cidr)
        self.node_mask = node_mask
        self._cidr_lock = threading.Lock()
        # CIDRs handed out but possibly not yet visible in the informer
        # store: without this, two back-to-back node syncs both read the
        # stale store and collide on the same subnet. Mapped to the node
        # they were issued for, so a failed patch or a deleted node returns
        # its subnet to the pool instead of leaking it forever.
        self._issued: dict = {}  # cidr -> node name
        self.node_informer = Informer(ListWatch(client, "nodes"))
        self.node_informer.add_event_handler(
            on_add=lambda n: self.enqueue(n.metadata.name),
            on_update=lambda o, n: self.enqueue(n.metadata.name),
            on_delete=lambda n: self.enqueue(n.metadata.name))

    # -- pod CIDR allocation ---------------------------------------------------

    def _used_cidrs(self):
        return {n.spec.pod_cidr for n in self.node_informer.store.list()
                if n.spec and n.spec.pod_cidr}

    def _allocate_cidr(self, node_name: str) -> str:
        with self._cidr_lock:
            # a retry after an ambiguous patch failure reuses the subnet
            # already issued to this node: if the lost write actually landed
            # the store converges on the same value, and if it didn't, the
            # pool doesn't shrink by one per retry
            for s, n in self._issued.items():
                if n == node_name:
                    return s
            visible = self._used_cidrs()
            # issued entries that made it into the store are recorded on
            # their nodes now; drop the guard so the map stays bounded
            for s in [s for s in self._issued if s in visible]:
                del self._issued[s]
            used = visible | set(self._issued)
            for subnet in self.net.subnets(new_prefix=self.node_mask):
                s = str(subnet)
                if s not in used:
                    self._issued[s] = node_name
                    return s
        raise RuntimeError(f"cluster CIDR {self.net} exhausted")

    def _release_issued(self, cidr: str = "", node: str = "") -> None:
        with self._cidr_lock:
            if cidr:
                self._issued.pop(cidr, None)
            if node:
                for s in [s for s, n in self._issued.items() if n == node]:
                    del self._issued[s]

    def sync(self, key: str) -> None:
        node = self.node_informer.store.get(key)
        if node is None:
            # node gone: its route must go too (routecontroller.go
            # reconcile), and any CIDR issued-but-unrecorded for it returns
            # to the pool
            self._release_issued(node=key)
            if key in self.cloud.list_routes():
                self.cloud.delete_route(key)
                log.info("deleted route for departed node %s", key)
            return
        cidr = node.spec.pod_cidr if node.spec else ""
        if not cidr:
            cidr = self._allocate_cidr(key)
            try:
                self.client.patch("nodes", key,
                                  {"spec": {"podCIDR": cidr}})
            except Exception as e:
                # reclaim ONLY when the server provably rejected the write
                # (4xx): a timeout/5xx/transport failure may have landed
                # server-side, and reissuing that subnet to another node
                # would overlap two pod CIDRs. Ambiguous failures keep the
                # guard entry; it is pruned once the CIDR shows up in the
                # store, or when this node is deleted.
                if isinstance(e, ApiError) and 400 <= e.code < 500:
                    self._release_issued(cidr=cidr)
                    if e.is_not_found:
                        return
                raise
            log.info("allocated podCIDR %s to node %s", cidr, key)
        if self.cloud.list_routes().get(key) != cidr:
            self.cloud.create_route(key, cidr)
            log.info("route %s -> %s", key, cidr)

    def start(self):
        self.node_informer.run()
        self.node_informer.wait_for_sync()
        return self.run()

    def stop(self):
        super().stop()
        self.node_informer.stop()
