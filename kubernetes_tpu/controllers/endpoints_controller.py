"""Endpoints controller: services -> ready pod addresses.

Parity target: reference pkg/controller/endpoint/endpoints_controller.go
(519 ln) — for each service, gather pods matching its selector, split by
readiness into addresses/notReadyAddresses, resolve target ports, and write
the Endpoints object the proxy consumes."""

from __future__ import annotations

import logging

from kubernetes_tpu.api import labels as labelsel
from kubernetes_tpu.api import types as api
from kubernetes_tpu.client import Informer, ListWatch, RESTClient
from kubernetes_tpu.client.rest import ApiError
from kubernetes_tpu.controllers.base import Controller

log = logging.getLogger("endpoints-controller")


class EndpointsController(Controller):
    name = "endpoints"

    def __init__(self, client: RESTClient, workers: int = 2):
        super().__init__(workers)
        self.client = client
        self.svc_informer = Informer(ListWatch(client, "services"))
        self.pod_informer = Informer(ListWatch(client, "pods"))
        self.svc_informer.add_event_handler(
            on_add=lambda s: self.enqueue(_key(s)),
            on_update=lambda o, n: self.enqueue(_key(n)),
            on_delete=lambda s: self.enqueue(_key(s)))
        self.pod_informer.add_event_handler(
            on_add=self._pod_changed,
            on_update=lambda o, n: self._pod_changed(n),
            on_delete=self._pod_changed)

    def _pod_changed(self, pod: api.Pod):
        lbls = (pod.metadata.labels or {})
        for svc in self.svc_informer.store.list():
            if svc.metadata.namespace != pod.metadata.namespace:
                continue
            sel = svc.spec.selector if svc.spec else None
            if sel and labelsel.selector_from_map(sel).matches(lbls):
                self.enqueue(_key(svc))

    def sync(self, key: str) -> None:
        ns, name = key.split("/", 1)
        svc = self.svc_informer.store.get(key)
        if svc is None:
            try:
                self.client.delete("endpoints", name, ns)
            except ApiError as e:
                if not e.is_not_found:
                    raise
            return
        if not (svc.spec and svc.spec.selector):
            return  # headless/manual endpoints are user-managed
        sel = labelsel.selector_from_map(svc.spec.selector)
        # named targetPorts resolve PER POD (reference FindPort per address):
        # pods whose resolutions differ land in separate subsets, so
        # heterogeneous backends (e.g. host-network processes on distinct
        # ports) each stay reachable — grouped by the resolved port tuple
        groups: dict = {}
        for pod in self.pod_informer.store.list():
            if pod.metadata.namespace != ns:
                continue
            if not sel.matches(pod.metadata.labels or {}):
                continue
            if not (pod.status and pod.status.pod_ip):
                continue
            if pod.metadata.deletion_timestamp is not None:
                continue
            addr = api.EndpointAddress(
                ip=pod.status.pod_ip,
                node_name=pod.spec.node_name if pod.spec else None,
                target_ref=api.ObjectReference(
                    kind="Pod", namespace=ns, name=pod.metadata.name,
                    uid=pod.metadata.uid))
            port_key = tuple(_target_port(p, pod)
                             for p in (svc.spec.ports or []))
            ready, not_ready = groups.setdefault(port_key, ([], []))
            (ready if _is_ready(pod) else not_ready).append(addr)
        subsets = []
        for port_key in sorted(groups):
            ready, not_ready = groups[port_key]
            ports = [api.EndpointPort(name=p.name,
                                      protocol=p.protocol or "TCP",
                                      port=port_key[i])
                     for i, p in enumerate(svc.spec.ports or [])]
            subsets.append(api.EndpointSubset(
                addresses=ready or None,
                not_ready_addresses=not_ready or None,
                ports=ports or None))
        desired = api.Endpoints(
            metadata=api.ObjectMeta(name=name, namespace=ns),
            subsets=subsets or None)
        try:
            current = self.client.get("endpoints", name, ns)
            if current.subsets == desired.subsets:
                return
            current.subsets = desired.subsets
            self.client.update("endpoints", current)
        except ApiError as e:
            if e.is_not_found:
                self.client.create("endpoints", desired, ns)
            else:
                # includes conflict: a concurrent writer bumped the version
                # between our get and update — raise so the worker requeues
                # and the next sync recomputes from a fresh read
                raise

    def start(self):
        self.svc_informer.run()
        self.pod_informer.run()
        self.svc_informer.wait_for_sync()
        self.pod_informer.wait_for_sync()
        return self.run()

    def stop(self):
        super().stop()
        self.svc_informer.stop()
        self.pod_informer.stop()


def _key(obj) -> str:
    return f"{obj.metadata.namespace}/{obj.metadata.name}"


def _is_ready(pod: api.Pod) -> bool:
    for c in ((pod.status.conditions or []) if pod.status else []):
        if c.type == api.POD_READY:
            return c.status == api.CONDITION_TRUE
    return False


def _target_port(p: api.ServicePort, pod) -> int:
    """Resolve targetPort: int as-is, numeric string parsed, named port
    looked up in the pod's container ports (reference FindPort)."""
    tp = p.target_port
    if isinstance(tp, int):
        return tp
    if isinstance(tp, str) and tp:
        if tp.isdigit():
            return int(tp)
        for c in ((pod.spec.containers or []) if pod and pod.spec else []):
            for cp in c.ports or []:
                if cp.name == tp:
                    return cp.container_port
    return p.port
