"""ResourceQuota controller: keep quota status.used consistent with reality.

Parity target: reference pkg/controller/resourcequota/resource_quota_controller.go
— the admission plugin books usage optimistically at request time; this
controller is the reconciler that recalculates true usage from the live
objects (full recalculation per quota key) and replenishes quota when
resources are deleted (replenishment informers enqueue the namespace's
quotas). Shares the evaluator logic with the admission plugin
(admission/plugins.py quota_usage_of)."""

from __future__ import annotations

import logging
from typing import Dict

from kubernetes_tpu.admission.plugins import (
    _COUNT_KEYS, format_usage, quota_usage_of,
)
from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.serialization import deep_copy
from kubernetes_tpu.client import Informer, ListWatch, RESTClient
from kubernetes_tpu.client.rest import ApiError
from kubernetes_tpu.controllers.base import Controller
from kubernetes_tpu.controllers.pod_control import is_pod_active

log = logging.getLogger("resourcequota-controller")

# resources whose churn changes quota usage (reference replenishment controllers)
TRACKED = tuple(_COUNT_KEYS)


class ResourceQuotaController(Controller):
    name = "resourcequota"

    def __init__(self, client: RESTClient, workers: int = 2,
                 resync_seconds: float = 30.0):
        super().__init__(workers)
        self.client = client
        self.resync_seconds = resync_seconds
        self.quota_informer = Informer(ListWatch(client, "resourcequotas"))
        self.quota_informer.add_event_handler(
            on_add=lambda q: self.enqueue(_key(q)),
            on_update=lambda old, new: self.enqueue(_key(new)))
        self.tracked_informers: Dict[str, Informer] = {}
        for res in TRACKED:
            inf = Informer(ListWatch(client, res))
            self.tracked_informers[res] = inf
            inf.add_event_handler(
                on_add=lambda obj: self._replenish(obj),
                # updates matter too: a pod reaching Succeeded/Failed releases
                # its quota without being deleted
                on_update=lambda old, new: self._replenish(new),
                on_delete=lambda obj: self._replenish(obj))

    def _replenish(self, obj):
        ns = obj.metadata.namespace if obj.metadata else ""
        if not ns:
            return
        for q in self.quota_informer.store.list():
            if q.metadata.namespace == ns:
                self.enqueue(_key(q))

    # --- reconcile -----------------------------------------------------------

    def _calculate_usage(self, ns: str, hard: Dict[str, str]) -> Dict[str, int]:
        used: Dict[str, int] = {k: 0 for k in hard}
        for res, inf in self.tracked_informers.items():
            for obj in inf.store.list():
                if obj.metadata.namespace != ns:
                    continue
                if res == "pods" and not is_pod_active(obj):
                    continue  # terminated pods release their quota
                for k, v in quota_usage_of(res, obj).items():
                    if k in used:
                        used[k] += v
        return used

    def sync(self, key: str) -> None:
        quota = self.quota_informer.store.get(key)
        if quota is None:
            self.disarm_resync(key)
            return
        hard = (quota.spec.hard if quota.spec else None) or {}
        used = self._calculate_usage(quota.metadata.namespace, hard)
        used_str = {k: format_usage(k, v) for k, v in used.items()}
        st = quota.status
        if st and st.hard == hard and st.used == used_str:
            self.arm_resync(key, self.resync_seconds)
            return
        fresh = deep_copy(quota)
        fresh.status = api.ResourceQuotaStatus(hard=dict(hard), used=used_str)
        try:
            self.client.update_status("resourcequotas", fresh)
        except ApiError as e:
            if not (e.is_not_found or e.is_conflict):
                raise
        self.arm_resync(key, self.resync_seconds)

    # --- lifecycle -----------------------------------------------------------

    def start(self):
        infs = [self.quota_informer, *self.tracked_informers.values()]
        for inf in infs:
            inf.run()
        for inf in infs:
            inf.wait_for_sync()
        return self.run()

    def stop(self):
        super().stop()
        for inf in [self.quota_informer, *self.tracked_informers.values()]:
            inf.stop()


def _key(obj) -> str:
    return f"{obj.metadata.namespace}/{obj.metadata.name}"
