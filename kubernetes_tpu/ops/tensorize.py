"""Host-side tensorization: cluster state -> dense, vocab-encoded arrays.

Everything string-shaped (labels, taints, ports, images, selectors, affinity
expressions, topology domains) is dictionary-encoded per batch into small
integer vocabularies, so device code is pure arithmetic:

- labels:  distinct (key, value) pairs over nodes -> columns of a bool
  [N, L] matrix; a nodeSelector becomes a required-column indicator and
  "all required present" is one [P, L] @ [L, N] matmul compared against the
  per-pod requirement count. NodeAffinity expressions (In/NotIn/Exists/
  DoesNotExist/Gt/Lt) compile to indicator rows over the same vocabulary
  (Gt/Lt rows are host-precomputed per node), terms are AND-reductions,
  term-sets OR-reductions — all matmuls (SURVEY §7 kernel formulation).
- taints:  distinct (key, value, effect) triples; toleration sets become
  tolerated-column indicators; "any untolerated NoSchedule taint" is again a
  matmul against the complement.
- ports:   distinct (protocol, hostPort) pairs; conflicts are an AND-matmul.
  Port occupancy is part of the scan carry (it changes as pods commit).
- spread:  pods sharing a selector signature (service/RC/RS sets,
  selector_spreading.go:84) form a group; per-node and per-zone group counts
  ride in the scan carry.
- images:  distinct image names; ImageLocality's per-node present-size is
  [P, I] @ (node_images * sizes) (priorities.go:137-207).
- topology: per failure-domain key, nodes map to globally-offset domain ids;
  inter-pod affinity terms become (term, domain) hit tables, precomputed
  against existing pods and updated in-carry for in-batch commits.

All vocab axes are padded to multiples of 128 (TPU lane width) and pod/node
axes to multiples of 8 (sublane), so XLA tiles every matmul onto the MXU.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from kubernetes_tpu.api import labels as labelsel
from kubernetes_tpu.api import types as api
from kubernetes_tpu.scheduler.cache import (
    DEFAULT_MEMORY_REQUEST, DEFAULT_MILLI_CPU_REQUEST, NodeInfo,
)

MB = 1024 * 1024


def _pad(n: int, mult: int) -> int:
    return max(mult, ((n + mult - 1) // mult) * mult)


class Vocab:
    """Stable insertion-ordered dictionary encoder."""

    def __init__(self):
        self._ids: Dict = {}

    def id(self, item) -> int:
        i = self._ids.get(item)
        if i is None:
            i = len(self._ids)
            self._ids[item] = i
        return i

    def get(self, item) -> Optional[int]:
        return self._ids.get(item)

    def __len__(self):
        return len(self._ids)

    def items(self):
        return self._ids.items()


@dataclass
class ClusterTensors:
    """Device-ready batch: N nodes x P pending pods (+ M existing pods folded
    into initial aggregates). All arrays are numpy; the kernel moves them to
    device once per batch."""

    node_names: List[str]
    pod_keys: List[str]             # ns/name of pending pods, FIFO order

    # node statics  (units: milliCPU, MiB, gpu, pod-slots)
    alloc: np.ndarray               # [N, 4] f32
    used0: np.ndarray               # [N, 4] f32  existing usage
    used0_nonzero: np.ndarray       # [N, 2] f32  nonzero-floored cpu/mem
    node_labels: np.ndarray         # [N, L] f32 (0/1)
    node_ports0: np.ndarray         # [N, PT] f32
    taints_nosched: np.ndarray      # [N, T] f32
    taints_prefer: np.ndarray       # [N, T] f32
    mem_pressure: np.ndarray        # [N] bool
    node_valid: np.ndarray          # [N] bool (padding rows are invalid)
    zone_id: np.ndarray             # [N] i32  (-1 = no zone); for spread
    n_zones: int

    # pod statics
    req: np.ndarray                 # [P, 4] f32
    nonzero_req: np.ndarray         # [P, 2] f32
    sel_required: np.ndarray        # [P, L] f32  nodeSelector pairs
    sel_count: np.ndarray           # [P] f32     number required
    pod_ports: np.ndarray           # [P, PT] f32
    tol_nosched: np.ndarray         # [P, T] f32  tolerated NoSchedule taints
    tol_prefer: np.ndarray          # [P, T] f32
    best_effort: np.ndarray         # [P] bool
    host_req: np.ndarray            # [P] i32  required node index or -1
    pod_valid: np.ndarray           # [P] bool

    # node affinity (required terms): expression/term/set matmuls
    expr_node: np.ndarray           # [E, N] f32  expression truth per node
    term_expr: np.ndarray           # [TM, E] f32 term -> its expressions
    term_expr_count: np.ndarray     # [TM] f32
    pod_term: np.ndarray            # [P, TM] f32 pod -> its terms (ORed)
    pod_has_affinity: np.ndarray    # [P] bool

    # preferred node affinity (score): weighted term rows
    pref_term_node: np.ndarray      # [PT2, N] f32 term truth per node
    pref_weight: np.ndarray         # [PT2] f32
    pod_pref_term: np.ndarray       # [P, PT2] f32

    # spread groups
    pod_group: np.ndarray           # [P] i32  group id for scoring (-1 none)
    pod_in_group: np.ndarray        # [P, G] f32  membership when committed
    group_counts0: np.ndarray       # [N, G] f32  existing matching pods
    n_groups: int

    # image locality
    image_node_sizes: np.ndarray    # [N, I] f32 (MiB present per image)
    pod_images: np.ndarray          # [P, I] f32

    # inter-pod affinity term tables (predicates.go:769-947,
    # interpod_affinity.go:86-216). K = topology-key vocab; TR/TA/TP =
    # required-affinity / required-anti-affinity / preferred terms owned by
    # *pending* pods (in-batch dynamics ride the scan carry); TS/TE = terms
    # owned by *existing* pods (static, applied in static_pass).
    node_dom: np.ndarray            # [K, N] i32 domain id per topo key (-1 none)
    req_topo: np.ndarray            # [TR, K] f32 term -> topo keys (empty key = defaults)
    req_own: np.ndarray             # [P, TR] f32 ownership counts
    req_match: np.ndarray           # [TR, P] f32 pending pod matches term
    req_hit0: np.ndarray            # [TR, N] f32 0/1 existing match in node's domain
    req_nomatch0: np.ndarray        # [TR] bool no existing pod matches anywhere
    anti_topo: np.ndarray           # [TA, K] f32
    anti_own: np.ndarray            # [P, TA] f32
    anti_match: np.ndarray          # [TA, P] f32
    anti_hit0: np.ndarray           # [TA, N] f32
    pref_topo: np.ndarray           # [TP, K] f32
    pref_own: np.ndarray            # [P, TP] f32 ownership counts
    pref_match: np.ndarray          # [TP, P] f32
    pref_w: np.ndarray              # [TP] f32 signed weight (anti < 0)
    pref_hit0: np.ndarray           # [TP, N] f32 existing match counts per domain
    sym_dom0: np.ndarray            # [TS, N] f32 existing pods' anti-term domains
    sym_match: np.ndarray           # [TS, P] f32
    te_dom0: np.ndarray             # [TE, N] f32 weight-accumulated domains of
                                    #   existing pods' preferred+hard terms
    te_match: np.ndarray            # [TE, P] f32
    hard_weight: np.ndarray         # [] f32 hardPodAffinityWeight (in-batch
                                    #   reverse-hard score, interpod_affinity.go:120-140)

    # volumes (predicates.go:105-269): exclusive-disk conflict columns and
    # per-family attach-count columns; node state rides the scan carry
    pod_disk_any: np.ndarray        # [P, D] f32
    pod_disk_rw: np.ndarray         # [P, D] f32
    node_disk_any0: np.ndarray      # [N, D] f32
    node_disk_rw0: np.ndarray       # [N, D] f32
    pod_ebs: np.ndarray             # [P, VE] f32
    node_ebs0: np.ndarray           # [N, VE] f32
    pod_gce: np.ndarray             # [P, VG] f32
    node_gce0: np.ndarray           # [N, VG] f32
    max_ebs: np.ndarray             # [] f32
    max_gce: np.ndarray             # [] f32

    n_real_nodes: int = 0
    n_real_pods: int = 0

    # scheduling-objective operands (scheduler/objectives/tensors.py) —
    # None unless the batch was tensorized with an enabled ObjectiveConfig,
    # so the default program's input signature (and jit key) is untouched
    pod_priority: Optional[np.ndarray] = None   # [P] f32 (preempt)
    vict_prio: Optional[np.ndarray] = None      # [KV, N] f32 (preempt)
    vict_cum: Optional[np.ndarray] = None       # [6, KV+1, N] f32 (preempt)
    pod_gang: Optional[np.ndarray] = None       # [P] i32 (gang; null=GG-1)
    gang_dom0: Optional[np.ndarray] = None      # [GG] i32 (gang)
    gang_failed0: Optional[np.ndarray] = None   # [GG] f32 (gang)
    node_gang_dom: Optional[np.ndarray] = None  # [N] i32 (gang)
    objective_info: Optional[object] = None     # host-side decode companion

    def arrays(self) -> dict:
        """All ndarray fields, for device upload."""
        return {k: v for k, v in self.__dict__.items()
                if isinstance(v, np.ndarray)}


# --- helpers -----------------------------------------------------------------

def _labels_of(obj) -> Dict[str, str]:
    return (obj.metadata.labels or {}) if obj.metadata else {}


def _pod_req_vec(pod: api.Pod) -> Tuple[np.ndarray, np.ndarray]:
    r = api.pod_resource_request(pod)
    req = np.array([r[api.RESOURCE_CPU], r[api.RESOURCE_MEMORY] / MB,
                    r[api.RESOURCE_GPU], 1.0], dtype=np.float32)
    cpu = mem = 0.0
    for c in (pod.spec.containers or []) if pod.spec else []:
        cr = (c.resources.requests if c.resources and c.resources.requests else {})
        from kubernetes_tpu.api.quantity import parse_cpu, parse_quantity
        ccpu = parse_cpu(cr.get(api.RESOURCE_CPU, 0))
        cmem = parse_quantity(cr.get(api.RESOURCE_MEMORY, 0))
        cpu += ccpu if ccpu else DEFAULT_MILLI_CPU_REQUEST
        mem += cmem if cmem else DEFAULT_MEMORY_REQUEST
    return req, np.array([cpu, mem / MB], dtype=np.float32)


def _pod_ports_set(pod: api.Pod):
    out = set()
    for c in (pod.spec.containers or []) if pod.spec else []:
        for p in c.ports or []:
            if p.host_port:
                out.add((p.protocol or "TCP", p.host_port))
    return out


def _selector_signature(selectors: Sequence[labelsel.Selector], ns: str):
    return (ns, tuple(sorted(str(s) for s in selectors)))


class Tensorizer:
    """Builds ClusterTensors from (nodes, existing pods, pending pods).

    The listers (service/RC/RS) are consulted per pending pod to derive its
    spread group, mirroring SelectorSpread's lister usage."""

    def __init__(self, plugin_args=None,
                 failure_domains=(api.LABEL_HOSTNAME, api.LABEL_ZONE, api.LABEL_REGION),
                 objective=None):
        self.args = plugin_args
        self.failure_domains = tuple(failure_domains)
        # enabled ObjectiveConfig -> the objective operand arrays ride the
        # batch (scheduler/objectives/tensors.py); None/default -> layout
        # unchanged
        from kubernetes_tpu.scheduler.objectives.config import (
            resolve_objective,
        )
        self.objective = resolve_objective(objective)

    # -- public ---------------------------------------------------------------

    def build(self, nodes: List[api.Node], existing: List[api.Pod],
              pending: List[api.Pod]) -> ClusterTensors:
        N, P = len(nodes), len(pending)
        # nodes are the lane (last) axis of every [P, N] matmul output: pad
        # to the 128-lane TPU tile; pods are the sublane axis: pad to 8
        Np, Pp = _pad(N, 128), _pad(P, 8)

        label_vocab = Vocab()
        for node in nodes:
            for kv in _labels_of(node).items():
                label_vocab.id(kv)
        # collect label pairs referenced by pod selectors too (so unmatched
        # requirements still get a column and fail cleanly), plus PV
        # zone/region pairs (VolumeZone folds into the selector tensors)
        for pod in pending:
            for kv in ((pod.spec.node_selector or {}) if pod.spec else {}).items():
                label_vocab.id(kv)
            for pair in self._pv_zone_pairs(pod):
                label_vocab.id(pair)

        taint_vocab = Vocab()
        for node in nodes:
            for t in ((node.spec.taints or []) if node.spec else []):
                taint_vocab.id((t.key, t.value, t.effect))

        port_vocab = Vocab()
        for pod in list(existing) + list(pending):
            for pp in _pod_ports_set(pod):
                port_vocab.id(pp)

        image_vocab = Vocab()
        for pod in pending:
            for c in (pod.spec.containers or []) if pod.spec else []:
                if c.image:
                    image_vocab.id(c.image)

        zone_vocab = Vocab()

        # --- nodes -----------------------------------------------------------
        L = _pad(len(label_vocab), 128)
        T = _pad(len(taint_vocab), 128)
        PT = _pad(len(port_vocab), 128)
        I = _pad(len(image_vocab), 128)

        alloc = np.zeros((Np, 4), np.float32)
        node_labels = np.zeros((Np, L), np.float32)
        taints_ns = np.zeros((Np, T), np.float32)
        taints_pref = np.zeros((Np, T), np.float32)
        mem_pressure = np.zeros(Np, bool)
        node_valid = np.zeros(Np, bool)
        zone_id = np.full(Np, -1, np.int32)
        image_node_sizes = np.zeros((Np, I), np.float32)
        node_index = {}

        for i, node in enumerate(nodes):
            node_index[node.metadata.name] = i
            node_valid[i] = True
            a = api.node_allocatable(node)
            alloc[i] = (a[api.RESOURCE_CPU], a[api.RESOURCE_MEMORY] / MB,
                        a[api.RESOURCE_GPU], a[api.RESOURCE_PODS])
            for kv in _labels_of(node).items():
                node_labels[i, label_vocab.id(kv)] = 1.0
            for t in ((node.spec.taints or []) if node.spec else []):
                tid = taint_vocab.id((t.key, t.value, t.effect))
                if t.effect == api.TAINT_NO_SCHEDULE:
                    taints_ns[i, tid] = 1.0
                elif t.effect == api.TAINT_PREFER_NO_SCHEDULE:
                    taints_pref[i, tid] = 1.0
            for cond in ((node.status.conditions or []) if node.status else []):
                if cond.type == api.NODE_MEMORY_PRESSURE and cond.status == api.CONDITION_TRUE:
                    mem_pressure[i] = True
            zk = _zone_key(node)
            if zk:
                zone_id[i] = zone_vocab.id(zk)
            for img in ((node.status.images or []) if node.status else []):
                for name in (img.names or []):
                    iid = image_vocab.get(name)
                    if iid is not None:
                        image_node_sizes[i, iid] = img.size_bytes / MB

        # --- existing usage --------------------------------------------------
        used0 = np.zeros((Np, 4), np.float32)
        used0_nz = np.zeros((Np, 2), np.float32)
        node_ports0 = np.zeros((Np, PT), np.float32)
        for pod in existing:
            n = node_index.get(pod.spec.node_name if pod.spec else "")
            if n is None:
                continue
            rq, nz = _pod_req_vec(pod)
            used0[n] += rq
            used0_nz[n] += nz
            for pp in _pod_ports_set(pod):
                node_ports0[n, port_vocab.id(pp)] = 1.0

        # --- pending pods ----------------------------------------------------
        req = np.zeros((Pp, 4), np.float32)
        nonzero_req = np.zeros((Pp, 2), np.float32)
        sel_required = np.zeros((Pp, L), np.float32)
        pod_ports = np.zeros((Pp, PT), np.float32)
        tol_ns = np.zeros((Pp, T), np.float32)
        tol_pref = np.zeros((Pp, T), np.float32)
        best_effort = np.zeros(Pp, bool)
        host_req = np.full(Pp, -1, np.int32)
        pod_valid = np.zeros(Pp, bool)
        pod_images = np.zeros((Pp, I), np.float32)

        for p, pod in enumerate(pending):
            pod_valid[p] = True
            req[p], nonzero_req[p] = _pod_req_vec(pod)
            for kv in ((pod.spec.node_selector or {}) if pod.spec else {}).items():
                sel_required[p, label_vocab.id(kv)] = 1.0
            for pp in _pod_ports_set(pod):
                pod_ports[p, port_vocab.id(pp)] = 1.0
            best_effort[p] = _is_best_effort(pod)
            want = pod.spec.node_name if pod.spec else ""
            if want:
                host_req[p] = node_index.get(want, -2)  # -2: named unknown node
            for taint, tid in taint_vocab.items():
                t = api.Taint(key=taint[0], value=taint[1], effect=taint[2])
                for tol in ((pod.spec.tolerations or []) if pod.spec else []):
                    if tol.tolerates(t):
                        if t.effect == api.TAINT_NO_SCHEDULE:
                            tol_ns[p, tid] = 1.0
                        elif t.effect == api.TAINT_PREFER_NO_SCHEDULE:
                            tol_pref[p, tid] = 1.0
                        break
            for c in (pod.spec.containers or []) if pod.spec else []:
                iid = image_vocab.get(c.image)
                if iid is not None:
                    pod_images[p, iid] = 1.0

        # --- volume zone (predicates.go:271-347): a PV's zone/region labels
        # become required node-label pairs, folded into the nodeSelector
        # tensors; an unresolvable/unbound PVC adds an unsatisfiable
        # requirement (sel_count bump with no column) = fail on every node
        self._fold_volume_zone(pending, sel_required, label_vocab, node_labels,
                               nodes)
        sel_count = sel_required.sum(axis=1)
        for p, pod in enumerate(pending):
            if self._has_broken_pvc(pod):
                sel_count[p] += 1.0

        # --- node affinity ---------------------------------------------------
        (expr_node, term_expr, term_expr_count, pod_term, pod_has_aff,
         pref_term_node, pref_weight, pod_pref_term) = self._affinity_tensors(
            nodes, pending, node_labels, label_vocab, Np, Pp)

        # --- spread groups ---------------------------------------------------
        pod_group, pod_in_group, group_counts0, n_groups = self._spread_tensors(
            nodes, existing, pending, node_index, Np, Pp)

        # --- inter-pod term tables -------------------------------------------
        interpod = self._interpod_tensors(
            nodes, existing, pending, node_index, Np, Pp)

        # --- volumes ---------------------------------------------------------
        volumes = self._volume_tensors(existing, pending, node_index, Np, Pp)

        # --- scheduling objectives (scheduler/objectives/tensors.py) ---------
        objective_kw = {}
        if self.objective is not None:
            from kubernetes_tpu.scheduler.objectives.tensors import (
                build_objective_tensors,
            )
            node_labels_d = {i: _labels_of(n) for i, n in enumerate(nodes)}
            # victim candidates: placed pods on listed nodes, excluding
            # terminating ones (a pod already on its way out is not a
            # victim worth nominating)
            placed = [
                (ep, node_index[ep.spec.node_name]) for ep in existing
                if ep.spec and ep.spec.node_name in node_index
                and not (ep.metadata and ep.metadata.deletion_timestamp)]
            arrays, info = build_objective_tensors(
                self.objective, pending, Pp, Np,
                lambda slot: node_labels_d.get(slot, {}), placed)
            objective_kw = dict(arrays)
            objective_kw["objective_info"] = info

        return ClusterTensors(
            node_names=[n.metadata.name for n in nodes],
            pod_keys=[f"{p.metadata.namespace}/{p.metadata.name}" for p in pending],
            alloc=alloc, used0=used0, used0_nonzero=used0_nz,
            node_labels=node_labels, node_ports0=node_ports0,
            taints_nosched=taints_ns, taints_prefer=taints_pref,
            mem_pressure=mem_pressure, node_valid=node_valid,
            zone_id=zone_id, n_zones=max(len(zone_vocab), 1),
            req=req, nonzero_req=nonzero_req,
            sel_required=sel_required, sel_count=sel_count,
            pod_ports=pod_ports, tol_nosched=tol_ns, tol_prefer=tol_pref,
            best_effort=best_effort, host_req=host_req, pod_valid=pod_valid,
            expr_node=expr_node, term_expr=term_expr,
            term_expr_count=term_expr_count, pod_term=pod_term,
            pod_has_affinity=pod_has_aff,
            pref_term_node=pref_term_node, pref_weight=pref_weight,
            pod_pref_term=pod_pref_term,
            pod_group=pod_group, pod_in_group=pod_in_group,
            group_counts0=group_counts0, n_groups=n_groups,
            image_node_sizes=image_node_sizes, pod_images=pod_images,
            n_real_nodes=N, n_real_pods=P,
            **interpod, **volumes, **objective_kw,
        )

    # -- node affinity --------------------------------------------------------

    def _affinity_tensors(self, nodes, pending, node_labels, label_vocab,
                          Np, Pp):
        """Compile required + preferred NodeAffinity into matmul operands.
        Expressions are deduped across the batch (RC-stamped pods share
        them), so E and TM stay tiny even for 30k pods."""
        expr_vocab = Vocab()     # canonical expression -> row
        expr_rows: List[np.ndarray] = []
        term_vocab = Vocab()     # tuple(expr ids) -> term row
        term_exprs: List[List[int]] = []
        pod_terms: List[List[int]] = []
        has_aff = np.zeros(Pp, bool)

        node_label_maps = [
            _labels_of(n) for n in nodes]

        def expr_id(e: api.NodeSelectorRequirement) -> int:
            key = (e.key, e.operator, tuple(e.values or ()))
            i = expr_vocab.get(key)
            if i is not None:
                return i
            i = expr_vocab.id(key)
            row = np.zeros(Np, np.float32)
            req = labelsel.Requirement(e.key, e.operator, tuple(e.values or ()))
            for n, lbls in enumerate(node_label_maps):
                if req.matches(lbls):
                    row[n] = 1.0
            expr_rows.append(row)
            return i

        def term_id(t: api.NodeSelectorTerm) -> int:
            eids = tuple(sorted(expr_id(e) for e in (t.match_expressions or [])))
            i = term_vocab.get(eids)
            if i is not None:
                return i
            i = term_vocab.id(eids)
            term_exprs.append(list(eids))
            return i

        pref_entries: List[Tuple[int, float]] = []   # (term row id, weight)
        pod_prefs: List[List[int]] = []

        for p, pod in enumerate(pending):
            aff = pod.spec.affinity if pod.spec else None
            na = aff.node_affinity if aff else None
            req = na.required_during_scheduling_ignored_during_execution if na else None
            tids: List[int] = []
            if req is not None:
                has_aff[p] = True
                for t in (req.node_selector_terms or []):
                    tids.append(term_id(t))
            pod_terms.append(tids)
            prefs: List[int] = []
            for pref in ((na.preferred_during_scheduling_ignored_during_execution or [])
                         if na else []):
                if pref.weight and pref.preference is not None:
                    pt = term_id(pref.preference)
                    prefs.append(len(pref_entries))
                    pref_entries.append((pt, float(pref.weight)))
            pod_prefs.append(prefs)

        E = _pad(len(expr_rows), 8)
        TM = _pad(len(term_exprs), 8)
        expr_node = np.zeros((E, Np), np.float32)
        for i, row in enumerate(expr_rows):
            expr_node[i] = row
        term_expr = np.zeros((TM, E), np.float32)
        term_count = np.zeros(TM, np.float32)
        for i, eids in enumerate(term_exprs):
            for e in eids:
                term_expr[i, e] = 1.0
            term_count[i] = len(eids)
        pod_term = np.zeros((Pp, TM), np.float32)
        for p, tids in enumerate(pod_terms):
            for t in tids:
                pod_term[p, t] = 1.0

        PT2 = _pad(len(pref_entries), 8)
        pref_term_node = np.zeros((PT2, Np), np.float32)
        pref_weight = np.zeros(PT2, np.float32)
        # term truth per node: all its exprs true
        term_node = (term_expr @ expr_node) >= term_count[:, None]
        for i, (tid, w) in enumerate(pref_entries):
            pref_term_node[i] = term_node[tid].astype(np.float32)
            pref_weight[i] = w
        pod_pref_term = np.zeros((Pp, PT2), np.float32)
        for p, prefs in enumerate(pod_prefs):
            for i in prefs:
                pod_pref_term[p, i] = 1.0

        return (expr_node, term_expr, term_count, pod_term, has_aff,
                pref_term_node, pref_weight, pod_pref_term)

    # -- spread ---------------------------------------------------------------

    def _pod_selectors(self, pod: api.Pod) -> List[labelsel.Selector]:
        if self.args is None:
            return []
        sels = []
        if self.args.service_lister:
            for svc in self.args.service_lister.get_pod_services(pod):
                sels.append(labelsel.selector_from_map(svc.spec.selector))
        if self.args.controller_lister:
            for rc in self.args.controller_lister.get_pod_controllers(pod):
                sels.append(labelsel.selector_from_map(rc.spec.selector))
        if self.args.replicaset_lister:
            for rs in self.args.replicaset_lister.get_pod_replica_sets(pod):
                sels.append(labelsel.selector_from_label_selector(rs.spec.selector))
        return sels

    def _spread_tensors(self, nodes, existing, pending, node_index, Np, Pp):
        group_vocab = Vocab()
        group_selectors: List[Tuple[str, List[labelsel.Selector]]] = []
        pod_group = np.full(Pp, -1, np.int32)
        for p, pod in enumerate(pending):
            sels = self._pod_selectors(pod)
            if not sels:
                continue
            sig = _selector_signature(sels, pod.metadata.namespace)
            gid = group_vocab.get(sig)
            if gid is None:
                gid = group_vocab.id(sig)
                group_selectors.append((pod.metadata.namespace, sels))
            pod_group[p] = gid

        G = max(len(group_selectors), 1)
        pod_in_group = np.zeros((Pp, G), np.float32)
        for p, pod in enumerate(pending):
            lbls = _labels_of(pod)
            for g, (ns, sels) in enumerate(group_selectors):
                if pod.metadata.namespace == ns and any(
                        s.matches(lbls) for s in sels):
                    pod_in_group[p, g] = 1.0

        group_counts0 = np.zeros((Np, G), np.float32)
        for pod in existing:
            n = node_index.get(pod.spec.node_name if pod.spec else "")
            if n is None or (pod.metadata and pod.metadata.deletion_timestamp):
                continue
            lbls = _labels_of(pod)
            for g, (ns, sels) in enumerate(group_selectors):
                if pod.metadata.namespace == ns and any(
                        s.matches(lbls) for s in sels):
                    group_counts0[n, g] += 1.0

        return pod_group, pod_in_group, group_counts0, G

    # -- volume zone / broken PVCs --------------------------------------------

    def _pod_pvs(self, pod: api.Pod):
        """Resolve the pod's PVC-backed volumes to PVs (None entries for
        unresolvable/unbound claims)."""
        args = self.args
        # both lookups required, matching the provider's NoVolumeZoneConflict
        # gate (a partial informer set must not mark PVC pods unschedulable)
        if args is None or not getattr(args, "pvc_lookup", None) \
                or not getattr(args, "pv_lookup", None):
            return []
        ns = pod.metadata.namespace if pod.metadata else ""
        out = []
        for v in (pod.spec.volumes or []) if pod.spec else []:
            if not v.persistent_volume_claim:
                continue
            pvc = args.pvc_lookup(ns, v.persistent_volume_claim.claim_name)
            if pvc is None or not (pvc.spec and pvc.spec.volume_name):
                out.append(None)
                continue
            out.append(args.pv_lookup(pvc.spec.volume_name))
        return out

    def _has_broken_pvc(self, pod: api.Pod) -> bool:
        return any(pv is None for pv in self._pod_pvs(pod))

    def _pv_zone_pairs(self, pod: api.Pod):
        """(key, value) node-label pairs the pod's bound PVs require
        (VolumeZoneChecker semantics: zone + region labels)."""
        out = []
        for pv in self._pod_pvs(pod):
            if pv is None:
                continue
            pv_labels = (pv.metadata.labels or {}) if pv.metadata else {}
            for key in (api.LABEL_ZONE, api.LABEL_REGION):
                want = pv_labels.get(key)
                if want:
                    out.append((key, want))
        return out

    def _fold_volume_zone(self, pending, sel_required, label_vocab,
                          node_labels, nodes):
        """VolumeZoneChecker as nodeSelector columns: every zone/region label
        on a bound PV becomes a required node-label pair (the pairs were
        registered in label_vocab during build's vocab collection, so columns
        always exist; a pair no node carries is an all-zero column = fail
        everywhere, exactly the oracle's outcome)."""
        for p, pod in enumerate(pending):
            for pair in self._pv_zone_pairs(pod):
                sel_required[p, label_vocab.id(pair)] = 1.0

    # -- inter-pod term tables ------------------------------------------------

    def _interpod_tensors(self, nodes, existing, pending, node_index, Np, Pp):
        """Compile hard + soft inter-pod (anti-)affinity into term tables
        (predicates.go:769-947, interpod_affinity.go:86-216). Terms are
        deduped by (resolved namespaces, selector, topology); ownership is a
        count matrix so duplicated terms keep their full weight."""
        from kubernetes_tpu.scheduler.predicates import (
            _pod_matches_term, _term_namespaces,
        )

        # topology-key vocabulary: every concrete key used by any term plus
        # the default failure-domain keys (empty topologyKey = any default,
        # non_zero.go:87-109)
        key_vocab = Vocab()
        for k in self.failure_domains:
            key_vocab.id(k)

        def topo_keys(term) -> List[int]:
            if term.topology_key:
                return [key_vocab.id(term.topology_key)]
            return [key_vocab.get(k) for k in self.failure_domains]

        def all_terms(pod, kind):
            aff = pod.spec.affinity if pod.spec else None
            if aff is None:
                return []
            if kind == "aff":
                src = aff.pod_affinity
                return (src.required_during_scheduling_ignored_during_execution
                        or []) if src else []
            if kind == "anti":
                src = aff.pod_anti_affinity
                return (src.required_during_scheduling_ignored_during_execution
                        or []) if src else []
            if kind == "pref":
                out = []
                if aff.pod_affinity:
                    for wt in (aff.pod_affinity.
                               preferred_during_scheduling_ignored_during_execution or []):
                        if wt.weight and wt.pod_affinity_term:
                            out.append((wt.pod_affinity_term, float(wt.weight)))
                if aff.pod_anti_affinity:
                    for wt in (aff.pod_anti_affinity.
                               preferred_during_scheduling_ignored_during_execution or []):
                        if wt.weight and wt.pod_affinity_term:
                            out.append((wt.pod_affinity_term, -float(wt.weight)))
                return out
            raise ValueError(kind)

        placed = [ep for ep in existing if ep.spec and ep.spec.node_name
                  and ep.spec.node_name in node_index]

        def term_key(owner, term, weight=None):
            names = _term_namespaces(owner, term)
            sel = labelsel.selector_from_label_selector(term.label_selector)
            return (frozenset(names) if names is not None else "*",
                    str(sel), term.topology_key or "", weight)

        class TermTable:
            """Deduped term rows with per-pending-pod match columns."""

            def __init__(self):
                self.vocab = Vocab()
                self.rows = []   # (namespaces frozenset|None as '*', selector, kids, weight)

            def add(self, owner, term, weight=None):
                tk = term_key(owner, term, weight)
                tid = self.vocab.get(tk)
                if tid is None:
                    tid = self.vocab.id(tk)
                    names = _term_namespaces(owner, term)
                    sel = labelsel.selector_from_label_selector(term.label_selector)
                    self.rows.append((names, sel, topo_keys(term), weight))
                return tid

            def match_matrix(self, pods, P_padded):
                t = np.zeros((_pad(len(self.rows), 8), P_padded), np.float32)
                for i, (names, sel, _, _) in enumerate(self.rows):
                    for p, pod in enumerate(pods):
                        if names is not None and pod.metadata.namespace not in names:
                            continue
                        if sel.matches((pod.metadata.labels or {})):
                            t[i, p] = 1.0
                return t

            def topo_matrix(self, K_padded):
                t = np.zeros((_pad(len(self.rows), 8), K_padded), np.float32)
                for i, (_, _, kids, _) in enumerate(self.rows):
                    for kid in kids:
                        t[i, kid] = 1.0
                return t

            def matches(self, tid, pod) -> bool:
                names, sel, _, _ = self.rows[tid]
                if names is not None and pod.metadata.namespace not in names:
                    return False
                return sel.matches((pod.metadata.labels or {}))

            def padded(self):
                return _pad(len(self.rows), 8)

        req_t, anti_t, pref_t = TermTable(), TermTable(), TermTable()
        req_own_pairs, anti_own_pairs, pref_own_pairs = [], [], []

        for p, pod in enumerate(pending):
            for term in all_terms(pod, "aff"):
                req_own_pairs.append((p, req_t.add(pod, term)))
            for term in all_terms(pod, "anti"):
                anti_own_pairs.append((p, anti_t.add(pod, term)))
            for term, w in all_terms(pod, "pref"):
                pref_own_pairs.append((p, pref_t.add(pod, term, w)))

        # existing pods' own terms (static; symmetry + reverse score)
        sym_t = TermTable()       # existing anti (hard): forbids matching pods
        te_t = TermTable()        # existing preferred + hard-affinity terms
        sym_entries, te_entries = [], []   # (tid, owner node idx[, weight])
        hw = float(self.args.hard_pod_affinity_weight
                   if self.args is not None else 1)
        for ep in placed:
            n = node_index[ep.spec.node_name]
            for term in all_terms(ep, "anti"):
                sym_entries.append((sym_t.add(ep, term), n))
            # reverse hard-affinity terms only count under a positive weight
            # (interpod_affinity.go:143 requires hardPodAffinityWeight > 0,
            # matching features_of's `> 0` gate)
            if hw > 0:
                for term in all_terms(ep, "aff"):
                    te_entries.append((te_t.add(ep, term, ("hard",)), n, hw))
            for term, w in all_terms(ep, "pref"):
                te_entries.append((te_t.add(ep, term, w), n, w))

        # per-key domain ids over nodes (built AFTER all terms registered
        # their concrete topology keys in key_vocab)
        K = len(key_vocab)
        Kp = _pad(K, 8)
        node_dom_p = np.full((Kp, Np), -1, np.int32)
        for key, kid in key_vocab.items():
            dom_vocab = Vocab()
            for n, node in enumerate(nodes):
                val = _labels_of(node).get(key)
                if val:
                    node_dom_p[kid, n] = dom_vocab.id(val)

        def domain_mask(node_idx: int, kids: List[int]) -> np.ndarray:
            """Nodes sharing a topology domain with nodes[node_idx] under any
            of the given keys."""
            m = np.zeros(Np, np.float32)
            for kid in kids:
                row = node_dom_p[kid]
                d = row[node_idx]
                if d >= 0:
                    m = np.maximum(m, (row == d).astype(np.float32))
            return m

        TR, TA, TP = req_t.padded(), anti_t.padded(), pref_t.padded()

        req_own = np.zeros((Pp, TR), np.float32)
        for p, t in req_own_pairs:
            req_own[p, t] += 1.0
        anti_own = np.zeros((Pp, TA), np.float32)
        for p, t in anti_own_pairs:
            anti_own[p, t] += 1.0
        pref_own = np.zeros((Pp, TP), np.float32)
        for p, t in pref_own_pairs:
            pref_own[p, t] += 1.0

        req_match = req_t.match_matrix(pending, Pp)
        anti_match = anti_t.match_matrix(pending, Pp)
        pref_match = pref_t.match_matrix(pending, Pp)
        req_topo = req_t.topo_matrix(Kp)
        anti_topo = anti_t.topo_matrix(Kp)
        pref_topo = pref_t.topo_matrix(Kp)
        pref_w = np.zeros(TP, np.float32)
        for i, (_, _, _, w) in enumerate(pref_t.rows):
            pref_w[i] = w

        # --- init from existing pods -----------------------------------------
        req_hit0 = np.zeros((TR, Np), np.float32)
        req_nomatch0 = np.ones(TR, bool)
        anti_hit0 = np.zeros((TA, Np), np.float32)
        pref_hit0 = np.zeros((TP, Np), np.float32)
        for ep in placed:
            n = node_index[ep.spec.node_name]
            for tid, (names, sel, kids, _) in enumerate(req_t.rows):
                if req_t.matches(tid, ep):
                    req_hit0[tid] = np.maximum(req_hit0[tid],
                                               domain_mask(n, kids))
                    req_nomatch0[tid] = False
            for tid, (names, sel, kids, _) in enumerate(anti_t.rows):
                if anti_t.matches(tid, ep):
                    anti_hit0[tid] = np.maximum(anti_hit0[tid],
                                                domain_mask(n, kids))
            for tid, (names, sel, kids, _) in enumerate(pref_t.rows):
                if pref_t.matches(tid, ep):
                    pref_hit0[tid] += domain_mask(n, kids)

        TS, TE = sym_t.padded(), te_t.padded()
        sym_dom0 = np.zeros((TS, Np), np.float32)
        for tid, n in sym_entries:
            kids = sym_t.rows[tid][2]
            sym_dom0[tid] = np.maximum(sym_dom0[tid], domain_mask(n, kids))
        sym_match = sym_t.match_matrix(pending, Pp)
        te_dom0 = np.zeros((TE, Np), np.float32)
        for tid, n, w in te_entries:
            kids = te_t.rows[tid][2]
            te_dom0[tid] += w * domain_mask(n, kids)
        te_match = te_t.match_matrix(pending, Pp)

        return dict(
            node_dom=node_dom_p,
            req_topo=req_topo, req_own=req_own, req_match=req_match,
            req_hit0=req_hit0, req_nomatch0=req_nomatch0,
            anti_topo=anti_topo, anti_own=anti_own, anti_match=anti_match,
            anti_hit0=anti_hit0,
            pref_topo=pref_topo, pref_own=pref_own, pref_match=pref_match,
            pref_w=pref_w, pref_hit0=pref_hit0,
            sym_dom0=sym_dom0, sym_match=sym_match,
            te_dom0=te_dom0, te_match=te_match,
            hard_weight=np.asarray(hw, np.float32),
        )

    # -- volumes --------------------------------------------------------------

    def _volume_tensors(self, existing, pending, node_index, Np, Pp):
        """NoDiskConflict + MaxPDVolumeCount operands
        (predicates.go:64-269). Exclusive-disk columns: GCE PD by name with a
        separate rw flag (both-read-only shares are legal), EBS by volume id,
        RBD by (pool, image, monitor) so any shared monitor conflicts."""
        from kubernetes_tpu.scheduler.predicates import MaxPDVolumeCountChecker

        args = self.args
        ebs_check = MaxPDVolumeCountChecker(
            "ebs", 0, getattr(args, "pvc_lookup", None) if args else None,
            getattr(args, "pv_lookup", None) if args else None)
        gce_check = MaxPDVolumeCountChecker(
            "gce-pd", 0, getattr(args, "pvc_lookup", None) if args else None,
            getattr(args, "pv_lookup", None) if args else None)

        def disk_cols(pod):
            """[(column key, rw)] exclusive-disk entries for a pod."""
            out = []
            for v in (pod.spec.volumes or []) if pod.spec else []:
                if v.gce_persistent_disk:
                    out.append((("gce", v.gce_persistent_disk.pd_name),
                                not v.gce_persistent_disk.read_only))
                if v.aws_elastic_block_store:
                    out.append((("ebs", v.aws_elastic_block_store.volume_id),
                                True))
                if v.rbd:
                    for mon in (v.rbd.monitors or []):
                        out.append((("rbd", v.rbd.pool, v.rbd.image, mon),
                                    True))
            return out

        disk_vocab, ebs_vocab, gce_vocab = Vocab(), Vocab(), Vocab()
        every = list(existing) + list(pending)
        for pod in every:
            for key, _ in disk_cols(pod):
                disk_vocab.id(key)
            ns = pod.metadata.namespace if pod.metadata else ""
            for v in (pod.spec.volumes or []) if pod.spec else []:
                vid = ebs_check._volume_id(v, ns)
                if vid is not None:
                    ebs_vocab.id(vid)
                vid = gce_check._volume_id(v, ns)
                if vid is not None:
                    gce_vocab.id(vid)

        D = _pad(len(disk_vocab), 128)
        VE = _pad(len(ebs_vocab), 128)
        VG = _pad(len(gce_vocab), 128)

        pod_disk_any = np.zeros((Pp, D), np.float32)
        pod_disk_rw = np.zeros((Pp, D), np.float32)
        pod_ebs = np.zeros((Pp, VE), np.float32)
        pod_gce = np.zeros((Pp, VG), np.float32)
        node_disk_any0 = np.zeros((Np, D), np.float32)
        node_disk_rw0 = np.zeros((Np, D), np.float32)
        node_ebs0 = np.zeros((Np, VE), np.float32)
        node_gce0 = np.zeros((Np, VG), np.float32)

        def fill(pod, disk_any, disk_rw, ebs_row, gce_row, idx):
            for key, rw in disk_cols(pod):
                c = disk_vocab.get(key)
                disk_any[idx, c] = 1.0
                if rw:
                    disk_rw[idx, c] = 1.0
            ns = pod.metadata.namespace if pod.metadata else ""
            for v in (pod.spec.volumes or []) if pod.spec else []:
                vid = ebs_check._volume_id(v, ns)
                if vid is not None:
                    ebs_row[idx, ebs_vocab.get(vid)] = 1.0
                vid = gce_check._volume_id(v, ns)
                if vid is not None:
                    gce_row[idx, gce_vocab.get(vid)] = 1.0

        for p, pod in enumerate(pending):
            fill(pod, pod_disk_any, pod_disk_rw, pod_ebs, pod_gce, p)
        for ep in existing:
            n = node_index.get(ep.spec.node_name if ep.spec else "")
            if n is None:
                continue
            fill(ep, node_disk_any0, node_disk_rw0, node_ebs0, node_gce0, n)

        from kubernetes_tpu.scheduler.predicates import (
            DEFAULT_MAX_EBS_VOLUMES, DEFAULT_MAX_GCE_PD_VOLUMES,
        )
        return dict(
            pod_disk_any=pod_disk_any, pod_disk_rw=pod_disk_rw,
            node_disk_any0=node_disk_any0, node_disk_rw0=node_disk_rw0,
            pod_ebs=pod_ebs, node_ebs0=node_ebs0,
            pod_gce=pod_gce, node_gce0=node_gce0,
            max_ebs=np.asarray(DEFAULT_MAX_EBS_VOLUMES, np.float32),
            max_gce=np.asarray(DEFAULT_MAX_GCE_PD_VOLUMES, np.float32),
        )


def _zone_key(node: api.Node) -> str:
    lbls = _labels_of(node)
    region = lbls.get(api.LABEL_REGION, "")
    zone = lbls.get(api.LABEL_ZONE, "")
    if not region and not zone:
        return ""
    return f"{region}:{zone}"


def _is_best_effort(pod: api.Pod) -> bool:
    for c in (pod.spec.containers or []) if pod.spec else []:
        if c.resources and (c.resources.requests or c.resources.limits):
            return False
    return True
