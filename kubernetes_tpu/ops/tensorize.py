"""Host-side tensorization: cluster state -> dense, vocab-encoded arrays.

Everything string-shaped (labels, taints, ports, images, selectors, affinity
expressions, topology domains) is dictionary-encoded per batch into small
integer vocabularies, so device code is pure arithmetic:

- labels:  distinct (key, value) pairs over nodes -> columns of a bool
  [N, L] matrix; a nodeSelector becomes a required-column indicator and
  "all required present" is one [P, L] @ [L, N] matmul compared against the
  per-pod requirement count. NodeAffinity expressions (In/NotIn/Exists/
  DoesNotExist/Gt/Lt) compile to indicator rows over the same vocabulary
  (Gt/Lt rows are host-precomputed per node), terms are AND-reductions,
  term-sets OR-reductions — all matmuls (SURVEY §7 kernel formulation).
- taints:  distinct (key, value, effect) triples; toleration sets become
  tolerated-column indicators; "any untolerated NoSchedule taint" is again a
  matmul against the complement.
- ports:   distinct (protocol, hostPort) pairs; conflicts are an AND-matmul.
  Port occupancy is part of the scan carry (it changes as pods commit).
- spread:  pods sharing a selector signature (service/RC/RS sets,
  selector_spreading.go:84) form a group; per-node and per-zone group counts
  ride in the scan carry.
- images:  distinct image names; ImageLocality's per-node present-size is
  [P, I] @ (node_images * sizes) (priorities.go:137-207).
- topology: per failure-domain key, nodes map to globally-offset domain ids;
  inter-pod affinity terms become (term, domain) hit tables, precomputed
  against existing pods and updated in-carry for in-batch commits.

All vocab axes are padded to multiples of 128 (TPU lane width) and pod/node
axes to multiples of 8 (sublane), so XLA tiles every matmul onto the MXU.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from kubernetes_tpu.api import labels as labelsel
from kubernetes_tpu.api import types as api
from kubernetes_tpu.scheduler.cache import (
    DEFAULT_MEMORY_REQUEST, DEFAULT_MILLI_CPU_REQUEST, NodeInfo,
)

MB = 1024 * 1024


def _pad(n: int, mult: int) -> int:
    return max(mult, ((n + mult - 1) // mult) * mult)


class Vocab:
    """Stable insertion-ordered dictionary encoder."""

    def __init__(self):
        self._ids: Dict = {}

    def id(self, item) -> int:
        i = self._ids.get(item)
        if i is None:
            i = len(self._ids)
            self._ids[item] = i
        return i

    def get(self, item) -> Optional[int]:
        return self._ids.get(item)

    def __len__(self):
        return len(self._ids)

    def items(self):
        return self._ids.items()


@dataclass
class ClusterTensors:
    """Device-ready batch: N nodes x P pending pods (+ M existing pods folded
    into initial aggregates). All arrays are numpy; the kernel moves them to
    device once per batch."""

    node_names: List[str]
    pod_keys: List[str]             # ns/name of pending pods, FIFO order

    # node statics  (units: milliCPU, MiB, gpu, pod-slots)
    alloc: np.ndarray               # [N, 4] f32
    used0: np.ndarray               # [N, 4] f32  existing usage
    used0_nonzero: np.ndarray       # [N, 2] f32  nonzero-floored cpu/mem
    node_labels: np.ndarray         # [N, L] f32 (0/1)
    node_ports0: np.ndarray         # [N, PT] f32
    taints_nosched: np.ndarray      # [N, T] f32
    taints_prefer: np.ndarray       # [N, T] f32
    mem_pressure: np.ndarray        # [N] bool
    node_valid: np.ndarray          # [N] bool (padding rows are invalid)
    zone_id: np.ndarray             # [N] i32  (-1 = no zone); for spread
    n_zones: int

    # pod statics
    req: np.ndarray                 # [P, 4] f32
    nonzero_req: np.ndarray         # [P, 2] f32
    sel_required: np.ndarray        # [P, L] f32  nodeSelector pairs
    sel_count: np.ndarray           # [P] f32     number required
    pod_ports: np.ndarray           # [P, PT] f32
    tol_nosched: np.ndarray         # [P, T] f32  tolerated NoSchedule taints
    tol_prefer: np.ndarray          # [P, T] f32
    best_effort: np.ndarray         # [P] bool
    host_req: np.ndarray            # [P] i32  required node index or -1
    pod_valid: np.ndarray           # [P] bool

    # node affinity (required terms): expression/term/set matmuls
    expr_node: np.ndarray           # [E, N] f32  expression truth per node
    term_expr: np.ndarray           # [TM, E] f32 term -> its expressions
    term_expr_count: np.ndarray     # [TM] f32
    pod_term: np.ndarray            # [P, TM] f32 pod -> its terms (ORed)
    pod_has_affinity: np.ndarray    # [P] bool

    # preferred node affinity (score): weighted term rows
    pref_term_node: np.ndarray      # [PT2, N] f32 term truth per node
    pref_weight: np.ndarray         # [PT2] f32
    pod_pref_term: np.ndarray       # [P, PT2] f32

    # spread groups
    pod_group: np.ndarray           # [P] i32  group id for scoring (-1 none)
    pod_in_group: np.ndarray        # [P, G] f32  membership when committed
    group_counts0: np.ndarray       # [N, G] f32  existing matching pods
    n_groups: int

    # image locality
    image_node_sizes: np.ndarray    # [N, I] f32 (MiB present per image)
    pod_images: np.ndarray          # [P, I] f32

    # inter-pod affinity (vs existing pods; static)
    interpod_forbidden: np.ndarray  # [P, N] f32 (1 = blocked: anti/symmetry)
    interpod_required_miss: np.ndarray  # [P, N] f32 (1 = hard affinity unmet)

    n_real_nodes: int = 0
    n_real_pods: int = 0

    def arrays(self) -> dict:
        """All ndarray fields, for device upload."""
        return {k: v for k, v in self.__dict__.items()
                if isinstance(v, np.ndarray)}


# --- helpers -----------------------------------------------------------------

def _labels_of(obj) -> Dict[str, str]:
    return (obj.metadata.labels or {}) if obj.metadata else {}


def _pod_req_vec(pod: api.Pod) -> Tuple[np.ndarray, np.ndarray]:
    r = api.pod_resource_request(pod)
    req = np.array([r[api.RESOURCE_CPU], r[api.RESOURCE_MEMORY] / MB,
                    r[api.RESOURCE_GPU], 1.0], dtype=np.float32)
    cpu = mem = 0.0
    for c in (pod.spec.containers or []) if pod.spec else []:
        cr = (c.resources.requests if c.resources and c.resources.requests else {})
        from kubernetes_tpu.api.quantity import parse_cpu, parse_quantity
        ccpu = parse_cpu(cr.get(api.RESOURCE_CPU, 0))
        cmem = parse_quantity(cr.get(api.RESOURCE_MEMORY, 0))
        cpu += ccpu if ccpu else DEFAULT_MILLI_CPU_REQUEST
        mem += cmem if cmem else DEFAULT_MEMORY_REQUEST
    return req, np.array([cpu, mem / MB], dtype=np.float32)


def _pod_ports_set(pod: api.Pod):
    out = set()
    for c in (pod.spec.containers or []) if pod.spec else []:
        for p in c.ports or []:
            if p.host_port:
                out.add((p.protocol or "TCP", p.host_port))
    return out


def _selector_signature(selectors: Sequence[labelsel.Selector], ns: str):
    return (ns, tuple(sorted(str(s) for s in selectors)))


class Tensorizer:
    """Builds ClusterTensors from (nodes, existing pods, pending pods).

    The listers (service/RC/RS) are consulted per pending pod to derive its
    spread group, mirroring SelectorSpread's lister usage."""

    def __init__(self, plugin_args=None,
                 failure_domains=(api.LABEL_HOSTNAME, api.LABEL_ZONE, api.LABEL_REGION)):
        self.args = plugin_args
        self.failure_domains = tuple(failure_domains)

    # -- public ---------------------------------------------------------------

    def build(self, nodes: List[api.Node], existing: List[api.Pod],
              pending: List[api.Pod]) -> ClusterTensors:
        N, P = len(nodes), len(pending)
        # nodes are the lane (last) axis of every [P, N] matmul output: pad
        # to the 128-lane TPU tile; pods are the sublane axis: pad to 8
        Np, Pp = _pad(N, 128), _pad(P, 8)

        label_vocab = Vocab()
        for node in nodes:
            for kv in _labels_of(node).items():
                label_vocab.id(kv)
        # collect label pairs referenced by pod selectors too (so unmatched
        # requirements still get a column and fail cleanly)
        for pod in pending:
            for kv in ((pod.spec.node_selector or {}) if pod.spec else {}).items():
                label_vocab.id(kv)

        taint_vocab = Vocab()
        for node in nodes:
            for t in ((node.spec.taints or []) if node.spec else []):
                taint_vocab.id((t.key, t.value, t.effect))

        port_vocab = Vocab()
        for pod in list(existing) + list(pending):
            for pp in _pod_ports_set(pod):
                port_vocab.id(pp)

        image_vocab = Vocab()
        for pod in pending:
            for c in (pod.spec.containers or []) if pod.spec else []:
                if c.image:
                    image_vocab.id(c.image)

        zone_vocab = Vocab()

        # --- nodes -----------------------------------------------------------
        L = _pad(len(label_vocab), 128)
        T = _pad(len(taint_vocab), 128)
        PT = _pad(len(port_vocab), 128)
        I = _pad(len(image_vocab), 128)

        alloc = np.zeros((Np, 4), np.float32)
        node_labels = np.zeros((Np, L), np.float32)
        taints_ns = np.zeros((Np, T), np.float32)
        taints_pref = np.zeros((Np, T), np.float32)
        mem_pressure = np.zeros(Np, bool)
        node_valid = np.zeros(Np, bool)
        zone_id = np.full(Np, -1, np.int32)
        image_node_sizes = np.zeros((Np, I), np.float32)
        node_index = {}

        for i, node in enumerate(nodes):
            node_index[node.metadata.name] = i
            node_valid[i] = True
            a = api.node_allocatable(node)
            alloc[i] = (a[api.RESOURCE_CPU], a[api.RESOURCE_MEMORY] / MB,
                        a[api.RESOURCE_GPU], a[api.RESOURCE_PODS])
            for kv in _labels_of(node).items():
                node_labels[i, label_vocab.id(kv)] = 1.0
            for t in ((node.spec.taints or []) if node.spec else []):
                tid = taint_vocab.id((t.key, t.value, t.effect))
                if t.effect == api.TAINT_NO_SCHEDULE:
                    taints_ns[i, tid] = 1.0
                elif t.effect == api.TAINT_PREFER_NO_SCHEDULE:
                    taints_pref[i, tid] = 1.0
            for cond in ((node.status.conditions or []) if node.status else []):
                if cond.type == api.NODE_MEMORY_PRESSURE and cond.status == api.CONDITION_TRUE:
                    mem_pressure[i] = True
            zk = _zone_key(node)
            if zk:
                zone_id[i] = zone_vocab.id(zk)
            for img in ((node.status.images or []) if node.status else []):
                for name in (img.names or []):
                    iid = image_vocab.get(name)
                    if iid is not None:
                        image_node_sizes[i, iid] = img.size_bytes / MB

        # --- existing usage --------------------------------------------------
        used0 = np.zeros((Np, 4), np.float32)
        used0_nz = np.zeros((Np, 2), np.float32)
        node_ports0 = np.zeros((Np, PT), np.float32)
        for pod in existing:
            n = node_index.get(pod.spec.node_name if pod.spec else "")
            if n is None:
                continue
            rq, nz = _pod_req_vec(pod)
            used0[n] += rq
            used0_nz[n] += nz
            for pp in _pod_ports_set(pod):
                node_ports0[n, port_vocab.id(pp)] = 1.0

        # --- pending pods ----------------------------------------------------
        req = np.zeros((Pp, 4), np.float32)
        nonzero_req = np.zeros((Pp, 2), np.float32)
        sel_required = np.zeros((Pp, L), np.float32)
        pod_ports = np.zeros((Pp, PT), np.float32)
        tol_ns = np.zeros((Pp, T), np.float32)
        tol_pref = np.zeros((Pp, T), np.float32)
        best_effort = np.zeros(Pp, bool)
        host_req = np.full(Pp, -1, np.int32)
        pod_valid = np.zeros(Pp, bool)
        pod_images = np.zeros((Pp, I), np.float32)

        for p, pod in enumerate(pending):
            pod_valid[p] = True
            req[p], nonzero_req[p] = _pod_req_vec(pod)
            for kv in ((pod.spec.node_selector or {}) if pod.spec else {}).items():
                sel_required[p, label_vocab.id(kv)] = 1.0
            for pp in _pod_ports_set(pod):
                pod_ports[p, port_vocab.id(pp)] = 1.0
            best_effort[p] = _is_best_effort(pod)
            want = pod.spec.node_name if pod.spec else ""
            if want:
                host_req[p] = node_index.get(want, -2)  # -2: named unknown node
            for taint, tid in taint_vocab.items():
                t = api.Taint(key=taint[0], value=taint[1], effect=taint[2])
                for tol in ((pod.spec.tolerations or []) if pod.spec else []):
                    if tol.tolerates(t):
                        if t.effect == api.TAINT_NO_SCHEDULE:
                            tol_ns[p, tid] = 1.0
                        elif t.effect == api.TAINT_PREFER_NO_SCHEDULE:
                            tol_pref[p, tid] = 1.0
                        break
            for c in (pod.spec.containers or []) if pod.spec else []:
                iid = image_vocab.get(c.image)
                if iid is not None:
                    pod_images[p, iid] = 1.0

        sel_count = sel_required.sum(axis=1)

        # --- node affinity ---------------------------------------------------
        (expr_node, term_expr, term_expr_count, pod_term, pod_has_aff,
         pref_term_node, pref_weight, pod_pref_term) = self._affinity_tensors(
            nodes, pending, node_labels, label_vocab, Np, Pp)

        # --- spread groups ---------------------------------------------------
        pod_group, pod_in_group, group_counts0, n_groups = self._spread_tensors(
            nodes, existing, pending, node_index, Np, Pp)

        # --- inter-pod (vs existing, static) ---------------------------------
        forbidden, required_miss = self._interpod_static(
            nodes, existing, pending, node_index, Np, Pp)

        return ClusterTensors(
            node_names=[n.metadata.name for n in nodes],
            pod_keys=[f"{p.metadata.namespace}/{p.metadata.name}" for p in pending],
            alloc=alloc, used0=used0, used0_nonzero=used0_nz,
            node_labels=node_labels, node_ports0=node_ports0,
            taints_nosched=taints_ns, taints_prefer=taints_pref,
            mem_pressure=mem_pressure, node_valid=node_valid,
            zone_id=zone_id, n_zones=max(len(zone_vocab), 1),
            req=req, nonzero_req=nonzero_req,
            sel_required=sel_required, sel_count=sel_count,
            pod_ports=pod_ports, tol_nosched=tol_ns, tol_prefer=tol_pref,
            best_effort=best_effort, host_req=host_req, pod_valid=pod_valid,
            expr_node=expr_node, term_expr=term_expr,
            term_expr_count=term_expr_count, pod_term=pod_term,
            pod_has_affinity=pod_has_aff,
            pref_term_node=pref_term_node, pref_weight=pref_weight,
            pod_pref_term=pod_pref_term,
            pod_group=pod_group, pod_in_group=pod_in_group,
            group_counts0=group_counts0, n_groups=n_groups,
            image_node_sizes=image_node_sizes, pod_images=pod_images,
            interpod_forbidden=forbidden, interpod_required_miss=required_miss,
            n_real_nodes=N, n_real_pods=P,
        )

    # -- node affinity --------------------------------------------------------

    def _affinity_tensors(self, nodes, pending, node_labels, label_vocab,
                          Np, Pp):
        """Compile required + preferred NodeAffinity into matmul operands.
        Expressions are deduped across the batch (RC-stamped pods share
        them), so E and TM stay tiny even for 30k pods."""
        expr_vocab = Vocab()     # canonical expression -> row
        expr_rows: List[np.ndarray] = []
        term_vocab = Vocab()     # tuple(expr ids) -> term row
        term_exprs: List[List[int]] = []
        pod_terms: List[List[int]] = []
        has_aff = np.zeros(Pp, bool)

        node_label_maps = [
            _labels_of(n) for n in nodes]

        def expr_id(e: api.NodeSelectorRequirement) -> int:
            key = (e.key, e.operator, tuple(e.values or ()))
            i = expr_vocab.get(key)
            if i is not None:
                return i
            i = expr_vocab.id(key)
            row = np.zeros(Np, np.float32)
            req = labelsel.Requirement(e.key, e.operator, tuple(e.values or ()))
            for n, lbls in enumerate(node_label_maps):
                if req.matches(lbls):
                    row[n] = 1.0
            expr_rows.append(row)
            return i

        def term_id(t: api.NodeSelectorTerm) -> int:
            eids = tuple(sorted(expr_id(e) for e in (t.match_expressions or [])))
            i = term_vocab.get(eids)
            if i is not None:
                return i
            i = term_vocab.id(eids)
            term_exprs.append(list(eids))
            return i

        pref_entries: List[Tuple[int, float]] = []   # (term row id, weight)
        pod_prefs: List[List[int]] = []

        for p, pod in enumerate(pending):
            aff = pod.spec.affinity if pod.spec else None
            na = aff.node_affinity if aff else None
            req = na.required_during_scheduling_ignored_during_execution if na else None
            tids: List[int] = []
            if req is not None:
                has_aff[p] = True
                for t in (req.node_selector_terms or []):
                    tids.append(term_id(t))
            pod_terms.append(tids)
            prefs: List[int] = []
            for pref in ((na.preferred_during_scheduling_ignored_during_execution or [])
                         if na else []):
                if pref.weight and pref.preference is not None:
                    pt = term_id(pref.preference)
                    prefs.append(len(pref_entries))
                    pref_entries.append((pt, float(pref.weight)))
            pod_prefs.append(prefs)

        E = _pad(len(expr_rows), 8)
        TM = _pad(len(term_exprs), 8)
        expr_node = np.zeros((E, Np), np.float32)
        for i, row in enumerate(expr_rows):
            expr_node[i] = row
        term_expr = np.zeros((TM, E), np.float32)
        term_count = np.zeros(TM, np.float32)
        for i, eids in enumerate(term_exprs):
            for e in eids:
                term_expr[i, e] = 1.0
            term_count[i] = len(eids)
        pod_term = np.zeros((Pp, TM), np.float32)
        for p, tids in enumerate(pod_terms):
            for t in tids:
                pod_term[p, t] = 1.0

        PT2 = _pad(len(pref_entries), 8)
        pref_term_node = np.zeros((PT2, Np), np.float32)
        pref_weight = np.zeros(PT2, np.float32)
        # term truth per node: all its exprs true
        term_node = (term_expr @ expr_node) >= term_count[:, None]
        for i, (tid, w) in enumerate(pref_entries):
            pref_term_node[i] = term_node[tid].astype(np.float32)
            pref_weight[i] = w
        pod_pref_term = np.zeros((Pp, PT2), np.float32)
        for p, prefs in enumerate(pod_prefs):
            for i in prefs:
                pod_pref_term[p, i] = 1.0

        return (expr_node, term_expr, term_count, pod_term, has_aff,
                pref_term_node, pref_weight, pod_pref_term)

    # -- spread ---------------------------------------------------------------

    def _pod_selectors(self, pod: api.Pod) -> List[labelsel.Selector]:
        if self.args is None:
            return []
        sels = []
        if self.args.service_lister:
            for svc in self.args.service_lister.get_pod_services(pod):
                sels.append(labelsel.selector_from_map(svc.spec.selector))
        if self.args.controller_lister:
            for rc in self.args.controller_lister.get_pod_controllers(pod):
                sels.append(labelsel.selector_from_map(rc.spec.selector))
        if self.args.replicaset_lister:
            for rs in self.args.replicaset_lister.get_pod_replica_sets(pod):
                sels.append(labelsel.selector_from_label_selector(rs.spec.selector))
        return sels

    def _spread_tensors(self, nodes, existing, pending, node_index, Np, Pp):
        group_vocab = Vocab()
        group_selectors: List[Tuple[str, List[labelsel.Selector]]] = []
        pod_group = np.full(Pp, -1, np.int32)
        for p, pod in enumerate(pending):
            sels = self._pod_selectors(pod)
            if not sels:
                continue
            sig = _selector_signature(sels, pod.metadata.namespace)
            gid = group_vocab.get(sig)
            if gid is None:
                gid = group_vocab.id(sig)
                group_selectors.append((pod.metadata.namespace, sels))
            pod_group[p] = gid

        G = max(len(group_selectors), 1)
        pod_in_group = np.zeros((Pp, G), np.float32)
        for p, pod in enumerate(pending):
            lbls = _labels_of(pod)
            for g, (ns, sels) in enumerate(group_selectors):
                if pod.metadata.namespace == ns and any(
                        s.matches(lbls) for s in sels):
                    pod_in_group[p, g] = 1.0

        group_counts0 = np.zeros((Np, G), np.float32)
        for pod in existing:
            n = node_index.get(pod.spec.node_name if pod.spec else "")
            if n is None or (pod.metadata and pod.metadata.deletion_timestamp):
                continue
            lbls = _labels_of(pod)
            for g, (ns, sels) in enumerate(group_selectors):
                if pod.metadata.namespace == ns and any(
                        s.matches(lbls) for s in sels):
                    group_counts0[n, g] += 1.0

        return pod_group, pod_in_group, group_counts0, G

    # -- inter-pod static -----------------------------------------------------

    def _interpod_static(self, nodes, existing, pending, node_index, Np, Pp):
        """Hard inter-pod (anti-)affinity against existing pods, plus
        symmetry from existing pods' anti-affinity, as static [P, N] masks
        (predicates.go:769-947). In-batch interactions are handled by the
        scan carry (kernel.py) for anti-affinity self-spread terms."""
        from kubernetes_tpu.scheduler.predicates import (
            _pod_matches_term, _same_topology,
        )
        forbidden = np.zeros((Pp, Np), np.float32)
        required_miss = np.zeros((Pp, Np), np.float32)
        placed = [ep for ep in existing if ep.spec and ep.spec.node_name]

        def nodes_in_domain_of(ep_node_name: str, topo_key: str) -> List[int]:
            base = next((n for n in nodes if n.metadata.name == ep_node_name), None)
            if base is None:
                return []
            return [node_index[n.metadata.name] for n in nodes
                    if _same_topology(base, n, topo_key, self.failure_domains)]

        # existing pods' anti-affinity (symmetry)
        for ep in placed:
            aff = ep.spec.affinity if ep.spec else None
            anti = aff.pod_anti_affinity if aff else None
            for term in ((anti.required_during_scheduling_ignored_during_execution or [])
                         if anti else []):
                blocked = None
                for p, pod in enumerate(pending):
                    if _pod_matches_term(pod, ep, term):
                        if blocked is None:
                            blocked = nodes_in_domain_of(ep.spec.node_name,
                                                         term.topology_key)
                        forbidden[p, blocked] = 1.0

        for p, pod in enumerate(pending):
            aff = pod.spec.affinity if pod.spec else None
            if aff is None:
                continue
            anti_terms = ((aff.pod_anti_affinity.required_during_scheduling_ignored_during_execution or [])
                          if aff.pod_anti_affinity else [])
            for term in anti_terms:
                for ep in placed:
                    if _pod_matches_term(ep, pod, term):
                        for n in nodes_in_domain_of(ep.spec.node_name,
                                                    term.topology_key):
                            forbidden[p, n] = 1.0
            req_terms = ((aff.pod_affinity.required_during_scheduling_ignored_during_execution or [])
                         if aff.pod_affinity else [])
            for term in req_terms:
                ok_nodes = set()
                any_match = False
                for ep in placed:
                    if _pod_matches_term(ep, pod, term):
                        any_match = True
                        ok_nodes.update(nodes_in_domain_of(ep.spec.node_name,
                                                           term.topology_key))
                if not any_match:
                    # disregard rule (predicates.go:818-844): self-selecting
                    # term with no match anywhere may schedule
                    if _pod_matches_term(pod, pod, term) and not any(
                            _pod_matches_term(q, pod, term) for q in placed):
                        continue
                    required_miss[p, :] = 1.0
                else:
                    miss = np.ones(Np, np.float32)
                    miss[list(ok_nodes)] = 0.0
                    required_miss[p] = np.maximum(required_miss[p], miss)

        return forbidden, required_miss


def _zone_key(node: api.Node) -> str:
    lbls = _labels_of(node)
    region = lbls.get(api.LABEL_REGION, "")
    zone = lbls.get(api.LABEL_ZONE, "")
    if not region and not zone:
        return ""
    return f"{region}:{zone}"


def _is_best_effort(pod: api.Pod) -> bool:
    for c in (pod.spec.containers or []) if pod.spec else []:
        if c.resources and (c.resources.requests or c.resources.limits):
            return False
    return True
