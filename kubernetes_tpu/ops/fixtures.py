"""Shared feature-dense cluster builder for the multichip proofs.

One batch shape used by BOTH the driver's dryrun_multichip and the in-suite
sharded-equivalence tests (tests/test_multichip.py), so the layout the
driver validates is exactly the layout the tests prove binding-identical.
Exercises every optional scan carry: node selectors, taints/tolerations,
hard/preferred inter-pod (anti-)affinity, EBS+GCE volumes, host ports, and
— with_existing — the static symmetry (sym_dom0) and reverse-score
(te_dom0) tables owned by already-bound pods.

Import-safe: no device access, no platform mutation at import time.
"""

from __future__ import annotations


def feature_batch(n_nodes=48, n_pods=32, with_existing=False):
    from kubernetes_tpu.api import types as api
    from kubernetes_tpu.ops.tensorize import Tensorizer
    from kubernetes_tpu.scheduler.batch import ListServiceLister, make_plugin_args

    nodes = []
    for i in range(n_nodes):
        labels = {api.LABEL_HOSTNAME: f"n{i}", api.LABEL_ZONE: f"z{i % 4}"}
        if i % 3 == 0:
            labels["disk"] = "ssd"
        nodes.append(api.Node(
            metadata=api.ObjectMeta(name=f"n{i}", labels=labels),
            spec=api.NodeSpec(taints=(
                [api.Taint(key="ded", value="x", effect="NoSchedule")]
                if i % 8 == 0 else None)),
            status=api.NodeStatus(
                allocatable={"cpu": "4", "memory": "16Gi", "pods": "32"},
                conditions=[api.NodeCondition(type="Ready", status="True")])))
    svc = api.Service(metadata=api.ObjectMeta(name="s", namespace="default"),
                      spec=api.ServiceSpec(selector={"app": "web"},
                                           ports=[api.ServicePort(port=80)]))
    pending = []
    for i in range(n_pods):
        labels = {"app": "web" if i % 2 else "db", "uniq": f"u{i}"}
        # exercise the full kernel carry surface (interpod term tables +
        # volume columns) without making any pod unschedulable: anti/affinity
        # terms select each pod's unique label, volumes are per-pod unique
        affinity = None
        volumes = None
        if i % 6 == 1:
            affinity = api.Affinity(pod_anti_affinity=api.PodAntiAffinity(
                required_during_scheduling_ignored_during_execution=[
                    api.PodAffinityTerm(
                        label_selector=api.LabelSelector(
                            match_labels={"uniq": f"u{i}"}),
                        topology_key=api.LABEL_ZONE)]))
        elif i % 6 == 3:
            affinity = api.Affinity(pod_affinity=api.PodAffinity(
                required_during_scheduling_ignored_during_execution=[
                    api.PodAffinityTerm(
                        label_selector=api.LabelSelector(
                            match_labels={"uniq": f"u{i}"}),
                        topology_key=api.LABEL_ZONE)],
                preferred_during_scheduling_ignored_during_execution=[
                    api.WeightedPodAffinityTerm(
                        weight=10,
                        pod_affinity_term=api.PodAffinityTerm(
                            label_selector=api.LabelSelector(
                                match_labels={"app": "web"}),
                            topology_key=api.LABEL_ZONE))]))
        elif i % 12 == 5:
            volumes = [api.Volume(
                name=f"v{i}", aws_elastic_block_store=
                api.AWSElasticBlockStoreVolumeSource(volume_id=f"vol-{i}"))]
        elif i % 12 == 11:
            volumes = [api.Volume(
                name=f"v{i}", gce_persistent_disk=
                api.GCEPersistentDiskVolumeSource(pd_name=f"pd-{i}",
                                                  read_only=True))]
        pending.append(api.Pod(
            metadata=api.ObjectMeta(name=f"p{i}", namespace="default",
                                    labels=labels),
            spec=api.PodSpec(
                node_selector={"disk": "ssd"} if i % 5 == 0 else None,
                tolerations=([api.Toleration(key="ded", operator="Exists")]
                             if i % 8 == 0 else None),
                affinity=affinity, volumes=volumes,
                containers=[api.Container(
                    name="c", image="pause",
                    # unique host port per pod: traces the port-occupancy
                    # carry without ever conflicting
                    ports=([api.ContainerPort(container_port=8080,
                                              host_port=9000 + i)]
                           if i % 6 == 2 else None),
                    resources=api.ResourceRequirements(
                        requests={"cpu": "250m", "memory": "256Mi"}))])))
    # existing bound pods owning anti + preferred/hard terms: traces the
    # static symmetry (sym_dom0) and reverse-score (te_dom0) carries too,
    # so the sharded proof covers the FULL default-provider surface
    existing = []
    if with_existing:
        for i in range(max(n_nodes // 8, 4)):
            kw = {}
            if i % 3 == 0:
                kw["affinity"] = api.Affinity(
                    pod_anti_affinity=api.PodAntiAffinity(
                        required_during_scheduling_ignored_during_execution=[
                            api.PodAffinityTerm(
                                label_selector=api.LabelSelector(
                                    match_labels={"sym": f"s{i // 3 % 3}"}),
                                topology_key=api.LABEL_HOSTNAME)]))
            elif i % 3 == 1:
                kw["affinity"] = api.Affinity(pod_affinity=api.PodAffinity(
                    preferred_during_scheduling_ignored_during_execution=[
                        api.WeightedPodAffinityTerm(
                            weight=4,
                            pod_affinity_term=api.PodAffinityTerm(
                                label_selector=api.LabelSelector(
                                    match_labels={"app": "web"}),
                                topology_key=api.LABEL_ZONE))]))
            existing.append(api.Pod(
                metadata=api.ObjectMeta(name=f"e{i}", namespace="default",
                                        labels={"app": "existing"}),
                spec=api.PodSpec(
                    node_name=f"n{(i * 5) % n_nodes}",
                    containers=[api.Container(
                        name="c", image="pause",
                        resources=api.ResourceRequirements(
                            requests={"cpu": "100m", "memory": "128Mi"}))],
                    **kw)))
    args = make_plugin_args(nodes, service_lister=ListServiceLister([svc]))
    return Tensorizer(plugin_args=args).build(nodes, existing, pending)
