"""Incremental tensorization: the device-resident cluster mirror.

The full Tensorizer (ops/tensorize.py) rebuilds the world from Python
objects per batch — the anti-pattern the reference itself suffers in its
clone-per-decision cache (plugin/pkg/scheduler/schedulercache/cache.go:77-85)
and SURVEY §7 hard part #2 exists to kill. This module maintains the same
tensors *incrementally*:

- **Node-side state** (statics + placed-pod aggregates) is mirrored from
  SchedulerCache delta events (cache.add_listener): every array is updated
  in O(changed cells) when a node or placed pod changes, with reversible
  count representations (occupancy = clipped counts, affinity hit tables =
  per-domain match counts) so removals are exact.
- **Vocabularies are stable and grow-only** across batches (labels, taints,
  ports, images, zones, topology keys, disk/volume ids, affinity
  expressions/terms, spread groups), so array columns keep their meaning
  and the jit cache stays warm.
- **Pod-side tensors** are built per batch, vectorized through per-shape
  memoization: pods stamped from the same template (the RC/kubemark/bench
  reality) share every derived row, so a 30k-pod batch parses each distinct
  shape once. No per-pod imports, no O(P×T) toleration double-loop.
- **Device residency**: DeviceCache re-uploads only arrays whose version
  bumped since the last batch; indicator matrices travel as int8 (4× less
  HBM traffic than f32) and are cast on-device by the kernel.

Semantic deltas vs the full Tensorizer (both deliberate):
- hit tables carry match *counts* instead of 0/1 — the kernel only ever
  tests >0 / ==0 on them, and counts make removal exact;
- pods on currently-unschedulable nodes still contribute inter-pod affinity
  domain hits (the reference's InterPodAffinity lists ALL pods,
  predicates.go:774; the full Tensorizer only sees pods on listed nodes).

Reference seams mirrored: schedulercache delta flow (cache.go:101-156),
NodeInfo aggregation (node_info.go:118-156), the tensor layout contract of
ops/tensorize.py (ClusterTensors).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from kubernetes_tpu.api import labels as labelsel
from kubernetes_tpu.api import types as api
from kubernetes_tpu.ops.tensorize import (
    MB, ClusterTensors, Vocab, _is_best_effort, _labels_of, _pad,
    _pod_ports_set, _pod_req_vec, _selector_signature, _zone_key,
)
from kubernetes_tpu.client.listers import node_is_ready

LANE = 128   # TPU lane width: last-axis pad for big one-hot matrices
SUB = 8      # sublane pad for small term axes


def _grow(arr: np.ndarray, shape: Tuple[int, ...]) -> np.ndarray:
    out = np.zeros(shape, arr.dtype)
    out[tuple(slice(0, s) for s in arr.shape)] = arr
    return out


def _pod_key(pod: api.Pod) -> str:
    m = pod.metadata
    return f"{m.namespace}/{m.name}" if m else ""


def _labels_sig(pod: api.Pod):
    return (pod.metadata.namespace if pod.metadata else "",
            tuple(sorted((_labels_of(pod)).items())))


def _bucket(p: int) -> int:
    """Pod-axis padding: next power of two >= 8 — few distinct shapes keep
    the jit cache warm across variable batch sizes."""
    n = SUB
    while n < p:
        n *= 2
    return n


class _TermTable:
    """Grow-only deduped inter-pod term rows with reversible per-node
    domain-hit counts. Rows: (namespaces frozenset|None, selector,
    topo key ids, weight)."""

    def __init__(self, n_cap: int, weighted: bool = False):
        self.vocab = Vocab()
        self.rows: List[tuple] = []
        self.hits = np.zeros((SUB, n_cap), np.float64 if weighted else np.int32)
        self.totals = np.zeros(SUB, np.int64)  # matches anywhere, per term
        self.weighted = weighted

    def padded(self) -> int:
        return max(SUB, _pad(len(self.rows), SUB))

    def grow_nodes(self, n_cap: int):
        self.hits = _grow(self.hits, (self.hits.shape[0], n_cap))

    def ensure_rows(self):
        need = self.padded()
        if self.hits.shape[0] < need:
            self.hits = _grow(self.hits, (need, self.hits.shape[1]))
            self.totals = _grow(self.totals, (need,))

    def add(self, key, names, sel, kids, weight=None) -> Tuple[int, bool]:
        tid = self.vocab.get(key)
        if tid is not None:
            return tid, False
        tid = self.vocab.id(key)
        self.rows.append((names, sel, kids, weight))
        self.ensure_rows()
        return tid, True

    def matches(self, tid: int, ns: str, lbls: dict) -> bool:
        names, sel, _, _ = self.rows[tid]
        if names is not None and ns not in names:
            return False
        return sel.matches(lbls)

class IncrementalTensorizer:
    """Mirrors cluster state as device-ready arrays; listener side of
    SchedulerCache.add_listener (called under the cache lock)."""

    def __init__(self, plugin_args=None,
                 failure_domains=(api.LABEL_HOSTNAME, api.LABEL_ZONE,
                                  api.LABEL_REGION),
                 node_cap: int = LANE, pod_bucket: Optional[int] = None,
                 objective=None):
        self.args = plugin_args
        self.failure_domains = tuple(failure_domains)
        # enabled ObjectiveConfig -> the objective operand arrays ride every
        # batch (scheduler/objectives/tensors.py); None/default -> the
        # pre-objective tensor layout, bit for bit
        from kubernetes_tpu.scheduler.objectives.config import (
            resolve_objective,
        )
        self.objective = resolve_objective(objective)
        # fixed pod-axis pad (usually the scheduler's batch_size): every
        # full batch AND the tail then trace to one program shape, so the
        # whole drain costs a single XLA compile
        self.pod_bucket = pod_bucket
        self._lock = threading.RLock()
        self._versions: Dict[str, int] = {}

        # vocabs (grow-only)
        self._labelv = Vocab()
        self._taintv = Vocab()
        self._portv = Vocab()
        self._imagev = Vocab()
        self._zonev = Vocab()
        self._keyv = Vocab()          # topology keys
        for k in self.failure_domains:
            self._keyv.id(k)
        self._domv: Dict[int, Vocab] = {}   # per topo key: value -> domain id
        self._diskv = Vocab()
        self._ebsv = Vocab()
        self._gcev = Vocab()
        self._groupv = Vocab()        # spread-group signature -> gid
        self._group_rows: List[Tuple[str, list]] = []   # (ns, selectors)

        # node slots
        N = node_cap
        self._node_index: Dict[str, int] = {}
        self._free: List[int] = []
        self._hi = 0                  # high-water slot
        self._node_names: List[str] = [""] * N
        self._node_labels_d: Dict[int, dict] = {}   # slot -> labels dict
        self._node_images_d: Dict[int, dict] = {}   # slot -> image -> MiB
        self._slot_pods: Dict[int, int] = {}        # slot -> placed-pod count

        # node statics
        self.alloc = np.zeros((N, 4), np.float32)
        self.node_labels = np.zeros((N, LANE), np.int8)
        self.taints_nosched = np.zeros((N, LANE), np.int8)
        self.taints_prefer = np.zeros((N, LANE), np.int8)
        self.mem_pressure = np.zeros(N, bool)
        self.node_valid = np.zeros(N, bool)
        self.zone_id = np.full(N, -1, np.int32)
        self.image_node_sizes = np.zeros((N, LANE), np.float32)
        self.node_dom = np.full((_pad(len(self._keyv), SUB), N), -1, np.int32)

        # placed-pod aggregates (counts internal, clipped occupancy exposed)
        self.used0 = np.zeros((N, 4), np.float64)
        self.used0_nonzero = np.zeros((N, 2), np.float64)
        self._ports_cnt = np.zeros((N, LANE), np.int16)
        self.node_ports0 = np.zeros((N, LANE), np.int8)
        self._disk_any_cnt = np.zeros((N, LANE), np.int16)
        self._disk_rw_cnt = np.zeros((N, LANE), np.int16)
        self.node_disk_any0 = np.zeros((N, LANE), np.int8)
        self.node_disk_rw0 = np.zeros((N, LANE), np.int8)
        self._ebs_cnt = np.zeros((N, LANE), np.int16)
        self.node_ebs0 = np.zeros((N, LANE), np.int8)
        self._gce_cnt = np.zeros((N, LANE), np.int16)
        self.node_gce0 = np.zeros((N, LANE), np.int8)
        self.group_counts0 = np.zeros((N, SUB), np.int32)

        # inter-pod term tables: pending-owned (req/anti/pref) + placed-owned
        # (sym = anti terms of placed pods, te = weighted reverse scores)
        self.req_t = _TermTable(N)
        self.anti_t = _TermTable(N)
        self.pref_t = _TermTable(N)
        self.sym_t = _TermTable(N)
        self.te_t = _TermTable(N, weighted=True)

        # preempt mode: per-slot victim candidate lists kept SORTED by
        # (priority, pod key) and mirrored into the vict_prio/vict_cum
        # prefix tables in O(pods-on-node) per pod event — the delta-path
        # replacement for the per-batch host-side O(placed·log) rebuild
        # (ROADMAP 3b). KV is grow-only, so the kernel's jit key no longer
        # churns with the per-batch victim maximum.
        self._preempt = (self.objective is not None
                         and self.objective.preempt)
        if self._preempt:
            from kubernetes_tpu.scheduler.objectives.config import (
                INF_PRIORITY,
            )
            self._vict_kv = 8
            self._vict_lists: Dict[int, list] = {}  # slot -> [(prio, key, vec6)]
            self._vict_entry: Dict[str, tuple] = {}  # key -> (slot, prio, vec6)
            self.vict_prio = np.full((self._vict_kv, N), INF_PRIORITY,
                                     np.float32)
            self.vict_cum = np.zeros((6, self._vict_kv + 1, N), np.float32)

        # placed-pod registry, grouped by (ns, labels signature) for fast
        # new-term/new-group initialization scans
        self._placed: Dict[str, Tuple[api.Pod, int]] = {}
        self._by_sig: Dict[tuple, Dict[str, int]] = {}
        self._terminating: set = set()
        self._dead_slots: set = set()   # node removed, pods still draining
        self._live_nodes: set = set()   # names with a live node object
        # PVC-backed volume columns as resolved at ADD time, so removal
        # reverses the same cells even if the PVC/PV changed meanwhile
        self._pvc_cols: Dict[str, Tuple[list, list]] = {}

        # node-affinity expression machinery
        self._exprv = Vocab()          # (key, op, values) -> expr id
        self._expr_reqs: List[labelsel.Requirement] = []
        self.expr_node = np.zeros((SUB, N), np.int8)
        self._termv = Vocab()          # tuple(expr ids) -> term id
        self._term_exprs: List[List[int]] = []
        self._prefv = Vocab()          # (term id, weight) -> pref entry id
        self._pref_entries: List[Tuple[int, float]] = []
        self.pref_term_node = np.zeros((SUB, N), np.int8)
        self.pref_weight = np.zeros(SUB, np.float32)

        # cross-batch pod-shape memo (pure spec derivations only)
        self._shape_memo: Dict[tuple, dict] = {}
        self._match_memo: Dict[tuple, dict] = {}   # (ns, labels) -> per-table ids

        # stats for the bench
        self.builds = 0
        self.pod_events = 0
        self.node_events = 0
        self.last_build_seconds = 0.0
        self.last_upload_bytes = 0
        # a listener callback that threw means this mirror missed an event:
        # it must refuse to schedule (the cache isolates listener exceptions,
        # so without this flag the staleness would be silent)
        self.broken: Optional[str] = None

    # --- dirty tracking ------------------------------------------------------

    def _touch(self, *names: str):
        for n in names:
            self._versions[n] = self._versions.get(n, 0) + 1

    @property
    def n_cap(self) -> int:
        return self.alloc.shape[0]

    # --- capacity growth -----------------------------------------------------

    def _grow_nodes(self):
        N = self.n_cap * 2
        for name in ("alloc", "node_labels", "taints_nosched", "taints_prefer",
                     "mem_pressure", "node_valid", "image_node_sizes",
                     "used0", "used0_nonzero", "_ports_cnt", "node_ports0",
                     "_disk_any_cnt", "_disk_rw_cnt", "node_disk_any0",
                     "node_disk_rw0", "_ebs_cnt", "node_ebs0", "_gce_cnt",
                     "node_gce0", "group_counts0", "expr_node",
                     "pref_term_node"):
            arr = getattr(self, name)
            shape = (N,) + arr.shape[1:] if arr.ndim > 1 or name in (
                "mem_pressure", "node_valid") else (N,)
            if name in ("expr_node", "pref_term_node"):
                shape = (arr.shape[0], N)
            setattr(self, name, _grow(arr, shape))
        zid = np.full(N, -1, np.int32)
        zid[: self.zone_id.shape[0]] = self.zone_id
        self.zone_id = zid
        nd = np.full((self.node_dom.shape[0], N), -1, np.int32)
        nd[:, : self.node_dom.shape[1]] = self.node_dom
        self.node_dom = nd
        for t in (self.req_t, self.anti_t, self.pref_t, self.sym_t, self.te_t):
            t.grow_nodes(N)
        if self._preempt:
            from kubernetes_tpu.scheduler.objectives.config import (
                INF_PRIORITY,
            )
            vp = np.full((self._vict_kv, N), INF_PRIORITY, np.float32)
            vp[:, : self.vict_prio.shape[1]] = self.vict_prio
            vc = np.zeros((6, self._vict_kv + 1, N), np.float32)
            vc[:, :, : self.vict_cum.shape[2]] = self.vict_cum
            self.vict_prio, self.vict_cum = vp, vc
            self._touch("vict_prio", "vict_cum")
        self._node_names.extend([""] * (N - len(self._node_names)))
        self._touch("alloc", "node_labels", "taints_nosched", "taints_prefer",
                    "mem_pressure", "node_valid", "zone_id", "image_node_sizes",
                    "node_dom", "used0", "used0_nonzero", "node_ports0",
                    "node_disk_any0", "node_disk_rw0", "node_ebs0",
                    "node_gce0", "group_counts0", "expr_node", "pref_term_node",
                    "req_hit0", "anti_hit0", "pref_hit0", "sym_dom0", "te_dom0")

    def _grow_cols(self, name: str, vocab: Vocab, pad: int = LANE,
                   extra: Tuple[str, ...] = ()):
        """Widen a [N, C] column family when its vocab outgrows it."""
        arr = getattr(self, name)
        need = _pad(len(vocab), pad)
        if arr.shape[1] < need:
            for n in (name,) + extra:
                a = getattr(self, n)
                setattr(self, n, _grow(a, (a.shape[0], need)))
                self._touch(n)

    # --- domain helpers ------------------------------------------------------

    def _dom_id(self, kid: int, val: str) -> int:
        v = self._domv.get(kid)
        if v is None:
            v = self._domv[kid] = Vocab()
        return v.id(val)

    def _ensure_key_rows(self):
        need = _pad(len(self._keyv), SUB)
        if self.node_dom.shape[0] < need:
            nd = np.full((need, self.n_cap), -1, np.int32)
            nd[: self.node_dom.shape[0]] = self.node_dom
            self.node_dom = nd
            self._touch("node_dom")

    def _register_topo_key(self, key: str) -> int:
        """New concrete topology key: backfill domain ids for all nodes."""
        existing = self._keyv.get(key)
        if existing is not None:
            return existing
        kid = self._keyv.id(key)
        self._ensure_key_rows()
        for slot, lbls in self._node_labels_d.items():
            val = lbls.get(key)
            if val:
                self.node_dom[kid, slot] = self._dom_id(kid, val)
        self._touch("node_dom")
        return kid

    def _domain_mask(self, slot: int, kids: List[int]) -> np.ndarray:
        """0/1 over node slots sharing a topology domain with `slot` under
        any of the keys (the tensorize.py domain_mask contract)."""
        m = np.zeros(self.n_cap, np.int32)
        for kid in kids:
            row = self.node_dom[kid]
            d = row[slot]
            if d >= 0:
                np.maximum(m, (row == d).astype(np.int32), out=m)
        return m

    # --- node events (listener interface) ------------------------------------

    def node_added(self, node: api.Node):
        try:
            self._node_added(node)
        except Exception as e:
            self.broken = f"node_added({node.metadata.name}): {e!r}"
            raise

    def _node_added(self, node: api.Node):
        with self._lock:
            self.node_events += 1
            self._live_nodes.add(node.metadata.name)
            slot = self._ensure_slot(node.metadata.name)
            self._dead_slots.discard(slot)   # back from the dead (re-add)
            self._fill_node_statics(slot, node)

    def node_updated(self, node: api.Node):
        try:
            self._node_updated(node)
        except Exception as e:
            self.broken = f"node_updated({node.metadata.name}): {e!r}"
            raise

    def _node_updated(self, node: api.Node):
        with self._lock:
            self.node_events += 1
            slot = self._node_index.get(node.metadata.name)
            if slot is None:
                return self._node_added(node)
            old_labels = self._node_labels_d.get(slot, {})
            self._fill_node_statics(slot, node)
            if old_labels != (_labels_of(node)):
                # domain topology changed under the hit tables: re-derive
                # every placed contribution (rare — heartbeats only touch
                # status, which takes the cheap path above)
                self._reinit_interpod()

    def node_removed(self, node: api.Node):
        try:
            self._node_removed(node)
        except Exception as e:
            self.broken = f"node_removed({node.metadata.name}): {e!r}"
            raise

    def _node_removed(self, node: api.Node):
        with self._lock:
            self.node_events += 1
            self._live_nodes.discard(node.metadata.name)
            slot = self._node_index.get(node.metadata.name)
            if slot is None:
                return
            self.node_valid[slot] = False
            self._node_labels_d[slot] = {}
            self._node_images_d.pop(slot, None)
            self.node_labels[slot] = 0
            self.taints_nosched[slot] = 0
            self.taints_prefer[slot] = 0
            self.node_dom[:, slot] = -1
            self.zone_id[slot] = -1
            self.expr_node[:, slot] = 0
            self.pref_term_node[:, slot] = 0
            self._touch("node_valid", "node_labels", "taints_nosched",
                        "taints_prefer", "node_dom", "zone_id", "expr_node",
                        "pref_term_node")
            if not self._slot_pods.get(slot):
                del self._node_index[node.metadata.name]
                self._node_names[slot] = ""
                self._free.append(slot)
            else:
                # pods still draining: reclaim when the last one leaves
                self._dead_slots.add(slot)
            self._reinit_interpod()

    def _fill_node_statics(self, slot: int, node: api.Node):
        """Write node-derived rows, touching only what actually changed —
        routine status heartbeats must not defeat the device cache."""
        touched = []
        a = api.node_allocatable(node)
        alloc_row = np.array(
            [a[api.RESOURCE_CPU], a[api.RESOURCE_MEMORY] / MB,
             a[api.RESOURCE_GPU], a[api.RESOURCE_PODS]], np.float32)
        if not np.array_equal(self.alloc[slot], alloc_row):
            self.alloc[slot] = alloc_row
            touched.append("alloc")

        lbls = _labels_of(node)
        if self._node_labels_d.get(slot) != lbls:
            self._node_labels_d[slot] = lbls
            for kv in lbls.items():
                self._labelv.id(kv)
            self._grow_cols("node_labels", self._labelv)
            row = np.zeros(self.node_labels.shape[1], np.int8)
            for kv in lbls.items():
                row[self._labelv.get(kv)] = 1
            self.node_labels[slot] = row
            touched.append("node_labels")

            zk = _zone_key(node)
            zid = self._zonev.id(zk) if zk else -1
            if self.zone_id[slot] != zid:
                self.zone_id[slot] = zid
                touched.append("zone_id")

            # topology domains for every registered key
            for key, kid in list(self._keyv.items()):
                val = lbls.get(key)
                self.node_dom[kid, slot] = (self._dom_id(kid, val)
                                            if val else -1)
            touched.append("node_dom")

            # node-affinity expression columns + pref term truth
            for eid, req in enumerate(self._expr_reqs):
                self.expr_node[eid, slot] = 1 if req.matches(lbls) else 0
            for pid, (tid, _w) in enumerate(self._pref_entries):
                eids = self._term_exprs[tid]
                self.pref_term_node[pid, slot] = (
                    1 if all(self.expr_node[e, slot] for e in eids) else 0)
            touched += ["expr_node", "pref_term_node"]

        for t in ((node.spec.taints or []) if node.spec else []):
            self._taintv.id((t.key, t.value, t.effect))
        self._grow_cols("taints_nosched", self._taintv,
                        extra=("taints_prefer",))
        tns = np.zeros(self.taints_nosched.shape[1], np.int8)
        tpf = np.zeros_like(tns)
        for t in ((node.spec.taints or []) if node.spec else []):
            tid = self._taintv.get((t.key, t.value, t.effect))
            if t.effect == api.TAINT_NO_SCHEDULE:
                tns[tid] = 1
            elif t.effect == api.TAINT_PREFER_NO_SCHEDULE:
                tpf[tid] = 1
        if not np.array_equal(self.taints_nosched[slot], tns):
            self.taints_nosched[slot] = tns
            touched.append("taints_nosched")
        if not np.array_equal(self.taints_prefer[slot], tpf):
            self.taints_prefer[slot] = tpf
            touched.append("taints_prefer")

        mp = any(
            c.type == api.NODE_MEMORY_PRESSURE and c.status == api.CONDITION_TRUE
            for c in ((node.status.conditions or []) if node.status else []))
        if bool(self.mem_pressure[slot]) != mp:
            self.mem_pressure[slot] = mp
            touched.append("mem_pressure")
        valid = node_is_ready(node)
        if bool(self.node_valid[slot]) != valid:
            self.node_valid[slot] = valid
            touched.append("node_valid")

        # images present on the node (ImageLocality)
        imgs = {}
        for img in ((node.status.images or []) if node.status else []):
            for iname in (img.names or []):
                imgs[iname] = img.size_bytes / MB
        if self._node_images_d.get(slot) != imgs:
            self._node_images_d[slot] = imgs
            self._grow_cols("image_node_sizes", self._imagev)
            irow = np.zeros(self.image_node_sizes.shape[1], np.float32)
            for iname, mib in imgs.items():
                iid = self._imagev.get(iname)
                if iid is not None:
                    irow[iid] = mib
            self.image_node_sizes[slot] = irow
            touched.append("image_node_sizes")

        if touched:
            self._touch(*touched)

    # --- pod events (listener interface) --------------------------------------

    def _ensure_slot(self, node_name: str) -> int:
        """Slot for a node we may not have statics for yet (pod observed
        before its node, cache.go's NodeInfo(None) case)."""
        slot = self._node_index.get(node_name)
        if slot is None:
            if self._free:
                slot = self._free.pop()
            else:
                if self._hi >= self.n_cap:
                    self._grow_nodes()
                slot = self._hi
                self._hi += 1
            self._node_index[node_name] = slot
            self._node_names[slot] = node_name
            self._slot_pods.setdefault(slot, 0)
        if node_name not in self._live_nodes:
            # no live node object behind this slot (pod-before-node, or a
            # MODIFIED while draining off a removed node): keep it marked
            # dead so it frees when the last pod leaves
            self._dead_slots.add(slot)
        return slot

    def pod_added(self, pod: api.Pod):
        try:
            with self._lock:
                self.pod_events += 1
                self._apply_pod(pod, +1)
        except Exception as e:
            self.broken = f"pod_added({_pod_key(pod)}): {e!r}"
            raise

    def pod_removed(self, pod: api.Pod):
        try:
            with self._lock:
                self.pod_events += 1
                self._apply_pod(pod, -1)
        except Exception as e:
            self.broken = f"pod_removed({_pod_key(pod)}): {e!r}"
            raise

    def _apply_pod(self, pod: api.Pod, sign: int):
        node_name = pod.spec.node_name if pod.spec else ""
        if not node_name:
            return
        slot = self._ensure_slot(node_name)
        key = _pod_key(pod)
        if sign > 0 and key in self._placed:
            self._apply_pod(self._placed[key][0], -1)  # update = remove+add

        # the shape memo collapses per-event parsing to one hit per template
        # (node_name is excluded from the signature for exactly this)
        shape = self._shape_of(pod)
        self.used0[slot] += sign * shape["req4"].astype(np.float64)
        self.used0_nonzero[slot] += sign * shape["nz2"].astype(np.float64)
        self._touch("used0", "used0_nonzero")

        if shape["port_cols"]:
            for c in shape["port_cols"]:
                self._ports_cnt[slot, c] += sign
                self.node_ports0[slot, c] = 1 if self._ports_cnt[slot, c] > 0 else 0
            self._touch("node_ports0")

        if self._preempt:
            self._apply_victim(pod, slot, sign, shape, key)
        self._apply_volumes(pod, slot, sign, shape, key)
        self._apply_groups(pod, slot, sign)
        self._apply_interpod(pod, slot, sign)

        sig = _labels_sig(pod)
        if sign > 0:
            self._placed[key] = (pod, slot)
            self._by_sig.setdefault(sig, {})[key] = slot
            self._slot_pods[slot] = self._slot_pods.get(slot, 0) + 1
            if pod.metadata and pod.metadata.deletion_timestamp:
                self._terminating.add(key)
        else:
            self._placed.pop(key, None)
            self._terminating.discard(key)
            grp = self._by_sig.get(sig)
            if grp is not None:
                grp.pop(key, None)
                if not grp:
                    del self._by_sig[sig]
            self._slot_pods[slot] = max(self._slot_pods.get(slot, 0) - 1, 0)
            if not self._slot_pods[slot] and slot in self._dead_slots:
                # last pod drained off a removed node: reclaim the slot so
                # node churn doesn't grow the slot space without bound
                self._dead_slots.discard(slot)
                self._node_index.pop(node_name, None)
                self._node_names[slot] = ""
                self._free.append(slot)

    # --- volumes (NoDiskConflict / MaxPDVolumeCount occupancy) ---------------

    def _disk_cols(self, pod: api.Pod):
        out = []
        for v in (pod.spec.volumes or []) if pod.spec else []:
            if v.gce_persistent_disk:
                out.append((("gce", v.gce_persistent_disk.pd_name),
                            not v.gce_persistent_disk.read_only))
            if v.aws_elastic_block_store:
                out.append((("ebs", v.aws_elastic_block_store.volume_id), True))
            if v.rbd:
                for mon in (v.rbd.monitors or []):
                    out.append((("rbd", v.rbd.pool, v.rbd.image, mon), True))
        return out

    def _volume_checkers(self):
        ck = getattr(self, "_checkers", None)
        if ck is None:
            from kubernetes_tpu.scheduler.predicates import (
                MaxPDVolumeCountChecker,
            )
            args = self.args
            pvc = getattr(args, "pvc_lookup", None) if args else None
            pv = getattr(args, "pv_lookup", None) if args else None
            ck = self._checkers = (MaxPDVolumeCountChecker("ebs", 0, pvc, pv),
                                   MaxPDVolumeCountChecker("gce-pd", 0, pvc, pv))
        return ck

    def _apply_volumes(self, pod: api.Pod, slot: int, sign: int, shape: dict,
                       key: str):
        if not (shape["disk_pairs"] or shape["direct_ebs"]
                or shape["direct_gce"] or shape["has_pvc"]):
            return
        for c, rw in shape["disk_pairs"]:
            self._disk_any_cnt[slot, c] += sign
            self.node_disk_any0[slot, c] = 1 if self._disk_any_cnt[slot, c] > 0 else 0
            if rw:
                self._disk_rw_cnt[slot, c] += sign
                self.node_disk_rw0[slot, c] = 1 if self._disk_rw_cnt[slot, c] > 0 else 0
        ecols = list(shape["direct_ebs"])
        gcols = list(shape["direct_gce"])
        if shape["has_pvc"]:
            if sign < 0 and key in self._pvc_cols:
                pe, pg = self._pvc_cols.pop(key)
            else:
                ns = pod.metadata.namespace if pod.metadata else ""
                _z, _b, pe, pg = self._pvc_info(ns, shape["claims"], {})
                if sign > 0:
                    self._pvc_cols[key] = (pe, pg)
            ecols += pe
            gcols += pg
        for c in ecols:
            self._ebs_cnt[slot, c] += sign
            self.node_ebs0[slot, c] = 1 if self._ebs_cnt[slot, c] > 0 else 0
        for c in gcols:
            self._gce_cnt[slot, c] += sign
            self.node_gce0[slot, c] = 1 if self._gce_cnt[slot, c] > 0 else 0
        self._touch("node_disk_any0", "node_disk_rw0", "node_ebs0", "node_gce0")

    # --- preempt victim prefix tables (delta path) ----------------------------

    def _apply_victim(self, pod: api.Pod, slot: int, sign: int, shape: dict,
                      key: str):
        """Keep vict_prio/vict_cum exact under pod add/remove: a sorted
        per-slot candidate list plus an O(pods-on-node) column rewrite —
        never a full re-sort of the placed set."""
        import bisect

        from kubernetes_tpu.scheduler.objectives.config import pod_priority
        if sign > 0:
            if pod.metadata and pod.metadata.deletion_timestamp:
                return  # a pod on its way out is not a victim candidate
            pr = pod_priority(pod)
            vec = np.concatenate([shape["req4"], shape["nz2"]]).astype(
                np.float32)
            lst = self._vict_lists.setdefault(slot, [])
            # keys are unique per slot, so the (prio, key) prefix always
            # decides the order before the ndarray is ever compared
            bisect.insort(lst, (pr, key, vec))
            self._vict_entry[key] = (slot, pr, vec)
        else:
            ent = self._vict_entry.pop(key, None)
            if ent is None:
                return  # was terminating at add time: never a candidate
            slot = ent[0]
            lst = self._vict_lists.get(slot, [])
            for j, e in enumerate(lst):
                if e[1] == key:
                    del lst[j]
                    break
        while len(self._vict_lists.get(slot, ())) > self._vict_kv:
            self._grow_victims()
        self._rebuild_vict_col(slot)
        self._touch("vict_prio", "vict_cum")

    def _grow_victims(self):
        from kubernetes_tpu.scheduler.objectives.config import INF_PRIORITY
        kv2 = self._vict_kv * 2
        vp = np.full((kv2, self.n_cap), INF_PRIORITY, np.float32)
        vp[: self._vict_kv] = self.vict_prio
        vc = np.zeros((6, kv2 + 1, self.n_cap), np.float32)
        vc[:, : self._vict_kv + 1] = self.vict_cum
        # beyond the last victim the prefix stays flat (clipped gathers
        # then read "no further relief")
        vc[:, self._vict_kv + 1:] = self.vict_cum[:, -1:, :]
        self._vict_kv = kv2
        self.vict_prio, self.vict_cum = vp, vc
        self._touch("vict_prio", "vict_cum")

    def _rebuild_vict_col(self, slot: int):
        from kubernetes_tpu.scheduler.objectives.config import INF_PRIORITY
        lst = self._vict_lists.get(slot, ())
        kv = self._vict_kv
        self.vict_prio[:, slot] = INF_PRIORITY
        acc = np.zeros(6, np.float32)
        col = np.zeros((6, kv + 1), np.float32)
        for j, (pr, _key, vec) in enumerate(lst):
            self.vict_prio[j, slot] = pr
            acc = acc + vec
            col[:, j + 1] = acc
        col[:, len(lst) + 1:] = acc[:, None]
        self.vict_cum[:, :, slot] = col

    # --- spread groups --------------------------------------------------------

    def _groups_of(self, ns: str, lbls: dict) -> List[int]:
        out = []
        for g, (gns, sels) in enumerate(self._group_rows):
            if gns == ns and any(s.matches(lbls) for s in sels):
                out.append(g)
        return out

    def _apply_groups(self, pod: api.Pod, slot: int, sign: int):
        if pod.metadata and pod.metadata.deletion_timestamp:
            return  # terminating pods don't count toward spread
        if not self._group_rows:
            return
        ns = pod.metadata.namespace if pod.metadata else ""
        for g in self._groups_of(ns, _labels_of(pod)):
            self.group_counts0[slot, g] += sign
        self._touch("group_counts0")

    def _register_group(self, ns: str, sels: list, sig) -> int:
        """New spread group: column + counts initialized from placed pods."""
        gid = self._groupv.id(sig)
        self._group_rows.append((ns, sels))
        need = _pad(len(self._group_rows), SUB)
        if self.group_counts0.shape[1] < need:
            self.group_counts0 = _grow(
                self.group_counts0, (self.n_cap, need))
        for (pns, plbls), members in self._by_sig.items():
            if pns != ns or not any(s.matches(dict(plbls)) for s in sels):
                continue
            live = [s for k, s in members.items() if k not in self._terminating]
            if live:
                np.add.at(self.group_counts0[:, gid],
                          np.asarray(live, np.int64), 1)
        self._touch("group_counts0")
        return gid

    # --- inter-pod affinity term machinery ------------------------------------

    def _pod_terms(self, pod: api.Pod, kind: str):
        aff = pod.spec.affinity if pod.spec else None
        if aff is None:
            return []
        if kind == "aff":
            src = aff.pod_affinity
            return (src.required_during_scheduling_ignored_during_execution
                    or []) if src else []
        if kind == "anti":
            src = aff.pod_anti_affinity
            return (src.required_during_scheduling_ignored_during_execution
                    or []) if src else []
        out = []
        if aff.pod_affinity:
            for wt in (aff.pod_affinity.
                       preferred_during_scheduling_ignored_during_execution or []):
                if wt.weight and wt.pod_affinity_term:
                    out.append((wt.pod_affinity_term, float(wt.weight)))
        if aff.pod_anti_affinity:
            for wt in (aff.pod_anti_affinity.
                       preferred_during_scheduling_ignored_during_execution or []):
                if wt.weight and wt.pod_affinity_term:
                    out.append((wt.pod_affinity_term, -float(wt.weight)))
        return out

    def _term_parts(self, owner: api.Pod, term, weight=None):
        from kubernetes_tpu.scheduler.predicates import _term_namespaces
        names = _term_namespaces(owner, term)
        sel = labelsel.selector_from_label_selector(term.label_selector)
        if term.topology_key:
            kids = [self._register_topo_key(term.topology_key)]
        else:
            kids = [self._keyv.get(k) for k in self.failure_domains]
        key = (frozenset(names) if names is not None else "*",
               str(sel), term.topology_key or "", weight)
        return key, names, sel, kids

    def _add_term(self, table: _TermTable, owner: api.Pod, term,
                  weight=None) -> int:
        """Register a pending-owned term; a NEW row's hit counts are
        initialized from all placed pods (grouped by labels signature, so
        the scan is per distinct shape, not per pod)."""
        key, names, sel, kids = self._term_parts(owner, term, weight)
        tid, fresh = table.add(key, names, sel, kids, weight)
        if not fresh:
            return tid
        for (pns, plbls), members in self._by_sig.items():
            if names is not None and pns not in names:
                continue
            if not sel.matches(dict(plbls)):
                continue
            table.totals[tid] += len(members)
            if len(kids) == 1 and kids[0] is not None:
                # single topology key: exact via bincount + domain gather
                row = self.node_dom[kids[0]]
                idx = np.fromiter(members.values(), np.int64, len(members))
                doms = row[idx]
                doms = doms[doms >= 0]
                if doms.size:
                    n_dom = int(row.max()) + 1
                    per_dom = np.bincount(doms, minlength=n_dom)
                    valid = row >= 0
                    add = np.zeros(self.n_cap, table.hits.dtype)
                    add[valid] = per_dom[row[valid]]
                    table.hits[tid] += add
            else:
                for s in members.values():
                    table.hits[tid] += self._domain_mask(s, [k for k in kids
                                                             if k is not None])
        return tid

    def _apply_interpod(self, pod: api.Pod, slot: int, sign: int):
        ns = pod.metadata.namespace if pod.metadata else ""
        lbls = _labels_of(pod)

        # 1) this placed pod matches pending-owned term rows -> hit counts
        # (the match set is the same one build() needs, so reuse its memo
        # instead of a per-event O(terms) selector rescan)
        lsig = tuple(sorted(lbls.items()))
        touched = []
        for name, memo_name, table in (("req_hit0", "req", self.req_t),
                                       ("anti_hit0", "anti", self.anti_t),
                                       ("pref_hit0", "pref", self.pref_t)):
            for tid in self._match_ids(memo_name, table, ns, lsig):
                kids = [k for k in table.rows[tid][2] if k is not None]
                table.hits[tid] += sign * self._domain_mask(slot, kids)
                table.totals[tid] += sign
                touched.append(name)

        # 2) this placed pod's own terms -> sym (hard anti) and te (reverse
        # preferred + reverse-hard) tables
        hw = float(self.args.hard_pod_affinity_weight
                   if self.args is not None else 1)
        for term in self._pod_terms(pod, "anti"):
            key, names, sel, kids = self._term_parts(pod, term)
            tid, _ = self.sym_t.add(key, names, sel, kids)
            kids = [k for k in kids if k is not None]
            self.sym_t.hits[tid] += sign * self._domain_mask(slot, kids)
            touched.append("sym_dom0")
        if hw > 0:
            for term in self._pod_terms(pod, "aff"):
                key, names, sel, kids = self._term_parts(pod, term, ("hard",))
                tid, _ = self.te_t.add(key, names, sel, kids, ("hard",))
                kids = [k for k in kids if k is not None]
                self.te_t.hits[tid] += sign * hw * self._domain_mask(slot, kids)
                touched.append("te_dom0")
        for term, w in self._pod_terms(pod, "pref"):
            key, names, sel, kids = self._term_parts(pod, term, w)
            tid, _ = self.te_t.add(key, names, sel, kids, w)
            kids = [k for k in kids if k is not None]
            self.te_t.hits[tid] += sign * w * self._domain_mask(slot, kids)
            touched.append("te_dom0")
        if touched:
            self._touch(*set(touched))

    def _reinit_interpod(self):
        """Re-derive every placed contribution to the hit tables (node
        topology changed under them)."""
        for t in (self.req_t, self.anti_t, self.pref_t, self.sym_t, self.te_t):
            t.hits[:] = 0
            t.totals[:] = 0
        for pod, slot in self._placed.values():
            self._apply_interpod(pod, slot, +1)
        self._touch("req_hit0", "anti_hit0", "pref_hit0", "sym_dom0",
                    "te_dom0")

    # --- node-affinity registration ------------------------------------------

    def _expr_id(self, e: api.NodeSelectorRequirement) -> int:
        key = (e.key, e.operator, tuple(e.values or ()))
        i = self._exprv.get(key)
        if i is not None:
            return i
        i = self._exprv.id(key)
        req = labelsel.Requirement(e.key, e.operator, tuple(e.values or ()))
        self._expr_reqs.append(req)
        need = _pad(len(self._expr_reqs), SUB)
        if self.expr_node.shape[0] < need:
            self.expr_node = _grow(self.expr_node, (need, self.n_cap))
        for slot, lbls in self._node_labels_d.items():
            if req.matches(lbls):
                self.expr_node[i, slot] = 1
        self._touch("expr_node")
        return i

    def _term_id(self, t: api.NodeSelectorTerm) -> int:
        eids = tuple(sorted(self._expr_id(e)
                            for e in (t.match_expressions or [])))
        i = self._termv.get(eids)
        if i is None:
            i = self._termv.id(eids)
            self._term_exprs.append(list(eids))
        return i

    def _pref_entry_id(self, tid: int, w: float) -> int:
        key = (tid, w)
        i = self._prefv.get(key)
        if i is not None:
            return i
        i = self._prefv.id(key)
        self._pref_entries.append((tid, w))
        need = _pad(len(self._pref_entries), SUB)
        if self.pref_term_node.shape[0] < need:
            self.pref_term_node = _grow(self.pref_term_node,
                                        (need, self.n_cap))
            self.pref_weight = _grow(self.pref_weight, (need,))
        eids = self._term_exprs[tid]
        for slot in self._node_labels_d:
            self.pref_term_node[i, slot] = (
                1 if all(self.expr_node[e, slot] for e in eids) else 0)
        self.pref_weight[i] = w
        self._touch("pref_term_node", "pref_weight")
        return i

    def _image_id(self, name: str) -> int:
        iid = self._imagev.get(name)
        if iid is not None:
            return iid
        iid = self._imagev.id(name)
        self._grow_cols("image_node_sizes", self._imagev)
        for slot, imgs in self._node_images_d.items():
            mib = imgs.get(name)
            if mib:
                self.image_node_sizes[slot, iid] = mib
        self._touch("image_node_sizes")
        return iid

    # --- pod shapes (cross-batch memo of pure spec derivations) ---------------

    @staticmethod
    def _selector_sig(ls: Optional[api.LabelSelector]):
        if ls is None:
            return None
        return (tuple(sorted((ls.match_labels or {}).items())),
                tuple((r.key, r.operator, tuple(r.values or ()))
                      for r in (ls.match_expressions or [])))

    def _aff_sig(self, aff: Optional[api.Affinity]):
        if aff is None:
            return None

        def pterm(t):
            return (tuple(t.namespaces or ()), self._selector_sig(t.label_selector),
                    t.topology_key or "")

        def nterm(t):
            return tuple((e.key, e.operator, tuple(e.values or ()))
                         for e in (t.match_expressions or []))

        na = pa = an = None
        if aff.node_affinity:
            req = aff.node_affinity.required_during_scheduling_ignored_during_execution
            na = (tuple(nterm(t) for t in (req.node_selector_terms or []))
                  if req is not None else None,
                  tuple((p.weight, nterm(p.preference))
                        for p in (aff.node_affinity.
                                  preferred_during_scheduling_ignored_during_execution or [])
                        if p.preference is not None))
        if aff.pod_affinity:
            pa = (tuple(pterm(t) for t in (
                      aff.pod_affinity.required_during_scheduling_ignored_during_execution or [])),
                  tuple((w.weight, pterm(w.pod_affinity_term))
                        for w in (aff.pod_affinity.
                                  preferred_during_scheduling_ignored_during_execution or [])
                        if w.pod_affinity_term))
        if aff.pod_anti_affinity:
            an = (tuple(pterm(t) for t in (
                      aff.pod_anti_affinity.required_during_scheduling_ignored_during_execution or [])),
                  tuple((w.weight, pterm(w.pod_affinity_term))
                        for w in (aff.pod_anti_affinity.
                                  preferred_during_scheduling_ignored_during_execution or [])
                        if w.pod_affinity_term))
        return (na, pa, an)

    def _spec_sig(self, pod: api.Pod):
        s = pod.spec
        if s is None:
            return ()
        conts = tuple(
            (c.image or "",
             tuple(sorted((c.resources.requests or {}).items()))
             if c.resources and c.resources.requests else (),
             bool(c.resources and (c.resources.requests or c.resources.limits)),
             tuple((p.protocol or "TCP", p.host_port)
                   for p in (c.ports or []) if p.host_port))
            for c in (s.containers or []))
        tols = tuple((t.key, t.operator, t.value, t.effect)
                     for t in (s.tolerations or []))
        vols = tuple(
            (v.name,
             (v.gce_persistent_disk.pd_name, v.gce_persistent_disk.read_only)
             if v.gce_persistent_disk else None,
             v.aws_elastic_block_store.volume_id
             if v.aws_elastic_block_store else None,
             (v.rbd.pool, v.rbd.image, tuple(v.rbd.monitors or ()))
             if v.rbd else None,
             v.persistent_volume_claim.claim_name
             if v.persistent_volume_claim else None)
            for v in (s.volumes or []))
        # node_name is deliberately NOT in the signature: placed pods from
        # one template then share the shape entry (host_req is derived per
        # pod in build())
        return (conts, tols, tuple(sorted((s.node_selector or {}).items())),
                vols, self._aff_sig(s.affinity),
                pod.metadata.namespace if pod.metadata else "")

    def _shape_of(self, pod: api.Pod) -> dict:
        sig = self._spec_sig(pod)
        shape = self._shape_memo.get(sig)
        if shape is None:
            if len(self._shape_memo) > 100_000:
                self._shape_memo.clear()
            shape = self._shape_memo[sig] = self._build_shape(pod)
        return shape

    def _build_shape(self, pod: api.Pod) -> dict:
        """Everything derivable from the spec alone, vocab ids resolved."""
        s = pod.spec
        rq, nz = _pod_req_vec(pod)
        sel_cols = [self._labelv.id(kv)
                    for kv in ((s.node_selector or {}) if s else {}).items()]
        self._grow_cols("node_labels", self._labelv)
        port_cols = []
        for pp in _pod_ports_set(pod):
            self._portv.id(pp)
            self._grow_cols("node_ports0", self._portv, extra=("_ports_cnt",))
            port_cols.append(self._portv.get(pp))
        image_cols = [self._image_id(c.image)
                      for c in ((s.containers or []) if s else []) if c.image]

        # node affinity
        aff = s.affinity if s else None
        na = aff.node_affinity if aff else None
        req = na.required_during_scheduling_ignored_during_execution if na else None
        term_ids = ([self._term_id(t) for t in (req.node_selector_terms or [])]
                    if req is not None else None)
        pref_pairs: Dict[int, int] = {}
        for p in ((na.preferred_during_scheduling_ignored_during_execution or [])
                  if na else []):
            if p.weight and p.preference is not None:
                pid = self._pref_entry_id(self._term_id(p.preference),
                                          float(p.weight))
                pref_pairs[pid] = pref_pairs.get(pid, 0) + 1

        # inter-pod terms owned by this (pending) shape
        req_tids = [self._add_term(self.req_t, pod, t)
                    for t in self._pod_terms(pod, "aff")]
        anti_tids = [self._add_term(self.anti_t, pod, t)
                     for t in self._pod_terms(pod, "anti")]
        pref_tids = [(self._add_term(self.pref_t, pod, t, w), w)
                     for t, w in self._pod_terms(pod, "pref")]
        if req_tids or anti_tids or pref_tids:
            self._touch("req_hit0", "anti_hit0", "pref_hit0")

        # direct (non-PVC) volume columns; PVC-backed resolve per batch
        disk_pairs = []
        for ck, rw in self._disk_cols(pod):
            self._diskv.id(ck)
            self._grow_cols("node_disk_any0", self._diskv,
                            extra=("node_disk_rw0", "_disk_any_cnt",
                                   "_disk_rw_cnt"))
            disk_pairs.append((self._diskv.get(ck), rw))
        ebs_ck, gce_ck = self._volume_checkers()
        direct_ebs, direct_gce, has_pvc = [], [], False
        for v in ((s.volumes or []) if s else []):
            if v.persistent_volume_claim:
                has_pvc = True
                continue
            vid = ebs_ck._volume_id(v, "")
            if vid is not None:
                self._ebsv.id(vid)
                self._grow_cols("node_ebs0", self._ebsv, extra=("_ebs_cnt",))
                direct_ebs.append(self._ebsv.get(vid))
            vid = gce_ck._volume_id(v, "")
            if vid is not None:
                self._gcev.id(vid)
                self._grow_cols("node_gce0", self._gcev, extra=("_gce_cnt",))
                direct_gce.append(self._gcev.get(vid))

        return {
            "req4": rq, "nz2": nz, "best_effort": _is_best_effort(pod),
            "sel_cols": sel_cols, "port_cols": port_cols,
            "image_cols": image_cols,
            "tols": list((s.tolerations or []) if s else []),
            "tol_ns": [], "tol_pref": [], "tol_upto": 0,
            "term_ids": term_ids, "pref_pairs": pref_pairs,
            "req_tids": req_tids, "anti_tids": anti_tids,
            "pref_tids": pref_tids,
            "disk_pairs": disk_pairs, "direct_ebs": direct_ebs,
            "direct_gce": direct_gce, "has_pvc": has_pvc,
            "claims": [v.persistent_volume_claim.claim_name
                       for v in ((s.volumes or []) if s else [])
                       if v.persistent_volume_claim],
        }

    def _tol_cols(self, shape: dict):
        """Lazily extend a shape's tolerated-taint columns as the taint
        vocabulary grows (kills the O(P×T) per-batch double loop)."""
        tv = len(self._taintv)
        if shape["tol_upto"] < tv and shape["tols"]:
            items = list(self._taintv.items())[shape["tol_upto"]:]
            for (tk, tval, teff), tid in items:
                t = api.Taint(key=tk, value=tval, effect=teff)
                for tol in shape["tols"]:
                    if tol.tolerates(t):
                        if teff == api.TAINT_NO_SCHEDULE:
                            shape["tol_ns"].append(tid)
                        elif teff == api.TAINT_PREFER_NO_SCHEDULE:
                            shape["tol_pref"].append(tid)
                        break
        shape["tol_upto"] = tv
        return shape["tol_ns"], shape["tol_pref"]

    def _match_ids(self, table_name: str, table: _TermTable, ns: str,
                   lbls_sig) -> List[int]:
        """Term rows matching a pending pod's (ns, labels), memoized with
        lazy extension as tables grow."""
        mkey = (table_name, ns, lbls_sig)
        m = self._match_memo.get(mkey)
        if m is None:
            if len(self._match_memo) > 300_000:
                self._match_memo.clear()
            m = self._match_memo[mkey] = {"ids": [], "upto": 0}
        if m["upto"] < len(table.rows):
            lbls = dict(lbls_sig)
            for tid in range(m["upto"], len(table.rows)):
                if table.matches(tid, ns, lbls):
                    m["ids"].append(tid)
            m["upto"] = len(table.rows)
        return m["ids"]

    # --- per-batch PVC resolution ---------------------------------------------

    def _pvc_info(self, ns: str, claims: List[str], memo: dict):
        """(zone label ids, broken) for a pod's claims — per-batch memo (the
        PV/PVC listers are live state, never cached across batches)."""
        key = (ns, tuple(claims))
        hit = memo.get(key)
        if hit is not None:
            return hit
        args = self.args
        if args is None or not getattr(args, "pvc_lookup", None) \
                or not getattr(args, "pv_lookup", None):
            memo[key] = ([], False, [], [])
            return memo[key]
        zone_cols, broken, ebs_cols, gce_cols = [], False, [], []
        ebs_ck, gce_ck = self._volume_checkers()
        for claim in claims:
            pvc = args.pvc_lookup(ns, claim)
            if pvc is None or not (pvc.spec and pvc.spec.volume_name):
                broken = True
                continue
            pv = args.pv_lookup(pvc.spec.volume_name)
            if pv is None:
                broken = True
                continue
            pv_labels = (pv.metadata.labels or {}) if pv.metadata else {}
            for lk in (api.LABEL_ZONE, api.LABEL_REGION):
                want = pv_labels.get(lk)
                if want:
                    zone_cols.append(self._labelv.id((lk, want)))
            v = api.Volume(name=claim,
                           persistent_volume_claim=api.
                           PersistentVolumeClaimVolumeSource(claim_name=claim))
            vid = ebs_ck._volume_id(v, ns)
            if vid is not None:
                self._ebsv.id(vid)
                self._grow_cols("node_ebs0", self._ebsv, extra=("_ebs_cnt",))
                ebs_cols.append(self._ebsv.get(vid))
            vid = gce_ck._volume_id(v, ns)
            if vid is not None:
                self._gcev.id(vid)
                self._grow_cols("node_gce0", self._gcev, extra=("_gce_cnt",))
                gce_cols.append(self._gcev.get(vid))
        memo[key] = (zone_cols, broken, ebs_cols, gce_cols)
        return memo[key]

    # --- spread-group derivation (per batch; listers are live) ----------------

    def _pod_selectors(self, pod: api.Pod):
        args = self.args
        if args is None:
            return []
        sels = []
        if args.service_lister:
            for svc in args.service_lister.get_pod_services(pod):
                sels.append(labelsel.selector_from_map(svc.spec.selector))
        if args.controller_lister:
            for rc in args.controller_lister.get_pod_controllers(pod):
                sels.append(labelsel.selector_from_map(rc.spec.selector))
        if args.replicaset_lister:
            for rs in args.replicaset_lister.get_pod_replica_sets(pod):
                sels.append(labelsel.selector_from_label_selector(rs.spec.selector))
        return sels

    # --- batch build ----------------------------------------------------------

    def build(self, pending: List[api.Pod]) -> ClusterTensors:
        import time as _t
        if self.broken:
            raise RuntimeError(f"incremental mirror broken: {self.broken}")
        t0 = _t.perf_counter()
        with self._lock:
            ct = self._build_locked(pending)
        self.builds += 1
        self.last_build_seconds = _t.perf_counter() - t0
        return ct

    def _build_locked(self, pending: List[api.Pod]) -> ClusterTensors:
        P = len(pending)
        Pp = _bucket(P)
        if self.pod_bucket and P <= self.pod_bucket:
            Pp = self.pod_bucket
        shapes = [self._shape_of(pod) for pod in pending]

        # pass 1: group registration per distinct (ns, labels) signature
        group_memo: Dict[tuple, Tuple[int, List[int]]] = {}
        pvc_memo: dict = {}
        for pod in pending:
            sig = _labels_sig(pod)
            if sig in group_memo:
                continue
            sels = self._pod_selectors(pod)
            gid = -1
            if sels:
                gsig = _selector_signature(sels, sig[0])
                gid = self._groupv.get(gsig)
                if gid is None:
                    gid = self._register_group(sig[0], sels, gsig)
            group_memo[sig] = (gid, [])
        # pass 2: membership across ALL registered groups
        member_memo: Dict[tuple, List[int]] = {}
        for sig in group_memo:
            lbls = dict(sig[1])
            member_memo[sig] = [g for g, (gns, sels)
                                in enumerate(self._group_rows)
                                if gns == sig[0]
                                and any(s.matches(lbls) for s in sels)]

        # pass 3: PVC resolution registers label/volume columns — run it
        # before the column widths below are frozen
        for pod, shape in zip(pending, shapes):
            if shape["has_pvc"]:
                self._pvc_info(pod.metadata.namespace if pod.metadata else "",
                               shape["claims"], pvc_memo)
        self._grow_cols("node_labels", self._labelv)

        N = self.n_cap
        G = self.group_counts0.shape[1]
        L = self.node_labels.shape[1]
        T = self.taints_nosched.shape[1]
        PT = self.node_ports0.shape[1]
        I = self.image_node_sizes.shape[1]
        TM = _pad(len(self._term_exprs), SUB)
        E = self.expr_node.shape[0]
        PT2 = self.pref_term_node.shape[0]
        TR = self.req_t.hits.shape[0]
        TA = self.anti_t.hits.shape[0]
        TP = self.pref_t.hits.shape[0]
        TS = self.sym_t.hits.shape[0]
        TE = self.te_t.hits.shape[0]
        D = self.node_disk_any0.shape[1]
        VE = self.node_ebs0.shape[1]
        VG = self.node_gce0.shape[1]

        req = np.zeros((Pp, 4), np.float32)
        nonzero_req = np.zeros((Pp, 2), np.float32)
        sel_required = np.zeros((Pp, L), np.int8)
        sel_count = np.zeros(Pp, np.float32)
        pod_ports = np.zeros((Pp, PT), np.int8)
        tol_ns = np.zeros((Pp, T), np.int8)
        tol_pref = np.zeros((Pp, T), np.int8)
        best_effort = np.zeros(Pp, bool)
        host_req = np.full(Pp, -1, np.int32)
        pod_valid = np.zeros(Pp, bool)
        pod_images = np.zeros((Pp, I), np.int8)
        pod_term = np.zeros((Pp, TM), np.int8)
        pod_has_aff = np.zeros(Pp, bool)
        pod_pref_term = np.zeros((Pp, PT2), np.float32)
        pod_group = np.full(Pp, -1, np.int32)
        pod_in_group = np.zeros((Pp, G), np.int8)
        req_own = np.zeros((Pp, TR), np.float32)
        anti_own = np.zeros((Pp, TA), np.float32)
        pref_own = np.zeros((Pp, TP), np.float32)
        req_match = np.zeros((TR, Pp), np.int8)
        anti_match = np.zeros((TA, Pp), np.int8)
        pref_match = np.zeros((TP, Pp), np.int8)
        sym_match = np.zeros((TS, Pp), np.int8)
        te_match = np.zeros((TE, Pp), np.int8)
        pod_disk_any = np.zeros((Pp, D), np.int8)
        pod_disk_rw = np.zeros((Pp, D), np.int8)
        pod_ebs = np.zeros((Pp, VE), np.int8)
        pod_gce = np.zeros((Pp, VG), np.int8)

        for p, (pod, shape) in enumerate(zip(pending, shapes)):
            pod_valid[p] = True
            req[p] = shape["req4"]
            nonzero_req[p] = shape["nz2"]
            best_effort[p] = shape["best_effort"]
            for c in shape["sel_cols"]:
                sel_required[p, c] = 1
            for c in shape["port_cols"]:
                pod_ports[p, c] = 1
            for c in shape["image_cols"]:
                pod_images[p, c] = 1
            tns, tpf = self._tol_cols(shape)
            for c in tns:
                tol_ns[p, c] = 1
            for c in tpf:
                tol_pref[p, c] = 1
            want = pod.spec.node_name if pod.spec else ""
            if want:
                host_req[p] = self._node_index.get(want, -2)
            if shape["term_ids"] is not None:
                pod_has_aff[p] = True
                for t in shape["term_ids"]:
                    pod_term[p, t] = 1
            for pid, cnt in shape["pref_pairs"].items():
                pod_pref_term[p, pid] = cnt
            for t in shape["req_tids"]:
                req_own[p, t] += 1.0
            for t in shape["anti_tids"]:
                anti_own[p, t] += 1.0
            for t, _w in shape["pref_tids"]:
                pref_own[p, t] += 1.0
            for c, rw in shape["disk_pairs"]:
                pod_disk_any[p, c] = 1
                if rw:
                    pod_disk_rw[p, c] = 1
            for c in shape["direct_ebs"]:
                pod_ebs[p, c] = 1
            for c in shape["direct_gce"]:
                pod_gce[p, c] = 1
            sel_count[p] = len(set(shape["sel_cols"]))
            if shape["has_pvc"]:
                ns = pod.metadata.namespace if pod.metadata else ""
                zcols, broken, ecols, gcols = self._pvc_info(
                    ns, shape["claims"], pvc_memo)
                extra = [c for c in zcols if not sel_required[p, c]]
                for c in extra:
                    sel_required[p, c] = 1
                sel_count[p] += len(set(extra))
                if broken:
                    sel_count[p] += 1.0
                for c in ecols:
                    pod_ebs[p, c] = 1
                for c in gcols:
                    pod_gce[p, c] = 1

            sig = _labels_sig(pod)
            pod_group[p] = group_memo[sig][0]
            for g in member_memo[sig]:
                pod_in_group[p, g] = 1
            ns, lsig = sig
            for t in self._match_ids("req", self.req_t, ns, lsig):
                req_match[t, p] = 1
            for t in self._match_ids("anti", self.anti_t, ns, lsig):
                anti_match[t, p] = 1
            for t in self._match_ids("pref", self.pref_t, ns, lsig):
                pref_match[t, p] = 1
            for t in self._match_ids("sym", self.sym_t, ns, lsig):
                sym_match[t, p] = 1
            for t in self._match_ids("te", self.te_t, ns, lsig):
                te_match[t, p] = 1

        # small derived tables (fresh each batch; cheap)
        term_expr = np.zeros((TM, E), np.float32)
        term_count = np.zeros(TM, np.float32)
        for i, eids in enumerate(self._term_exprs):
            for e in eids:
                term_expr[i, e] = 1.0
            term_count[i] = len(eids)

        def topo(table: _TermTable, rows_pad: int):
            K = self.node_dom.shape[0]
            t = np.zeros((rows_pad, K), np.float32)
            for i, (_n, _s, kids, _w) in enumerate(table.rows):
                for kid in kids:
                    if kid is not None:
                        t[i, kid] = 1.0
            return t

        pref_w = np.zeros(TP, np.float32)
        for i, (_n, _s, _k, w) in enumerate(self.pref_t.rows):
            pref_w[i] = w

        hw = float(self.args.hard_pod_affinity_weight
                   if self.args is not None else 1)
        from kubernetes_tpu.scheduler.predicates import (
            DEFAULT_MAX_EBS_VOLUMES, DEFAULT_MAX_GCE_PD_VOLUMES,
        )
        objective_kw = {}
        if self.objective is not None:
            import dataclasses

            from kubernetes_tpu.scheduler.objectives.config import (
                pod_priority,
            )
            from kubernetes_tpu.scheduler.objectives.tensors import (
                build_objective_tensors,
            )
            # preempt's victim prefix tables live in the DELTA path
            # (_apply_victim): maintained per pod event, device-resident
            # via the node-side cache — build_objective_tensors only runs
            # for the gang arrays and the per-batch pending priorities
            obj_for_build = (dataclasses.replace(self.objective,
                                                 preempt=False)
                             if self._preempt else self.objective)
            arrays, info = build_objective_tensors(
                obj_for_build, pending, Pp, N,
                lambda slot: self._node_labels_d.get(slot, {}), [])
            if self._preempt:
                prio = np.zeros(Pp, np.float32)
                for p, pod in enumerate(pending):
                    prio[p] = pod_priority(pod)
                arrays["pod_priority"] = prio
                arrays["vict_prio"] = self.vict_prio
                arrays["vict_cum"] = self.vict_cum
                info.victim_order = [
                    [key for _pr, key, _v in self._vict_lists.get(s, ())]
                    for s in range(N)]
            objective_kw = dict(arrays)
            objective_kw["objective_info"] = info
        return ClusterTensors(
            node_names=list(self._node_names),
            pod_keys=[_pod_key(p) for p in pending],
            alloc=self.alloc, used0=self.used0,
            used0_nonzero=self.used0_nonzero,
            node_labels=self.node_labels, node_ports0=self.node_ports0,
            taints_nosched=self.taints_nosched,
            taints_prefer=self.taints_prefer,
            mem_pressure=self.mem_pressure, node_valid=self.node_valid,
            zone_id=self.zone_id, n_zones=max(len(self._zonev), 1),
            req=req, nonzero_req=nonzero_req,
            sel_required=sel_required, sel_count=sel_count,
            pod_ports=pod_ports, tol_nosched=tol_ns, tol_prefer=tol_pref,
            best_effort=best_effort, host_req=host_req, pod_valid=pod_valid,
            expr_node=self.expr_node, term_expr=term_expr,
            term_expr_count=term_count, pod_term=pod_term,
            pod_has_affinity=pod_has_aff,
            pref_term_node=self.pref_term_node, pref_weight=self.pref_weight,
            pod_pref_term=pod_pref_term,
            pod_group=pod_group, pod_in_group=pod_in_group,
            group_counts0=self.group_counts0,
            n_groups=max(len(self._group_rows), 1),
            image_node_sizes=self.image_node_sizes, pod_images=pod_images,
            node_dom=self.node_dom,
            req_topo=topo(self.req_t, TR), req_own=req_own,
            req_match=req_match, req_hit0=self.req_t.hits,
            req_nomatch0=(self.req_t.totals == 0),
            anti_topo=topo(self.anti_t, TA), anti_own=anti_own,
            anti_match=anti_match, anti_hit0=self.anti_t.hits,
            pref_topo=topo(self.pref_t, TP), pref_own=pref_own,
            pref_match=pref_match, pref_w=pref_w,
            pref_hit0=self.pref_t.hits,
            sym_dom0=self.sym_t.hits, sym_match=sym_match,
            te_dom0=self.te_t.hits, te_match=te_match,
            hard_weight=np.asarray(hw, np.float32),
            pod_disk_any=pod_disk_any, pod_disk_rw=pod_disk_rw,
            node_disk_any0=self.node_disk_any0,
            node_disk_rw0=self.node_disk_rw0,
            pod_ebs=pod_ebs, node_ebs0=self.node_ebs0,
            pod_gce=pod_gce, node_gce0=self.node_gce0,
            max_ebs=np.asarray(DEFAULT_MAX_EBS_VOLUMES, np.float32),
            max_gce=np.asarray(DEFAULT_MAX_GCE_PD_VOLUMES, np.float32),
            n_real_nodes=self._hi, n_real_pods=P,
            **objective_kw,
        )

    # --- device residency -----------------------------------------------------

    # node-side fields whose device copies survive across batches (everything
    # else is pod-side / derived-fresh and re-uploads every batch)
    _NODE_SIDE = frozenset((
        "alloc", "used0", "used0_nonzero", "node_labels", "node_ports0",
        "taints_nosched", "taints_prefer", "mem_pressure", "node_valid",
        "zone_id", "image_node_sizes", "node_dom", "group_counts0",
        "expr_node", "pref_term_node", "pref_weight", "req_hit0", "anti_hit0",
        "pref_hit0", "sym_dom0", "te_dom0", "node_disk_any0", "node_disk_rw0",
        "node_ebs0", "node_gce0",
        # preempt victim prefix tables: delta-maintained, so their device
        # copies survive across batches exactly like the other node state
        "vict_prio", "vict_cum",
    ))

    def device_sync(self, ct: ClusterTensors, device=None):
        """jax-array view of the batch: node-side arrays re-upload only when
        their version bumped since the last sync (double-buffered on device —
        the previous batch's buffers stay alive until replaced). Staging
        takes the mirror lock itself (reentrant), the transfer is
        lock-free — see _stage_uploads."""
        with self._lock:
            plan = self._stage_uploads(ct)
        return self._upload_staged(plan, device=device)

    def _stage_uploads(self, ct: ClusterTensors) -> list:
        """Under the mirror lock: decide what needs upload and snapshot the
        dirty node-side arrays as PRIVATE host copies. The actual device
        transfer (_upload_staged) then runs with NO lock held — a device
        call that hangs must never be abandoned (watchdog) while holding
        the lock every cache listener needs, and the copies make the
        transfer immune to concurrent in-place listener mutation."""
        if not hasattr(self, "_dev_cache"):
            self._dev_cache: Dict[str, Tuple[int, object]] = {}
        plan = []
        for k, v in ct.arrays().items():
            if k in self._NODE_SIDE:
                ver = self._versions.get(k, 0)
                hit = self._dev_cache.get(k)
                if hit is not None and hit[0] == ver:
                    plan.append((k, None, None, hit[1]))
                    continue
                # private copy: node-side arrays ARE the live mirror and
                # listeners mutate them in place (astype already copies)
                copy = (v.astype(np.float32) if v.dtype == np.float64
                        else v.copy())
                plan.append((k, ver, copy, None))
            else:
                if v.dtype == np.float64:
                    v = v.astype(np.float32)
                # pod-side / derived-fresh: built per batch, never mutated
                # by listeners — safe to upload without a copy
                plan.append((k, None, v, None))
        return plan

    def _upload_staged(self, plan: list, device=None):
        """Device transfer of a staged plan; lock-free (see _stage_uploads).

        The transfer is materialized HERE (block_until_ready on the arrays
        actually moved), not lazily inside the solve: the upload stage's
        wall time, watchdog deadline, and host/device split
        (`scheduler_kernel_device_seconds{stage="upload"}`) all then
        describe the transfer itself — a hung H2D copy surfaces as an
        upload timeout, not a mysterious solve timeout."""
        import time as _time

        import jax
        import jax.numpy as jnp

        from kubernetes_tpu.observability import profiling

        t0 = _time.perf_counter()
        out = {}
        moved = []
        uploaded = 0
        for k, ver, host, cached in plan:
            if cached is not None:
                out[k] = cached
                continue
            arr = jnp.asarray(host)
            if device is not None:
                arr = jax.device_put(arr, device)
            if ver is not None:
                self._dev_cache[k] = (ver, arr)
            out[k] = arr
            moved.append(arr)
            uploaded += host.nbytes
        t_submit = _time.perf_counter()
        if moved:
            jax.block_until_ready(moved)
        profiling.record_dispatch("upload", t_submit - t0,
                                  _time.perf_counter() - t_submit)
        self.last_upload_bytes = uploaded
        return out

    # --- the full incremental decision path -----------------------------------

    def schedule(self, pending: List[api.Pod], weights=None,
                 device=None, stage=None, explain: bool = False):
        """build + device sync + kernel; returns node name (or None) per
        pending pod, FIFO order — drop-in for scheduler.batch.tpu_batch.
        With explain, returns (names, DecisionRecords) decoded from the
        kernel's per-predicate provenance (observability/explain.py).
        With an enabled objective (ctor arg), the return additionally grows
        an ObjectiveOutcome, exactly like kernel.schedule_batch.

        `stage(name, fn)` (ops/watchdog.run_stages hook) observes the
        pipeline as named stages: tensorize -> upload -> compile|solve.
        The mirror lock is held ONLY across host-side work (build + staging
        private copies of the dirty arrays): the device-touching stages
        (upload, solve) run lock-free, so a watchdog that abandons a hung
        device call never strands the lock the cache listeners need —
        which would deadlock the informer pipeline, a strictly worse wedge
        than the hang being converted."""
        from kubernetes_tpu.ops.kernel import (
            Weights, decode_dispatch, dispatch, features_of,
            record_wave_count, resolve_wave,
        )
        weights = weights or Weights()
        run = stage or (lambda _n, fn: fn())
        objective = self.objective
        wave = resolve_wave(None, n_pods=len(pending))
        perm = None
        if objective is not None and objective.gang:
            # gang members must be contiguous in scan order; solve in the
            # gang-grouped order and un-permute the results below
            from kubernetes_tpu.scheduler.objectives.config import gang_order
            pending, perm = gang_order(pending)

        def _tensorize():
            with self._lock:
                ct = self.build(pending)
                # feature flags must be derived under the same lock as the
                # staged copies: ct aliases the live mirror, and a listener
                # delta in between could make the static trace flags
                # disagree with the uploaded arrays
                return ct, self._stage_uploads(ct), features_of(ct)

        ct, plan, feats = run("tensorize", _tensorize)
        n_zones = ct.n_zones
        arrays = run("upload", lambda: self._upload_staged(plan,
                                                           device=device))
        out = dispatch(arrays, n_zones, weights, feats, stage=stage,
                       explain=explain, objective=objective, wave=wave)
        out = record_wave_count(out, wave)
        ret = decode_dispatch(ct, out, weights, feats, explain, objective)
        if perm is None:
            return ret
        from kubernetes_tpu.ops.kernel import unpermute_result
        return unpermute_result(ret, perm)
