"""The filter-and-score kernel: batched masks/scores + sequential commit.

Two stages, mirroring the decomposition in SURVEY §7:

Stage A (assignment-independent, MXU-batched):
  - predicate masks: node selector / NodeAffinity / taints / memory-pressure /
    host pinning — each one matmul + compare over the vocab-encoded tensors
    (predicates.go:416-1002 vectorized)
  - static inter-pod symmetry with *existing* pods' anti-affinity terms rides
    the per-step matvec against sym_dom0 (predicates.go:883-921)
  - score ingredients that don't depend on commits: preferred-affinity weight
    counts, intolerable-PreferNoSchedule counts, image-locality buckets

Stage B (lax.scan over pods in FIFO order):
  replicates the reference's one-pod-at-a-time semantics exactly — each step
  sees capacity/ports/spread/affinity/volume state that includes every prior
  in-batch commit (the on-device analogue of AssumePod, cache.go:101):

  - hard inter-pod affinity (predicates.go:769-844): per-term domain-hit rows
    req_hit[TR,N] carried and max-updated when a committed pod matches the
    term; the disregard rule (self-selecting term, no match anywhere) uses a
    carried req_nomatch[TR] flag.
  - hard anti-affinity + symmetry (predicates.go:858-921): anti_hit[TA,N]
    forbids term owners; sym_dyn[TA,N] forbids later pods matching an
    already-committed owner's term (in-batch symmetry); sym_dom0[TS,N] covers
    existing pods' terms statically.
  - soft InterPodAffinityPriority (interpod_affinity.go:86-216): forward
    weighted match counts via carried pref_hit[TP,N]; reverse direction from
    existing pods via te_dom0[TE,N] (weights pre-folded, incl. the
    hardPodAffinityWeight for hard terms) and from in-batch commits via
    te_dyn[TP,N] / hw_dyn[TR,N]; min-max normalized over the feasible set
    with the window clamped to include 0 (`var maxCount int` starts at 0).
  - volumes (predicates.go:64-269): NoDiskConflict via carried per-node
    exclusive-disk occupancy (both-read-only GCE shares legal);
    MaxPDVolumeCount via carried EBS/GCE attach-column occupancy vs
    max_ebs/max_gce (union counts, pass when the pod brings no volumes).

  Priorities normalize over the *feasible* node set per pod (the reference
  prioritizes only filtered nodes, generic_scheduler.go:94-107). Ties break
  round-robin over the canonical node order with a carried counter
  (selectHost, generic_scheduler.go:116-133).

Feature flags (Features) are computed host-side from the batch and are static
jit arguments: a batch with no inter-pod terms / volumes / host-ports traces
none of those carries, so the common case stays a lean
capacity+spread+affinity scan (no [N,D]-sized HBM traffic per step).

Integer-truncation points match the Go code: calculateScore's
((cap-req)*10)/cap, the (cpu+mem)/2 average, int(fScore) everywhere
(priorities.go:33-43 etc.) — implemented as floor on non-negative f32.

All shapes are static per batch (padded); the jit cache is keyed by padded
(P, N, vocab) sizes + Features, so repeated batches of similar shape reuse
the compile.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Dict, List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.ops.tensorize import ClusterTensors

# numpy scalar, not jnp: module import must stay device-free (backend init
# at import time would grab the chip even for CPU-only test runs)
NEG = np.float32(-1e9)
POS = np.float32(1e9)


@dataclass(frozen=True)
class Weights:
    """Priority weights (DefaultProvider: all 1, image/equal off —
    defaults.go:150-197)."""

    least_requested: int = 1
    balanced: int = 1
    spread: int = 1
    node_affinity: int = 1
    taint_toleration: int = 1
    interpod_affinity: int = 1
    image_locality: int = 0
    equal: int = 0


class Features(NamedTuple):
    """Which optional carries this batch needs (static jit key)."""

    req: bool = False        # pending pods own hard affinity terms
    anti: bool = False       # pending pods own hard anti-affinity terms
    sym: bool = False        # existing pods own anti terms (static symmetry)
    pref: bool = False       # pending pods own preferred terms
    te: bool = False         # existing pods' terms carry reverse score
    hw: bool = False         # reverse hard-affinity weight > 0 (needs req)
    disk: bool = False       # exclusive-disk conflict columns in play
    ebs: bool = False        # EBS attach-count columns in play
    gce: bool = False        # GCE-PD attach-count columns in play
    ports: bool = False      # host ports requested by pending pods


def features_of(ct: ClusterTensors) -> Features:
    """Host-side batch inspection -> static trace flags."""
    has_req = bool(ct.req_own.any())
    return Features(
        req=has_req,
        anti=bool(ct.anti_own.any()),
        sym=bool(ct.sym_dom0.any()),
        pref=bool(ct.pref_own.any()),
        te=bool(ct.te_dom0.any()),
        hw=has_req and float(ct.hard_weight) > 0,
        disk=bool(ct.pod_disk_any.any()),
        ebs=bool(ct.pod_ebs.any()),
        gce=bool(ct.pod_gce.any()),
        ports=bool(ct.pod_ports.any()),
    )


# --- stage A -----------------------------------------------------------------

def static_pass(t: dict) -> dict:
    """All [P, N] mask/score ingredients that don't depend on assignment."""
    node_labels = t["node_labels"]          # [N, L]
    N = t["alloc"].shape[0]

    sel_ok = (t["sel_required"] @ node_labels.T) >= t["sel_count"][:, None]

    term_node = (t["term_expr"] @ t["expr_node"]) >= t["term_expr_count"][:, None]
    aff_hits = t["pod_term"] @ term_node.astype(jnp.float32)
    aff_ok = (~t["pod_has_affinity"][:, None]) | (aff_hits >= 1.0)

    untol = (1.0 - t["tol_nosched"]) @ t["taints_nosched"].T
    taint_ok = untol == 0.0

    mem_ok = ~(t["best_effort"][:, None] & t["mem_pressure"][None, :])

    idx = jnp.arange(N, dtype=jnp.int32)
    host = t["host_req"][:, None]
    host_ok = (host == -1) | (host == idx[None, :])

    static_mask = (
        t["node_valid"][None, :] & sel_ok & aff_ok & taint_ok & mem_ok & host_ok)

    pref_count = (t["pod_pref_term"] * t["pref_weight"][None, :]) @ t["pref_term_node"]
    taint_pref_count = (1.0 - t["tol_prefer"]) @ t["taints_prefer"].T

    image_mib = t["pod_images"] @ t["image_node_sizes"].T
    min_mib, max_mib = 23.0, 1000.0
    image_score = jnp.where(
        image_mib < min_mib, 0.0,
        jnp.where(image_mib >= max_mib, 10.0,
                  jnp.floor(10.0 * (image_mib - min_mib) / (max_mib - min_mib)) + 1.0))

    return {"mask": static_mask, "pref_count": pref_count,
            "taint_pref_count": taint_pref_count, "image_score": image_score}


# --- stage B -----------------------------------------------------------------

def _masked_max(x, mask):
    return jnp.max(jnp.where(mask, x, NEG))


def _masked_min(x, mask):
    return jnp.min(jnp.where(mask, x, POS))


def greedy_commit(t: dict, s: dict, w: Weights, feats: Features):
    """lax.scan over pods; returns assignments [P] i32 (-1 = unschedulable)."""
    assert not feats.hw or feats.req, "hw carry requires the req term table"
    alloc = t["alloc"]                      # [N, 4]
    N = alloc.shape[0]
    zone_id = t["zone_id"]                  # [N]
    Z = int(t["n_zones"]) if isinstance(t["n_zones"], int) else t["n_zones"]
    idx_n = jnp.arange(N, dtype=jnp.int32)

    zero_req = jnp.all(t["req"][:, :3] == 0.0, axis=1)  # pods axis excluded

    # zone membership one-hot; zone counts are recomputed per step over the
    # *feasible* node set (the reference sums countsByZone over filtered
    # nodes only, selector_spreading.go:186-196)
    zone_onehot = ((zone_id[:, None] == jnp.arange(Z)[None, :])
                   & (zone_id >= 0)[:, None]).astype(jnp.float32)  # [N, Z]

    # static interpod operands captured by the step closure
    node_dom = t["node_dom"]                # [K, N] i32
    sym_dom0 = t["sym_dom0"]                # [TS, N]
    te_dom0 = t["te_dom0"]                  # [TE, N]
    pref_w = t["pref_w"]                    # [TP]
    hard_w = t["hard_weight"]               # [] f32

    use_dm = feats.req or feats.anti or feats.pref
    use_ip_score = feats.pref or feats.te or feats.hw

    xs = {
        "req": t["req"], "nz": t["nonzero_req"],
        "mask": s["mask"], "pref": s["pref_count"],
        "taint_pref": s["taint_pref_count"], "image": s["image_score"],
        "group": t["pod_group"], "in_group": t["pod_in_group"],
        "valid": t["pod_valid"], "zero_req": zero_req,
    }
    if feats.ports:
        xs["ports"] = t["pod_ports"]
    if feats.req:
        xs["req_own"] = t["req_own"]                  # [P, TR]
        xs["req_matchT"] = t["req_match"].T           # [P, TR]
    if feats.anti:
        xs["anti_own"] = t["anti_own"]                # [P, TA]
        xs["anti_matchT"] = t["anti_match"].T         # [P, TA]
    if feats.pref:
        xs["pref_own"] = t["pref_own"]                # [P, TP]
        xs["pref_matchT"] = t["pref_match"].T         # [P, TP]
    if feats.sym:
        xs["sym_matchT"] = t["sym_match"].T           # [P, TS]
    if feats.te:
        xs["te_matchT"] = t["te_match"].T             # [P, TE]
    if feats.disk:
        xs["disk_any"] = t["pod_disk_any"]            # [P, D]
        xs["disk_rw"] = t["pod_disk_rw"]              # [P, D]
    if feats.ebs:
        xs["ebs"] = t["pod_ebs"]                      # [P, VE]
    if feats.gce:
        xs["gce"] = t["pod_gce"]                      # [P, VG]

    init = {
        "used": t["used0"], "used_nz": t["used0_nonzero"],
        "gcounts": t["group_counts0"], "rr": jnp.int32(0),
    }
    if feats.ports:
        init["ports"] = t["node_ports0"]
    if feats.req:
        init["req_hit"] = t["req_hit0"]               # [TR, N]
        init["req_nomatch"] = t["req_nomatch0"]       # [TR] bool
    if feats.hw:
        init["hw_dyn"] = jnp.zeros_like(t["req_hit0"])
    if feats.anti:
        init["anti_hit"] = t["anti_hit0"]             # [TA, N]
        init["sym_dyn"] = jnp.zeros_like(t["anti_hit0"])
    if feats.pref:
        init["pref_hit"] = t["pref_hit0"]             # [TP, N]
        init["te_dyn"] = jnp.zeros_like(t["pref_hit0"])
    if feats.disk:
        init["disk_any"] = t["node_disk_any0"]        # [N, D]
        init["disk_rw"] = t["node_disk_rw0"]          # [N, D]
    if feats.ebs:
        init["ebs_occ"] = t["node_ebs0"]              # [N, VE]
    if feats.gce:
        init["gce_occ"] = t["node_gce0"]              # [N, VG]

    wf = {k: jnp.float32(v) for k, v in w.__dict__.items()}

    def step(carry, x):
        used, used_nz, gcounts, rr = (
            carry["used"], carry["used_nz"], carry["gcounts"], carry["rr"])

        # --- dynamic predicates (PodFitsResources + ports) -------------------
        pod_count_ok = used[:, 3] + 1.0 <= alloc[:, 3]
        res_fit = jnp.all(used[:, :3] + x["req"][None, :3] <= alloc[:, :3], axis=1)
        res_ok = x["zero_req"] | res_fit        # zero-request: count-only
        mask = x["mask"] & pod_count_ok & res_ok
        if feats.ports:
            mask = mask & ((carry["ports"] @ x["ports"]) == 0.0)

        # --- volumes (predicates.go:64-269) ----------------------------------
        if feats.disk:
            # conflict unless every shared column is read-only on both sides:
            # pod-rw vs node-any plus pod-any vs node-rw covers "not both ro"
            clash = (carry["disk_any"] @ x["disk_rw"]
                     + carry["disk_rw"] @ x["disk_any"])
            mask = mask & (clash == 0.0)
        if feats.ebs:
            pod_cnt = jnp.sum(x["ebs"])
            union = (jnp.sum(carry["ebs_occ"], axis=1) + pod_cnt
                     - carry["ebs_occ"] @ x["ebs"])
            mask = mask & ((pod_cnt == 0.0) | (union <= t["max_ebs"]))
        if feats.gce:
            pod_cnt = jnp.sum(x["gce"])
            union = (jnp.sum(carry["gce_occ"], axis=1) + pod_cnt
                     - carry["gce_occ"] @ x["gce"])
            mask = mask & ((pod_cnt == 0.0) | (union <= t["max_gce"]))

        # --- hard inter-pod affinity (predicates.go:769-844) -----------------
        if feats.req:
            # per-term ok: a matching pod in this node's domain, or the
            # disregard rule (self-selecting term, no match anywhere)
            disregard = (x["req_matchT"] > 0) & carry["req_nomatch"]
            term_ok = (carry["req_hit"] > 0) | disregard[:, None]
            viol = x["req_own"] @ (1.0 - term_ok.astype(jnp.float32))
            mask = mask & (viol == 0.0)
        # --- anti-affinity + symmetry (predicates.go:858-921) ----------------
        if feats.anti:
            v = (x["anti_own"] @ carry["anti_hit"]
                 + x["anti_matchT"] @ carry["sym_dyn"])
            mask = mask & (v == 0.0)
        if feats.sym:
            mask = mask & ((x["sym_matchT"] @ sym_dom0) == 0.0)

        feasible = jnp.any(mask) & x["valid"]

        # --- dynamic scores --------------------------------------------------
        cap_c, cap_m = alloc[:, 0], alloc[:, 1]
        tot_c = used_nz[:, 0] + x["nz"][0]
        tot_m = used_nz[:, 1] + x["nz"][1]
        cpu_sc = jnp.where((cap_c > 0) & (tot_c <= cap_c),
                           jnp.floor((cap_c - tot_c) * 10.0 / cap_c), 0.0)
        mem_sc = jnp.where((cap_m > 0) & (tot_m <= cap_m),
                           jnp.floor((cap_m - tot_m) * 10.0 / cap_m), 0.0)
        least = jnp.floor((cpu_sc + mem_sc) / 2.0)

        frac_c = jnp.where(cap_c > 0, tot_c / cap_c, 1.0)
        frac_m = jnp.where(cap_m > 0, tot_m / cap_m, 1.0)
        balanced = jnp.where((frac_c >= 1.0) | (frac_m >= 1.0), 0.0,
                             jnp.floor(10.0 - jnp.abs(frac_c - frac_m) * 10.0))

        # spread (maxes over the *feasible* node set, like the reference's
        # filtered-node prioritization)
        g = x["group"]
        has_group = g >= 0
        counts = jnp.where(has_group, gcounts[:, jnp.maximum(g, 0)], 0.0)
        maxc = jnp.maximum(_masked_max(counts, mask), 0.0)
        fscore = jnp.where(maxc > 0.0, 10.0 * (maxc - counts) / maxc, 10.0)
        # zone sums over feasible nodes only (filtered-node semantics)
        zsum = (jnp.where(mask, counts, 0.0) @ zone_onehot)          # [Z]
        node_zc = zsum[jnp.maximum(zone_id, 0)]
        maxz = jnp.maximum(_masked_max(jnp.where(zone_id >= 0, node_zc, NEG), mask), 0.0)
        zscore = jnp.where(maxz > 0.0, 10.0 * (maxz - node_zc) / maxz, 10.0)
        have_zones = jnp.any(mask & (zone_id >= 0))  # zones among feasible nodes
        blend = jnp.where((zone_id >= 0) & has_group & have_zones & (maxz > 0.0),
                          fscore * (1.0 / 3.0) + (2.0 / 3.0) * zscore, fscore)
        spread = jnp.floor(jnp.where(has_group, blend, 10.0))

        # node-affinity preferred (normalized over feasible set)
        max_pref = _masked_max(x["pref"], mask)
        node_aff = jnp.where(max_pref > 0.0,
                             jnp.floor(10.0 * x["pref"] / max_pref), 0.0)

        # taint PreferNoSchedule (normalized over feasible set)
        max_tp = _masked_max(x["taint_pref"], mask)
        taint_sc = jnp.where(max_tp > 0.0,
                             jnp.floor((1.0 - x["taint_pref"] / max_tp) * 10.0), 10.0)

        # soft inter-pod affinity (interpod_affinity.go:86-216): forward
        # weighted matches + reverse preferences of placed pods about us,
        # min-max normalized over the feasible set with 0 in the window
        if use_ip_score:
            c = jnp.zeros((N,), jnp.float32)
            if feats.pref:
                c = c + (x["pref_own"] * pref_w) @ carry["pref_hit"]
                c = c + x["pref_matchT"] @ carry["te_dyn"]
            if feats.te:
                c = c + x["te_matchT"] @ te_dom0
            if feats.hw:
                c = c + hard_w * (x["req_matchT"] @ carry["hw_dyn"])
            ip_max = jnp.maximum(_masked_max(c, mask), 0.0)
            ip_min = jnp.minimum(_masked_min(c, mask), 0.0)
            ip_rng = ip_max - ip_min
            interpod = jnp.where(ip_rng > 0.0,
                                 jnp.floor(10.0 * (c - ip_min) / ip_rng), 0.0)
        else:
            interpod = 0.0

        score = (wf["least_requested"] * least + wf["balanced"] * balanced
                 + wf["spread"] * spread + wf["node_affinity"] * node_aff
                 + wf["taint_toleration"] * taint_sc
                 + wf["interpod_affinity"] * interpod
                 + wf["image_locality"] * x["image"] + wf["equal"] * 1.0)

        # --- selectHost: max + round-robin tie-break -------------------------
        masked_score = jnp.where(mask, score, NEG)
        max_score = jnp.max(masked_score)
        is_max = mask & (masked_score == max_score)
        n_ties = jnp.sum(is_max.astype(jnp.int32))
        k = jnp.where(n_ties > 0, rr % jnp.maximum(n_ties, 1), 0)
        cum = jnp.cumsum(is_max.astype(jnp.int32))
        chosen = jnp.argmax(is_max & (cum == k + 1))
        chosen = jnp.where(feasible, chosen.astype(jnp.int32), jnp.int32(-1))

        # --- commit (the on-device AssumePod) --------------------------------
        commit = feasible
        onehot = ((idx_n == chosen) & commit).astype(jnp.float32)
        used = used + onehot[:, None] * x["req"][None, :]
        used_nz = used_nz + onehot[:, None] * x["nz"][None, :]
        gcounts = gcounts + onehot[:, None] * x["in_group"][None, :]
        rr = rr + commit.astype(jnp.int32)

        out = {"used": used, "used_nz": used_nz, "gcounts": gcounts, "rr": rr}
        if feats.ports:
            out["ports"] = jnp.maximum(
                carry["ports"], onehot[:, None] * x["ports"][None, :])

        if use_dm:
            # nodes sharing a topology domain with the chosen node, per key
            # (zeroed when nothing committed, so all updates no-op)
            safe = jnp.maximum(chosen, 0)
            dom_c = node_dom[:, safe]                            # [K]
            eq = ((node_dom == dom_c[:, None]) & (node_dom >= 0)
                  ).astype(jnp.float32) * commit.astype(jnp.float32)  # [K, N]
        if feats.req:
            dm = ((t["req_topo"] @ eq) > 0).astype(jnp.float32)  # [TR, N]
            qmatch = x["req_matchT"]
            out["req_hit"] = jnp.maximum(carry["req_hit"],
                                         qmatch[:, None] * dm)
            out["req_nomatch"] = carry["req_nomatch"] & ~((qmatch > 0) & commit)
            if feats.hw:
                out["hw_dyn"] = carry["hw_dyn"] + x["req_own"][:, None] * dm
        if feats.anti:
            dm = ((t["anti_topo"] @ eq) > 0).astype(jnp.float32)
            out["anti_hit"] = jnp.maximum(carry["anti_hit"],
                                          x["anti_matchT"][:, None] * dm)
            out["sym_dyn"] = jnp.maximum(
                carry["sym_dyn"],
                (x["anti_own"] > 0).astype(jnp.float32)[:, None] * dm)
        if feats.pref:
            dm = ((t["pref_topo"] @ eq) > 0).astype(jnp.float32)
            out["pref_hit"] = carry["pref_hit"] + x["pref_matchT"][:, None] * dm
            out["te_dyn"] = (carry["te_dyn"]
                             + (x["pref_own"] * pref_w)[:, None] * dm)
        if feats.disk:
            out["disk_any"] = jnp.maximum(
                carry["disk_any"], onehot[:, None] * x["disk_any"][None, :])
            out["disk_rw"] = jnp.maximum(
                carry["disk_rw"], onehot[:, None] * x["disk_rw"][None, :])
        if feats.ebs:
            out["ebs_occ"] = jnp.maximum(
                carry["ebs_occ"], onehot[:, None] * x["ebs"][None, :])
        if feats.gce:
            out["gce_occ"] = jnp.maximum(
                carry["gce_occ"], onehot[:, None] * x["gce"][None, :])

        return out, chosen

    # unroll amortizes per-iteration loop overhead; the body is tiny
    # (elementwise over N + a few [T, N] matvecs) so overhead dominates
    _, assignments = jax.lax.scan(step, init, xs, unroll=8)
    return assignments


# --- public API ---------------------------------------------------------------

# integer fields that stay integral on device (indices, not indicators)
_INT_FIELDS = frozenset(("zone_id", "host_req", "node_dom", "pod_group"))


@functools.partial(jax.jit, static_argnames=("n_zones", "weights", "feats"))
def _schedule_jit(tensors: dict, n_zones: int, weights: Weights,
                  feats: Features):
    # indicator/count matrices may arrive packed (int8/int16/int32 — 4x less
    # upload traffic than f32, ops/incremental.py); widen on-device where
    # the MXU wants floats. XLA fuses the casts into the consumers.
    t = {}
    for k, v in tensors.items():
        if (k in _INT_FIELDS or v.dtype == jnp.bool_
                or jnp.issubdtype(v.dtype, jnp.floating)):
            t[k] = v
        else:
            t[k] = v.astype(jnp.float32)
    t["n_zones"] = n_zones
    s = static_pass(t)
    return greedy_commit(t, s, weights, feats)


def assignments_to_names(out: np.ndarray,
                         ct: ClusterTensors) -> List[Optional[str]]:
    """Decode kernel output ([P] node indices, -1 = unschedulable) to node
    names — the ONE decoder shared by the unsharded, sharded, and
    incremental paths, so equivalence tests compare kernels, not decoders.
    Handles both dense node_names (full Tensorizer) and slot-indexed lists
    with empty holes (incremental mirror)."""
    result: List[Optional[str]] = []
    for i in range(ct.n_real_pods):
        n = int(out[i])
        name = ct.node_names[n] if 0 <= n < len(ct.node_names) else ""
        result.append(name or None)
    return result


def schedule_batch(ct: ClusterTensors, weights: Optional[Weights] = None,
                   device=None) -> List[Optional[str]]:
    """Schedule a tensorized batch; returns node name (or None) per pending
    pod, FIFO order."""
    weights = weights or Weights()
    feats = features_of(ct)
    arrays = {k: jnp.asarray(v) for k, v in ct.arrays().items()}
    if device is not None:
        arrays = jax.device_put(arrays, device)
    out = np.asarray(_schedule_jit(arrays, ct.n_zones, weights, feats))
    return assignments_to_names(out, ct)
