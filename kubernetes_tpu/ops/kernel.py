"""The filter-and-score kernel: batched masks/scores + sequential commit.

Two stages, mirroring the decomposition in SURVEY §7:

Stage A (assignment-independent, MXU-batched):
  - predicate masks: node selector / NodeAffinity / taints / memory-pressure /
    host pinning / inter-pod static — each one matmul + compare over the
    vocab-encoded tensors (predicates.go:416-1002 vectorized)
  - score ingredients that don't depend on commits: preferred-affinity weight
    counts, intolerable-PreferNoSchedule counts, image-locality buckets

Stage B (lax.scan over pods in FIFO order):
  replicates the reference's one-pod-at-a-time semantics exactly — each step
  sees capacity/ports/spread state that includes every prior in-batch commit
  (the on-device analogue of AssumePod, cache.go:101). Priorities normalize
  over the *feasible* node set per pod (the reference prioritizes only
  filtered nodes, generic_scheduler.go:94-107), so normalizations are
  computed in-step against the dynamic mask. Ties break round-robin over the
  canonical node order with a carried counter (selectHost,
  generic_scheduler.go:116-133).

Integer-truncation points match the Go code: calculateScore's
((cap-req)*10)/cap, the (cpu+mem)/2 average, int(fScore) everywhere
(priorities.go:33-43 etc.) — implemented as floor on non-negative f32.

All shapes are static per batch (padded); the jit cache is keyed by padded
(P, N, vocab) sizes, so repeated batches of similar shape reuse the compile.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.ops.tensorize import ClusterTensors

# numpy scalar, not jnp: module import must stay device-free (backend init
# at import time would grab the chip even for CPU-only test runs)
NEG = np.float32(-1e9)


@dataclass(frozen=True)
class Weights:
    """Priority weights (DefaultProvider: all 1, image/equal off —
    defaults.go:150-197)."""

    least_requested: int = 1
    balanced: int = 1
    spread: int = 1
    node_affinity: int = 1
    taint_toleration: int = 1
    image_locality: int = 0
    equal: int = 0


# --- stage A -----------------------------------------------------------------

def static_pass(t: dict) -> dict:
    """All [P, N] mask/score ingredients that don't depend on assignment."""
    node_labels = t["node_labels"]          # [N, L]
    P = t["req"].shape[0]
    N = t["alloc"].shape[0]

    sel_ok = (t["sel_required"] @ node_labels.T) >= t["sel_count"][:, None]

    term_node = (t["term_expr"] @ t["expr_node"]) >= t["term_expr_count"][:, None]
    aff_hits = t["pod_term"] @ term_node.astype(jnp.float32)
    aff_ok = (~t["pod_has_affinity"][:, None]) | (aff_hits >= 1.0)

    untol = (1.0 - t["tol_nosched"]) @ t["taints_nosched"].T
    taint_ok = untol == 0.0

    mem_ok = ~(t["best_effort"][:, None] & t["mem_pressure"][None, :])

    idx = jnp.arange(N, dtype=jnp.int32)
    host = t["host_req"][:, None]
    host_ok = (host == -1) | (host == idx[None, :])

    static_mask = (
        t["node_valid"][None, :] & sel_ok & aff_ok & taint_ok & mem_ok & host_ok
        & (t["interpod_forbidden"] == 0.0) & (t["interpod_required_miss"] == 0.0))

    pref_count = (t["pod_pref_term"] * t["pref_weight"][None, :]) @ t["pref_term_node"]
    taint_pref_count = (1.0 - t["tol_prefer"]) @ t["taints_prefer"].T

    image_mib = t["pod_images"] @ t["image_node_sizes"].T
    min_mib, max_mib = 23.0, 1000.0
    image_score = jnp.where(
        image_mib < min_mib, 0.0,
        jnp.where(image_mib >= max_mib, 10.0,
                  jnp.floor(10.0 * (image_mib - min_mib) / (max_mib - min_mib)) + 1.0))

    return {"mask": static_mask, "pref_count": pref_count,
            "taint_pref_count": taint_pref_count, "image_score": image_score}


# --- stage B -----------------------------------------------------------------

def _masked_max(x, mask):
    return jnp.max(jnp.where(mask, x, NEG))


def greedy_commit(t: dict, s: dict, w: Weights):
    """lax.scan over pods; returns assignments [P] i32 (-1 = unschedulable)."""
    alloc = t["alloc"]                      # [N, 4]
    N = alloc.shape[0]
    zone_id = t["zone_id"]                  # [N]
    Z = int(t["n_zones"]) if isinstance(t["n_zones"], int) else t["n_zones"]
    G = t["group_counts0"].shape[1]
    idx_n = jnp.arange(N, dtype=jnp.int32)

    zero_req = jnp.all(t["req"][:, :3] == 0.0, axis=1)  # pods axis excluded

    # zone membership one-hot; zone counts are recomputed per step over the
    # *feasible* node set (the reference sums countsByZone over filtered
    # nodes only, selector_spreading.go:186-196)
    zone_onehot = ((zone_id[:, None] == jnp.arange(Z)[None, :])
                   & (zone_id >= 0)[:, None]).astype(jnp.float32)  # [N, Z]

    xs = {
        "req": t["req"], "nz": t["nonzero_req"], "ports": t["pod_ports"],
        "mask": s["mask"], "pref": s["pref_count"],
        "taint_pref": s["taint_pref_count"], "image": s["image_score"],
        "group": t["pod_group"], "in_group": t["pod_in_group"],
        "valid": t["pod_valid"], "zero_req": zero_req,
    }

    init = {
        "used": t["used0"], "used_nz": t["used0_nonzero"],
        "ports": t["node_ports0"], "gcounts": t["group_counts0"],
        "rr": jnp.int32(0),
    }

    wf = {k: jnp.float32(v) for k, v in w.__dict__.items()}

    def step(carry, x):
        used, used_nz, ports, gcounts, rr = (
            carry["used"], carry["used_nz"], carry["ports"],
            carry["gcounts"], carry["rr"])

        # --- dynamic predicates (PodFitsResources + ports) -------------------
        pod_count_ok = used[:, 3] + 1.0 <= alloc[:, 3]
        res_fit = jnp.all(used[:, :3] + x["req"][None, :3] <= alloc[:, :3], axis=1)
        res_ok = x["zero_req"] | res_fit        # zero-request: count-only
        port_clash = (ports @ x["ports"]) > 0.0
        mask = x["mask"] & pod_count_ok & res_ok & (~port_clash)
        feasible = jnp.any(mask) & x["valid"]

        # --- dynamic scores --------------------------------------------------
        cap_c, cap_m = alloc[:, 0], alloc[:, 1]
        tot_c = used_nz[:, 0] + x["nz"][0]
        tot_m = used_nz[:, 1] + x["nz"][1]
        cpu_sc = jnp.where((cap_c > 0) & (tot_c <= cap_c),
                           jnp.floor((cap_c - tot_c) * 10.0 / cap_c), 0.0)
        mem_sc = jnp.where((cap_m > 0) & (tot_m <= cap_m),
                           jnp.floor((cap_m - tot_m) * 10.0 / cap_m), 0.0)
        least = jnp.floor((cpu_sc + mem_sc) / 2.0)

        frac_c = jnp.where(cap_c > 0, tot_c / cap_c, 1.0)
        frac_m = jnp.where(cap_m > 0, tot_m / cap_m, 1.0)
        balanced = jnp.where((frac_c >= 1.0) | (frac_m >= 1.0), 0.0,
                             jnp.floor(10.0 - jnp.abs(frac_c - frac_m) * 10.0))

        # spread (maxes over the *feasible* node set, like the reference's
        # filtered-node prioritization)
        g = x["group"]
        has_group = g >= 0
        counts = jnp.where(has_group, gcounts[:, jnp.maximum(g, 0)], 0.0)
        maxc = jnp.maximum(_masked_max(counts, mask), 0.0)
        fscore = jnp.where(maxc > 0.0, 10.0 * (maxc - counts) / maxc, 10.0)
        # zone sums over feasible nodes only (filtered-node semantics)
        zsum = (jnp.where(mask, counts, 0.0) @ zone_onehot)          # [Z]
        node_zc = zsum[jnp.maximum(zone_id, 0)]
        maxz = jnp.maximum(_masked_max(jnp.where(zone_id >= 0, node_zc, NEG), mask), 0.0)
        zscore = jnp.where(maxz > 0.0, 10.0 * (maxz - node_zc) / maxz, 10.0)
        have_zones = jnp.any(mask & (zone_id >= 0))  # zones among feasible nodes
        blend = jnp.where((zone_id >= 0) & has_group & have_zones & (maxz > 0.0),
                          fscore * (1.0 / 3.0) + (2.0 / 3.0) * zscore, fscore)
        spread = jnp.floor(jnp.where(has_group, blend, 10.0))

        # node-affinity preferred (normalized over feasible set)
        max_pref = _masked_max(x["pref"], mask)
        node_aff = jnp.where(max_pref > 0.0,
                             jnp.floor(10.0 * x["pref"] / max_pref), 0.0)

        # taint PreferNoSchedule (normalized over feasible set)
        max_tp = _masked_max(x["taint_pref"], mask)
        taint_sc = jnp.where(max_tp > 0.0,
                             jnp.floor((1.0 - x["taint_pref"] / max_tp) * 10.0), 10.0)

        score = (wf["least_requested"] * least + wf["balanced"] * balanced
                 + wf["spread"] * spread + wf["node_affinity"] * node_aff
                 + wf["taint_toleration"] * taint_sc
                 + wf["image_locality"] * x["image"] + wf["equal"] * 1.0)

        # --- selectHost: max + round-robin tie-break -------------------------
        masked_score = jnp.where(mask, score, NEG)
        max_score = jnp.max(masked_score)
        is_max = mask & (masked_score == max_score)
        n_ties = jnp.sum(is_max.astype(jnp.int32))
        k = jnp.where(n_ties > 0, rr % jnp.maximum(n_ties, 1), 0)
        cum = jnp.cumsum(is_max.astype(jnp.int32))
        chosen = jnp.argmax(is_max & (cum == k + 1))
        chosen = jnp.where(feasible, chosen.astype(jnp.int32), jnp.int32(-1))

        # --- commit (the on-device AssumePod) --------------------------------
        commit = feasible
        onehot = ((idx_n == chosen) & commit).astype(jnp.float32)
        used = used + onehot[:, None] * x["req"][None, :]
        used_nz = used_nz + onehot[:, None] * x["nz"][None, :]
        ports = jnp.maximum(ports, onehot[:, None] * x["ports"][None, :])
        gcounts = gcounts + onehot[:, None] * x["in_group"][None, :]
        rr = rr + commit.astype(jnp.int32)

        return ({"used": used, "used_nz": used_nz, "ports": ports,
                 "gcounts": gcounts, "rr": rr}, chosen)

    # unroll amortizes per-iteration loop overhead; the body is tiny
    # (elementwise over N + one [N, PT] matvec) so overhead dominates
    _, assignments = jax.lax.scan(step, init, xs, unroll=8)
    return assignments


# --- public API ---------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("n_zones", "weights"))
def _schedule_jit(tensors: dict, n_zones: int, weights: Weights):
    t = dict(tensors)
    t["n_zones"] = n_zones
    s = static_pass(t)
    return greedy_commit(t, s, weights)


def schedule_batch(ct: ClusterTensors, weights: Optional[Weights] = None,
                   device=None) -> List[Optional[str]]:
    """Schedule a tensorized batch; returns node name (or None) per pending
    pod, FIFO order."""
    weights = weights or Weights()
    arrays = {k: jnp.asarray(v) for k, v in ct.arrays().items()}
    if device is not None:
        arrays = jax.device_put(arrays, device)
    out = np.asarray(_schedule_jit(arrays, ct.n_zones, weights))
    result: List[Optional[str]] = []
    for i in range(ct.n_real_pods):
        n = int(out[i])
        result.append(ct.node_names[n] if 0 <= n < ct.n_real_nodes else None)
    return result
