"""The filter-and-score kernel: batched masks/scores + sequential commit.

Two stages, mirroring the decomposition in SURVEY §7:

Stage A (assignment-independent, MXU-batched):
  - predicate masks: node selector / NodeAffinity / taints / memory-pressure /
    host pinning — each one matmul + compare over the vocab-encoded tensors
    (predicates.go:416-1002 vectorized)
  - score ingredients that don't depend on commits: preferred-affinity weight
    counts, intolerable-PreferNoSchedule counts, image-locality buckets
    (each traced only when the batch actually exercises it — Features)

Stage B (lax.scan over pods in FIFO order):
  replicates the reference's one-pod-at-a-time semantics exactly — each step
  sees capacity/ports/spread/affinity/volume state that includes every prior
  in-batch commit (the on-device analogue of AssumePod, cache.go:101).

  The scan body is engineered for MINIMAL OP COUNT: on TPU the per-step cost
  of this loop is dominated by per-op dispatch overhead (~1µs/op measured on
  v5e), not FLOPs or HBM bandwidth, so semantically-grouped small ops are
  packed into single fused ops:

  - one [P, W] f32 row ("prow") carries every per-pod operand — requests,
    group membership, all eight interpod own/match rows, volume/port column
    ids (as exact f32 integers) — so the scan slices ONE xs leaf per step
    instead of ~20;
  - all five vocab occupancy carries (host-ports, exclusive-disk any/rw,
    EBS and GCE attach columns — predicates.go:64-269,687) live in ONE
    [5, V, N] array; the per-pod columns are fetched with ONE gather and
    committed with ONE scatter against reserved always-zero null columns,
    replacing five [N, V] matvecs + five full-array maximum rewrites;
  - all six dynamic inter-pod affinity hit tables (req_hit/hw_dyn/anti_hit/
    sym_dyn/pref_hit/te_dyn — predicates.go:769-947,
    interpod_affinity.go:86-216) live in ONE [6, T, N] carry contracted by
    ONE batched dot_general; the two static tables (sym_dom0/te_dom0) by a
    second. The hard-affinity disregard rule (self-selecting term with no
    match anywhere, predicates.go:818-844) is linearized:
    own @ (1 - (hit|dis)) == own·(1-dis) @ (1 - hit) for binary hit/dis,
    so it rides the same contraction. Commit updates to all six tables are
    ONE fused elementwise op over the pack (max-rows and add-rows selected
    by a static mask), fed by ONE batched topo matmul for the three
    domain-hit rows;
  - the five masked score reductions (spread max, zone max, interpod
    min/max, feasibility/zone-presence flags) are ONE [6, N] stacked max.

  Every score ingredient is integer-valued f32 (weights, counts, floored
  scores), so regrouping sums into batched contractions is bit-exact against
  the reference formulation — the randomized differential tests
  (tests/test_tpu_kernel.py) pin this.

  Priorities normalize over the *feasible* node set per pod (the reference
  prioritizes only filtered nodes, generic_scheduler.go:94-107). Ties break
  round-robin over the canonical node order with a carried counter
  (selectHost, generic_scheduler.go:116-133).

Feature flags (Features) are computed host-side from the batch and are static
jit arguments: a batch with no inter-pod terms / volumes / host-ports traces
none of those carries, so the common case stays a lean
capacity+spread+affinity scan.

Integer-truncation points match the Go code: calculateScore's
((cap-req)*10)/cap, the (cpu+mem)/2 average, int(fScore) everywhere
(priorities.go:33-43 etc.) — implemented as floor on non-negative f32.

All shapes are static per batch (padded); the jit cache is keyed by padded
(P, N, vocab) sizes + Features, so repeated batches of similar shape reuse
the compile.

The serial scan is no longer the default solve: ops/wave.py restructures
stage B into WAVE COMMIT — bulk-committing non-interacting FIFO prefixes
per step, bit-identical to this scan by construction (it runs this module's
step function for complex pods and proves fixed-point equality for the
rest) — shrinking the sequential dimension from P pod-steps to the
measured wave count. KTPU_WAVE=0 selects the serial scan.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Dict, List, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.ops.tensorize import ClusterTensors
from kubernetes_tpu.scheduler.objectives.config import ObjectiveConfig

# numpy scalar, not jnp: module import must stay device-free (backend init
# at import time would grab the chip even for CPU-only test runs)
NEG = np.float32(-1e9)


@dataclass(frozen=True)
class Weights:
    """Priority weights (DefaultProvider: all 1, image/equal off —
    defaults.go:150-197)."""

    least_requested: int = 1
    balanced: int = 1
    spread: int = 1
    node_affinity: int = 1
    taint_toleration: int = 1
    interpod_affinity: int = 1
    image_locality: int = 0
    equal: int = 0


def _slot_bucket(n: int) -> int:
    """Bucket a per-pod max column count to a power of two (static jit key
    stability across similar batches)."""
    if n <= 0:
        return 0
    return 1 << max(0, int(n - 1).bit_length())


class Features(NamedTuple):
    """Which optional carries this batch needs (static jit key)."""

    req: bool = False        # pending pods own hard affinity terms
    anti: bool = False       # pending pods own hard anti-affinity terms
    sym: bool = False        # existing pods own anti terms (static symmetry)
    pref: bool = False       # pending pods own preferred terms
    te: bool = False         # existing pods' terms carry reverse score
    hw: bool = False         # reverse hard-affinity weight > 0 (needs req)
    disk: bool = False       # exclusive-disk conflict columns in play
    ebs: bool = False        # EBS attach-count columns in play
    gce: bool = False        # GCE-PD attach-count columns in play
    ports: bool = False      # host ports requested by pending pods
    node_pref: bool = False  # preferred node-affinity terms in play
    taint_pref: bool = False  # PreferNoSchedule taints in play
    image: bool = False      # any pod images known (ImageLocality input)
    sp: int = 0              # max host-port columns per pod (bucketed)
    sd: int = 0              # max exclusive-disk columns per pod (bucketed)
    se: int = 0              # max EBS columns per pod (bucketed)
    sg: int = 0              # max GCE-PD columns per pod (bucketed)

    @property
    def interpod(self) -> bool:
        """Any dynamic inter-pod carry traced."""
        return self.req or self.anti or self.pref or self.hw

    @property
    def static_terms(self) -> bool:
        """Any static existing-pod term table traced."""
        return self.sym or self.te

    @property
    def vocab(self) -> bool:
        """Any vocab occupancy carry traced."""
        return self.ports or self.disk or self.ebs or self.gce


def explain_component_names(feats: Features, w: Weights,
                            obj: Optional[ObjectiveConfig] = None) -> List[str]:
    """Score components the kernel emits on-device when `explain` is on, in
    stack order. Must mirror the rows greedy_commit actually stacks: the
    host decode (observability/explain.py) zips this list against the
    emitted [P, C] component matrix. Components the batch can't exercise
    are absent here and reconstructed host-side as their constant oracle
    value (taint_toleration=10 when untraced, 0 otherwise)."""
    names = ["least_requested", "balanced", "spread"]
    if feats.node_pref:
        names.append("node_affinity")
    if feats.taint_pref:
        names.append("taint_toleration")
    if feats.interpod or feats.static_terms:
        names.append("interpod_affinity")
    if feats.image and w.image_locality != 0:
        names.append("image_locality")
    if obj is not None and obj.binpack and obj.binpack_weight != 0:
        names.append("binpack")
    return names


def features_of(ct: ClusterTensors) -> Features:
    """Host-side batch inspection -> static trace flags."""
    has_req = bool(ct.req_own.any())

    def _maxcols(mat) -> int:
        return _slot_bucket(int(np.asarray(mat, np.float32).sum(axis=1).max())
                            if mat.size else 0)

    ports = bool(ct.pod_ports.any())
    disk = bool(ct.pod_disk_any.any())
    ebs = bool(ct.pod_ebs.any())
    gce = bool(ct.pod_gce.any())
    return Features(
        req=has_req,
        anti=bool(ct.anti_own.any()),
        sym=bool(ct.sym_dom0.any()),
        pref=bool(ct.pref_own.any()),
        te=bool(ct.te_dom0.any()),
        hw=has_req and float(ct.hard_weight) > 0,
        disk=disk,
        ebs=ebs,
        gce=gce,
        ports=ports,
        node_pref=bool(ct.pod_pref_term.any()),
        taint_pref=bool(ct.taints_prefer.any()),
        image=bool(ct.pod_images.any()),
        sp=_maxcols(ct.pod_ports) if ports else 0,
        sd=_maxcols(ct.pod_disk_any) if disk else 0,
        se=_maxcols(ct.pod_ebs) if ebs else 0,
        sg=_maxcols(ct.pod_gce) if gce else 0,
    )


# --- stage A -----------------------------------------------------------------

def static_pass(t: dict, feats: Optional[Features] = None,
                weights: Optional[Weights] = None,
                explain: bool = False) -> dict:
    """All [P, N] mask/score ingredients that don't depend on assignment.

    With feats/weights given, score rows the batch can't exercise are left
    out entirely (no [P, N] materialization, no per-step stream).

    With explain, also emits `static_surv` [P, 5]: cumulative surviving-node
    counts after each static predicate in the canonical order (selector,
    node-affinity, taints, memory-pressure, host) — reductions over the
    ingredient masks already in registers, never a [P, N, K] tensor. The
    masks themselves (and therefore the assignments) are untouched."""
    node_labels = t["node_labels"]          # [N, L]
    N = t["alloc"].shape[0]

    sel_ok = (t["sel_required"] @ node_labels.T) >= t["sel_count"][:, None]

    term_node = (t["term_expr"] @ t["expr_node"]) >= t["term_expr_count"][:, None]
    aff_hits = t["pod_term"] @ term_node.astype(jnp.float32)
    aff_ok = (~t["pod_has_affinity"][:, None]) | (aff_hits >= 1.0)

    untol = (1.0 - t["tol_nosched"]) @ t["taints_nosched"].T
    taint_ok = untol == 0.0

    mem_ok = ~(t["best_effort"][:, None] & t["mem_pressure"][None, :])

    idx = jnp.arange(N, dtype=jnp.int32)
    host = t["host_req"][:, None]
    host_ok = (host == -1) | (host == idx[None, :])

    static_mask = (
        t["node_valid"][None, :] & sel_ok & aff_ok & taint_ok & mem_ok & host_ok)

    out = {"mask": static_mask}
    if explain:
        # cumulative survivor counts, canonical static order (the chain's
        # last element equals static_mask, so the counts are exactly the
        # masks the solve uses)
        cum = jnp.broadcast_to(t["node_valid"][None, :], sel_ok.shape)
        counts = []
        for m in (sel_ok, aff_ok, taint_ok, mem_ok, host_ok):
            cum = cum & m
            counts.append(jnp.sum(cum.astype(jnp.float32), axis=1))
        out["static_surv"] = jnp.stack(counts, axis=1)  # [P, 5]
    if feats is None or feats.node_pref:
        out["pref_count"] = (
            (t["pod_pref_term"] * t["pref_weight"][None, :]) @ t["pref_term_node"])
    if feats is None or feats.taint_pref:
        out["taint_pref_count"] = (1.0 - t["tol_prefer"]) @ t["taints_prefer"].T
    if feats is None or (feats.image and (weights is None
                                          or weights.image_locality != 0)):
        image_mib = t["pod_images"] @ t["image_node_sizes"].T
        min_mib, max_mib = 23.0, 1000.0
        out["image_score"] = jnp.where(
            image_mib < min_mib, 0.0,
            jnp.where(image_mib >= max_mib, 10.0,
                      jnp.floor(10.0 * (image_mib - min_mib)
                                / (max_mib - min_mib)) + 1.0))
    return out


# --- stage B -----------------------------------------------------------------

# vocab pack channel order (fixed): host-ports, exclusive-disk any,
# exclusive-disk rw, EBS attach, GCE-PD attach
_CH_PORTS, _CH_DANY, _CH_DRW, _CH_EBS, _CH_GCE = range(5)


def _extract_cols(mat, slots: int, null_id: int):
    """[P, V] binary indicator -> ([P, slots] column ids (null_id padded),
    [P, slots] values at those columns). Runs once per dispatch."""
    P = mat.shape[0]
    ids, vals = [], []
    m = mat
    rows = jnp.arange(P)
    for _ in range(slots):
        i = jnp.argmax(m, axis=1)
        v = m[rows, i]
        ids.append(jnp.where(v > 0, i, null_id))
        vals.append(v)
        m = m * (1.0 - jax.nn.one_hot(i, mat.shape[1], dtype=m.dtype))
    return (jnp.stack(ids, axis=1).astype(jnp.float32),
            jnp.stack(vals, axis=1))


def _pack_vocab(t: dict, feats: Features, N: int):
    """Build the [5, Vp, N] occupancy carry (node state, transposed so the
    gathered column slices are contiguous) + the per-pod slot streams.

    Vp reserves >=1 always-zero null column: slot entries of pods without
    that feature point at it, so gathers read zeros and scatters write
    zeros — no per-slot validity masks needed in the scan body."""
    widths = [t["node_ports0"].shape[1], t["node_disk_any0"].shape[1],
              t["node_disk_rw0"].shape[1], t["node_ebs0"].shape[1],
              t["node_gce0"].shape[1]]
    V = max(widths)
    Vp = V + 128  # >=128 guaranteed-zero null columns; null id = V

    def chan(a):  # [N, v] -> [Vp, N]
        a = a.T
        return jnp.pad(a, ((0, Vp - a.shape[0]), (0, 0)))

    vocab0 = jnp.stack([
        chan(t["node_ports0"]), chan(t["node_disk_any0"]),
        chan(t["node_disk_rw0"]), chan(t["node_ebs0"]), chan(t["node_gce0"])])

    # unified slot list: (static channel, per-pod id, per-pod commit value)
    chans: List[int] = []
    id_cols, val_cols = [], []
    if feats.ports:
        ids, vals = _extract_cols(t["pod_ports"], feats.sp, V)
        for s in range(feats.sp):
            chans.append(_CH_PORTS)
            id_cols.append(ids[:, s])
            val_cols.append(vals[:, s])
    if feats.disk:
        ids, vals = _extract_cols(t["pod_disk_any"], feats.sd, V)
        rows = jnp.arange(t["pod_disk_rw"].shape[0])
        for s in range(feats.sd):
            rw = t["pod_disk_rw"][rows, ids[:, s].astype(jnp.int32)
                                  % t["pod_disk_rw"].shape[1]]
            rw = rw * vals[:, s]
            # two slots per disk column: the any-channel (commit value 1)
            # and the rw-channel (commit value = pod's rw flag)
            chans.append(_CH_DANY)
            id_cols.append(ids[:, s])
            val_cols.append(vals[:, s])
            chans.append(_CH_DRW)
            id_cols.append(ids[:, s])
            val_cols.append(rw)
    if feats.ebs:
        ids, vals = _extract_cols(t["pod_ebs"], feats.se, V)
        for s in range(feats.se):
            chans.append(_CH_EBS)
            id_cols.append(ids[:, s])
            val_cols.append(vals[:, s])
    if feats.gce:
        ids, vals = _extract_cols(t["pod_gce"], feats.sg, V)
        for s in range(feats.sg):
            chans.append(_CH_GCE)
            id_cols.append(ids[:, s])
            val_cols.append(vals[:, s])

    slot_ids = jnp.stack(id_cols, axis=1)     # [P, SS] f32 (exact ints)
    slot_vals = jnp.stack(val_cols, axis=1)   # [P, SS] f32
    chan_idx = np.asarray(chans, np.int32)    # [SS] static
    return vocab0, chan_idx, slot_ids, slot_vals


class _Layout:
    """Static offsets into the packed per-pod row."""

    def __init__(self):
        self.off = 0
        self.spans: Dict[str, slice] = {}

    def add(self, name: str, width: int) -> None:
        self.spans[name] = slice(self.off, self.off + width)
        self.off += width

    def of(self, row, name: str):
        return row[self.spans[name]]


def build_program(t: dict, s: dict, w: Weights, feats: Features,
                  explain: bool = False,
                  obj: Optional[ObjectiveConfig] = None):
    """Shared solver builder: the packing prologue + the per-pod step
    function, used by BOTH the serial scan (greedy_commit) and the wave
    solver (ops/wave.py). The wave path's single-pod commits run this exact
    step function, and its batched decide reads the same packed operands
    through `ctx`, so the two solvers cannot drift apart formula-wise.
    Returns (step, xs, init, ctx)."""
    assert not feats.hw or feats.req, "hw carry requires the req term table"
    obj_on = obj is not None and obj.enabled
    use_gang = obj_on and obj.gang
    use_preempt = obj_on and obj.preempt
    use_binpack = obj_on and obj.binpack and obj.binpack_weight != 0
    alloc = t["alloc"]                      # [N, 4]
    N = alloc.shape[0]
    G = t["group_counts0"].shape[1]
    # n_zones arrives as a STATIC python int (jit static_argnames) packed
    # into t; the isinstance guard keeps the traced-dict path working
    # kube-verify: disable-next-line=host-sync-in-kernel
    Z = int(t["n_zones"]) if isinstance(t["n_zones"], int) else t["n_zones"]
    idx_n = jnp.arange(N, dtype=jnp.int32)

    use_ip = feats.interpod
    use_st = feats.static_terms
    use_vocab = feats.vocab
    use_image = feats.image and w.image_locality != 0

    # ---- prologue: one-time packing (runs on device, once per dispatch) ----
    allocT = alloc.T                        # [4, N]
    cap_c, cap_m = allocT[0], allocT[1]

    # zone membership (spread's zone blend recomputes per step over the
    # feasible set — selector_spreading.go:186-196)
    zone_onehot_t = ((t["zone_id"][None, :] == jnp.arange(Z)[:, None])
                     & (t["zone_id"] >= 0)[None, :]).astype(jnp.float32)  # [Z,N]

    # node state pack [R, N]: used(4) | used_nz(2) | ebs_count | gce_count |
    # gcounts(G) | null group row
    nstate0 = jnp.concatenate([
        t["used0"].T, t["used0_nonzero"].T,
        jnp.sum(t["node_ebs0"], axis=1)[None, :],
        jnp.sum(t["node_gce0"], axis=1)[None, :],
        t["group_counts0"].T, jnp.zeros((1, N), jnp.float32)], axis=0)
    _R_EBS, _R_GCE, _R_G0 = 6, 7, 8
    null_group = G  # relative to gcounts rows

    if use_vocab:
        vocab0, chan_idx, slot_ids, slot_vals = _pack_vocab(t, feats, N)
        SS = chan_idx.shape[0]
    else:
        SS = 0

    if use_ip:
        T = max(t["req_own"].shape[1], t["anti_own"].shape[1],
                t["pref_own"].shape[1])

        def padT(a, rows_axis0=True):  # pad term axis to T
            if rows_axis0:  # [Tx, N] -> [T, N]
                return jnp.pad(a, ((0, T - a.shape[0]), (0, 0)))
            return jnp.pad(a, ((0, 0), (0, T - a.shape[1])))  # [P, Tx] -> [P, T]

        # req/anti hit rows binarize (only `>0` is ever tested; the
        # incremental mirror ships them as decrement-able counts) — required
        # for the linearized disregard contraction, which needs 0/1 values
        hits0 = jnp.stack([
            (padT(t["req_hit0"]) > 0).astype(jnp.float32),
            jnp.zeros((T, N), jnp.float32),
            (padT(t["anti_hit0"]) > 0).astype(jnp.float32),
            jnp.zeros((T, N), jnp.float32),
            padT(t["pref_hit0"]), jnp.zeros((T, N), jnp.float32)])  # [6, T, N]
        # add-rows vs max-rows of the hit pack (static selector)
        hit_is_max = np.asarray([1, 0, 1, 1, 0, 0], bool)[:, None, None]
        topo_stack = jnp.concatenate([
            jnp.pad(t["req_topo"], ((0, T - t["req_topo"].shape[0]), (0, 0))),
            jnp.pad(t["anti_topo"], ((0, T - t["anti_topo"].shape[0]), (0, 0))),
            jnp.pad(t["pref_topo"], ((0, T - t["pref_topo"].shape[0]), (0, 0))),
        ], axis=0)                                            # [3T, K]
        req_nomatch0 = jnp.pad(t["req_nomatch0"],
                               (0, T - t["req_nomatch0"].shape[0]))
        pref_w = jnp.pad(t["pref_w"], (0, T - t["pref_w"].shape[0]))
        node_dom = t["node_dom"]                              # [K, N] i32
        hard_w = t["hard_weight"]
    if use_st:
        T2 = max(t["sym_dom0"].shape[0], t["te_dom0"].shape[0])

        def padT2(a):
            return jnp.pad(a, ((0, T2 - a.shape[0]), (0, 0)))

        static2 = jnp.stack([padT2(t["sym_dom0"]), padT2(t["te_dom0"])])

    # ---- the packed per-pod row (ONE xs leaf sliced per step) --------------
    lay = _Layout()
    pieces = []

    def put(name, arr2d):
        lay.add(name, arr2d.shape[1])
        pieces.append(arr2d.astype(jnp.float32))

    put("req", t["req"])                                     # 4
    put("nz", t["nonzero_req"])                              # 2
    zero_req = jnp.all(t["req"][:, :3] == 0.0, axis=1)
    put("flags", jnp.stack([
        zero_req.astype(jnp.float32),
        t["pod_valid"].astype(jnp.float32),
        (t["pod_group"] >= 0).astype(jnp.float32),
        jnp.where(t["pod_group"] >= 0, t["pod_group"], null_group
                  ).astype(jnp.float32)], axis=1))           # 4
    put("in_group", jnp.pad(t["pod_in_group"], ((0, 0), (0, 1))))  # G+1
    if use_vocab:
        put("slot_ids", slot_ids)                            # SS
        put("slot_vals", slot_vals)                          # SS
        put("vol_cnt", jnp.stack([
            jnp.sum(t["pod_ebs"], axis=1),
            jnp.sum(t["pod_gce"], axis=1)], axis=1))         # 2
    if use_ip:
        put("req_own", padT(t["req_own"], False))
        put("req_match", padT(t["req_match"].T, False))
        put("anti_own", padT(t["anti_own"], False))
        put("anti_match", padT(t["anti_match"].T, False))
        put("pref_own", padT(t["pref_own"], False))
        put("pref_match", padT(t["pref_match"].T, False))
    if use_st:
        lay.add("sym_match", T2)
        pieces.append(jnp.pad(t["sym_match"].T,
                              ((0, 0), (0, T2 - t["sym_match"].shape[0]))))
        lay.add("te_match", T2)
        pieces.append(jnp.pad(t["te_match"].T,
                              ((0, 0), (0, T2 - t["te_match"].shape[0]))))
    if use_preempt:
        vict_cum = t["vict_cum"]       # [6, KV+1, N] prefix relief per node
        vict_prio = t["vict_prio"]     # [KV, N] sorted victim priorities
        KV = vict_prio.shape[0]
        put("prio", t["pod_priority"][:, None])              # 1
    if use_gang:
        g_null = t["gang_dom0"].shape[0] - 1   # last gang slot = null
        put("gangrow", jnp.stack([
            t["pod_gang"].astype(jnp.float32),
            (t["pod_gang"] < g_null).astype(jnp.float32)], axis=1))  # 2
    prow = jnp.concatenate(pieces, axis=1)                   # [P, W]

    xs = {"prow": prow, "mask": s["mask"]}
    if feats.node_pref:
        xs["pref"] = s["pref_count"]
    if feats.taint_pref:
        xs["taint_pref"] = s["taint_pref_count"]
    if use_image:
        xs["image"] = s["image_score"]

    init = {"nstate": nstate0, "rr": jnp.int32(0)}
    if use_vocab:
        init["vocab"] = vocab0
    if use_ip:
        init["hits"] = hits0
        init["req_nomatch"] = req_nomatch0
    if use_preempt:
        init["evicted"] = jnp.zeros((N,), jnp.float32)
    if use_gang:
        init["gang_dom"] = t["gang_dom0"]          # [GG] i32, -1 = unchosen
        init["gang_failed"] = t["gang_failed0"]    # [GG] f32 flags
        init["gang_delta"] = jnp.zeros_like(nstate0)
        init["cur_gang"] = jnp.int32(g_null)

    wf = {k: np.float32(v) for k, v in w.__dict__.items()}

    def step(carry, x):
        nstate, rr = carry["nstate"], carry["rr"]
        row = x["prow"]
        g = lay.of(row, "flags")[3].astype(jnp.int32)
        req_v = lay.of(row, "req")
        nz_v = lay.of(row, "nz")
        flags = lay.of(row, "flags")
        zero_req_f, valid_f, has_group_f = flags[0], flags[1], flags[2]
        if use_gang:
            grow_v = lay.of(row, "gangrow")
            gid = grow_v[0].astype(jnp.int32)
            is_gang = grow_v[1] > 0
            # gangs are contiguous: a gang-id change means the previous
            # gang is closed (fully placed or already failed) — its delta
            # accumulator resets for the newly-opened gang
            gang_delta = jnp.where(gid != carry["cur_gang"], 0.0,
                                   carry["gang_delta"])
            g_failed = carry["gang_failed"][gid] > 0
            g_dom = carry["gang_dom"][gid]

        # --- dynamic predicates (PodFitsResources) ---------------------------
        used = nstate[:4]                   # [4, N]
        used_nz = nstate[4:6]
        pod_count_ok = used[3] + 1.0 <= allocT[3]
        if explain:
            # per-resource rows: pc & (z|c) & (z|m) & (z|g) distributes to
            # pc & (z | (c&m&g)) for booleans, so the final mask is
            # bit-identical to the fused form below — each row is one
            # elimination bucket (Too many pods / Insufficient cpu/mem/gpu)
            surv_rows = []
            mask = x["mask"]

            def narrow(m):
                nonlocal mask
                if m is not None:
                    mask = mask & m
                surv_rows.append(mask)

            narrow(pod_count_ok)
            for r in range(3):
                narrow((zero_req_f > 0)
                       | (used[r] + req_v[r] <= allocT[r]))
        else:
            res_fit = jnp.all(used[:3] + req_v[:3, None] <= allocT[:3], axis=0)
            mask = x["mask"] & pod_count_ok & ((zero_req_f > 0) | res_fit)
        if use_preempt:
            # everything preemption can't relieve: the full mask EXCEPT the
            # resource rows (victim eviction frees cpu/mem/gpu/pod-slots
            # only; ports/disks/affinity keep their current-state verdicts)
            nonres = x["mask"]

        # --- vocab features: ports + volumes (predicates.go:64-269,687) ------
        if use_vocab:
            vocab = carry["vocab"]
            sids = lay.of(row, "slot_ids").astype(jnp.int32)   # [SS]
            svals = lay.of(row, "slot_vals")                   # [SS]
            cols = vocab[chan_idx, sids, :]                    # [SS, N]
            port_clash = jnp.zeros((N,), jnp.float32)
            disk_clash = jnp.zeros((N,), jnp.float32)
            ebs_hit = jnp.zeros((N,), jnp.float32)
            gce_hit = jnp.zeros((N,), jnp.float32)
            for si, ch in enumerate(chan_idx):
                if ch == _CH_PORTS:
                    port_clash = port_clash + cols[si]
                elif ch == _CH_DANY:
                    # node-any column x pod rw flag (the rw slot value
                    # directly follows in the slot list)
                    disk_clash = disk_clash + cols[si] * svals[si + 1]
                elif ch == _CH_DRW:
                    # node-rw column x pod any flag
                    disk_clash = disk_clash + cols[si] * svals[si - 1]
                elif ch == _CH_EBS:
                    ebs_hit = ebs_hit + cols[si]
                else:
                    gce_hit = gce_hit + cols[si]
            if feats.ports:
                port_ok = port_clash == 0.0
                mask = mask & port_ok
                if use_preempt:
                    nonres = nonres & port_ok
            if explain:
                surv_rows.append(mask)          # row: host ports
            if feats.disk:
                disk_ok = disk_clash == 0.0
                mask = mask & disk_ok
                if use_preempt:
                    nonres = nonres & disk_ok
            if explain:
                surv_rows.append(mask)          # row: disk conflict
            if feats.ebs:
                cnt_e = lay.of(row, "vol_cnt")[0]
                union = nstate[_R_EBS] + cnt_e - ebs_hit
                ebs_ok = (cnt_e == 0.0) | (union <= t["max_ebs"])
                mask = mask & ebs_ok
                if use_preempt:
                    nonres = nonres & ebs_ok
            if feats.gce:
                cnt_g = lay.of(row, "vol_cnt")[1]
                union = nstate[_R_GCE] + cnt_g - gce_hit
                gce_ok = (cnt_g == 0.0) | (union <= t["max_gce"])
                mask = mask & gce_ok
                if use_preempt:
                    nonres = nonres & gce_ok
            if explain:
                surv_rows.append(mask)          # row: attach-count caps
        elif explain:
            # no vocab carries traced: zero eliminations on these rows
            surv_rows.extend([mask, mask, mask])

        # --- inter-pod affinity: mask + score in two contractions ------------
        # (predicates.go:769-921, interpod_affinity.go:86-216)
        viol = None
        c = None
        if use_ip:
            hits = carry["hits"]
            req_own_v = lay.of(row, "req_own")
            req_match_v = lay.of(row, "req_match")
            anti_own_v = lay.of(row, "anti_own")
            anti_match_v = lay.of(row, "anti_match")
            pref_own_v = lay.of(row, "pref_own")
            pref_match_v = lay.of(row, "pref_match")
            # disregard rule: own @ (1-(hit|dis)) == (own·(1-dis)) @ (1-hit)
            # for binary hit/dis (predicates.go:818-844)
            disregard = ((req_match_v > 0) & carry["req_nomatch"]
                         ).astype(jnp.float32)
            own_eff = req_own_v * (1.0 - disregard)            # [T]
            lhs6 = jnp.stack([
                -own_eff,                    # row0: req violations (negated)
                hard_w * req_match_v,        # row1: reverse-hard score
                anti_own_v,                  # row2: anti violations
                anti_match_v,                # row3: in-batch symmetry
                pref_own_v * pref_w,         # row4: forward preferred score
                pref_match_v,                # row5: reverse preferred score
            ])[:, None, :]                                     # [6, 1, T]
            ip6 = jax.lax.dot_general(
                lhs6, hits, (((2,), (1,)), ((0,), (0,))))[:, 0, :]  # [6, N]
            viol = jnp.sum(own_eff) + ip6[0] + ip6[2] + ip6[3]
            c = ip6[1] + ip6[4] + ip6[5]
        if use_st:
            lhs2 = jnp.stack([lay.of(row, "sym_match"),
                              lay.of(row, "te_match")])[:, None, :]
            ip2 = jax.lax.dot_general(
                lhs2, static2, (((2,), (1,)), ((0,), (0,))))[:, 0, :]  # [2, N]
            viol = ip2[0] if viol is None else viol + ip2[0]
            c = ip2[1] if c is None else c + ip2[1]
        if viol is not None:
            ip_ok = viol == 0.0
            mask = mask & ip_ok
            if use_preempt:
                nonres = nonres & ip_ok
        if explain:
            surv_rows.append(mask)              # row: inter-pod affinity
        if use_gang:
            # gang members only land on nodes carrying the topology label,
            # inside the domain the gang's first member chose; members of
            # an already-failed gang are masked out entirely
            gang_allow = jnp.where(
                is_gang,
                (t["node_gang_dom"] >= 0)
                & ((g_dom < 0) | (t["node_gang_dom"] == g_dom))
                & jnp.logical_not(g_failed),
                True)
            mask = mask & gang_allow
            if use_preempt:
                nonres = nonres & gang_allow
            if explain:
                surv_rows.append(mask)          # row: gang topology
        if explain:
            # the ONE stacked masked reduction: cumulative masks -> counts
            # (8 rows; 9 with the gang-topology row)
            dyn_surv = jnp.sum(
                jnp.stack([r.astype(jnp.float32) for r in surv_rows]),
                axis=1)

        # --- dynamic scores --------------------------------------------------
        tot_c = used_nz[0] + nz_v[0]
        tot_m = used_nz[1] + nz_v[1]
        cpu_sc = jnp.where((cap_c > 0) & (tot_c <= cap_c),
                           jnp.floor((cap_c - tot_c) * 10.0 / cap_c), 0.0)
        mem_sc = jnp.where((cap_m > 0) & (tot_m <= cap_m),
                           jnp.floor((cap_m - tot_m) * 10.0 / cap_m), 0.0)
        least = jnp.floor((cpu_sc + mem_sc) / 2.0)

        frac_c = jnp.where(cap_c > 0, tot_c / cap_c, 1.0)
        frac_m = jnp.where(cap_m > 0, tot_m / cap_m, 1.0)
        balanced = jnp.where((frac_c >= 1.0) | (frac_m >= 1.0), 0.0,
                             jnp.floor(10.0 - jnp.abs(frac_c - frac_m) * 10.0))

        # spread counts for this pod's group (null row when none)
        counts = jax.lax.dynamic_slice(
            nstate, (_R_G0 + g, jnp.int32(0)), (1, N))[0]
        zsum = zone_onehot_t @ jnp.where(mask, counts, 0.0)    # [Z]
        node_zc = zsum @ zone_onehot_t                         # [N]

        # --- ONE stacked masked reduction for all per-step maxima ------------
        maskf = mask
        stack_rows = [
            jnp.where(maskf, counts, NEG),                     # 0: maxc
            jnp.where(maskf & (t["zone_id"] >= 0), node_zc, NEG),  # 1: maxz
            jnp.where(maskf, 1.0, NEG),                        # 2: feasible
            jnp.where(maskf & (t["zone_id"] >= 0), 1.0, NEG),  # 3: have_zones
        ]
        ri = {"maxc": 0, "maxz": 1, "feas": 2, "zones": 3}
        if c is not None:
            ri["ipmax"] = len(stack_rows)
            stack_rows.append(jnp.where(maskf, c, NEG))
            ri["ipmin"] = len(stack_rows)
            stack_rows.append(jnp.where(maskf, -c, NEG))
        if feats.node_pref:
            ri["pref"] = len(stack_rows)
            stack_rows.append(jnp.where(maskf, x["pref"], NEG))
        if feats.taint_pref:
            ri["tp"] = len(stack_rows)
            stack_rows.append(jnp.where(maskf, x["taint_pref"], NEG))
        mx = jnp.max(jnp.stack(stack_rows), axis=1)            # [rows]

        feasible = (mx[ri["feas"]] > 0.0) & (valid_f > 0)
        maxc = jnp.maximum(mx[ri["maxc"]], 0.0)
        fscore = jnp.where(maxc > 0.0, 10.0 * (maxc - counts) / maxc, 10.0)
        maxz = jnp.maximum(mx[ri["maxz"]], 0.0)
        zscore = jnp.where(maxz > 0.0, 10.0 * (maxz - node_zc) / maxz, 10.0)
        have_zones = mx[ri["zones"]] > 0.0
        has_group = has_group_f > 0
        blend = jnp.where((t["zone_id"] >= 0) & has_group & have_zones
                          & (maxz > 0.0),
                          fscore * (1.0 / 3.0) + (2.0 / 3.0) * zscore, fscore)
        spread = jnp.floor(jnp.where(has_group, blend, 10.0))

        # weighted per-component contributions; `comps` (explain only)
        # mirrors explain_component_names order for the host decode
        comps = []
        c_lr = wf["least_requested"] * least
        c_ba = wf["balanced"] * balanced
        c_sp = wf["spread"] * spread
        if explain:
            comps += [c_lr, c_ba, c_sp]
        score = c_lr + c_ba + c_sp + wf["equal"] * 1.0
        if feats.node_pref:
            max_pref = mx[ri["pref"]]
            c_na = wf["node_affinity"] * jnp.where(
                max_pref > 0.0, jnp.floor(10.0 * x["pref"] / max_pref), 0.0)
            score = score + c_na
            if explain:
                comps.append(c_na)
        if feats.taint_pref:
            max_tp = mx[ri["tp"]]
            c_tt = wf["taint_toleration"] * jnp.where(
                max_tp > 0.0,
                jnp.floor((1.0 - x["taint_pref"] / max_tp) * 10.0), 10.0)
            score = score + c_tt
            if explain:
                comps.append(c_tt)
        else:
            # constant 10 for every feasible node — shifts all candidates
            # equally, so the argmax/tie set is unchanged; omitted (the
            # explain decode reconstructs the constant host-side)
            pass
        if c is not None:
            ip_max = jnp.maximum(mx[ri["ipmax"]], 0.0)
            ip_min = jnp.minimum(-mx[ri["ipmin"]], 0.0)
            ip_rng = ip_max - ip_min
            c_ip = wf["interpod_affinity"] * jnp.where(
                ip_rng > 0.0, jnp.floor(10.0 * (c - ip_min) / ip_rng), 0.0)
            score = score + c_ip
            if explain:
                comps.append(c_ip)
        if use_image:
            c_im = wf["image_locality"] * x["image"]
            score = score + c_im
            if explain:
                comps.append(c_im)
        if use_binpack:
            # MostRequested fragmentation minimizer ("Priority Matters"):
            # floor(used*10/cap) per resource, cpu/mem averaged — the exact
            # integer-truncation mirror of _calculate_score inverted
            bcpu = jnp.where((cap_c > 0) & (tot_c <= cap_c),
                             jnp.floor(tot_c * 10.0 / cap_c), 0.0)
            bmem = jnp.where((cap_m > 0) & (tot_m <= cap_m),
                             jnp.floor(tot_m * 10.0 / cap_m), 0.0)
            c_bp = np.float32(obj.binpack_weight) * jnp.floor(
                (bcpu + bmem) / 2.0)
            score = score + c_bp
            if explain:
                comps.append(c_bp)

        # --- selectHost: max + round-robin tie-break -------------------------
        masked_score = jnp.where(mask, score, NEG)
        max_score = jnp.max(masked_score)
        is_max = mask & (masked_score == max_score)
        cum = jnp.cumsum(is_max.astype(jnp.int32))
        n_ties = cum[N - 1]
        k = jnp.where(n_ties > 0, rr % jnp.maximum(n_ties, 1), 0)
        chosen = jnp.argmax(is_max & (cum == k + 1)).astype(jnp.int32)
        chosen = jnp.where(feasible, chosen, jnp.int32(-1))

        # --- objective: gang all-or-nothing rollback -------------------------
        if use_gang:
            # a gang member with zero feasible nodes fails its whole gang:
            # every prior member's nstate delta reverses inside the scan, so
            # subsequent (non-gang) pods see the freed capacity
            fail_now = is_gang & jnp.logical_not(g_failed) \
                & jnp.logical_not(feasible)
            failf = fail_now.astype(jnp.float32)
            nstate = nstate - gang_delta * failf
            gang_delta = gang_delta * (1.0 - failf)

        # --- objective: priority preemption (masked argmin victim select) ----
        if use_preempt:
            can_p = jnp.logical_not(feasible) & (valid_f > 0)
            if use_gang:
                can_p = can_p & jnp.logical_not(is_gang)  # gangs never preempt
            pod_prio = lay.of(row, "prio")[0]
            ev = carry["evicted"]                         # [N] f32 exact ints
            kr = jnp.arange(KV + 1, dtype=jnp.float32)    # victim counts 0..KV
            # prefix gathers offset by the victims this solve already
            # evicted per node: relief of k MORE victims = cum[e+k] - cum[e]
            idx = jnp.clip(ev[None, :] + kr[:, None], 0.0,
                           np.float32(KV)).astype(jnp.int32)       # [KV+1, N]
            cum_k = jnp.take_along_axis(vict_cum, idx[None, :, :], axis=1)
            cum_e = jnp.take_along_axis(
                vict_cum, ev.astype(jnp.int32)[None, None, :], axis=1)
            relief = cum_k - cum_e                        # [6, KV+1, N]
            # the k-th victim's priority gates k: sorted ascending, so all k
            # victims are strictly lower-priority iff the k-th one is
            # (never preempt equal-or-higher — reference pod-priority rule);
            # INF padding keeps k beyond the candidate list ineligible
            jp_ = jnp.clip(ev[None, :] + kr[:, None] - 1.0, 0.0,
                           np.float32(KV - 1)).astype(jnp.int32)
            top_prio = jnp.take_along_axis(vict_prio, jp_, axis=0)  # [KV+1, N]
            okk = (kr[:, None] >= 1.0) & (top_prio < pod_prio)
            fit = (used[3][None, :] - relief[3] + 1.0
                   <= allocT[3][None, :]) & nonres[None, :]
            for r in range(3):
                fit = fit & ((zero_req_f > 0)
                             | (used[r][None, :] - relief[r] + req_v[r]
                                <= allocT[r][None, :]))
            BIGK = np.float32(1e9)
            kcand = jnp.where(fit & okk, kr[:, None], BIGK)
            kmin = jnp.min(kcand, axis=0)                 # [N] min victims
            has = kmin < BIGK
            # nominated node = lexicographic argmin of (highest victim
            # priority, victim count, canonical node order)
            jsel = jnp.clip(ev + kmin - 1.0, 0.0,
                            np.float32(KV - 1)).astype(jnp.int32)
            topsel = jnp.take_along_axis(vict_prio, jsel[None, :], axis=0)[0]
            m1 = jnp.min(jnp.where(has, topsel, np.float32(1e18)))
            elig2 = has & (topsel == m1)
            m2 = jnp.min(jnp.where(elig2, kmin, BIGK))
            pnode = jnp.argmax(elig2 & (kmin == m2)).astype(jnp.int32)
            do_p = can_p & jnp.any(has)
            do_pf = do_p.astype(jnp.float32)
            k_sel = jnp.where(do_p, m2, 0.0)
            m2i = jnp.clip(m2, 0.0, np.float32(KV)).astype(jnp.int32)
            rel_col = jax.lax.dynamic_slice(
                relief, (0, 0, pnode), (6, KV + 1, 1))[:, :, 0]   # [6, KV+1]
            rel_sel = jax.lax.dynamic_slice(
                rel_col, (jnp.int32(0), m2i), (6, 1))[:, 0] * do_pf
            ponehot = (idx_n == pnode).astype(jnp.float32) * do_pf
            # relieve the victims' resource occupancy (used + used_nz rows)
            # and commit the preemptor at the nominated node below
            nstate = nstate - jnp.concatenate(
                [rel_sel, jnp.zeros((nstate.shape[0] - 6,), jnp.float32)]
            )[:, None] * ponehot[None, :]
            chosen = jnp.where(do_p, pnode, chosen)

        # --- commit (the on-device AssumePod) --------------------------------
        commit = (feasible | do_p) if use_preempt else feasible
        commitf = commit.astype(jnp.float32)
        safe = jnp.maximum(chosen, 0)
        onehot = ((idx_n == safe).astype(jnp.float32)) * commitf

        if use_vocab:
            col_at = jax.lax.dynamic_slice(
                cols, (0, safe), (cols.shape[0], 1))[:, 0]     # [SS]
            if feats.ebs:
                ebs_at = jnp.sum(jnp.where(chan_idx == _CH_EBS, col_at, 0.0))
                ebs_inc = (cnt_e - ebs_at) * commitf
            else:
                ebs_inc = 0.0
            if feats.gce:
                gce_at = jnp.sum(jnp.where(chan_idx == _CH_GCE, col_at, 0.0))
                gce_inc = (cnt_g - gce_at) * commitf
            else:
                gce_inc = 0.0
        else:
            ebs_inc = gce_inc = 0.0

        inc = jnp.concatenate([
            req_v, nz_v,
            jnp.stack([jnp.asarray(ebs_inc, jnp.float32),
                       jnp.asarray(gce_inc, jnp.float32)]),
            lay.of(row, "in_group")]) * commitf                # [R]
        out = {"nstate": nstate + inc[:, None] * onehot[None, :],
               "rr": rr + commit.astype(jnp.int32)}
        if use_preempt:
            out["evicted"] = ev + k_sel * ponehot
        if use_gang:
            # a member commit accumulates its exact nstate delta into the
            # open gang's rollback buffer; the first commit pins the gang's
            # topology domain
            out["gang_delta"] = gang_delta + (
                inc[:, None] * onehot[None, :]) * jnp.where(is_gang, 1.0, 0.0)
            out["gang_failed"] = carry["gang_failed"].at[gid].max(failf)
            new_dom = jnp.where((g_dom < 0) & commit & is_gang,
                                t["node_gang_dom"][safe], g_dom)
            out["gang_dom"] = carry["gang_dom"].at[gid].set(new_dom)
            out["cur_gang"] = gid

        if use_vocab:
            out["vocab"] = vocab.at[chan_idx, sids, safe].max(svals * commitf)

        if use_ip:
            dom_c = jax.lax.dynamic_slice(
                node_dom, (0, safe), (node_dom.shape[0], 1))   # [K, 1]
            eq = (((node_dom == dom_c) & (node_dom >= 0))
                  .astype(jnp.float32) * commitf)              # [K, N]
            dm3 = ((topo_stack @ eq) > 0).astype(jnp.float32)  # [3T, N]
            dm6 = jnp.repeat(dm3.reshape(3, T, N), 2, axis=0)  # [6, T, N]
            coef6 = jnp.stack([
                req_match_v,                  # row0 req_hit (max)
                req_own_v,                    # row1 hw_dyn (add)
                anti_match_v,                 # row2 anti_hit (max)
                (anti_own_v > 0).astype(jnp.float32),  # row3 sym_dyn (max)
                pref_match_v,                 # row4 pref_hit (add)
                pref_own_v * pref_w,          # row5 te_dyn (add)
            ])                                                 # [6, T]
            U = coef6[:, :, None] * dm6
            out["hits"] = jnp.where(hit_is_max,
                                    jnp.maximum(hits, U), hits + U)
            out["req_nomatch"] = carry["req_nomatch"] & ~(
                (req_match_v > 0) & commit)

        if not explain and not obj_on:
            return out, chosen

        if explain:
            # --- explain extras: winner/runner-up score decomposition --------
            comp_stack = jnp.stack(comps)                      # [C, N]
            Cn = comp_stack.shape[0]
            win_comp = jax.lax.dynamic_slice(
                comp_stack, (0, safe), (Cn, 1))[:, 0]          # [C]
            # runner-up: best masked score excluding the winner (NEG when the
            # feasible set has no second node — decoded to "no runner-up")
            run_masked = jnp.where(idx_n == safe, NEG, masked_score)
            run_total = jnp.max(run_masked)
            run_idx = jnp.argmax(run_masked).astype(jnp.int32)
            run_comp = jax.lax.dynamic_slice(
                comp_stack, (0, run_idx), (Cn, 1))[:, 0]
            extras = {
                "surv": dyn_surv, "win_comp": win_comp,
                "win_total": max_score, "run_idx": run_idx,
                "run_total": run_total, "run_comp": run_comp,
            }
        if not obj_on:
            return out, (chosen, extras)
        objy = {}
        if use_preempt:
            objy["pk"] = k_sel.astype(jnp.int32)
        if explain:
            return out, (chosen, objy, extras)
        return out, (chosen, objy)

    from types import SimpleNamespace
    ctx = SimpleNamespace(
        obj_on=obj_on, use_gang=use_gang, use_preempt=use_preempt,
        use_binpack=use_binpack, use_ip=use_ip, use_st=use_st,
        use_vocab=use_vocab, use_image=use_image, explain=explain,
        feats=feats, obj=obj, wf=wf, lay=lay, N=N, G=G, Z=Z,
        null_group=null_group, idx_n=idx_n, allocT=allocT,
        cap_c=cap_c, cap_m=cap_m, zone_onehot_t=zone_onehot_t,
        zone_id=t["zone_id"],
        chan_idx=chan_idx if use_vocab else None,
        SS=SS,
        max_ebs=t.get("max_ebs"), max_gce=t.get("max_gce"),
        T=T if use_ip else 0,
        topo_stack=topo_stack if use_ip else None,
        hit_is_max=hit_is_max if use_ip else None,
        node_dom=node_dom if use_ip else None,
        hard_w=hard_w if use_ip else None,
        pref_w=pref_w if use_ip else None,
        static2=static2 if use_st else None,
        KV=KV if use_preempt else 0,
        g_null=g_null if use_gang else 0,
        node_gang_dom=t["node_gang_dom"] if use_gang else None,
    )
    return step, xs, init, ctx


def greedy_commit(t: dict, s: dict, w: Weights, feats: Features,
                  explain: bool = False,
                  obj: Optional[ObjectiveConfig] = None):
    """lax.scan over pods; returns assignments [P] i32 (-1 = unschedulable).

    Exactly the reference's sequential semantics (scheduler.go:93-155 one
    pod at a time over generic_scheduler.go:70-133), with the per-step work
    packed into ~25 fused ops (see module docstring).

    With explain, additionally returns a dict of per-pod provenance emitted
    straight from the scan — (assignments, extras) instead of assignments:

    - ``surv`` [P, 8]: cumulative surviving-node counts after each dynamic
      predicate (pod-count, cpu, mem, gpu, ports, disk, volume-caps,
      inter-pod), continuing the static chain from static_pass — ONE
      stacked masked reduction over the mask ingredients the step already
      computed, never a [P, N, K] tensor. Rows for untraced features repeat
      the previous count (zero eliminations), keeping the axis static.
    - ``win_comp`` [P, C] / ``win_total`` [P]: the weighted score
      decomposition at the chosen node (component order:
      explain_component_names) and its total.
    - ``run_idx`` / ``run_total`` / ``run_comp``: the runner-up node (max
      score excluding the winner; NEG total = no second feasible node).

    When explain is off this function traces the exact program it always
    has — the flag is a static jit key, so `off` is bit-identical to
    today's assignments, and `on` only ADDS reductions (the mask and score
    math feeding the argmax is shared, also bit-identical).

    With `obj` (an enabled ObjectiveConfig — also a static jit key, so the
    default/None path is the untouched pre-objective program), the scan
    additionally solves the scheduling-objective modes in-step:

    - binpack: a MostRequested fragmentation score component;
    - preempt: a pod with zero feasible nodes nominates victims as a masked
      argmin over (victim priority, victim count, node order) against the
      per-node sorted victim prefix tables (vict_prio/vict_cum), relieves
      the victims' resource occupancy in-carry, and commits at the
      nominated node; per-pod victim counts stream out as `pk`;
    - gang: gang members (contiguous in pod order — objectives.gang_order)
      are masked to nodes sharing one topology-label domain, commit deltas
      accumulate in a per-open-gang carry, and a member with zero feasible
      nodes rolls the whole gang's nstate deltas back inside the scan and
      marks the gang failed (all-or-nothing — the host decode nullifies the
      already-emitted member assignments). Port/affinity-hit shadows from
      rolled-back members deliberately persist until the next batch
      (conservative; state is rebuilt per batch), and gang members never
      preempt — both mirrored exactly by the oracle replay."""
    step, xs, init, _ = build_program(t, s, w, feats, explain, obj)
    obj_on = obj is not None and obj.enabled
    use_gang = obj_on and obj.gang

    # unroll amortizes per-iteration loop overhead; the body is tiny
    # (elementwise over N + a few [T, N] contractions) so overhead dominates
    if not obj_on:
        if not explain:
            _, assignments = jax.lax.scan(step, init, xs, unroll=8)
            return assignments
        _, (assignments, extras) = jax.lax.scan(step, init, xs, unroll=8)
        return assignments, extras
    carry_f, ys = jax.lax.scan(step, init, xs, unroll=8)
    if explain:
        assignments, objy, extras = ys
    else:
        assignments, objy = ys
    objout = dict(objy)
    if use_gang:
        objout["gang_failed"] = carry_f["gang_failed"]
    if explain:
        return assignments, objout, extras
    return assignments, objout


# --- public API ---------------------------------------------------------------

# integer fields that stay integral on device (indices, not indicators)
_INT_FIELDS = frozenset(("zone_id", "host_req", "node_dom", "pod_group",
                         "pod_gang", "node_gang_dom", "gang_dom0"))


# wave-commit solve (ops/wave.py): default chunk width and the env seam.
# KTPU_WAVE=0 forces the serial per-pod scan; KTPU_WAVE_CHUNK tunes the
# per-wave decide width (the parallel pod-axis slab each wave considers).
WAVE_CHUNK = 512


def resolve_wave(wave=None, n_pods: Optional[int] = None) -> int:
    """Resolve a wave selector to a static chunk size (0 = serial scan).

    None consults KTPU_WAVE / KTPU_WAVE_CHUNK (wave commit is the default
    solve path); True selects the default chunk; an int is the chunk.

    In the automatic (None) mode, batches below KTPU_WAVE_MIN pods
    (default 256) take the serial scan: a handful of scan steps beats the
    wave program's chunked decide there, and small batches dominate test
    suites and light traffic — the wave machinery is for the shapes where
    the serial dimension is the wall. An explicit `wave` always wins."""
    import os
    if wave is None:
        if os.environ.get("KTPU_WAVE", "1") in ("0", "off", "false"):
            return 0
        if n_pods is not None and n_pods < int(
                os.environ.get("KTPU_WAVE_MIN", 256)):
            return 0
        return int(os.environ.get("KTPU_WAVE_CHUNK", WAVE_CHUNK))
    if wave is True:
        return WAVE_CHUNK
    return int(wave)


@functools.partial(jax.jit,
                   static_argnames=("n_zones", "weights", "feats", "explain",
                                    "objective", "wave"))
def _schedule_jit(tensors: dict, n_zones: int, weights: Weights,
                  feats: Features, explain: bool = False,
                  objective: Optional[ObjectiveConfig] = None,
                  wave: int = 0):
    # indicator/count matrices may arrive packed (int8/int16/int32 — 4x less
    # upload traffic than f32, ops/incremental.py); widen on-device where
    # the MXU wants floats. XLA fuses the casts into the consumers.
    t = {}
    for k, v in tensors.items():
        if (k in _INT_FIELDS or v.dtype == jnp.bool_
                or jnp.issubdtype(v.dtype, jnp.floating)):
            t[k] = v
        else:
            t[k] = v.astype(jnp.float32)
    t["n_zones"] = n_zones
    s = static_pass(t, feats, weights, explain=explain)
    obj_on = objective is not None and objective.enabled
    if wave:
        # wave-commit solve: same outputs as the serial branches below
        # (bit-identical — tests/test_wave_parity.py), wrapped as
        # (ret, wave_count) with wave_count a traced i32 scalar
        from kubernetes_tpu.ops.wave import wave_commit
        # `wave` is a static jit argument (a Python int at trace time)
        ret, waves = wave_commit(t, s, weights, feats, explain=explain,
                                 obj=objective if obj_on else None,
                                 chunk=wave)
        if explain:
            ret[-1]["static_surv"] = s["static_surv"]
        return ret, waves
    if not obj_on:
        if not explain:
            return greedy_commit(t, s, weights, feats)
        assignments, extras = greedy_commit(t, s, weights, feats, explain=True)
        extras["static_surv"] = s["static_surv"]
        return assignments, extras
    ret = greedy_commit(t, s, weights, feats, explain=explain, obj=objective)
    if not explain:
        return ret
    assignments, objout, extras = ret
    extras["static_surv"] = s["static_surv"]
    return assignments, objout, extras


def assignments_to_names(out: np.ndarray,
                         ct: ClusterTensors) -> List[Optional[str]]:
    """Decode kernel output ([P] node indices, -1 = unschedulable) to node
    names — the ONE decoder shared by the unsharded, sharded, and
    incremental paths, so equivalence tests compare kernels, not decoders.
    Handles both dense node_names (full Tensorizer) and slot-indexed lists
    with empty holes (incremental mirror)."""
    result: List[Optional[str]] = []
    for i in range(ct.n_real_pods):
        n = int(out[i])
        name = ct.node_names[n] if 0 <= n < len(ct.node_names) else ""
        result.append(name or None)
    return result


def unpermute_result(ret, perm: List[int]):
    """Map a gang-ordered solve result back to the caller's pending order.

    `perm` is objectives.gang_order's permutation (ordered[j] ==
    pending[perm[j]]); only the positional names list needs re-mapping —
    DecisionRecords and ObjectiveOutcomes are keyed by pod, not position."""
    def back(names):
        out: List[Optional[str]] = [None] * len(perm)
        for j, i in enumerate(perm):
            out[i] = names[j]
        return out

    if isinstance(ret, tuple):
        return (back(ret[0]),) + ret[1:]
    return back(ret)


# static dispatch keys already traced in this process: the first dispatch
# for a key pays the XLA compile and is attributed to the "compile" stage
# (and classified against the persistent compile cache); repeats are "solve"
_DISPATCHED: set = set()


def _dispatch_key(arrays: dict, n_zones: int, weights: Weights,
                  feats: Features, explain: bool = False,
                  objective: Optional[ObjectiveConfig] = None,
                  wave: int = 0) -> tuple:
    shapes = tuple(sorted((k, tuple(v.shape), str(v.dtype))
                          for k, v in arrays.items()))
    return shapes, n_zones, weights, feats, explain, objective, wave


def dispatch(arrays: dict, n_zones: int, weights: Weights, feats: Features,
             stage=None, explain: bool = False,
             objective: Optional[ObjectiveConfig] = None, wave: int = 0):
    """Run the jit'd solve with host materialization as the sync barrier.

    `stage(name, fn)` (the watchdog/span hook, ops/watchdog.run_stages) sees
    the dispatch as stage "compile" the first time a static shape is traced
    — with a compile-cache hit/miss event recorded, fingerprint-labeled —
    and as stage "solve" afterwards.

    The stage wall time is additionally split into host vs device
    components (`scheduler_kernel_device_seconds{stage,component}`,
    observability/profiling.py): the async `_schedule_jit` call returning
    bounds the host side (trace / lower / compile / dispatch), and the
    blocking materialization — which cannot complete until the scan has
    run on device — is the device side."""
    import time as _time

    from kubernetes_tpu.observability import profiling
    from kubernetes_tpu.utils import platform as plat

    key = _dispatch_key(arrays, n_zones, weights, feats, explain, objective,
                        wave)
    first = key not in _DISPATCHED
    name = "compile" if first else "solve"

    def _run():
        before = plat.compile_cache_snapshot() if first else None
        t0 = _time.perf_counter()
        pending = _schedule_jit(arrays, n_zones, weights, feats, explain,
                                objective, wave)
        t_host = _time.perf_counter()
        # device execution + D2H, the sync barrier (every leaf when explain)
        out = jax.tree_util.tree_map(np.asarray, pending)
        profiling.record_dispatch(name, t_host - t0,
                                  _time.perf_counter() - t_host)
        if first:
            plat.record_compile_cache_event(before)
        return out

    run = stage or (lambda _n, fn: fn())
    out = run(name, _run)
    _DISPATCHED.add(key)
    return out


def record_wave_count(out, wave: int):
    """Split a wave dispatch's (ret, wave_count) pair, export the count as
    the scheduler_kernel_wave_count gauge, and hand back the serial-shaped
    ret. Pass-through when the serial path ran."""
    if not wave:
        return out
    ret, waves = out
    from kubernetes_tpu.utils.metrics import REGISTRY as METRICS
    METRICS.set_gauge("scheduler_kernel_wave_count", float(waves))
    return ret


def schedule_batch(ct: ClusterTensors, weights: Optional[Weights] = None,
                   device=None, stage=None, explain: bool = False,
                   objective: Optional[ObjectiveConfig] = None,
                   wave=None):
    """Schedule a tensorized batch; returns node name (or None) per pending
    pod, FIFO order. With explain, returns (names, decision records) — the
    records carry per-predicate survivor counts and winner/runner-up score
    decompositions decoded by observability/explain.py.

    With an enabled objective (the ct must have been tensorized with the
    same config), the return grows an ObjectiveOutcome:
    (names, outcome) or (names, records, outcome) — preempted pods and
    rejected-gang members read as unplaced in `names`, with the nominated
    node / victim sets / gang verdicts on the outcome."""
    weights = weights or Weights()
    feats = features_of(ct)
    run = stage or (lambda _n, fn: fn())
    from kubernetes_tpu.scheduler.objectives.config import resolve_objective
    objective = resolve_objective(objective)
    wave = resolve_wave(wave, n_pods=ct.n_real_pods)

    def _upload():
        import time as _time

        from kubernetes_tpu.observability import profiling
        t0 = _time.perf_counter()
        arrays = {k: jnp.asarray(v) for k, v in ct.arrays().items()}
        if device is not None:
            arrays = jax.device_put(arrays, device)
        t_submit = _time.perf_counter()
        # materialize the transfer inside the upload stage (same contract
        # as IncrementalTensorizer._upload_staged: a hung H2D copy is an
        # upload timeout, not a solve timeout)
        jax.block_until_ready(arrays)
        profiling.record_dispatch("upload", t_submit - t0,
                                  _time.perf_counter() - t_submit)
        return arrays

    arrays = run("upload", _upload)
    out = dispatch(arrays, ct.n_zones, weights, feats, stage=stage,
                   explain=explain, objective=objective, wave=wave)
    out = record_wave_count(out, wave)
    return decode_dispatch(ct, out, weights, feats, explain, objective)


def decode_dispatch(ct: ClusterTensors, out, weights: Weights,
                    feats: Features, explain: bool,
                    objective: Optional[ObjectiveConfig] = None):
    """Shared host decode for the full and incremental paths: assignments ->
    names, explain extras -> DecisionRecords, objective outputs ->
    ObjectiveOutcome (with the all-or-nothing / nominated-not-bound view
    applied to names)."""
    if objective is None:
        if not explain:
            return assignments_to_names(out, ct)
        out, extras = out
        names = assignments_to_names(out, ct)
        from kubernetes_tpu.observability.explain import decode_batch
        return names, decode_batch(ct, out, extras, weights, feats)
    from kubernetes_tpu.scheduler.objectives.decode import decode_objective
    if explain:
        out, objout, extras = out
    else:
        out, objout = out
    names = assignments_to_names(out, ct)
    outcome = decode_objective(ct, out, objout, objective, names)
    if not explain:
        return names, outcome
    from kubernetes_tpu.observability.explain import decode_batch
    from kubernetes_tpu.scheduler.objectives.decode import annotate_records
    records = decode_batch(ct, out, extras, weights, feats,
                           objective=objective)
    annotate_records(records, outcome)
    return names, records, outcome
