"""Kernel-stage watchdogs: convert a hang into a labeled timeout.

Round-5 postmortem: the flagship bench loaded an AOT compile-cache entry
built for different machine features and the CPU fallback then sat wedged
for 600 s with no indication of WHERE (tensorize? upload? compile? solve?).
A hung XLA/axon call cannot be interrupted from Python, so the watchdog
inverts control instead: the staged pipeline runs on a disposable daemon
thread that records which named stage it is inside, and the CALLING thread
enforces each stage's deadline.  On violation the caller gets a structured
`StageTimeout` naming the stage (and the `scheduler_stage_timeout_total`
counter ticks) while the zombie worker is abandoned — the scheduler then
takes its normal device-error fallback path instead of wedging.

Stage durations are exported to `scheduler_stage_seconds{stage=...}`, which
is also where bench.py sources its per-stage breakdown.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from kubernetes_tpu.utils.metrics import REGISTRY as METRICS

STAGE_METRIC = "scheduler_stage_seconds"
TIMEOUT_METRIC = "scheduler_stage_timeout_total"

# generous production defaults (bench.py historically used the same orders
# of magnitude for its own hang guards); tests inject tiny ones. A None
# deadline disarms the watchdog for that stage: tensorize is host-side
# Python that runs WHILE HOLDING the mirror lock, so abandoning it on a
# deadline would strand the lock every cache listener needs (the contract
# below) — and a slow-but-progressing host build misclassified as a device
# error would be a false degradation. The device-risk stages
# (upload/compile/solve) run lock-free and stay deadlined.
DEFAULT_DEADLINES: Dict[str, Optional[float]] = {
    "tensorize": None,
    "upload": 300.0,
    "compile": 900.0,
    "solve": 600.0,
}
DEFAULT_STAGE_DEADLINE = 600.0


class StageTimeout(TimeoutError):
    """A named pipeline stage blew its deadline. Subclasses TimeoutError so
    the scheduler's failure classifier treats it as a (possibly transient)
    device-side fault: backoff + sequential fallback, never a silent wedge."""

    def __init__(self, stage: str, deadline: float):
        self.stage = stage
        self.deadline = deadline
        super().__init__(
            f"kernel stage {stage!r} exceeded its {deadline:g}s deadline")


def run_stages(work: Callable, deadlines: Optional[Dict[str, float]] = None,
               default_deadline: float = DEFAULT_STAGE_DEADLINE,
               registry=METRICS, span=None, poll: float = 0.05):
    """Run `work(stage)` on a daemon worker thread, where `stage(name, fn)`
    executes fn as a named, deadlined, metered pipeline stage.

    The caller blocks until the work completes (its result/exception
    propagates) or the current stage exceeds its deadline — then a
    StageTimeout is raised here and the worker is abandoned (a hung device
    call cannot be killed; a labeled error beats a wedged scheduler).

    CONTRACT: because a timed-out worker is abandoned mid-stage, a stage
    that can hang (any device call) must not hold locks that other threads
    need — an abandoned worker parked inside one would convert the hang
    into a process-wide deadlock (see IncrementalTensorizer.schedule: the
    mirror lock covers host-only staging; upload/solve run lock-free).

    With `span` given, each stage also becomes a child span of it.
    """
    deadlines = deadlines or {}
    state = {"stage": None, "since": 0.0, "child": None}
    state_lock = threading.Lock()
    done = threading.Event()
    box: dict = {}

    def stage(name: str, fn: Callable):
        # every stage is also a jax.profiler TraceAnnotation, so an open
        # /profilez window shows tensorize/upload/compile/solve as named
        # regions (observability/profiling.py; no-op without a profiler)
        from kubernetes_tpu.observability.profiling import annotate
        child = span.child(name) if span is not None else None
        with state_lock:
            state["stage"] = name
            state["since"] = time.monotonic()
            state["child"] = child
        t0 = time.perf_counter()
        try:
            with annotate(f"ktpu:{name}"):
                return fn()
        finally:
            dt = time.perf_counter() - t0
            if registry is not None:
                registry.observe(STAGE_METRIC, dt, stage=name)
            if child is not None:
                child.finish()
            with state_lock:
                state["stage"] = None
                state["child"] = None

    def runner():
        try:
            box["value"] = work(stage)
        except BaseException as e:  # surfaced to the caller below
            box["err"] = e
        finally:
            done.set()

    worker = threading.Thread(target=runner, name="kernel-stages",
                              daemon=True)
    worker.start()
    while not done.wait(poll):
        with state_lock:
            name, since = state["stage"], state["since"]
        if name is None:
            continue
        limit = deadlines.get(name, default_deadline)
        if limit is None:
            continue  # explicitly disarmed (lock-holding host stage)
        if time.monotonic() - since > limit:
            if registry is not None:
                registry.inc(TIMEOUT_METRIC, stage=name)
            if span is not None:
                span.attrs["timeout_stage"] = name
            with state_lock:
                child = state["child"]
            if child is not None and child.name == name:
                # the abandoned worker will never run the stage's finally:
                # close its span HERE (finish is first-write-wins, so a
                # later unblocked worker's finish is a no-op) so the
                # timed-out stage is visible in the recent-spans ring and
                # any flight-recorder bundle
                child.attrs["timeout"] = True
                child.finish()
            try:
                # lazy import: ops must stay importable without pulling the
                # observability package in at module-import time
                from kubernetes_tpu.observability.flightrecorder import (
                    RECORDER,
                )
                RECORDER.dump("stage-timeout", force=False,
                              trigger={"stage": name, "deadline": limit})
            except Exception:
                import logging
                logging.getLogger("watchdog").exception(
                    "flight recorder dump failed on stage timeout")
            raise StageTimeout(name, limit)
    if "err" in box:
        raise box["err"]
    return box["value"]
