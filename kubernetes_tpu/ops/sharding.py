"""Multi-chip sharding layout for the scheduling kernel.

The domain's two scale axes map onto a ("pods", "nodes") device mesh — the
dp-analog (independent rows of the pending batch) and tp-analog (the node
tensor axis every [P, N] matmul contracts over), per SURVEY §2.9/§7:
stage A's [P,L]@[L,N] work shards on both axes; stage B's scan carries
node-sharded state and XLA inserts the cross-shard max/argmax collectives
for host selection (psum/all-gather over ICI on real hardware).

This is the single source of truth for which tensor axis shards where;
__graft_entry__.dryrun_multichip and the in-suite equivalence tests
(tests/test_multichip.py) both consume it, so the layout the driver
validates is the layout the tests prove binding-equivalent.
"""

from __future__ import annotations

from typing import Dict, List, Optional


def partition_specs() -> Dict[str, object]:
    """PartitionSpec per ClusterTensors field: P-axis -> "pods", N-axis ->
    "nodes", vocab/term axes replicated."""
    from jax.sharding import PartitionSpec as P

    return {
        "alloc": P("nodes", None), "used0": P("nodes", None),
        "used0_nonzero": P("nodes", None), "node_labels": P("nodes", None),
        "node_ports0": P("nodes", None), "taints_nosched": P("nodes", None),
        "taints_prefer": P("nodes", None), "mem_pressure": P("nodes"),
        "node_valid": P("nodes"), "zone_id": P("nodes"),
        "group_counts0": P("nodes", None), "image_node_sizes": P("nodes", None),
        "expr_node": P(None, "nodes"), "pref_term_node": P(None, "nodes"),
        "req": P("pods", None), "nonzero_req": P("pods", None),
        "sel_required": P("pods", None), "sel_count": P("pods"),
        "pod_ports": P("pods", None), "tol_nosched": P("pods", None),
        "tol_prefer": P("pods", None), "best_effort": P("pods"),
        "host_req": P("pods"), "pod_valid": P("pods"),
        "pod_term": P("pods", None), "pod_has_affinity": P("pods"),
        "pod_pref_term": P("pods", None), "pod_group": P("pods"),
        "pod_in_group": P("pods", None), "pod_images": P("pods", None),
        "term_expr": P(), "term_expr_count": P(), "pref_weight": P(),
        # inter-pod term tables: term axis replicated, node axis sharded,
        # pod-match columns sharded on pods
        "node_dom": P(None, "nodes"),
        "req_topo": P(), "req_own": P("pods", None),
        "req_match": P(None, "pods"), "req_hit0": P(None, "nodes"),
        "req_nomatch0": P(),
        "anti_topo": P(), "anti_own": P("pods", None),
        "anti_match": P(None, "pods"), "anti_hit0": P(None, "nodes"),
        "pref_topo": P(), "pref_own": P("pods", None),
        "pref_match": P(None, "pods"), "pref_w": P(),
        "pref_hit0": P(None, "nodes"),
        "sym_dom0": P(None, "nodes"), "sym_match": P(None, "pods"),
        "te_dom0": P(None, "nodes"), "te_match": P(None, "pods"),
        "hard_weight": P(),
        "pod_disk_any": P("pods", None), "pod_disk_rw": P("pods", None),
        "node_disk_any0": P("nodes", None), "node_disk_rw0": P("nodes", None),
        "pod_ebs": P("pods", None), "node_ebs0": P("nodes", None),
        "pod_gce": P("pods", None), "node_gce0": P("nodes", None),
        "max_ebs": P(), "max_gce": P(),
        # objective-mode operands (scheduler/objectives/tensors.py)
        "pod_priority": P("pods"), "vict_prio": P(None, "nodes"),
        "vict_cum": P(None, None, "nodes"), "pod_gang": P("pods"),
        "gang_dom0": P(), "gang_failed0": P(),
        "node_gang_dom": P("nodes"),
    }


def make_mesh(n_devices: int):
    """("pods", "nodes") mesh over the first n devices: 2-way dp when the
    count allows, rest tp (the nodes axis carries most of the FLOPs)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    devices = jax.devices()[:n_devices]
    assert len(devices) == n_devices, (
        f"need {n_devices} devices, have {len(jax.devices())}")
    dp = 2 if n_devices % 2 == 0 and n_devices >= 4 else 1
    tp = n_devices // dp
    return Mesh(np.array(devices).reshape(dp, tp), ("pods", "nodes"))


def shard_arrays(mesh, np_arrays: dict) -> dict:
    """device_put every tensor with its layout's NamedSharding."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    specs = partition_specs()
    out = {}
    for k, v in np_arrays.items():
        spec = specs.get(k, P())
        out[k] = jax.device_put(jnp.asarray(v), NamedSharding(mesh, spec))
    return out


def schedule_batch_sharded(ct, mesh, weights=None,
                           wave=None) -> List[Optional[str]]:
    """The sharded twin of kernel.schedule_batch: same program (wave or
    serial, per kernel.resolve_wave), inputs laid out over the mesh;
    returns node name (or None) per pending pod."""
    import numpy as np

    from kubernetes_tpu.ops.kernel import (
        Weights, _schedule_jit, assignments_to_names, features_of,
        record_wave_count, resolve_wave,
    )

    weights = weights or Weights()
    feats = features_of(ct)
    wv = resolve_wave(wave, n_pods=ct.n_real_pods)
    with mesh:
        arrays = shard_arrays(mesh, ct.arrays())
        out = _schedule_jit(arrays, ct.n_zones, weights, feats,
                            False, None, wv)
        out = np.asarray(record_wave_count(out, wv))
    return assignments_to_names(out, ct)
