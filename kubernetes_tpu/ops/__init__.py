"""TPU kernels: the scheduler's filter-and-score pipeline as tensor ops.

This is the re-expression of reference plugin/pkg/scheduler's hot loop
(SURVEY §2.9, §7) as a batched constraint-satisfaction kernel:

  tensorize.py  host-side compilation of cluster state + a pending-pod batch
                into dense, vocabulary-encoded tensors (the tensorization of
                schedulercache.NodeInfo, node_info.go:32-49)
  kernel.py     the two-stage device program:
                  stage A (batched, MXU): assignment-independent predicate
                  masks and score matrices over pods x nodes — label/affinity/
                  taint/port/image terms as [P,L] @ [L,N] matmuls
                  stage B (lax.scan): sequential greedy commit replicating the
                  one-pod-at-a-time assume semantics (AssumePod, cache.go:101)
                  with capacity/ports/spread updated in-carry, round-robin
                  tie-break matching selectHost (generic_scheduler.go:116-133)

The kernel's bindings must equal the Python oracle's, pod for pod — enforced
by the differential tests (tests/test_tpu_kernel.py).
"""
