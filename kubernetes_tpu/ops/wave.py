"""Wave-based commit: bulk-schedule non-interacting FIFO prefixes per step.

The serial stage-B scan (ops/kernel.greedy_commit) executes ~25 fused ops
once per pod — 30,000 sequential steps at the bench shape, the wall the
round-5 VERDICT diagnoses. This module replaces the per-pod scan with a
`lax.while_loop` over *waves*: each iteration decides a whole chunk of
remaining pods in parallel against the wave-start carry, proves which FIFO
prefix of those decisions is invariant under each other's commits, and
scatters that prefix into the carry in bulk. The sequential dimension
shrinks from P pod-steps to the measured wave count (O(P/chunk) when pods
don't interact; degrades gracefully toward P when they all do).

Exact-parity construction (pinned bit-for-bit by tests/test_wave_parity.py
and the tools/wave_smoke.py verify gate):

- Pass A decides every chunk pod against the wave-start state S0 with the
  same formulas as the serial step (all score ingredients are
  integer-valued f32, so batched reductions are bit-exact — see the
  kernel module docstring).
- Pass B re-decides each pod against its *at-turn* state: S0 plus the
  commits of every earlier chunk pod per pass A, reconstructed exactly
  with strict-lower-triangular prefix matmuls over the capacity
  (used/used_nz), volume-attach-count, and spread-group rows, and with
  the round-robin tie counter advanced by the exclusive prefix count of
  earlier commits. By induction, wherever pass B agrees with pass A for
  every earlier pod, pass A *is* the serial decision.
- The committed prefix ends at the first pod where (a) pass B disagrees
  with pass A, (b) the pod reads inter-pod-affinity or port/disk/volume
  state some earlier committed pod writes (conservative term/column
  overlap matmuls — those carries are max-updated, so the at-turn value
  is only provably unchanged when the read/write sets are disjoint), or
  (c) the pod is *complex*: a gang member, a potential preemptor
  (infeasible pod in preempt mode), or a writer of multi-topology-key
  add-row affinity terms. A complex pod at the head of a wave commits
  alone through the *serial step function itself* (build_program's step),
  so gang rollback, victim nomination, and every other stateful subtlety
  reproduce the serial semantics by construction, not by transcription.
- Pods proven unschedulable (infeasible in pass A and pass B, non-complex)
  "commit" their -1 in bulk — a mass-infeasible tail costs one wave, not
  P steps.

All conflict resolution is FIFO: the prefix rule never reorders pods, so
the wave result — assignments, preemption victims, gang verdicts, explain
survivor counts and score decompositions — is the serial FIFO result
exactly, wave count being the only new output.

No host synchronization anywhere in the loop: the wave count is a traced
i32 in the carry, materialized with the rest of the outputs.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from kubernetes_tpu.ops.kernel import (
    NEG, WAVE_CHUNK, _CH_DANY, _CH_DRW, _CH_EBS, _CH_GCE, _CH_PORTS,
    Features, Weights, build_program,
)
from kubernetes_tpu.scheduler.objectives.config import ObjectiveConfig

# nstate row layout (ops/kernel.build_program): used(4) | used_nz(2) |
# ebs_count | gce_count | group rows
R_EBS, R_GCE, R_G0 = 6, 7, 8


def wave_commit(t: dict, s: dict, w: Weights, feats: Features,
                explain: bool = False,
                obj: Optional[ObjectiveConfig] = None,
                chunk: int = WAVE_CHUNK, refine: int = 8):
    """Solve the batch by wave commit; returns (ret, wave_count) where
    `ret` has exactly greedy_commit's return structure (same dtypes, same
    values bit-for-bit) and wave_count is an i32 scalar."""
    import os
    refine_passes = max(int(os.environ.get("KTPU_WAVE_REFINE", refine)), 1)
    step, xs, init, c = build_program(t, s, w, feats, explain, obj)
    P = xs["prow"].shape[0]
    Wc = int(min(max(chunk, 1), P))
    Pp2 = P + Wc  # frontier padding: chunk slices never clamp backwards

    def pad(a):
        widths = [(0, Wc)] + [(0, 0)] * (a.ndim - 1)
        return jnp.pad(a, widths)

    xsp = {k: pad(v) for k, v in xs.items()}
    N = c.N
    idx_n = c.idx_n
    lay = c.lay
    wf = c.wf
    idx_q = jnp.arange(Wc, dtype=jnp.int32)
    # strict lower triangle: prefix[q] sums contributions of pods i < q
    tril = jnp.tril(jnp.ones((Wc, Wc), jnp.float32), -1)

    # output buffers shaped like the serial scan's stacked ys
    x0_probe = jax.tree_util.tree_map(lambda a: a[0], xsp)
    y_shape = jax.eval_shape(lambda cc, xx: step(cc, xx)[1], init, x0_probe)
    outs0 = jax.tree_util.tree_map(
        lambda sd: jnp.zeros((Pp2,) + tuple(sd.shape), sd.dtype), y_shape)

    def pack_y(chosen, pk, extras):
        """Build a [Wc]-rows y-tree matching the serial step's structure."""
        if not c.obj_on:
            return chosen if not explain else (chosen, extras)
        objy = {}
        if c.use_preempt:
            objy["pk"] = pk
        if explain:
            return (chosen, objy, extras)
        return (chosen, objy)

    def body(loop):
        pos, waves, st, outs = loop
        cx = {k: jax.lax.dynamic_slice_in_dim(v, pos, Wc, axis=0)
              for k, v in xsp.items()}
        prow = cx["prow"]                                   # [Wc, W]

        def sp(name):
            return prow[:, lay.spans[name]]

        nstate = st["nstate"]
        used, used_nz = nstate[:4], nstate[4:6]
        req_b = sp("req")                                   # [Wc, 4]
        nz_b = sp("nz")                                     # [Wc, 2]
        flags_b = sp("flags")
        zero_req = flags_b[:, 0] > 0
        valid = flags_b[:, 1] > 0
        has_group = flags_b[:, 2] > 0
        g_b = flags_b[:, 3].astype(jnp.int32)
        in_group_b = sp("in_group")                         # [Wc, G+1]
        mask0 = cx["mask"]                                  # [Wc, N] bool
        counts0_b = jnp.take(nstate[R_G0:], g_b, axis=0)    # [Wc, N]

        # --- wave-invariant feature pieces (valid at-turn for any pod with
        # no read/write overlap against earlier commits — the prefix cut
        # below guarantees exactly that) --------------------------------------
        port_ok = disk_ok = None
        cols = None
        ebs_hit = gce_hit = None
        cnt_e = cnt_g = None
        if c.use_vocab:
            vocab = st["vocab"]
            sids = sp("slot_ids").astype(jnp.int32)         # [Wc, SS]
            svals = sp("slot_vals")
            chan_b = jnp.broadcast_to(
                jnp.asarray(c.chan_idx)[None, :], (Wc, c.SS))
            cols = vocab[chan_b, sids, :]                   # [Wc, SS, N]
            port_clash = jnp.zeros((Wc, N), jnp.float32)
            disk_clash = jnp.zeros((Wc, N), jnp.float32)
            ebs_hit = jnp.zeros((Wc, N), jnp.float32)
            gce_hit = jnp.zeros((Wc, N), jnp.float32)
            for si, ch in enumerate(c.chan_idx):
                if ch == _CH_PORTS:
                    port_clash = port_clash + cols[:, si]
                elif ch == _CH_DANY:
                    disk_clash = disk_clash + cols[:, si] * svals[:, si + 1,
                                                                  None]
                elif ch == _CH_DRW:
                    disk_clash = disk_clash + cols[:, si] * svals[:, si - 1,
                                                                  None]
                elif ch == _CH_EBS:
                    ebs_hit = ebs_hit + cols[:, si]
                else:
                    gce_hit = gce_hit + cols[:, si]
            if feats.ports:
                port_ok = port_clash == 0.0
            if feats.disk:
                disk_ok = disk_clash == 0.0
            if feats.ebs:
                cnt_e = sp("vol_cnt")[:, 0]
            if feats.gce:
                cnt_g = sp("vol_cnt")[:, 1]

        viol = None
        cips = None
        if c.use_ip:
            hits = st["hits"]
            req_own_b = sp("req_own")
            req_match_b = sp("req_match")
            anti_own_b = sp("anti_own")
            anti_match_b = sp("anti_match")
            pref_own_b = sp("pref_own")
            pref_match_b = sp("pref_match")
            disregard = ((req_match_b > 0) & st["req_nomatch"][None, :]
                         ).astype(jnp.float32)
            own_eff = req_own_b * (1.0 - disregard)         # [Wc, T]
            lhs6 = jnp.stack([
                -own_eff, c.hard_w * req_match_b, anti_own_b, anti_match_b,
                pref_own_b * c.pref_w[None, :], pref_match_b,
            ], axis=1)                                      # [Wc, 6, T]
            ip6 = jnp.einsum("qst,stn->qsn", lhs6, hits)    # [Wc, 6, N]
            viol = (jnp.sum(own_eff, axis=1)[:, None]
                    + ip6[:, 0] + ip6[:, 2] + ip6[:, 3])
            cips = ip6[:, 1] + ip6[:, 4] + ip6[:, 5]
        if c.use_st:
            lhs2 = jnp.stack([sp("sym_match"), sp("te_match")], axis=1)
            ip2 = jnp.einsum("qst,stn->qsn", lhs2, c.static2)
            viol = ip2[:, 0] if viol is None else viol + ip2[:, 0]
            cips = ip2[:, 1] if cips is None else cips + ip2[:, 1]

        if c.use_gang:
            grow_b = sp("gangrow")
            is_gang_b = grow_b[:, 1] > 0

        # --- the shared decide: same formulas as the serial step, batched
        # over pods, parameterized on the state rows that change per-commit
        # (pass A feeds broadcast wave-start rows, pass B per-pod at-turn
        # rows; everything else is wave-invariant from above) -----------------
        def decide(usedS, used_nzS, countsS, ebs_totS, gce_totS):
            mask = mask0 & (usedS[:, 3] + 1.0 <= c.allocT[3][None, :])
            surv_rows = [mask] if explain else None
            for r in range(3):
                fit_r = usedS[:, r] + req_b[:, r, None] <= c.allocT[r][None]
                mask = mask & (zero_req[:, None] | fit_r)
                if explain:
                    surv_rows.append(mask)
            if c.use_vocab:
                if feats.ports:
                    mask = mask & port_ok
                if explain:
                    surv_rows.append(mask)
                if feats.disk:
                    mask = mask & disk_ok
                if explain:
                    surv_rows.append(mask)
                if feats.ebs:
                    union = ebs_totS + cnt_e[:, None] - ebs_hit
                    mask = mask & ((cnt_e[:, None] == 0.0)
                                   | (union <= c.max_ebs))
                if feats.gce:
                    union = gce_totS + cnt_g[:, None] - gce_hit
                    mask = mask & ((cnt_g[:, None] == 0.0)
                                   | (union <= c.max_gce))
                if explain:
                    surv_rows.append(mask)
            elif explain:
                surv_rows.extend([mask, mask, mask])
            if viol is not None:
                mask = mask & (viol == 0.0)
            if explain:
                surv_rows.append(mask)
            if c.use_gang:
                # bulk-committable pods are never gang members (complex),
                # and the serial gang_allow for non-members is True
                if explain:
                    surv_rows.append(mask)

            tot_c = used_nzS[:, 0] + nz_b[:, 0, None]       # [Wc, N]
            tot_m = used_nzS[:, 1] + nz_b[:, 1, None]
            cpu_sc = jnp.where(
                (c.cap_c > 0) & (tot_c <= c.cap_c),
                jnp.floor((c.cap_c - tot_c) * 10.0 / c.cap_c), 0.0)
            mem_sc = jnp.where(
                (c.cap_m > 0) & (tot_m <= c.cap_m),
                jnp.floor((c.cap_m - tot_m) * 10.0 / c.cap_m), 0.0)
            least = jnp.floor((cpu_sc + mem_sc) / 2.0)
            frac_c = jnp.where(c.cap_c > 0, tot_c / c.cap_c, 1.0)
            frac_m = jnp.where(c.cap_m > 0, tot_m / c.cap_m, 1.0)
            balanced = jnp.where(
                (frac_c >= 1.0) | (frac_m >= 1.0), 0.0,
                jnp.floor(10.0 - jnp.abs(frac_c - frac_m) * 10.0))

            zsum = jnp.einsum("zn,qn->qz", c.zone_onehot_t,
                              jnp.where(mask, countsS, 0.0))
            node_zc = jnp.einsum("qz,zn->qn", zsum, c.zone_onehot_t)
            zrow = (c.zone_id >= 0)[None, :]
            maxc = jnp.maximum(
                jnp.max(jnp.where(mask, countsS, NEG), axis=1), 0.0)
            maxz = jnp.maximum(
                jnp.max(jnp.where(mask & zrow, node_zc, NEG), axis=1), 0.0)
            feasible = (jnp.max(jnp.where(mask, 1.0, NEG), axis=1) > 0.0) \
                & valid
            have_zones = jnp.max(
                jnp.where(mask & zrow, 1.0, NEG), axis=1) > 0.0
            fscore = jnp.where(maxc[:, None] > 0.0,
                               10.0 * (maxc[:, None] - countsS)
                               / maxc[:, None], 10.0)
            zscore = jnp.where(maxz[:, None] > 0.0,
                               10.0 * (maxz[:, None] - node_zc)
                               / maxz[:, None], 10.0)
            blend = jnp.where(
                zrow & has_group[:, None] & have_zones[:, None]
                & (maxz[:, None] > 0.0),
                fscore * (1.0 / 3.0) + (2.0 / 3.0) * zscore, fscore)
            spread = jnp.floor(jnp.where(has_group[:, None], blend, 10.0))

            comps = []
            c_lr = wf["least_requested"] * least
            c_ba = wf["balanced"] * balanced
            c_sp = wf["spread"] * spread
            if explain:
                comps += [c_lr, c_ba, c_sp]
            score = c_lr + c_ba + c_sp + wf["equal"] * 1.0
            if feats.node_pref:
                xp = cx["pref"]
                max_pref = jnp.max(jnp.where(mask, xp, NEG), axis=1)
                c_na = wf["node_affinity"] * jnp.where(
                    max_pref[:, None] > 0.0,
                    jnp.floor(10.0 * xp / max_pref[:, None]), 0.0)
                score = score + c_na
                if explain:
                    comps.append(c_na)
            if feats.taint_pref:
                xt = cx["taint_pref"]
                max_tp = jnp.max(jnp.where(mask, xt, NEG), axis=1)
                c_tt = wf["taint_toleration"] * jnp.where(
                    max_tp[:, None] > 0.0,
                    jnp.floor((1.0 - xt / max_tp[:, None]) * 10.0), 10.0)
                score = score + c_tt
                if explain:
                    comps.append(c_tt)
            if cips is not None:
                ip_max = jnp.maximum(
                    jnp.max(jnp.where(mask, cips, NEG), axis=1), 0.0)
                ip_min = jnp.minimum(
                    -jnp.max(jnp.where(mask, -cips, NEG), axis=1), 0.0)
                ip_rng = ip_max - ip_min
                c_ip = wf["interpod_affinity"] * jnp.where(
                    ip_rng[:, None] > 0.0,
                    jnp.floor(10.0 * (cips - ip_min[:, None])
                              / ip_rng[:, None]), 0.0)
                score = score + c_ip
                if explain:
                    comps.append(c_ip)
            if c.use_image:
                c_im = wf["image_locality"] * cx["image"]
                score = score + c_im
                if explain:
                    comps.append(c_im)
            if c.use_binpack:
                bcpu = jnp.where((c.cap_c > 0) & (tot_c <= c.cap_c),
                                 jnp.floor(tot_c * 10.0 / c.cap_c), 0.0)
                bmem = jnp.where((c.cap_m > 0) & (tot_m <= c.cap_m),
                                 jnp.floor(tot_m * 10.0 / c.cap_m), 0.0)
                c_bp = np.float32(c.obj.binpack_weight) * jnp.floor(
                    (bcpu + bmem) / 2.0)
                score = score + c_bp
                if explain:
                    comps.append(c_bp)

            masked_score = jnp.where(mask, score, NEG)
            max_score = jnp.max(masked_score, axis=1)
            is_max = mask & (masked_score == max_score[:, None])
            cum = jnp.cumsum(is_max.astype(jnp.int32), axis=1)
            n_ties = cum[:, N - 1]
            out = {"mask": mask, "feasible": feasible,
                   "masked_score": masked_score, "max_score": max_score,
                   "is_max": is_max, "cum": cum, "n_ties": n_ties}
            if explain:
                out["surv"] = jnp.sum(jnp.stack(
                    [r.astype(jnp.float32) for r in surv_rows], axis=1),
                    axis=2)                                  # [Wc, SR]
                out["comp_stack"] = jnp.stack(comps, axis=1)  # [Wc, C, N]
            return out

        def select(dd, rr_q):
            k = jnp.where(dd["n_ties"] > 0,
                          rr_q % jnp.maximum(dd["n_ties"], 1), 0)
            chosen = jnp.argmax(
                dd["is_max"] & (dd["cum"] == (k + 1)[:, None]),
                axis=1).astype(jnp.int32)
            return jnp.where(dd["feasible"], chosen, jnp.int32(-1))

        def inc_of(chosen, commitf):
            """Per-pod nstate increment columns [Wc, 8]: req, nz, ebs, gce
            (the group rows ride separately through in_group_b)."""
            if c.use_vocab and (feats.ebs or feats.gce):
                safe = jnp.maximum(chosen, 0)
                col_at = jnp.take_along_axis(
                    cols, safe[:, None, None], axis=2)[:, :, 0]  # [Wc, SS]
                chan_row = jnp.asarray(c.chan_idx)[None, :]
                if feats.ebs:
                    ebs_at = jnp.sum(jnp.where(chan_row == _CH_EBS,
                                               col_at, 0.0), axis=1)
                    ebs_inc = (cnt_e - ebs_at) * commitf
                else:
                    ebs_inc = jnp.zeros((Wc,), jnp.float32)
                if feats.gce:
                    gce_at = jnp.sum(jnp.where(chan_row == _CH_GCE,
                                               col_at, 0.0), axis=1)
                    gce_inc = (cnt_g - gce_at) * commitf
                else:
                    gce_inc = jnp.zeros((Wc,), jnp.float32)
            else:
                ebs_inc = gce_inc = jnp.zeros((Wc,), jnp.float32)
            return jnp.concatenate(
                [req_b, nz_b, ebs_inc[:, None], gce_inc[:, None]], axis=1)

        # --- pass A: decide vs wave-start state ------------------------------
        d0 = decide(used[None], used_nz[None], counts0_b,
                    nstate[R_EBS][None], nstate[R_GCE][None])
        commit0 = d0["feasible"]
        commit0f = commit0.astype(jnp.float32)
        csum = jnp.cumsum(commit0.astype(jnp.int32))
        rr_q = st["rr"] + csum - commit0.astype(jnp.int32)   # exclusive
        chosen0 = select(d0, rr_q)

        # --- tie-rotation prediction -----------------------------------------
        # The big-batch regime (integer-floored scores over thousands of
        # near-identical nodes) is one huge tie set that the serial scan
        # walks round-robin, each commit knocking its node out of the tie
        # (its least-requested/spread score drops). Frozen wave-start
        # choices are then wrong from the second pod on — but for a run of
        # IDENTICAL pods whose commits each remove exactly their pick, the
        # serial picks have a closed form: with M ties, rr = a, and
        # Q = floor(a / M), pod j takes the tie-set element of original
        # rank a - Q*M + j*(2+Q), valid while that rank stays below M.
        # The prediction is speculative — pass B verifies it exactly, so a
        # wrong guess costs wave length, never correctness.
        ident = jnp.all(prow == prow[0:1], axis=1) \
            & jnp.all(mask0 == mask0[0:1], axis=1)
        if feats.node_pref:
            ident = ident & jnp.all(cx["pref"] == cx["pref"][0:1], axis=1)
        if feats.taint_pref:
            ident = ident & jnp.all(
                cx["taint_pref"] == cx["taint_pref"][0:1], axis=1)
        if c.use_image:
            ident = ident & jnp.all(cx["image"] == cx["image"][0:1], axis=1)
        ident_run = jnp.cumprod(ident.astype(jnp.int32)) > 0
        # only predict rotation when the commit perturbs its node's score
        # (nonzero requests or spread-group membership); otherwise frozen
        # choices are already exact for static tie sets
        rot_heur = jnp.any(req_b[0, :3] > 0) | has_group[0]
        M = d0["n_ties"][0]
        a = st["rr"]
        Q = a // jnp.maximum(M, 1)
        o_q = a - Q * M + idx_q * (2 + Q)
        rot_ok = ident_run & rot_heur & d0["feasible"][0] & (M > 0) \
            & (o_q < M)
        cmp = d0["is_max"][0][None, :] \
            & (d0["cum"][0][None, :] == (o_q + 1)[:, None])
        p_rot = jnp.argmax(cmp, axis=1).astype(jnp.int32)
        chosen0 = jnp.where(rot_ok, p_rot, chosen0)

        # --- pass B: refine to the serial fixed point ------------------------
        # Each refinement pass re-decides every pod against its exact
        # at-turn state (wave-start + prefix matmuls over the previous
        # pass's choices). A pod whose choice is a per-pod fixed point of
        # this recurrence — decide(prefix(χ))_q == χ_q with every earlier
        # pod also fixed — IS the serial FIFO decision, by induction from
        # pod 0. One pass per interaction "hop": a commit that perturbs a
        # later pod's choice is absorbed by the next pass, so runs where
        # every pod reacts to its predecessors (zone-blend spread, score
        # cascades) still converge in a handful of passes instead of
        # cutting the wave to one pod.
        w_sp = jax.nn.one_hot(g_b, in_group_b.shape[1],
                              dtype=jnp.float32) @ in_group_b.T   # [Wc, Wc]

        def refine(ch_prev):
            commitP = ch_prev >= 0
            commitPf = commitP.astype(jnp.float32)
            csumP = jnp.cumsum(commitP.astype(jnp.int32))
            rrP = st["rr"] + csumP - commitP.astype(jnp.int32)
            onehotP = ((idx_n[None, :]
                        == jnp.maximum(ch_prev, 0)[:, None])
                       .astype(jnp.float32)) * commitPf[:, None]
            incP = inc_of(ch_prev, commitPf)           # [Wc, 8]
            pref8 = jnp.einsum("ij,jr,jn->irn", tril, incP, onehotP)
            counts_at = counts0_b + (tril * w_sp) @ onehotP
            dd = decide(used[None] + pref8[:, :4],
                        used_nz[None] + pref8[:, 4:6], counts_at,
                        nstate[R_EBS][None] + pref8[:, 6],
                        nstate[R_GCE][None] + pref8[:, 7])
            return select(dd, rrP), dd, rrP

        ch_cur, dd, rr_at = refine(chosen0)

        def ref_cond(carry):
            i, prev, cur, _dd, _rr = carry
            return (i < refine_passes - 1) & jnp.any(prev != cur)

        def ref_body(carry):
            i, _prev, cur, _dd, _rr = carry
            nxt, dd2, rr2 = refine(cur)
            return (i + 1, cur, nxt, dd2, rr2)

        _, ch_prev, ch_cur, dd, rr_at = jax.lax.while_loop(
            ref_cond, ref_body, (jnp.int32(0), chosen0, ch_cur, dd, rr_at))
        commit1 = ch_cur >= 0
        commit1f = commit1.astype(jnp.float32)
        mismatch = ch_prev != ch_cur

        # --- conservative read/write overlap (hits + vocab columns) ----------
        overlap = jnp.zeros((Wc,), bool)
        if c.use_ip:
            X = (req_match_b @ req_own_b.T + req_own_b @ req_match_b.T
                 + anti_match_b @ anti_own_b.T + anti_own_b @ anti_match_b.T
                 + pref_match_b @ pref_own_b.T + pref_own_b @ pref_match_b.T)
            overlap = overlap | (((tril * X.T) @ commit1f) > 0)
        if c.use_vocab:
            Vp = st["vocab"].shape[1]
            cls = np.asarray([0 if ch == _CH_PORTS
                              else 1 if ch in (_CH_DANY, _CH_DRW)
                              else 2 if ch == _CH_EBS else 3
                              for ch in c.chan_idx])
            Vmat = jnp.zeros((Wc, Wc), jnp.float32)
            oh = jax.nn.one_hot(sids, Vp, dtype=jnp.float32) \
                * (svals > 0)[:, :, None]                    # [Wc, SS, Vp]
            for cl in range(4):
                take = [si for si, ch in enumerate(c.chan_idx)
                        if cls[si] == cl
                        and not (cl == 1 and ch == _CH_DRW)]
                if not take:
                    continue
                E = jnp.sum(oh[:, np.asarray(take), :], axis=1)  # [Wc, Vp]
                Vmat = Vmat + E @ E.T
            overlap = overlap | (((tril * Vmat.T) @ commit1f) > 0)

        # --- complex pods: serial-only (wave-head single commits) ------------
        cpx = jnp.zeros((Wc,), bool)
        if c.use_gang:
            cpx = cpx | is_gang_b
        if c.use_preempt:
            # any at-turn-infeasible pod would nominate victims at its
            # serial turn — only the full serial step does that
            cpx = cpx | (~commit1 & valid) | (~d0["feasible"] & valid)
        if c.use_ip:
            # add-row hit updates sum UNbinarized domain hits; only exact
            # for single-topology-key terms — multi-key writers go serial
            multi_req = (jnp.sum(c.topo_stack[: c.T], axis=1) > 1.0) \
                .astype(jnp.float32)
            multi_pref = (jnp.sum(c.topo_stack[2 * c.T:], axis=1) > 1.0) \
                .astype(jnp.float32)
            cpx = cpx | ((req_own_b @ multi_req
                          + pref_match_b @ multi_pref
                          + pref_own_b @ multi_pref) > 0)

        bad = mismatch | overlap | cpx
        L = jnp.min(jnp.where(bad, idx_q, Wc))

        def bulk(_):
            sel = idx_q < L
            commitF = commit1 & sel
            commitFf = commitF.astype(jnp.float32)
            safeF = jnp.maximum(ch_cur, 0)
            onehotF = ((idx_n[None, :] == safeF[:, None])
                       .astype(jnp.float32)) * commitFf[:, None]
            incF = jnp.concatenate(
                [inc_of(ch_cur, commitFf), in_group_b], axis=1)
            nst = nstate + jnp.einsum("qr,qn->rn", incF, onehotF)
            out_c = {"nstate": nst,
                     "rr": st["rr"] + jnp.sum(commitF.astype(jnp.int32))}
            if c.use_vocab:
                out_c["vocab"] = st["vocab"].at[
                    chan_b, sids, safeF[:, None]].max(
                        svals * commitFf[:, None])
            if c.use_ip:
                dom_cF = jnp.take(c.node_dom, safeF, axis=1).T  # [Wc, K]
                eq = (((c.node_dom[None, :, :] == dom_cF[:, :, None])
                       & (c.node_dom[None, :, :] >= 0))
                      .astype(jnp.float32)) * commitFf[:, None, None]
                coefF = jnp.stack([
                    req_match_b, req_own_b, anti_match_b,
                    (anti_own_b > 0).astype(jnp.float32),
                    pref_match_b, pref_own_b * c.pref_w[None, :],
                ], axis=1)                                   # [Wc, 6, T]
                K = c.node_dom.shape[0]
                topo6 = jnp.repeat(
                    c.topo_stack.reshape(3, c.T, K), 2, axis=0)  # [6, T, K]
                A = jnp.einsum("qst,stk->stqk", coefF, topo6) \
                    .reshape(6 * c.T, Wc * K)
                U = (A @ eq.reshape(Wc * K, N)).reshape(6, c.T, N)
                hits_new = jnp.where(
                    c.hit_is_max,
                    jnp.maximum(st["hits"], (U > 0).astype(jnp.float32)),
                    st["hits"] + U)
                out_c["hits"] = hits_new
                matched = jnp.einsum(
                    "q,qt->t", commitFf,
                    (req_match_b > 0).astype(jnp.float32)) > 0
                out_c["req_nomatch"] = st["req_nomatch"] & ~matched
            if c.use_preempt:
                out_c["evicted"] = st["evicted"]
            if c.use_gang:
                out_c["gang_dom"] = st["gang_dom"]
                out_c["gang_failed"] = st["gang_failed"]
                # the first non-gang pod after an open gang resets the
                # rollback accumulator (serial: gid change clears it)
                out_c["gang_delta"] = jnp.where(
                    st["cur_gang"] != c.g_null, 0.0, st["gang_delta"])
                out_c["cur_gang"] = jnp.int32(c.g_null)
            if explain:
                safe = safeF
                comp1 = dd["comp_stack"]                     # [Wc, C, N]
                win_comp = jnp.take_along_axis(
                    comp1, safe[:, None, None], axis=2)[:, :, 0]
                run_masked = jnp.where(idx_n[None, :] == safe[:, None],
                                       NEG, dd["masked_score"])
                run_total = jnp.max(run_masked, axis=1)
                run_idx = jnp.argmax(run_masked, axis=1).astype(jnp.int32)
                run_comp = jnp.take_along_axis(
                    comp1, run_idx[:, None, None], axis=2)[:, :, 0]
                extras = {"surv": dd["surv"], "win_comp": win_comp,
                          "win_total": dd["max_score"], "run_idx": run_idx,
                          "run_total": run_total, "run_comp": run_comp}
            else:
                extras = None
            pk = jnp.zeros((Wc,), jnp.int32) if c.use_preempt else None
            return out_c, pack_y(ch_cur, pk, extras), L

        def single(_):
            x0 = jax.tree_util.tree_map(lambda a: a[0], cx)
            carry1, y1 = step(st, x0)
            y_rows = jax.tree_util.tree_map(
                lambda v: jnp.zeros((Wc,) + jnp.shape(v),
                                    jnp.asarray(v).dtype).at[0].set(v), y1)
            return carry1, y_rows, jnp.int32(1)

        st2, y_rows, adv = jax.lax.cond(L == 0, single, bulk, operand=None)
        outs2 = jax.tree_util.tree_map(
            lambda buf, rows: jax.lax.dynamic_update_slice_in_dim(
                buf, rows, pos, axis=0),
            outs, y_rows)
        return (pos + adv, waves + 1, st2, outs2)

    pos0 = jnp.int32(0)
    waves0 = jnp.int32(0)
    posF, wavesF, carryF, outsF = jax.lax.while_loop(
        lambda lo: lo[0] < P, body, (pos0, waves0, init, outs0))
    ys = jax.tree_util.tree_map(lambda a: a[:P], outsF)

    obj_on = c.obj_on
    if not obj_on:
        if not explain:
            return ys, wavesF
        assignments, extras = ys
        return (assignments, extras), wavesF
    if explain:
        assignments, objy, extras = ys
    else:
        assignments, objy = ys
    objout = dict(objy)
    if c.use_gang:
        objout["gang_failed"] = carryF["gang_failed"]
    if explain:
        return (assignments, objout, extras), wavesF
    return (assignments, objout), wavesF
