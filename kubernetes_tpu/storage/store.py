"""In-process versioned KV store with watch.

Semantics mirrored from the reference's storage contract:

- Every write bumps a single monotonically-increasing resourceVersion
  (etcd modifiedIndex semantics, pkg/storage/etcd/api_object_versioner.go).
- `guaranteed_update` is the CAS retry loop (GuaranteedUpdate,
  pkg/storage/interfaces.go:130-163) — the cluster's only transaction
  primitive; the binding subresource and every status update ride on it.
- `watch(prefix, since_rv)` replays buffered events with rv > since_rv then
  streams live; a since_rv older than the retained window raises
  TooOldResourceVersion, which the API server surfaces as HTTP 410 Gone and
  clients answer with a re-LIST (the Reflector contract,
  pkg/client/cache/reflector.go:252).
- Values are plain JSON-ready dicts (the storage layer is codec-agnostic,
  like etcd storing bytes); typed encode/decode happens in the registry.

Thread-safe. Watcher queues are BOUNDED (watcher_queue): a watcher that
falls `watcher_queue` events behind is dropped with a terminal ERROR event
instead of blocking writers or growing without bound — the reference
cacher's slow-watcher termination (pkg/storage/cacher.go:73, chanSize
forwarder). Clients answer the ERROR by re-listing (Reflector contract).
"""

from __future__ import annotations

import json
import threading
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"
ERROR = "ERROR"


class StorageError(Exception):
    pass


class KeyExists(StorageError):
    pass


class KeyNotFound(StorageError):
    pass


class Conflict(StorageError):
    """CAS failure: resourceVersion precondition not met."""


class TooOldResourceVersion(StorageError):
    """Requested watch start is before the retained event window (HTTP 410)."""

    def __init__(self, requested: int, oldest: int):
        self.requested = requested
        self.oldest = oldest
        super().__init__(f"resourceVersion {requested} is too old (oldest retained: {oldest})")


@dataclass(frozen=True)
class Event:
    type: str  # ADDED | MODIFIED | DELETED
    key: str
    rv: int
    obj: dict  # for DELETED, the last state of the object
    prev_obj: Optional[dict] = None  # state before this event (etcd prevKV);
    # lets selector-filtered watches synthesize ADDED/DELETED on set
    # transitions (the reference cacher/etcd_watcher transform)


def _copy(obj: dict) -> dict:
    # values are JSON-shaped; json roundtrip is the fastest general deep copy
    return json.loads(json.dumps(obj))


class _Watcher:
    """One watch stream. Iterate to consume events; `stop()` to cancel.

    maxlen bounds the live queue: overflow drops the watcher with an ERROR
    event (slow-watcher termination, cacher.go:73). The initial replay is
    exempt (it is already bounded by the store's retained window)."""

    def __init__(self, store: "MemStore", prefix: str, pending: List[Event],
                 maxlen: int = 0):
        import queue

        self._store = store
        self.prefix = prefix
        self._q: "queue.Queue[Optional[Event]]" = queue.Queue()
        self._maxlen = maxlen
        # the replay prefix doesn't count against the live bound: a resuming
        # watcher near the window edge must not be dropped before its
        # consumer even runs
        self._grace = len(pending)
        self._stopped = False
        self.dropped = False
        for ev in pending:
            self._q.put(ev)

    @property
    def stopped(self) -> bool:
        return self._stopped

    def _deliver(self, ev: Event):
        if self._stopped or not ev.key.startswith(self.prefix):
            return
        if self._maxlen and self._q.qsize() >= self._maxlen + self._grace:
            # too far behind: cut it loose rather than block writers or
            # grow the queue without bound; the client re-lists
            self._stopped = True
            self.dropped = True
            self._store._remove_watcher(self)
            self._q.put(Event(ERROR, self.prefix, ev.rv, {
                "kind": "Status", "status": "Failure", "reason": "Expired",
                "message": f"watch fell {self._maxlen} events behind and "
                           f"was dropped; re-list and re-watch", "code": 410,
            }))
            self._q.put(None)
            return
        self._q.put(ev)

    def stop(self):
        if not self._stopped:
            self._stopped = True
            self._store._remove_watcher(self)
            self._q.put(None)  # unblock consumers

    def __iter__(self):
        return self

    def __next__(self) -> Event:
        ev = self._q.get()
        if ev is None:
            raise StopIteration
        return ev

    def next(self, timeout: Optional[float] = None) -> Optional[Event]:
        """Blocking pop with timeout; None on timeout or stop."""
        import queue

        try:
            ev = self._q.get(timeout=timeout)
        except queue.Empty:
            return None
        return ev


class MemStore:
    """The versioned KV + watch window. Keys are '/'-separated paths like
    '/pods/default/web-1' (reference key layout '/registry/pods/<ns>/<name>')."""

    def __init__(self, window: int = 4096, watcher_queue: int = 4096):
        self._lock = threading.RLock()
        self._data: Dict[str, Tuple[dict, int]] = {}
        self._rv = 0
        self._events: deque = deque(maxlen=window)
        self._watcher_queue = watcher_queue
        self._watchers: List[_Watcher] = []

    # --- reads ---------------------------------------------------------------

    @property
    def current_rv(self) -> int:
        with self._lock:
            return self._rv

    def get(self, key: str) -> Tuple[dict, int]:
        with self._lock:
            try:
                obj, rv = self._data[key]
            except KeyError:
                raise KeyNotFound(key) from None
            return _copy(obj), rv

    def list(self, prefix: str) -> Tuple[List[Tuple[dict, int]], int]:
        """All objects under prefix plus the store rv at snapshot time."""
        with self._lock:
            items = [(_copy(o), rv) for k, (o, rv) in sorted(self._data.items())
                     if k.startswith(prefix)]
            return items, self._rv

    def count(self, prefix: str) -> int:
        with self._lock:
            return sum(1 for k in self._data if k.startswith(prefix))

    # --- writes --------------------------------------------------------------

    def create(self, key: str, obj: dict) -> int:
        with self._lock:
            if key in self._data:
                raise KeyExists(key)
            self._rv += 1
            obj = _copy(obj)
            self._data[key] = (obj, self._rv)
            # events carry their own copy so a watcher mutating ev.obj cannot
            # corrupt authoritative state
            self._publish(Event(ADDED, key, self._rv, _copy(obj)))
            return self._rv

    def update(self, key: str, obj: dict, expect_rv: Optional[int] = None) -> int:
        """Unconditional (expect_rv=None) or CAS update."""
        with self._lock:
            if key not in self._data:
                raise KeyNotFound(key)
            prev, cur_rv = self._data[key]
            if expect_rv is not None and expect_rv != cur_rv:
                raise Conflict(f"{key}: rv {expect_rv} != current {cur_rv}")
            self._rv += 1
            obj = _copy(obj)
            self._data[key] = (obj, self._rv)
            self._publish(Event(MODIFIED, key, self._rv, _copy(obj), prev_obj=prev))
            return self._rv

    def guaranteed_update(self, key: str,
                          fn: Callable[[dict, int], Optional[dict]],
                          max_retries: int = 10) -> Tuple[dict, int]:
        """CAS retry loop: fn(current, current_rv) -> new object (or raise to
        abort). fn returning None aborts without error (no-op). In-process
        the lock makes one attempt sufficient, but the retry structure is
        kept because fn may observe state via other stores/side effects."""
        for _ in range(max_retries):
            obj, rv = self.get(key)
            new = fn(obj, rv)
            if new is None:
                return obj, rv
            try:
                new_rv = self.update(key, new, expect_rv=rv)
                return _copy(new), new_rv
            except Conflict:
                # request-scoped CAS accounting: the apiserver's audit
                # record reports how contended this write was (lazy import —
                # the storage layer stays importable standalone)
                from kubernetes_tpu.utils.trace import note_cas_retry
                note_cas_retry()
                continue
        raise Conflict(f"{key}: too much contention")

    def delete(self, key: str, expect_rv: Optional[int] = None) -> Tuple[dict, int]:
        with self._lock:
            if key not in self._data:
                raise KeyNotFound(key)
            obj, cur_rv = self._data[key]
            if expect_rv is not None and expect_rv != cur_rv:
                raise Conflict(f"{key}: rv {expect_rv} != current {cur_rv}")
            self._rv += 1
            del self._data[key]
            self._publish(Event(DELETED, key, self._rv, _copy(obj), prev_obj=obj))
            return _copy(obj), self._rv

    # --- watch ---------------------------------------------------------------

    def watch(self, prefix: str, since_rv: Optional[int] = None) -> _Watcher:
        """Stream events for keys under prefix. since_rv=None starts from now;
        otherwise replays retained events with rv > since_rv first.

        since_rv == 0 means "from the beginning of time", which is only valid
        while the window still reaches back to the first event."""
        with self._lock:
            pending: List[Event] = []
            if since_rv is not None and since_rv < self._rv:
                oldest_buffered = self._events[0].rv if self._events else self._rv + 1
                # we can serve since_rv if every event after it is retained
                if since_rv + 1 < oldest_buffered:
                    raise TooOldResourceVersion(since_rv, oldest_buffered)
                pending = [e for e in self._events
                           if e.rv > since_rv and e.key.startswith(prefix)]
            w = _Watcher(self, prefix, pending, maxlen=self._watcher_queue)
            self._watchers.append(w)
            return w

    def _publish(self, ev: Event):
        self._events.append(ev)
        for w in list(self._watchers):
            w._deliver(ev)

    def _remove_watcher(self, w: _Watcher):
        with self._lock:
            try:
                self._watchers.remove(w)
            except ValueError:
                pass

    def compact(self, keep: int = 0):
        """Drop retained events (forces laggy watchers to re-list) —
        etcd3 compaction analogue (pkg/storage/etcd3/compact.go)."""
        with self._lock:
            while len(self._events) > keep:
                self._events.popleft()
