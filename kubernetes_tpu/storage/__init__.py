"""L0 persistence: versioned, watchable KV store.

Parity target: reference pkg/storage — storage.Interface
(pkg/storage/interfaces.go:82-163: Create/Get/List/Delete/GuaranteedUpdate/
Watch/WatchList) fused with the Cacher/watchCache fan-out layer
(pkg/storage/cacher.go:73, watch_cache.go:64). The reference splits these
because etcd is an external process; here the store is in-process, so the
watch window is built in and every watcher is served from the same ring
buffer that a separate cache would have maintained.
"""

from kubernetes_tpu.storage.store import (
    Event, MemStore, StorageError, KeyExists, KeyNotFound, Conflict,
    TooOldResourceVersion, ADDED, MODIFIED, DELETED,
)
from kubernetes_tpu.storage.durable import DurableStore
from kubernetes_tpu.storage.replicated import (
    NoQuorum, ReplicatedStore, ReplicationGroup, StoreMember,
)
