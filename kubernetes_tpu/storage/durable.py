"""Durable storage: WAL + snapshot persistence behind the MemStore API.

The reference's L0 is etcd — raft-replicated, versioned keys, watchable
(pkg/storage/etcd/etcd_helper.go, api_object_versioner.go). In-process we
keep MemStore's exact semantics (single monotonically-increasing
resourceVersion, CAS guaranteed_update, bounded watch window with 410) and
add crash durability the way etcd itself does locally: every mutation is
appended to a write-ahead log before it is published, and the log is
periodically folded into a snapshot (etcd's snapshot + WAL-compaction
cycle, pkg/storage/etcd3/compact.go analogue for the on-disk form).

Recovery = load latest snapshot, replay WAL entries with rv beyond it.
The watch-event window deliberately does NOT survive restart: a restarted
server serves watches from "now", clients with older resourceVersions get
410 Gone and re-list — exactly the Reflector contract
(pkg/client/cache/reflector.go:252), so crash-restart needs no special
casing anywhere above L0.

Layout under data_dir/:
  snapshot.json   {"rv": N, "data": {key: [obj, rv]}}
  wal.log         one JSON line per mutation: {"t","k","rv","o"}
  wal.log.1       rotated segment awaiting compaction (exists only while a
                  snapshot is in flight or after a crash mid-snapshot)

Compaction never blocks the store: when the op threshold trips, the WAL is
rotated under the lock (cheap rename), and a background thread serializes
the state copy, fsyncs the snapshot, and deletes the old segment. A crash
at ANY point is safe — recovery loads the newest snapshot, then replays
wal.log.1 (if present) and wal.log, skipping entries the snapshot already
folded. A torn final WAL line (crash mid-append) is detected and dropped.
fsync=True makes every append durable before the write returns (etcd's
default); tests and benches keep it off.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from typing import Optional

_log = logging.getLogger("storage.durable")

from kubernetes_tpu.storage.store import (
    ADDED, DELETED, MODIFIED, Event, MemStore,
)

SNAPSHOT = "snapshot.json"
WAL = "wal.log"
WAL_OLD = "wal.log.1"


def fsync_dir(path: str) -> None:
    """fsync a directory: os.replace/os.remove only become durable once the
    containing directory's metadata hits disk (POSIX rename semantics — the
    file's own fsync says nothing about its NAME). Called after compaction
    renames so a crash cannot resurrect a deleted WAL segment next to the
    snapshot that superseded it. Never call this while holding a store lock
    (kube-verify replication-lock-io polices the replication layer's copy
    of this rule)."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


class DurableStore(MemStore):
    """MemStore + WAL/snapshot persistence. Drop-in for Registry(store=...)."""

    def __init__(self, data_dir: str, window: int = 4096,
                 watcher_queue: int = 4096, fsync: bool = False,
                 snapshot_every: int = 10000):
        super().__init__(window=window, watcher_queue=watcher_queue)
        self._dir = data_dir
        self._fsync = fsync
        self._snapshot_every = snapshot_every
        self._ops_since_snapshot = 0
        self._snapshotting = False
        self._snapshot_thread: Optional[threading.Thread] = None
        self._closed = False
        self.replayed = 0   # WAL entries applied during recovery
        self.dropped_entries = 0  # WAL lines discarded past a torn line
        os.makedirs(data_dir, exist_ok=True)
        self._recover()
        self._wal = open(os.path.join(data_dir, WAL), "a",
                         encoding="utf-8")
        if os.path.exists(os.path.join(data_dir, WAL_OLD)):
            # crash landed between WAL rotation and snapshot rename: the
            # recovered state already folds the old segment in, so compact
            # it away now (synchronously — no concurrency during init)
            self._snapshotting = True
            self._compact(self._rv, dict(self._data))

    # --- recovery --------------------------------------------------------------

    def _recover(self):
        snap_path = os.path.join(self._dir, SNAPSHOT)
        if os.path.exists(snap_path):
            with open(snap_path, encoding="utf-8") as f:
                snap = json.load(f)
            self._rv = snap["rv"]
            self._data = {k: (obj, rv) for k, (obj, rv) in
                          snap["data"].items()}
        # rotated-but-uncompacted segment first (crash mid-snapshot), then
        # the live log; snapshot-covered entries are skipped by rv
        torn = False
        for name in (WAL_OLD, WAL):
            path = os.path.join(self._dir, name)
            if not os.path.exists(path):
                continue
            with open(path, encoding="utf-8") as f:
                if torn:
                    # a tear in the earlier segment: entries here are
                    # rv-later than the gap — applying them would fabricate
                    # history across the hole
                    self.dropped_entries += sum(1 for _ in f)
                    continue
                for lineno, line in enumerate(f, start=1):
                    try:
                        e = json.loads(line)
                        t, k, rv, obj = e["t"], e["k"], e["rv"], e["o"]
                    except (json.JSONDecodeError, KeyError):
                        # a crash mid-append tears the line it was writing;
                        # recovery stops AT the tear and says how much it
                        # dropped — a mid-file tear (bit rot, concurrent
                        # writer bug) must never truncate history silently
                        torn = True
                        self.dropped_entries += 1 + sum(1 for _ in f)
                        _log.warning(
                            "%s torn at line %d; dropped %d entr%s after "
                            "the tear (recovered rv=%d)",
                            path, lineno, self.dropped_entries,
                            "y" if self.dropped_entries == 1 else "ies",
                            self._rv)
                        break
                    if rv <= self._rv:
                        continue  # already folded into the snapshot
                    if t == DELETED:
                        self._data.pop(k, None)
                    else:
                        self._data[k] = (obj, rv)
                    self._rv = rv
                    self.replayed += 1

    # --- persistence hook -------------------------------------------------------

    def _publish(self, ev: Event):
        # called with the store lock held, after the in-memory mutation and
        # before any watcher sees the event: the WAL is ahead of observers
        self._wal.write(json.dumps(
            {"t": ev.type, "k": ev.key, "rv": ev.rv, "o": ev.obj},
            separators=(",", ":")) + "\n")
        self._wal.flush()
        if self._fsync:
            os.fsync(self._wal.fileno())
        self._ops_since_snapshot += 1
        if (self._ops_since_snapshot >= self._snapshot_every
                and not self._snapshotting and not self._closed):
            # rotate under the lock (cheap), compact on a background thread
            # — a full-store JSON dump must never stall the request path
            self._snapshotting = True
            self._ops_since_snapshot = 0
            if os.path.exists(os.path.join(self._dir, WAL_OLD)):
                # a previous compaction failed and left its segment: compact
                # the CURRENT state (it covers both segments), no rotation
                snap_rv, snap_data = self._rv, dict(self._data)
            else:
                snap_rv, snap_data = self._rotate_wal_locked()
            t = threading.Thread(
                target=self._compact, args=(snap_rv, snap_data),
                name="store-snapshot", daemon=True)
            self._snapshot_thread = t
            t.start()
        super()._publish(ev)

    # --- snapshot / compaction ----------------------------------------------------

    def _rotate_wal_locked(self):
        """Swap in a fresh WAL segment and copy (rv, data) refs; caller
        holds the store lock (reached from _publish)."""
        self._wal.close()
        os.replace(os.path.join(self._dir, WAL),
                   os.path.join(self._dir, WAL_OLD))
        self._wal = open(os.path.join(self._dir, WAL), "w", encoding="utf-8")
        # shallow copy: stored objects are never mutated in place (the
        # store deep-copies on write), so refs are stable for serialization
        return self._rv, dict(self._data)

    def _compact(self, snap_rv: int, snap_data: dict):
        try:
            # make the WAL rotation rename durable FIRST: until the
            # directory entry hits disk, a crash could leave the old inode
            # still named wal.log while the snapshot below supersedes it —
            # recovery would then see segments in an order that never
            # existed. (Runs off-lock by construction: compaction is a
            # background/synchronous fold, never inside the store lock.)
            fsync_dir(self._dir)
            snap = {"rv": snap_rv,
                    "data": {k: [obj, rv] for k, (obj, rv) in
                             snap_data.items()}}
            tmp = os.path.join(self._dir, SNAPSHOT + ".tmp")
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(snap, f, separators=(",", ":"))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, os.path.join(self._dir, SNAPSHOT))
            # snapshot durable: any rotated segment is now redundant
            try:
                os.remove(os.path.join(self._dir, WAL_OLD))
            except FileNotFoundError:
                pass
            # ... and the replace+remove pair must be durable as a unit:
            # without this fsync a crash here can resurrect wal.log.1 next
            # to the NEW snapshot, re-ordering recovery's segment replay
            fsync_dir(self._dir)
        except Exception:
            # disk-full etc: data stays safe (segments remain), the next
            # threshold retries via the salvage path — but say so loudly
            _log.exception("snapshot compaction failed; WAL keeps growing "
                           "until a retry succeeds")
        finally:
            self._snapshotting = False

    def snapshot(self):
        """Synchronous fold (external callers / shutdown): rotate + compact
        on the calling thread; salvages a failed prior compaction's segment
        the same way the threshold path does."""
        with self._lock:
            if self._closed:
                # rotating would reopen the WAL handle close() just shut;
                # the final state is already durable (close drained it)
                _log.warning("snapshot() on closed store %s: no-op",
                             self._dir)
                return
            if self._snapshotting:
                return
            self._snapshotting = True
            self._ops_since_snapshot = 0
            if os.path.exists(os.path.join(self._dir, WAL_OLD)):
                snap_rv, snap_data = self._rv, dict(self._data)
            else:
                snap_rv, snap_data = self._rotate_wal_locked()
        self._compact(snap_rv, snap_data)

    def close(self):
        # flag first (stops new compactions spawning), then drain any
        # in-flight background compaction OUTSIDE the lock (the compactor
        # never takes the store lock, but join can outlast a slow disk and
        # must not stall readers) — an abandoned compactor racing close()
        # otherwise deletes/renames files under a store shutting down
        with self._lock:
            self._closed = True
            t = self._snapshot_thread
        if t is not None and t.is_alive():
            t.join(timeout=30)
        with self._lock:
            try:
                self._wal.flush()
                self._wal.close()
            except ValueError:
                pass  # already closed
