"""Quorum replication over the durable store: the control plane's HA floor.

The reference delegates all of this to etcd's raft layer
(pkg/storage/etcd/etcd_helper.go) — a write is acknowledged only once a
quorum of members has it on disk, a leader crash promotes the most
up-to-date survivor, and a restarted member catches up from snapshot +
log tail. In-process we own that layer (ROADMAP item 4):

  StoreMember        one replica: a term-stamped durable log (WAL +
                     snapshot, same on-disk idiom as DurableStore) plus the
                     applied key/value state. Members never serve clients.
  ReplicationGroup   election + quorum commit. All member RPCs flow through
                     one transport serialized by the ship gate; a commit is
                     append -> quorum ack (durable on >= 2 of 3) -> done.
  ReplicatedStore    the MemStore-compatible facade every apiserver's
                     Registry shares. Writes stage under the store lock,
                     replicate OUTSIDE it, and publish watch events only
                     after the quorum ack — an event a watcher has seen is
                     by construction on a majority of disks.

Semantics preserved exactly (the acceptance contract): one monotonically
increasing resourceVersion, CAS `update(expect_rv)` /
`guaranteed_update`, bounded watch window with 410 — the existing store
tests run parameterized over MemStore/DurableStore/ReplicatedStore.

Safety argument (raft §5.4.1, scoped to the in-process model): the facade
serializes all writes, so member logs are a prefix/overlap of one
sequence — the only divergence source is a partial ship (a member died
mid-round). An acked entry is on >= quorum members; election requires
votes from >= quorum members, each granting only to a candidate whose
log is at least as up-to-date — the intersection forces every acked
entry into the new leader. A commit that could NOT reach quorum leaves
its entry "stuck": the facade never re-stages that resourceVersion with
different content (which would fork the log); the stuck entry is rolled
forward — re-shipped until it commits — before any later write is
accepted, surfacing NoQuorum (HTTP 503) to clients meanwhile.

What this deliberately does not model: network partitions BETWEEN group
coordinators (there is one group object per process — the fabric itself
cannot split-brain). The chaos surface is member crash/restart at any
pipeline stage, which is what the leader_kill soak scenario and the
crash-recovery matrix in tests/test_replicated.py drive.

Lock/IO discipline (policed by kube-verify's `replication-lock-io`
checker): no transport send and no fsync ever runs while holding a store
or member lock. Locks cover staging and state application only; the
round-trip happens holding the commit gate (facade) and ship gate
(group) — writer batons that readers and watchers never touch.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.storage.durable import SNAPSHOT, WAL, fsync_dir
from kubernetes_tpu.storage.store import (
    ADDED, DELETED, MODIFIED, Conflict, Event, KeyExists, KeyNotFound,
    MemStore, StorageError, _copy,
)
from kubernetes_tpu.utils.metrics import REGISTRY as METRICS

_log = logging.getLogger("storage.replicated")


class NoQuorum(StorageError):
    """The write could not reach a durable majority. Outcome UNKNOWN: the
    entry may sit on a minority log and commit later (clients treat this
    like any timeout — re-read, then retry)."""


class MemberDown(StorageError):
    """Transport-level: the target replica is not serving."""


class LoopbackTransport:
    """In-process member RPC fabric with chaos hooks. `before_send(method,
    member)` runs before every delivery and may kill members or raise — the
    crash-matrix tests inject faults here, the soak kills members directly."""

    def __init__(self):
        self.before_send = None

    def call(self, member: "StoreMember", method: str, *args):
        hook = self.before_send
        if hook is not None:
            hook(method, member)
        if not member.alive:
            raise MemberDown(member.id)
        return getattr(member, method)(*args)


class StoreMember:
    """One storage replica: term-stamped durable log + applied state.

    Disk layout mirrors DurableStore (snapshot.json + wal.log); every WAL
    line additionally carries the entry's term (`m`). Members are written
    to only through the group (whose ship gate serializes all RPCs), so
    log lines never interleave even though the WAL write + fsync happen
    outside the member lock — the structural rotate-under-lock /
    ship-outside-lock split the replication-lock-io checker enforces."""

    def __init__(self, member_id: str, data_dir: str, fsync: bool = False,
                 snapshot_every: int = 10000):
        self.id = member_id
        self._dir = data_dir
        self._fsync = fsync
        self._snapshot_every = snapshot_every
        self._lock = threading.RLock()
        self._data: Dict[str, Tuple[dict, int]] = {}
        self._rv = 0                 # rv of the last applied entry
        self.term = 1                # highest term seen
        self.last_entry_term = 0     # term of the entry at self._rv
        self._voted_term = 0         # highest term this member voted in
        self._snap_rv = 0            # rv covered by the on-disk snapshot
        self._ops_since_snapshot = 0
        self.alive = True
        self.replayed = 0
        self.dropped_entries = 0
        os.makedirs(data_dir, exist_ok=True)
        self._recover()
        self._wal = open(os.path.join(data_dir, WAL), "a", encoding="utf-8")

    # --- recovery / restart ---------------------------------------------------

    def _recover(self) -> None:
        snap_path = os.path.join(self._dir, SNAPSHOT)
        if os.path.exists(snap_path):
            with open(snap_path, encoding="utf-8") as f:
                snap = json.load(f)
            self._rv = self._snap_rv = snap["rv"]
            self.term = max(self.term, snap.get("term", 1))
            self.last_entry_term = snap.get("entry_term", 0)
            self._data = {k: (obj, rv) for k, (obj, rv) in
                          snap["data"].items()}
        path = os.path.join(self._dir, WAL)
        if not os.path.exists(path):
            return
        with open(path, encoding="utf-8") as f:
            for lineno, line in enumerate(f, start=1):
                try:
                    e = json.loads(line)
                    t, k, rv = e["t"], e["k"], e["rv"]
                    obj, term = e["o"], e["m"]
                except (json.JSONDecodeError, KeyError):
                    # same contract as DurableStore: stop at the tear, say
                    # how much was dropped — never truncate silently
                    self.dropped_entries = 1 + sum(1 for _ in f)
                    _log.warning(
                        "member %s: %s torn at line %d; dropped %d "
                        "entr%s after the tear (recovered rv=%d)",
                        self.id, path, lineno, self.dropped_entries,
                        "y" if self.dropped_entries == 1 else "ies",
                        self._rv)
                    break
                if rv <= self._snap_rv:
                    continue  # folded into the snapshot already
                # last-wins per rv: a superseded slot (leader overwrite of
                # an orphan) appears as a later line for the same rv
                if t == DELETED:
                    self._data.pop(k, None)
                else:
                    self._data[k] = (obj, rv)
                self._rv = max(self._rv, rv)
                self.last_entry_term = term
                self.term = max(self.term, term)
                self.replayed += 1

    def restart(self) -> None:
        """Crash-restart: rebuild from disk alone (in-memory state is gone),
        then the group catches this member up before it serves votes."""
        with self._lock:
            self._data = {}
            self._rv = self._snap_rv = 0
            self.last_entry_term = 0
            self.replayed = 0
            self.dropped_entries = 0
            self._ops_since_snapshot = 0
        self._recover()
        self._wal = open(os.path.join(self._dir, WAL), "a", encoding="utf-8")
        with self._lock:
            self.alive = True

    def kill(self) -> None:
        """Simulated crash: stop serving; the WAL handle dies with us. Every
        acked append was already flushed (the ack IS the durability), so
        nothing acknowledged is lost."""
        with self._lock:
            self.alive = False
        try:
            self._wal.close()
        except (OSError, ValueError):
            pass

    def close(self) -> None:
        self.kill()

    # --- RPCs (reached only through the group's serialized transport) ---------

    def append_entries(self, term: int, entries: List[dict]) -> bool:
        """Durable log append + apply. Ack (True) means the entries are on
        this member's disk. Stage under the lock, write the log OUTSIDE it,
        apply under the lock."""
        with self._lock:
            if term < self.term:
                return False  # stale leader
            self.term = term
            fresh = [e for e in entries if e["rv"] > self._rv]
        if fresh:
            for e in fresh:
                self._wal.write(json.dumps(
                    {"m": e["m"], "t": e["t"], "k": e["k"],
                     "rv": e["rv"], "o": e["o"]},
                    separators=(",", ":")) + "\n")
            self._wal.flush()
            if self._fsync:
                os.fsync(self._wal.fileno())
        with self._lock:
            for e in fresh:
                if e["t"] == DELETED:
                    self._data.pop(e["k"], None)
                else:
                    self._data[e["k"]] = (e["o"], e["rv"])
                self._rv = e["rv"]
                self.last_entry_term = e["m"]
            self._ops_since_snapshot += len(fresh)
            needs_compact = self._ops_since_snapshot >= self._snapshot_every
        if needs_compact:
            self._compact()
        return True

    def request_vote(self, term: int, last_rv: int, last_term: int) -> bool:
        """Grant iff we have not voted in this term and the candidate's log
        is at least as up-to-date as ours (raft §5.4.1 — the rule that
        forces every quorum-acked entry into the next leader)."""
        with self._lock:
            if term <= self._voted_term or term < self.term:
                return False
            if (last_term, last_rv) < (self.last_entry_term, self._rv):
                return False
            self._voted_term = term
            self.term = max(self.term, term)
            return True

    def install_snapshot(self, term: int, rv: int, data: Dict[str, tuple],
                         entry_term: int) -> bool:
        """Full state transfer (catch-up fallback when the WAL tail was
        compacted away, or to truncate a divergent minority tail). Durable
        snapshot write happens outside the lock."""
        with self._lock:
            if term < self.term:
                return False
            self.term = term
        self._write_snapshot(rv, entry_term, dict(data))
        with self._lock:
            self._data = dict(data)
            self._rv = self._snap_rv = rv
            self.last_entry_term = entry_term
            self._ops_since_snapshot = 0
        return True

    # --- catch-up source (leader side) ----------------------------------------

    def read_log_tail(self, since_rv: int) -> Optional[List[dict]]:
        """Entries with rv > since_rv from the on-disk log, dedup'd last-wins
        per rv — the cheap catch-up path. None when the tail was compacted
        past since_rv (the caller falls back to install_snapshot)."""
        if since_rv < self._snap_rv:
            return None
        by_rv: Dict[int, dict] = {}
        path = os.path.join(self._dir, WAL)
        if os.path.exists(path):
            with open(path, encoding="utf-8") as f:
                for line in f:
                    try:
                        e = json.loads(line)
                    except json.JSONDecodeError:
                        break
                    if e.get("rv", 0) > since_rv:
                        by_rv[e["rv"]] = {"m": e["m"], "t": e["t"],
                                          "k": e["k"], "rv": e["rv"],
                                          "o": e["o"]}
        tail = [by_rv[rv] for rv in sorted(by_rv)]
        # contiguity: a hole means the log cannot replay cleanly from
        # since_rv — force the snapshot path rather than fabricate history
        expect = since_rv + 1
        for e in tail:
            if e["rv"] != expect:
                return None
            expect += 1
        if expect <= self._rv:
            return None  # log ends short of the applied state
        return tail

    # --- compaction -----------------------------------------------------------

    def _compact(self) -> None:
        """Fold the log into the snapshot. Runs inside an append RPC (the
        ship gate serializes all appends) but outside the member lock."""
        with self._lock:
            snap_rv, entry_term = self._rv, self.last_entry_term
            snap_data = dict(self._data)
            self._ops_since_snapshot = 0
        try:
            self._write_snapshot(snap_rv, entry_term, snap_data)
        except OSError:
            _log.exception("member %s: compaction failed; WAL keeps "
                           "growing until a retry succeeds", self.id)
            return
        with self._lock:
            self._snap_rv = snap_rv

    def _write_snapshot(self, rv: int, entry_term: int,
                        data: Dict[str, tuple]) -> None:
        snap = {"rv": rv, "term": self.term, "entry_term": entry_term,
                "data": {k: [obj, irv] for k, (obj, irv) in data.items()}}
        tmp = os.path.join(self._dir, SNAPSHOT + ".tmp")
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(snap, f, separators=(",", ":"))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self._dir, SNAPSHOT))
        fsync_dir(self._dir)
        # snapshot durable: the log it folded is redundant — truncate
        try:
            self._wal.close()
        except (OSError, ValueError):
            pass
        self._wal = open(os.path.join(self._dir, WAL), "w", encoding="utf-8")
        fsync_dir(self._dir)

    # --- introspection --------------------------------------------------------

    def last_log_pos(self) -> Tuple[int, int]:
        with self._lock:
            return (self.last_entry_term, self._rv)

    def state_digest(self) -> Tuple[int, str]:
        """(rv, stable content hash) — the convergence check the chaos soak
        and the crash matrix assert on."""
        import hashlib
        with self._lock:
            blob = json.dumps(sorted(self._data.items()),
                              separators=(",", ":"), sort_keys=True)
            return (self._rv,
                    hashlib.sha1(blob.encode()).hexdigest()[:16])

    def committed_state(self) -> Tuple[int, Dict[str, tuple], int]:
        with self._lock:
            return self._rv, dict(self._data), self.last_entry_term


class ReplicationGroup:
    """Election + quorum commit. One group object per process: it IS the
    members' communication fabric, so chaos means member crashes (any
    pipeline stage), not fabric partitions."""

    def __init__(self, members: List[StoreMember],
                 heartbeat_period: float = 0.0,
                 quorum_deadline: float = 5.0,
                 transport: Optional[LoopbackTransport] = None):
        if len(members) < 3:
            raise ValueError("quorum replication needs >= 3 members")
        self.members = list(members)
        self.transport = transport or LoopbackTransport()
        self.quorum = len(members) // 2 + 1
        self.quorum_deadline = quorum_deadline
        self._meta = threading.Lock()       # term/leader bookkeeping only
        self._ship_gate = threading.Lock()  # serializes ALL member RPCs
        self.term = max(m.term for m in members)
        self.leader_id: Optional[str] = None
        self.leader_transitions = 0
        self.failovers: List[float] = []    # detection -> new leader, seconds
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        with self._ship_gate:
            self._elect(time.monotonic())
        if heartbeat_period > 0:
            self._monitor = threading.Thread(
                target=self._monitor_loop, args=(heartbeat_period,),
                name="replication-monitor", daemon=True)
            self._monitor.start()

    # --- leader bookkeeping ---------------------------------------------------

    def leader(self) -> Optional[StoreMember]:
        with self._meta:
            lid = self.leader_id
        for m in self.members:
            if m.id == lid:
                return m
        return None

    def member(self, member_id: str) -> StoreMember:
        for m in self.members:
            if m.id == member_id:
                return m
        raise KeyError(member_id)

    def alive_members(self) -> List[StoreMember]:
        return [m for m in self.members if m.alive]

    def committed_state(self) -> Tuple[int, Dict[str, tuple], int]:
        lead = self.leader()
        if lead is None:
            raise NoQuorum("no leader")
        return lead.committed_state()

    def converged(self) -> bool:
        digests = {m.state_digest() for m in self.alive_members()}
        return len(digests) == 1

    # --- the commit pipeline --------------------------------------------------

    def commit(self, entry: dict) -> None:
        """Drive one entry to a durable quorum; raises NoQuorum after the
        deadline. Leader death mid-round triggers an election and a re-ship
        — callers above surface that as latency, never as data loss."""
        deadline = time.monotonic() + self.quorum_deadline
        with self._ship_gate:
            while True:
                lead = self._leader_or_elect()
                if lead is not None:
                    with self._meta:
                        entry["m"] = self.term
                    acks = 0
                    leader_ok = self._append(lead, [entry])
                    acks += int(leader_ok)
                    for m in self.members:
                        if m is lead or not m.alive:
                            continue
                        acks += int(self._append(m, [entry]))
                    if leader_ok and acks >= self.quorum:
                        METRICS.inc("storage_quorum_commits_total",
                                    result="ok")
                        return
                if time.monotonic() >= deadline:
                    METRICS.inc("storage_quorum_commits_total",
                                result="noquorum")
                    raise NoQuorum(
                        f"entry rv={entry.get('rv')} reached no durable "
                        f"majority within {self.quorum_deadline:g}s")
                time.sleep(0.02)

    def _append(self, m: StoreMember, entries: List[dict]) -> bool:
        try:
            return bool(self.transport.call(m, "append_entries",
                                            self.term, entries))
        except (MemberDown, OSError, ValueError):
            return False

    def _leader_or_elect(self) -> Optional[StoreMember]:
        """Caller holds the ship gate. Returns a live leader, electing one
        if the current leader is dead; None if election failed (retry until
        the caller's deadline)."""
        lead = self.leader()
        if lead is not None and lead.alive:
            return lead
        try:
            self._elect(time.monotonic())
        except NoQuorum:
            return None
        return self.leader()

    # --- election -------------------------------------------------------------

    def _elect(self, t_detect: float) -> None:
        """Caller holds the ship gate. Raft-shaped: bump the term, the most
        up-to-date live member stands, a quorum of votes installs it, then
        followers are reconciled to its log."""
        alive = self.alive_members()
        if len(alive) < self.quorum:
            raise NoQuorum(f"{len(alive)}/{len(self.members)} members "
                           f"alive; quorum is {self.quorum}")
        with self._meta:
            self.term += 1
            term = self.term
        cand = max(alive, key=lambda m: m.last_log_pos())
        last_term, last_rv = cand.last_log_pos()
        votes = 0
        for m in alive:
            try:
                votes += int(self.transport.call(
                    m, "request_vote", term, last_rv, last_term))
            except (MemberDown, OSError):
                pass
        if votes < self.quorum:
            raise NoQuorum(f"election term {term}: {votes} votes "
                           f"< quorum {self.quorum}")
        with self._meta:
            prev = self.leader_id
            self.leader_id = cand.id
        for m in alive:
            if m is not cand:
                self._catch_up_member(m, cand)
        if prev is not None and prev != cand.id:
            self.leader_transitions += 1
            took = time.monotonic() - t_detect
            self.failovers.append(took)
            METRICS.inc("storage_leader_transitions_total")
            METRICS.observe("storage_failover_seconds", took)
            _log.warning("storage leader failover: %s -> %s (term %d, "
                         "%.3fs)", prev, cand.id, term, took)

    # --- catch-up -------------------------------------------------------------

    def _catch_up_member(self, m: StoreMember, lead: StoreMember) -> None:
        """Caller holds the ship gate. Snapshot + WAL tail when the leader's
        log still covers the gap; full snapshot otherwise (also the path
        that truncates a divergent minority tail)."""
        m_term, m_rv = m.last_log_pos()
        l_term, l_rv = lead.last_log_pos()
        mode = "snapshot"
        if m_rv <= l_rv and (m_term, m_rv) <= (l_term, l_rv):
            tail = lead.read_log_tail(m_rv)
            if tail is not None:
                if tail:
                    try:
                        if self.transport.call(m, "append_entries",
                                               self.term, tail):
                            mode = "tail"
                    except (MemberDown, OSError):
                        return
                else:
                    mode = "tail"  # already level
        if mode == "snapshot":
            rv, data, entry_term = lead.committed_state()
            try:
                self.transport.call(m, "install_snapshot", self.term, rv,
                                    data, entry_term)
            except (MemberDown, OSError):
                return
        METRICS.inc("storage_member_catchup_total", mode=mode)

    # --- chaos / lifecycle ----------------------------------------------------

    def kill_member(self, member_id: str) -> None:
        self.member(member_id).kill()

    def kill_leader(self) -> Optional[str]:
        lead = self.leader()
        if lead is None:
            return None
        lead.kill()
        return lead.id

    def restart_member(self, member_id: str) -> None:
        """Crash-recover a member from its disk and catch it up from the
        current leader — the rejoin path the crash matrix exercises."""
        m = self.member(member_id)
        m.restart()
        with self._ship_gate:
            lead = self._leader_or_elect()
            if lead is not None and lead is not m:
                self._catch_up_member(m, lead)

    def heartbeat(self) -> bool:
        """One monitor tick: ping the leader (empty append); a dead leader
        triggers an election. Returns True when a live leader exists."""
        t0 = time.monotonic()
        with self._ship_gate:
            lead = self.leader()
            if lead is not None and lead.alive and self._append(lead, []):
                return True
            try:
                self._elect(t0)
            except NoQuorum:
                return False
            return True

    def _monitor_loop(self, period: float) -> None:
        while not self._stop.wait(period):
            try:
                self.heartbeat()
            except Exception:
                _log.exception("replication monitor tick failed")

    def stop(self) -> None:
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5)


class ReplicatedStore(MemStore):
    """MemStore-compatible facade over a ReplicationGroup. Drop-in for
    Registry(store=...); every apiserver in the process shares ONE facade,
    exactly as every reference apiserver shares one etcd cluster."""

    def __init__(self, group: ReplicationGroup, window: int = 4096,
                 watcher_queue: int = 4096):
        super().__init__(window=window, watcher_queue=watcher_queue)
        self._group = group
        rv, data, _term = group.committed_state()
        self._rv = rv
        self._data = data
        # the writer baton: serializes stage -> replicate -> publish.
        # Readers/watchers never touch it — they see the store lock only,
        # which is never held across the replication round-trip.
        self._commit_gate = threading.Lock()
        # a NoQuorum'd entry: its rv slot is burned (restaging it with
        # different content would fork member logs); it must commit before
        # any later write is accepted
        self._stuck: Optional[Tuple[dict, Optional[dict]]] = None

    @property
    def group(self) -> ReplicationGroup:
        return self._group

    @classmethod
    def local(cls, base_dir: str, n: int = 3, fsync: bool = False,
              heartbeat_period: float = 0.0, window: int = 4096,
              watcher_queue: int = 4096, snapshot_every: int = 10000,
              quorum_deadline: float = 5.0) -> "ReplicatedStore":
        """A 3-member (by default) replicated store rooted at base_dir —
        the constructor the soak harness, the smoke, and tests share."""
        members = [StoreMember(f"m{i}", os.path.join(base_dir, f"member-{i}"),
                               fsync=fsync, snapshot_every=snapshot_every)
                   for i in range(n)]
        group = ReplicationGroup(members, heartbeat_period=heartbeat_period,
                                 quorum_deadline=quorum_deadline)
        return cls(group, window=window, watcher_queue=watcher_queue)

    # --- write pipeline -------------------------------------------------------

    def create(self, key: str, obj: dict) -> int:
        with self._commit_gate:
            self._roll_forward()
            with self._lock:
                if key in self._data:
                    raise KeyExists(key)
                obj = _copy(obj)
                entry = {"t": ADDED, "k": key, "rv": self._rv + 1, "o": obj}
            self._replicate(entry, None)
            return self._apply_committed(entry, None)

    def update(self, key: str, obj: dict,
               expect_rv: Optional[int] = None) -> int:
        with self._commit_gate:
            self._roll_forward()
            with self._lock:
                if key not in self._data:
                    raise KeyNotFound(key)
                prev, cur_rv = self._data[key]
                if expect_rv is not None and expect_rv != cur_rv:
                    raise Conflict(f"{key}: rv {expect_rv} != current "
                                   f"{cur_rv}")
                obj = _copy(obj)
                entry = {"t": MODIFIED, "k": key, "rv": self._rv + 1,
                         "o": obj}
            self._replicate(entry, prev)
            return self._apply_committed(entry, prev)

    def delete(self, key: str,
               expect_rv: Optional[int] = None) -> Tuple[dict, int]:
        with self._commit_gate:
            self._roll_forward()
            with self._lock:
                if key not in self._data:
                    raise KeyNotFound(key)
                obj, cur_rv = self._data[key]
                if expect_rv is not None and expect_rv != cur_rv:
                    raise Conflict(f"{key}: rv {expect_rv} != current "
                                   f"{cur_rv}")
                entry = {"t": DELETED, "k": key, "rv": self._rv + 1,
                         "o": obj}
            self._replicate(entry, obj)
            self._apply_committed(entry, obj)
            return _copy(entry["o"]), entry["rv"]

    # guaranteed_update is inherited unchanged: its get/update(expect_rv)
    # loop IS the CAS contract, and a leader change mid-loop surfaces as
    # the Conflict/retry path clients already speak.

    def _roll_forward(self) -> None:
        """Caller holds the commit gate: drive any stuck entry to quorum
        before staging new work (its effects must be visible to the next
        write's preconditions)."""
        if self._stuck is None:
            return
        entry, prev = self._stuck
        self._group.commit(entry)  # NoQuorum propagates; stays stuck
        self._apply_committed(entry, prev)
        self._stuck = None

    def _replicate(self, entry: dict, prev: Optional[dict]) -> None:
        try:
            self._group.commit(entry)
        except NoQuorum:
            self._stuck = (entry, prev)
            raise

    def _apply_committed(self, entry: dict, prev: Optional[dict]) -> int:
        """Quorum reached: apply to the serving state and publish the watch
        event — the first moment any observer may see this write."""
        t, k, rv, obj = entry["t"], entry["k"], entry["rv"], entry["o"]
        with self._lock:
            if t == DELETED:
                self._data.pop(k, None)
            else:
                self._data[k] = (obj, rv)
            self._rv = rv
            self._publish(Event(t, k, rv, _copy(obj),
                                prev_obj=prev if t != ADDED else None))
        return rv

    # --- lifecycle ------------------------------------------------------------

    def snapshot(self) -> None:
        """Durability is the members' concern; their logs fold on their own
        cadence. Kept for DurableStore API compatibility."""

    def close(self) -> None:
        self._group.stop()
        for m in self._group.members:
            m.close()
