"""Hollow node + hollow cluster orchestration.

A HollowNode is the real Kubelet with FakeRuntime/FakeCadvisor
(hollow_kubelet.go:35) and optionally a Proxier with FakeIptables
(hollow_proxy.go:35). HollowCluster boots N of them against one API server,
for scheduler_perf/density-style scale runs (test/kubemark/start-kubemark.sh
semantics, in-process).

Efficiency note: at N=1000s, one informer per hollow kubelet would open
1000s of watch streams; like kubemark's shared-client setup, HollowCluster
can multiplex all hollow kubelets over a single pod informer
(shared_informer=True) while keeping per-node state separate.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional

from kubernetes_tpu.api import fields as fieldsel
from kubernetes_tpu.api import types as api
from kubernetes_tpu.client import Informer, ListWatch, RESTClient
from kubernetes_tpu.kubelet import FakeRuntime, Kubelet
from kubernetes_tpu.kubelet.runtime import FakeCadvisor
from kubernetes_tpu.proxy import FakeIptables, Proxier
from kubernetes_tpu.utils.metrics import REGISTRY as METRICS

log = logging.getLogger("kubemark")


class HollowNode:
    def __init__(self, client: RESTClient, name: str, run_proxy: bool = False,
                 cpu: str = "4", memory: str = "32Gi", pods: str = "110",
                 labels: Optional[Dict[str, str]] = None):
        self.kubelet = Kubelet(
            client, name, runtime=FakeRuntime(),
            cadvisor=FakeCadvisor(cpu=cpu, memory=memory, pods=pods),
            node_labels=labels)
        self.proxy = Proxier(client, FakeIptables(), node_name=name) if run_proxy else None

    def start(self):
        self.kubelet.start()
        if self.proxy:
            self.proxy.start()
        return self

    def stop(self):
        self.kubelet.stop()
        if self.proxy:
            self.proxy.stop()


class HollowCluster:
    """N hollow nodes sharing one client + one pod informer."""

    def __init__(self, client: RESTClient, num_nodes: int,
                 zone_count: int = 3, cpu: str = "4", memory: str = "32Gi",
                 pods: str = "110"):
        self.client = client
        self.nodes: List[Kubelet] = []
        self._shared_informer: Optional[Informer] = None
        self._num = num_nodes
        self._zone_count = zone_count
        self._resources = dict(cpu=cpu, memory=memory, pods=pods)
        self._kubelets: Dict[str, Kubelet] = {}
        self._stop_evt = threading.Event()
        self._hb_thread = None

    def start(self, heartbeat_period: float = 30.0):
        # register all nodes first (bulk), then one shared informer feeds
        # every hollow kubelet's runtime, and one shared thread heartbeats
        # all of them (per-node loops don't scale to thousands in-process)
        for i in range(self._num):
            name = f"hollow-{i:05d}"
            labels = {api.LABEL_HOSTNAME: name,
                      api.LABEL_ZONE: f"zone-{i % self._zone_count}"}
            kl = Kubelet(self.client, name, runtime=FakeRuntime(),
                         cadvisor=FakeCadvisor(**self._resources),
                         heartbeat_period=heartbeat_period,
                         node_labels=labels)
            kl.register_node()
            self._kubelets[name] = kl
            self.nodes.append(kl)

        inf = Informer(ListWatch(
            self.client, "pods",
            field_selector=fieldsel.parse_field_selector("spec.nodeName!=")))

        def route(pod: api.Pod):
            kl = self._kubelets.get(pod.spec.node_name if pod.spec else "")
            if kl is not None:
                kl._dispatch(pod)

        def route_delete(pod: api.Pod):
            kl = self._kubelets.get(pod.spec.node_name if pod.spec else "")
            if kl is not None:
                kl._pod_deleted(pod)

        inf.add_event_handler(on_add=route,
                              on_update=lambda o, n: route(n),
                              on_delete=route_delete)
        inf.run()
        inf.wait_for_sync(30)
        self._shared_informer = inf

        METRICS.set_gauge("kubemark_hollow_nodes", len(self._kubelets))

        def hb_loop():
            while not self._stop_evt.wait(heartbeat_period):
                desired_by_node: Dict[str, set] = {}
                for p in inf.store.list():
                    desired_by_node.setdefault(p.spec.node_name, set()).add(
                        f"{p.metadata.namespace}/{p.metadata.name}")
                running = 0
                for name, kl in self._kubelets.items():
                    kl.heartbeat()
                    # shared-resync: reap runtime pods no longer desired
                    desired = desired_by_node.get(name, set())
                    for key in list(kl.runtime.running()):
                        if key not in desired:
                            kl.runtime.kill_pod(key)
                    running += len(kl.runtime.running())
                # the soak scraper's view of the hollow fleet: how many
                # pods the fake runtimes are actually carrying
                METRICS.set_gauge("kubemark_hollow_pods_running", running)

        self._hb_thread = threading.Thread(target=hb_loop,
                                           name="hollow-heartbeat", daemon=True)
        self._hb_thread.start()
        return self

    def running_pods(self) -> int:
        """Pods currently held by the hollow runtimes, across all nodes."""
        return sum(len(kl.runtime.running()) for kl in self._kubelets.values())

    def stop(self):
        self._stop_evt.set()
        # join the heartbeat loop BEFORE zeroing: an in-flight iteration
        # (seconds of REST calls at 1000 nodes) would otherwise overwrite
        # the zeros with one last nonzero count after the fleet is gone
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=30)
        METRICS.set_gauge("kubemark_hollow_nodes", 0)
        METRICS.set_gauge("kubemark_hollow_pods_running", 0)
        if self._shared_informer:
            self._shared_informer.stop()
