"""Scale harness: hollow nodes.

Parity target: reference cmd/kubemark/hollow-node.go + pkg/kubemark —
production kubelet/proxy code wired to fakes (docker/cadvisor/iptables) so
thousands of "nodes" run on one machine; the cluster under test is real
(apiserver, scheduler, controllers), only the container runtime is hollow.
"""

from kubernetes_tpu.kubemark.hollow import HollowCluster, HollowNode
