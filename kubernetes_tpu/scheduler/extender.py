"""HTTP scheduler extender: out-of-process filter/prioritize.

Parity target: reference plugin/pkg/scheduler/extender.go:39-173 — POST
ExtenderArgs{pod, nodes} to filter/prioritize verbs of an external service;
this is the plug-in boundary the reference reserves for backends exactly like
our TPU decision plane (BASELINE.json north star). The TPU backend can run
either in-process (scheduler/tpu.py) or behind this HTTP seam.
"""

from __future__ import annotations

import http.client
import json
from typing import Dict, List, Tuple
from urllib.parse import urlparse

from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.serialization import from_dict, scheme, to_dict


class HTTPExtender:
    def __init__(self, url_prefix: str, filter_verb: str = "filter",
                 prioritize_verb: str = "prioritize", weight: int = 1,
                 timeout: float = 5.0):
        self.url = urlparse(url_prefix)
        self.filter_verb = filter_verb
        self.prioritize_verb = prioritize_verb
        self.weight = weight
        self.timeout = timeout

    def _post(self, verb: str, payload: dict) -> dict:
        conn = http.client.HTTPConnection(self.url.hostname, self.url.port,
                                          timeout=self.timeout)
        try:
            path = (self.url.path.rstrip("/") or "") + "/" + verb
            conn.request("POST", path, body=json.dumps(payload),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            data = resp.read()
            if resp.status != 200:
                raise RuntimeError(f"extender {verb} returned {resp.status}")
            return json.loads(data)
        finally:
            conn.close()

    def filter(self, pod: api.Pod,
               nodes: List[api.Node]) -> Tuple[List[api.Node], Dict[str, str]]:
        if not self.filter_verb:
            return nodes, {}
        payload = {"pod": scheme.encode(pod),
                   "nodes": {"items": [to_dict(n) for n in nodes]}}
        result = self._post(self.filter_verb, payload)
        items = result.get("nodes", {}).get("items", [])
        kept = [from_dict(api.Node, d) for d in items]
        failures = {n: f"extender: {r}" for n, r in
                    (result.get("failedNodes") or {}).items()}
        return kept, failures

    def prioritize(self, pod: api.Pod, nodes: List[api.Node]) -> Dict[str, int]:
        if not self.prioritize_verb:
            return {}
        payload = {"pod": scheme.encode(pod),
                   "nodes": {"items": [to_dict(n) for n in nodes]}}
        result = self._post(self.prioritize_verb, payload)
        out = {}
        for entry in result or []:
            out[entry["host"]] = entry["score"] * self.weight
        return out


def extenders_from_config(configs: List[dict]) -> List[HTTPExtender]:
    """Build extenders from policy-file entries (api/types.go:114-131)."""
    out = []
    for c in configs:
        out.append(HTTPExtender(
            url_prefix=c["urlPrefix"],
            filter_verb=c.get("filterVerb", ""),
            prioritize_verb=c.get("prioritizeVerb", ""),
            weight=c.get("weight", 1),
            timeout=c.get("httpTimeout", 5.0)))
    return out
