"""The filter stage: FitPredicate functions.

Parity target: reference plugin/pkg/scheduler/algorithm/predicates/
predicates.go (1,030 ln). Each predicate is `fn(pod, node_info) -> None` and
raises PredicateFailure (with a reason) on mismatch — Python's idiomatic
version of the reference's `(bool, error)` returns and error taxonomy
(error.go: InsufficientResourceError / PredicateFailureError).

Complete predicate inventory (SURVEY §2.5) with reference anchors:
  pod_fits_resources        predicates.go:416-451
  pod_fits_host             predicates.go:533
  pod_fits_host_ports       predicates.go:687
  pod_matches_node_selector predicates.go:470-531 (nodeSelector ∧ NodeAffinity)
  general_predicates        predicates.go:733 (bundle of the four above)
  no_disk_conflict          predicates.go:105 (GCE-PD / EBS / RBD clash)
  max_pd_volume_count       predicates.go:137-269 (EBS<=39 / GCE<=16)
  volume_zone               predicates.go:271-347 (PV zone labels vs node)
  node_label_presence       predicates.go:552
  service_affinity          predicates.go:596-685
  inter_pod_affinity        predicates.go:769-947 (hard affinity/anti-affinity
                            incl. symmetry with existing pods' rules)
  pod_tolerates_node_taints predicates.go:960-1002
  check_node_memory_pressure predicates.go:1011 (BestEffort QoS gate)
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Set

from kubernetes_tpu.api import labels as labelsel
from kubernetes_tpu.api import types as api
from kubernetes_tpu.scheduler.cache import NodeInfo, pod_request

DEFAULT_MAX_EBS_VOLUMES = 39
DEFAULT_MAX_GCE_PD_VOLUMES = 16


class PredicateFailure(Exception):
    """A pod does not fit a node, with the reason the reference reports."""

    def __init__(self, reason: str):
        self.reason = reason
        super().__init__(reason)


class InsufficientResource(PredicateFailure):
    def __init__(self, resource: str, requested: int, used: int, capacity: int):
        self.resource = resource
        self.requested = requested
        self.used = used
        self.capacity = capacity
        super().__init__(
            f"Insufficient {resource}: requested {requested}, used {used}, "
            f"capacity {capacity}")


# --- resources ----------------------------------------------------------------

def pod_fits_resources(pod: api.Pod, node_info: NodeInfo) -> None:
    """cpu/mem/gpu requests + pod-count vs Allocatable (predicates.go:416)."""
    node = _require_node(node_info)
    allowed = node_info.allowed_pod_number
    if len(node_info.pods) + 1 > allowed:
        raise InsufficientResource("pods", 1, len(node_info.pods), allowed)
    req = pod_request(pod)
    if req.milli_cpu == 0 and req.memory == 0 and req.gpu == 0:
        return
    alloc = node_info.allocatable
    used = node_info.requested
    if used.milli_cpu + req.milli_cpu > alloc.milli_cpu:
        raise InsufficientResource("cpu", req.milli_cpu, used.milli_cpu, alloc.milli_cpu)
    if used.memory + req.memory > alloc.memory:
        raise InsufficientResource("memory", req.memory, used.memory, alloc.memory)
    if used.gpu + req.gpu > alloc.gpu:
        raise InsufficientResource("gpu", req.gpu, used.gpu, alloc.gpu)


# --- host / ports -------------------------------------------------------------

def pod_fits_host(pod: api.Pod, node_info: NodeInfo) -> None:
    """spec.nodeName, when pre-set, must name this node (predicates.go:533)."""
    want = pod.spec.node_name if pod.spec else ""
    if want and want != _require_node(node_info).metadata.name:
        raise PredicateFailure(f"pod wants node {want}")


def pod_host_ports(pod: api.Pod) -> Set[tuple]:
    ports = set()
    for c in (pod.spec.containers or []) if pod.spec else []:
        for p in c.ports or []:
            if p.host_port:
                ports.add((p.protocol or "TCP", p.host_port))
    return ports


def pod_fits_host_ports(pod: api.Pod, node_info: NodeInfo) -> None:
    """Requested hostPorts must be free on the node (predicates.go:687)."""
    wanted = pod_host_ports(pod)
    if wanted and wanted & node_info.used_ports():
        clash = sorted(wanted & node_info.used_ports())
        raise PredicateFailure(f"host port(s) in use: {clash}")


# --- node selector / node affinity -------------------------------------------

def _term_matches_node(term: api.NodeSelectorTerm, node: api.Node) -> bool:
    """A NodeSelectorTerm is an AND of expressions (predicates.go
    nodeMatchesNodeSelectorTerms helper semantics)."""
    node_labels = (node.metadata.labels or {}) if node.metadata else {}
    for expr in term.match_expressions or []:
        req = labelsel.Requirement(expr.key, expr.operator,
                                   tuple(expr.values or ()))
        if not req.matches(node_labels):
            return False
    return True


def pod_matches_node_selector(pod: api.Pod, node_info: NodeInfo) -> None:
    """nodeSelector AND NodeAffinity.requiredDuringScheduling
    (predicates.go:470-531 PodSelectorMatches/podMatchesNodeLabels)."""
    node = _require_node(node_info)
    node_labels = (node.metadata.labels or {}) if node.metadata else {}
    if pod.spec and pod.spec.node_selector:
        if not labelsel.selector_from_map(pod.spec.node_selector).matches(node_labels):
            raise PredicateFailure("node selector mismatch")
    aff = pod.spec.affinity if pod.spec else None
    na = aff.node_affinity if aff else None
    req = na.required_during_scheduling_ignored_during_execution if na else None
    if req is not None:
        terms = req.node_selector_terms or []
        # nil/empty terms match nothing (reference NodeSelectorRequirementsAsSelector)
        if not any(_term_matches_node(t, node) for t in terms):
            raise PredicateFailure("node affinity mismatch")


# --- volumes ------------------------------------------------------------------

def _volume_conflict(v: api.Volume, existing: api.Volume) -> bool:
    """Same GCE PD (unless both read-only), same EBS volume, or same RBD
    image => conflict (predicates.go:64-103 isVolumeConflict)."""
    if v.gce_persistent_disk and existing.gce_persistent_disk:
        a, b = v.gce_persistent_disk, existing.gce_persistent_disk
        if a.pd_name == b.pd_name and not (a.read_only and b.read_only):
            return True
    if v.aws_elastic_block_store and existing.aws_elastic_block_store:
        if v.aws_elastic_block_store.volume_id == existing.aws_elastic_block_store.volume_id:
            return True
    if v.rbd and existing.rbd:
        a, b = v.rbd, existing.rbd
        if a.pool == b.pool and a.image == b.image and set(a.monitors or []) & set(b.monitors or []):
            return True
    return False


def no_disk_conflict(pod: api.Pod, node_info: NodeInfo) -> None:
    for v in (pod.spec.volumes or []) if pod.spec else []:
        for ep in node_info.pods:
            for ev in (ep.spec.volumes or []) if ep.spec else []:
                if _volume_conflict(v, ev):
                    raise PredicateFailure(f"disk conflict on volume {v.name}")


class MaxPDVolumeCountChecker:
    """Cloud-attach limits: count the node's unique attachable volumes of one
    family including the incoming pod's (predicates.go:137-269). PVC-backed
    volumes resolve through a PVC->PV lookup."""

    def __init__(self, family: str, max_volumes: int,
                 pvc_lookup: Optional[Callable[[str, str], Optional[api.PersistentVolumeClaim]]] = None,
                 pv_lookup: Optional[Callable[[str], Optional[api.PersistentVolume]]] = None):
        assert family in ("ebs", "gce-pd")
        self.family = family
        self.max_volumes = max_volumes
        self.pvc_lookup = pvc_lookup
        self.pv_lookup = pv_lookup

    def _volume_id(self, v: api.Volume, namespace: str) -> Optional[str]:
        if self.family == "ebs" and v.aws_elastic_block_store:
            return v.aws_elastic_block_store.volume_id
        if self.family == "gce-pd" and v.gce_persistent_disk:
            return v.gce_persistent_disk.pd_name
        if v.persistent_volume_claim and self.pvc_lookup:
            pvc = self.pvc_lookup(namespace, v.persistent_volume_claim.claim_name)
            if pvc and pvc.spec and pvc.spec.volume_name and self.pv_lookup:
                pv = self.pv_lookup(pvc.spec.volume_name)
                if pv and pv.spec:
                    if self.family == "ebs" and pv.spec.aws_elastic_block_store:
                        return pv.spec.aws_elastic_block_store.volume_id
                    if self.family == "gce-pd" and pv.spec.gce_persistent_disk:
                        return pv.spec.gce_persistent_disk.pd_name
        return None

    def __call__(self, pod: api.Pod, node_info: NodeInfo) -> None:
        ns = pod.metadata.namespace if pod.metadata else ""
        new_ids = {vid for v in ((pod.spec.volumes or []) if pod.spec else [])
                   if (vid := self._volume_id(v, ns)) is not None}
        if not new_ids:
            return
        existing: Set[str] = set()
        for ep in node_info.pods:
            ens = ep.metadata.namespace if ep.metadata else ""
            for v in (ep.spec.volumes or []) if ep.spec else []:
                vid = self._volume_id(v, ens)
                if vid is not None:
                    existing.add(vid)
        if len(existing | new_ids) > self.max_volumes:
            raise PredicateFailure(
                f"exceeds max {self.family} volume count {self.max_volumes}")


class VolumeZoneChecker:
    """PVs carry zone/region labels; the node must match them
    (predicates.go:271-347)."""

    def __init__(self, pvc_lookup, pv_lookup):
        self.pvc_lookup = pvc_lookup
        self.pv_lookup = pv_lookup

    def __call__(self, pod: api.Pod, node_info: NodeInfo) -> None:
        node = _require_node(node_info)
        node_labels = (node.metadata.labels or {}) if node.metadata else {}
        ns = pod.metadata.namespace if pod.metadata else ""
        for v in (pod.spec.volumes or []) if pod.spec else []:
            if not v.persistent_volume_claim:
                continue
            pvc = self.pvc_lookup(ns, v.persistent_volume_claim.claim_name)
            if pvc is None:
                raise PredicateFailure(
                    f"PVC {v.persistent_volume_claim.claim_name} not found")
            if not (pvc.spec and pvc.spec.volume_name):
                raise PredicateFailure(f"PVC {pvc.metadata.name} not bound")
            pv = self.pv_lookup(pvc.spec.volume_name)
            if pv is None:
                raise PredicateFailure(f"PV {pvc.spec.volume_name} not found")
            pv_labels = (pv.metadata.labels or {}) if pv.metadata else {}
            for key in (api.LABEL_ZONE, api.LABEL_REGION):
                want = pv_labels.get(key)
                if want and node_labels.get(key) != want:
                    raise PredicateFailure(
                        f"volume zone mismatch: PV wants {key}={want}")


# --- labels / service affinity ------------------------------------------------

class NodeLabelChecker:
    """Require labels present (or absent) on every node
    (predicates.go:552 NodeLabelChecker)."""

    def __init__(self, labels: List[str], presence: bool):
        self.labels = labels
        self.presence = presence

    def __call__(self, pod: api.Pod, node_info: NodeInfo) -> None:
        node = _require_node(node_info)
        node_labels = (node.metadata.labels or {}) if node.metadata else {}
        for l in self.labels:
            if (l in node_labels) != self.presence:
                raise PredicateFailure(
                    f"node label {l} {'absent' if self.presence else 'present'}")


class ServiceAffinity:
    """Pods of the same service must land on nodes agreeing on the given
    label keys (predicates.go:596-685)."""

    def __init__(self, pod_lister, service_lister, node_lookup,
                 labels: List[str]):
        self.pod_lister = pod_lister
        self.service_lister = service_lister
        self.node_lookup = node_lookup  # name -> Node
        self.labels = labels

    def __call__(self, pod: api.Pod, node_info: NodeInfo) -> None:
        node = _require_node(node_info)
        node_labels = (node.metadata.labels or {}) if node.metadata else {}
        # if the pod itself nodeSelector-pins every affinity label, use those
        wanted: Dict[str, str] = {}
        sel = (pod.spec.node_selector or {}) if pod.spec else {}
        if all(l in sel for l in self.labels):
            wanted = {l: sel[l] for l in self.labels}
        else:
            # otherwise adopt the labels of nodes running this service's pods
            services = self.service_lister.get_pod_services(pod)
            if services:
                svc_sel = labelsel.selector_from_map(services[0].spec.selector)
                ns = pod.metadata.namespace
                peers = [p for p in self.pod_lister.list(svc_sel)
                         if p.metadata.namespace == ns and p.spec and p.spec.node_name]
                if peers:
                    peer_node = self.node_lookup(peers[0].spec.node_name)
                    if peer_node is not None:
                        peer_labels = (peer_node.metadata.labels or {})
                        wanted = {l: peer_labels.get(l, "") for l in self.labels}
        for l, v in wanted.items():
            if node_labels.get(l, "") != v:
                raise PredicateFailure(f"service affinity: needs {l}={v!r}")


# --- taints -------------------------------------------------------------------

def node_taints(node: api.Node) -> List[api.Taint]:
    return (node.spec.taints or []) if node.spec else []


def pod_tolerations(pod: api.Pod) -> List[api.Toleration]:
    return (pod.spec.tolerations or []) if pod.spec else []


def pod_tolerates_node_taints(pod: api.Pod, node_info: NodeInfo) -> None:
    """Every NoSchedule taint must be tolerated (predicates.go:960-1002)."""
    node = _require_node(node_info)
    tolerations = pod_tolerations(pod)
    for taint in node_taints(node):
        if taint.effect != api.TAINT_NO_SCHEDULE:
            continue
        if not any(t.tolerates(taint) for t in tolerations):
            raise PredicateFailure(
                f"untolerated taint {taint.key}={taint.value}:{taint.effect}")


# --- memory pressure ----------------------------------------------------------

def is_best_effort(pod: api.Pod) -> bool:
    """BestEffort QoS: no container requests or limits at all (reference
    pkg/kubelet/qos semantics used by predicates.go:1011)."""
    for c in (pod.spec.containers or []) if pod.spec else []:
        if c.resources and (c.resources.requests or c.resources.limits):
            return False
    return True


def check_node_memory_pressure(pod: api.Pod, node_info: NodeInfo) -> None:
    """BestEffort pods don't schedule onto nodes reporting MemoryPressure
    (predicates.go:1011)."""
    if not is_best_effort(pod):
        return
    node = _require_node(node_info)
    for cond in (node.status.conditions or []) if node.status else []:
        if cond.type == api.NODE_MEMORY_PRESSURE and cond.status == api.CONDITION_TRUE:
            raise PredicateFailure("node has memory pressure")


# --- inter-pod affinity -------------------------------------------------------

def _term_namespaces(pod: api.Pod, term: api.PodAffinityTerm) -> Optional[Set[str]]:
    """None namespaces => pod's own namespace; empty list => all namespaces
    (reference GetNamespacesFromPodAffinityTerm, non_zero.go:76)."""
    if term.namespaces is None:
        return {pod.metadata.namespace}
    if len(term.namespaces) == 0:
        return None  # all
    return set(term.namespaces)


def _pod_matches_term(candidate: api.Pod, owner: api.Pod,
                      term: api.PodAffinityTerm) -> bool:
    """Does `candidate` match `owner`'s affinity term (namespace + selector)?
    (reference CheckIfPodMatchPodAffinityTerm, non_zero.go:114 — minus the
    topology check, applied by callers)."""
    names = _term_namespaces(owner, term)
    if names is not None and candidate.metadata.namespace not in names:
        return False
    sel = labelsel.selector_from_label_selector(term.label_selector)
    return sel.matches((candidate.metadata.labels or {}))


def _same_topology(node_a: Optional[api.Node], node_b: Optional[api.Node],
                   topology_key: str, default_keys=()) -> bool:
    """Nodes share a topology domain iff both carry the key with equal,
    non-empty values (non_zero.go:87-109). Empty key: any default key."""
    if node_a is None or node_b is None:
        return False
    la = (node_a.metadata.labels or {}) if node_a.metadata else {}
    lb = (node_b.metadata.labels or {}) if node_b.metadata else {}
    keys = [topology_key] if topology_key else list(default_keys)
    for k in keys:
        if la.get(k) and la.get(k) == lb.get(k):
            return True
    return False


class InterPodAffinity:
    """Hard inter-pod affinity + anti-affinity with symmetry
    (predicates.go:769-947). O(nodes x pods x terms) in the oracle; the TPU
    backend turns this into masked label-bitset matmuls."""

    def __init__(self, pod_lister, node_lookup,
                 failure_domains=(api.LABEL_HOSTNAME, api.LABEL_ZONE, api.LABEL_REGION)):
        self.pod_lister = pod_lister
        self.node_lookup = node_lookup  # name -> Node
        self.failure_domains = tuple(failure_domains)
        self._snapshot = None  # per-decision pod list (begin_pod)

    def begin_pod(self, pod: api.Pod):
        """Predicate-metadata hook: snapshot the assigned-pod list once per
        scheduling decision instead of once per node (the reference's
        predicate metadata precomputation; avoids O(nodes) full-store copies
        under the 16-way parallel filter)."""
        self._snapshot = self.pod_lister.list()

    def _any_pod_matches(self, pod: api.Pod, all_pods, node: api.Node,
                         term: api.PodAffinityTerm) -> bool:
        """AnyPodMatchesPodAffinityTerm (predicates.go:785): some existing
        pod matches the term AND sits in the same topology domain as `node`."""
        for ep in all_pods:
            if not (ep.spec and ep.spec.node_name):
                continue
            if not _pod_matches_term(ep, pod, term):
                continue
            ep_node = self.node_lookup(ep.spec.node_name)
            if _same_topology(ep_node, node, term.topology_key, self.failure_domains):
                return True
        return False

    def _check_affinity(self, pod, all_pods, node, terms) -> None:
        for term in terms:
            if self._any_pod_matches(pod, all_pods, node, term):
                continue
            # the disregard rule (predicates.go:818-844): if the term selects
            # the pod's own labels and NO existing pod anywhere matches it,
            # the first pod of a self-affine group may schedule
            if not _pod_matches_term(pod, pod, term):
                raise PredicateFailure("pod affinity not satisfied")
            for ep in all_pods:
                if _pod_matches_term(ep, pod, term):
                    raise PredicateFailure("pod affinity not satisfied")
            # disregarded: self-selecting term with no matches anywhere

    def _check_anti_affinity(self, pod, all_pods, node, terms) -> None:
        for term in terms:
            if self._any_pod_matches(pod, all_pods, node, term):
                raise PredicateFailure("pod anti-affinity violated")

    def _check_symmetry(self, pod, all_pods, node) -> None:
        """Existing pods' anti-affinity terms must not match the incoming pod
        within their topology (predicates.go:883-921)."""
        for ep in all_pods:
            ep_aff = ep.spec.affinity if ep.spec else None
            ep_anti = ep_aff.pod_anti_affinity if ep_aff else None
            terms = (ep_anti.required_during_scheduling_ignored_during_execution
                     or []) if ep_anti else []
            if not terms:
                continue
            for term in terms:
                if not _pod_matches_term(pod, ep, term):
                    continue
                ep_node = self.node_lookup(ep.spec.node_name) if ep.spec and ep.spec.node_name else None
                if _same_topology(ep_node, node, term.topology_key, self.failure_domains):
                    raise PredicateFailure(
                        "existing pod's anti-affinity forbids this pod here")

    def __call__(self, pod: api.Pod, node_info: NodeInfo) -> None:
        node = _require_node(node_info)
        aff = pod.spec.affinity if pod.spec else None
        all_pods = self._snapshot if self._snapshot is not None else self.pod_lister.list()
        if aff and aff.pod_affinity:
            self._check_affinity(
                pod, all_pods, node,
                aff.pod_affinity.required_during_scheduling_ignored_during_execution or [])
        if aff and aff.pod_anti_affinity:
            self._check_anti_affinity(
                pod, all_pods, node,
                aff.pod_anti_affinity.required_during_scheduling_ignored_during_execution or [])
        self._check_symmetry(pod, all_pods, node)


# --- bundles ------------------------------------------------------------------

def general_predicates(pod: api.Pod, node_info: NodeInfo) -> None:
    """The kubelet re-checks exactly this bundle at admission
    (predicates.go:733 GeneralPredicates)."""
    pod_fits_resources(pod, node_info)
    pod_fits_host(pod, node_info)
    pod_fits_host_ports(pod, node_info)
    pod_matches_node_selector(pod, node_info)


def _require_node(node_info: NodeInfo) -> api.Node:
    if node_info.node is None:
        raise PredicateFailure("node not found")
    return node_info.node
