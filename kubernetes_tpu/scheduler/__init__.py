"""L5 scheduler: shell + Python oracle + TPU decision plane.

Parity target: reference plugin/pkg/scheduler (13.5k LoC) — the complete
filter-and-score pipeline:

  shell        scheduler.py (loop), factory.py (informers/FIFO/binder/backoff),
               cache.py (assume/confirm/expire world model)
  oracle       predicates.py + priorities.py + generic.py — the sequential
               Python implementation matching the reference's DefaultProvider
               semantics; the differential reference for the TPU kernel
  plugin API   provider.py (algorithm providers, policy files),
               extender.py (HTTP extender)
  TPU backend  tpu.py — batched filter-and-score over pods x nodes tensors
               (kubernetes_tpu.ops) behind the same provider boundary
"""

from kubernetes_tpu.scheduler.cache import NodeInfo, SchedulerCache
from kubernetes_tpu.scheduler.generic import GenericScheduler, FitError
