"""The TPU batch scheduling path, wired into the live scheduler shell.

This is the in-process form of the plug-in boundary the reference reserves
for exactly this kind of backend (plugin/pkg/scheduler/extender.go:39-173,
provider registry factory/plugins.go): instead of scheduling one FIFO pod at
a time through the sequential algorithm, the BatchScheduler drains the
pending queue into a batch, tensorizes it against the schedulercache
snapshot, runs the whole batch through the device kernel (ops/kernel.py) in
one program, and assumes+binds every result through the identical
assume/bind/backoff machinery the sequential loop uses
(scheduler.go:93-155 semantics, N pods per iteration).

Failure containment:
- a pod the kernel can't place follows the normal FailedScheduling path
  (event + PodScheduled=False + exponential backoff requeue);
- a device/tensorize error falls back to the sequential oracle algorithm for
  the whole drained batch, so a broken device degrades to reference behavior
  instead of wedging the queue.

Failure *classification* (round-3 verdict #4): a transient device outage and
a deterministic kernel bug must not be handled identically. Exceptions from
the device path are classified by `_is_device_error`:

- device/transport errors (XlaRuntimeError with a transient status,
  OSError/ConnectionError/TimeoutError) retry with exponential backoff —
  the kernel is skipped until the backoff window passes, and after
  `degraded_after` consecutive failures the scheduler flips to the visible
  "degraded" health state (still retrying, capped backoff);
- anything else is a programming error: the scheduler flips to the "failed"
  health state, the occurrence is logged at ERROR with the full traceback,
  and the device path is disabled for a long cooldown (bug_cooldown,
  default 5 min) rather than forever — a data-dependent tensorize error
  from one poison pod must not condemn the process to the Python oracle for
  its lifetime; a *real* deterministic bug re-fails (and re-logs at ERROR)
  on every re-arm, keeping health at "failed". With strict=True a
  programming error re-raises so tests/CI can't miss it.
- a device error that persists `fail_after` consecutive batches is treated
  as a permanent outage: same failed-state/cooldown handling, but with its
  own reason label ("persistent-device") and log message so operators
  aren't sent chasing kernel code for a transport fault.

Health is exported as the `scheduler_kernel_health` gauge (1 ok / 0.5
degraded / 0 failed) plus `scheduler_kernel_fallbacks_total{reason=...}`;
`healthy()` is the hook the scheduler component entrypoint serves as
/healthz.

Observability (round-5 postmortem): the kernel pipeline runs as named,
deadlined stages (tensorize -> upload -> compile|solve) through
ops/watchdog.run_stages — durations land in
`scheduler_stage_seconds{stage}`, a hang becomes a StageTimeout +
`scheduler_stage_timeout_total{stage}` tick classified as a transient
device error (backoff + sequential fallback, never a silent wedge), and
each batch carries a Span whose stage children and per-pod trace links
make a stuck drain attributable to the exact stage.
"""

from __future__ import annotations

import logging
import os
import threading
import time
import traceback
from typing import List, Optional

from kubernetes_tpu.api import types as api
from kubernetes_tpu.ops.kernel import Weights
from kubernetes_tpu.ops.watchdog import DEFAULT_DEADLINES, run_stages
from kubernetes_tpu.scheduler.factory import ConfigFactory, Scheduler
from kubernetes_tpu.scheduler.generic import FitError
from kubernetes_tpu.utils.metrics import REGISTRY as METRICS
from kubernetes_tpu.utils.trace import Span

log = logging.getLogger("scheduler.tpu")

HEALTH_OK = "ok"
HEALTH_DEGRADED = "degraded"   # consecutive device errors; still retrying
HEALTH_FAILED = "failed"       # deterministic bug; device path disabled

_HEALTH_GAUGE = {HEALTH_OK: 1.0, HEALTH_DEGRADED: 0.5, HEALTH_FAILED: 0.0}

# XLA runtime statuses that indicate the *device/runtime* (not our program)
# failed. Everything else from XlaRuntimeError (INVALID_ARGUMENT,
# FAILED_PRECONDITION, UNIMPLEMENTED...) is deterministic for a fixed input.
# RESOURCE_EXHAUSTED is deliberately NOT here: OOM at a fixed batch shape
# reproduces every retry. INTERNAL stays (the axon transport surfaces tunnel
# failures as INTERNAL) — a *deterministic* INTERNAL is caught by the
# consecutive-failure limit in _on_kernel_failure instead.
_TRANSIENT_XLA_STATUS = (
    "UNAVAILABLE", "INTERNAL", "DEADLINE_EXCEEDED",
    "CANCELLED", "ABORTED", "UNKNOWN",
)


def _is_device_error(e: BaseException) -> bool:
    """True when the failure is plausibly transient (device/transport), false
    for deterministic programming errors."""
    name = type(e).__name__
    if name in ("XlaRuntimeError", "JaxRuntimeError"):
        # XlaRuntimeError messages lead with "STATUS: detail" — match the
        # leading token only, so a deterministic error merely *quoting* a
        # transient status isn't misclassified
        status = str(e).split(":", 1)[0].strip()
        return status in _TRANSIENT_XLA_STATUS
    return isinstance(e, (OSError, ConnectionError, TimeoutError))


class BatchScheduler(Scheduler):
    """Scheduler whose hot loop is the batched device kernel.

    `algorithm` is the sequential fallback (normally the oracle
    GenericScheduler built from the same provider keys) used when the device
    path fails.
    """

    def __init__(self, factory: ConfigFactory, algorithm,
                 batch_size: int = 4096, weights: Optional[Weights] = None,
                 bind_workers: int = 32, strict: bool = False,
                 degraded_after: int = 3, fail_after: int = 10,
                 retry_initial: float = 1.0, retry_max: float = 60.0,
                 bug_cooldown: float = 300.0, clock=time.monotonic,
                 incremental: bool = True,
                 stage_deadlines: Optional[dict] = None,
                 explain: Optional[bool] = None,
                 objective=None, microbatch_ms: float = 0.0):
        super().__init__(factory, algorithm)
        self.batch_size = batch_size
        # micro-batch window (ROADMAP item 2): after the first pop, wait up
        # to this many ms for more arrivals (or a full batch) before the
        # solve — one kernel round per window instead of per-burst, so the
        # device-resident incremental path amortizes across arrivals.
        # KTPU_MICROBATCH_MS is the env seam (the soak harness sets it).
        self.microbatch_ms = microbatch_ms or float(
            os.environ.get("KTPU_MICROBATCH_MS", 0) or 0)
        self.weights = weights or Weights()
        # scheduling-objective mode (scheduler/objectives): a name or an
        # ObjectiveConfig; None/default keeps the pre-objective kernel
        # program bit-identical, KTPU_OBJECTIVE is the env seam
        from kubernetes_tpu.scheduler.objectives.config import (
            resolve_objective,
        )
        self.objective = resolve_objective(objective, env=True)
        self._last_outcome = None
        # gangs whose rejection was already counted: a still-pending gang
        # is re-solved (and re-rejected) on every backoff retry, but it is
        # ONE rejected gang, not one per solve
        self._rejected_gangs_counted: set = set()
        # preemptors with an outstanding nomination (pod key -> node):
        # nothing reserves the freed capacity (no spec nominatedNodeName),
        # so without this a still-unschedulable preemptor would evict a
        # FRESH victim set on every backoff retry — an unbounded eviction
        # storm. One eviction round per nomination; cleared when the
        # preemptor binds.
        self._nominated: dict = {}
        # per-predicate decision provenance from the solve (ISSUE 12): the
        # kernel emits survivor counts + score decompositions, decoded into
        # the DecisionLedger / FailedScheduling breakdowns. Default on;
        # KTPU_EXPLAIN=0 opts out (assignments are bit-identical either way
        # — the flag only adds reductions to the traced program).
        self.explain = (explain if explain is not None
                        else os.environ.get("KTPU_EXPLAIN", "1") != "0")
        self._last_explain = None
        # per-stage watchdog deadlines (tensorize/upload/compile/solve): a
        # hang becomes a StageTimeout + scheduler_stage_timeout_total tick
        # and takes the device-error fallback path, never a silent wedge
        self.stage_deadlines = dict(DEFAULT_DEADLINES)
        self.stage_deadlines.update(stage_deadlines or {})
        # the incremental mirror replaces the per-batch world rebuild
        # (SURVEY §7 hard part #2); it subscribes to cache deltas and keeps
        # node-side tensors device-resident across batches
        self._inc = None
        if incremental:
            from kubernetes_tpu.ops.incremental import IncrementalTensorizer
            self._inc = IncrementalTensorizer(factory.plugin_args,
                                              pod_bucket=batch_size,
                                              objective=self.objective)
            factory.cache.add_listener(self._inc)
        self.kernel_batches = 0     # successful device batches
        self.kernel_pods = 0        # pods placed via the device path
        self.kernel_failures = 0    # device/tensorize errors (fell back)
        self.strict = strict        # re-raise programming errors
        self.disabled_reason: Optional[str] = None
        self._degraded_after = degraded_after
        self._fail_after = fail_after  # consecutive "transient" errors -> failed
        self._consecutive_device_errors = 0
        self._retry_initial = retry_initial
        self._retry_max = retry_max
        self._retry_backoff = retry_initial
        self._retry_at = 0.0        # monotonic time before which kernel is skipped
        self._bug_cooldown = bug_cooldown
        self._clock = clock
        self._set_health(HEALTH_OK)
        from concurrent.futures import ThreadPoolExecutor
        self._bind_pool = ThreadPoolExecutor(
            max_workers=bind_workers, thread_name_prefix="binder")

    # --- health / escalation (round-3 verdict #4) ----------------------------

    def healthy(self) -> bool:
        return self.health == HEALTH_OK

    def kernel_available(self) -> bool:
        """Is the device path currently eligible to run? (The failed state
        re-arms after its cooldown; health stays "failed" until a success.)"""
        return self._clock() >= self._retry_at

    def _set_health(self, state: str):
        self.health = state
        METRICS.set_gauge("scheduler_kernel_health", _HEALTH_GAUGE[state])

    def _on_kernel_success(self):
        self._consecutive_device_errors = 0
        self._retry_backoff = self._retry_initial
        self._retry_at = 0.0
        if self.health != HEALTH_OK:
            log.info("device kernel recovered from %s; health back to ok",
                     self.health)
            self.disabled_reason = None
        self._set_health(HEALTH_OK)

    def _on_kernel_failure(self, e: Exception, n_pods: int):
        self.kernel_failures += 1
        is_dev = _is_device_error(e)
        if is_dev and self._consecutive_device_errors + 1 < self._fail_after:
            METRICS.inc("scheduler_kernel_fallbacks_total", reason="device")
            self._consecutive_device_errors += 1
            self._retry_at = self._clock() + self._retry_backoff
            self._retry_backoff = min(self._retry_backoff * 2, self._retry_max)
            if self._consecutive_device_errors >= self._degraded_after:
                self._set_health(HEALTH_DEGRADED)
            log.warning(
                "device error on batch of %d (%d consecutive, retry in %.0fs,"
                " health=%s): %s", n_pods, self._consecutive_device_errors,
                max(self._retry_at - self._clock(), 0), self.health, e)
            return
        # failed state: loud, visible, and disabled for a long cooldown —
        # silently scheduling every batch through the Python oracle at a
        # warning log level is the round-2/3 advisor finding this closes
        reason = "persistent-device" if is_dev else "bug"
        METRICS.inc("scheduler_kernel_fallbacks_total", reason=reason)
        self.disabled_reason = f"{reason}: {e!r}"
        self._retry_at = self._clock() + self._bug_cooldown
        self._set_health(HEALTH_FAILED)
        if is_dev:
            log.error(
                "device error persisted %d consecutive batches — treating as "
                "an outage; device path DISABLED for %.0fs: %s",
                self._consecutive_device_errors + 1, self._bug_cooldown, e)
        else:
            log.error(
                "DETERMINISTIC kernel bug — device path DISABLED for %.0fs, "
                "batches run the sequential fallback:\n%s",
                self._bug_cooldown, traceback.format_exc())

    def _spawn_bind(self, pod, dest, t_start, did_assume):
        self._nominated.pop(
            f"{pod.metadata.namespace}/{pod.metadata.name}", None)
        try:
            self._bind_pool.submit(self._bind, pod, dest, t_start, did_assume)
        except RuntimeError:
            # stop() shut the pool down while this batch was mid-flight —
            # finish the bind inline instead of dropping the placement
            self._bind(pod, dest, t_start, did_assume)

    def _fallback_sequential(self, pods):
        """Schedule a drained batch through the sequential oracle — the one
        place batch-drop safety lives."""
        for pod in pods:
            self._schedule_pod(pod)

    # --- one batch (the batched scheduleOne) ---------------------------------

    def schedule_batch_once(self, timeout: Optional[float] = None) -> int:
        """Drain up to batch_size pending pods and schedule them in one
        device program. Returns the number of pods processed (0 on queue
        timeout/close)."""
        first = self.f.pending.pop(timeout=timeout)
        if first is None:
            return 0
        if self.microbatch_ms > 0:
            # accumulate the arrival window: solve every N ms or M pods,
            # whichever fills first — the steady-state rounds-per-second
            # knob (a full batch never waits)
            deadline = time.monotonic() + self.microbatch_ms / 1000.0
            while (len(self.f.pending) + 1 < self.batch_size
                   and time.monotonic() < deadline
                   and not self._stop.is_set()):
                time.sleep(0.001)
        pods = [first] + self.f.pending.drain(self.batch_size - 1)
        if self.objective is not None and self.objective.gang:
            # all-or-nothing cannot survive a count-based batch slice: two
            # solves each see a partial gang and commit (or reject) it
            # independently, splitting one gang across topology domains.
            # Pull the co-pending tail of any gang the drain cut at the
            # boundary into THIS batch.
            from kubernetes_tpu.scheduler.objectives.config import pod_gang
            gangs = {pod_gang(p) for p in pods} - {None}
            if gangs:
                pods += self.f.pending.drain_where(
                    lambda p: pod_gang(p) in gangs)
            if len(pods) > self.batch_size:
                # ...but the pull must not break the fixed pod-bucket
                # shape (P > bucket pads to the NEXT power of two: a
                # second XLA compile mid-churn + up to 2x padded solve) —
                # give back whole trailing units until the batch fits.
                # Only a single gang bigger than batch_size ever runs
                # oversized: one padded solve beats never placing it.
                units, by_gang = [], {}
                for p in pods:
                    g = pod_gang(p)
                    if g is None:
                        units.append([p])
                    elif g in by_gang:
                        by_gang[g].append(p)
                    else:
                        by_gang[g] = [p]
                        units.append(by_gang[g])
                pods, n, give_back = [], 0, []
                for i, unit in enumerate(units):
                    if not give_back and (
                            i == 0 or n + len(unit) <= self.batch_size):
                        pods.extend(unit)
                        n += len(unit)
                    else:
                        # a true trailing cut: once one unit goes back,
                        # everything after it does too — admitting a
                        # later-arrived unit past an earlier give-back
                        # would invert FIFO intake order
                        give_back.extend(unit)
                # back to the HEAD of the queue in original order, so the
                # cut units lead the next drain instead of aging at the
                # tail behind younger arrivals (requeue_front also keeps
                # any newer informer copy over our stale drained object)
                for p in reversed(give_back):
                    self.f.pending.requeue_front(p)
        t_start = time.perf_counter()
        # one batch span; per-pod roots close their queue_wait stage here
        # and carry a link to the batch trace that solves them
        batch_span = Span("schedule_batch", pods=len(pods))
        for pod in pods:
            self._note_popped(pod)
            self.f.spans.annotate(
                f"{pod.metadata.namespace}/{pod.metadata.name}",
                batch_trace=batch_span.trace_id,
                batch_span=batch_span.span_id)

        try:
            return self._schedule_batch(pods, t_start, batch_span)
        finally:
            batch_span.finish()

    def _schedule_batch(self, pods: List[api.Pod], t_start: float,
                        batch_span: Span) -> int:
        if not self.kernel_available():
            # disabled (failed-state cooldown) or inside the device-error
            # backoff window: sequential path, no device attempt
            batch_span.attrs["path"] = "sequential"
            self._fallback_sequential(pods)
            return len(pods)

        # host-side snapshot failures are NOT kernel failures: fall back with
        # a warning, no health impact (the classifier must only ever see
        # exceptions from the tensorize/device path)
        try:
            nodes = self.f.node_lister.list()
            if not nodes:
                for pod in pods:
                    self._handle_failure(pod, FitError(pod, {}))
                return len(pods)
            existing = None
            if self._inc is None:
                # full-rebuild path: snapshot the world per batch
                info = self.f.cache.get_node_name_to_info_map()
                node_set = {n.metadata.name for n in nodes}
                # every cached pod (incl. assumed ones from previous batches)
                # on a schedulable node is device state; pods on excluded
                # nodes matter for nothing the kernel models per-node
                existing = [p for name, ni in info.items() if name in node_set
                            for p in ni.pods]
        except Exception as e:
            log.warning("cluster snapshot failed (%s); sequential fallback", e)
            batch_span.attrs["path"] = "sequential"
            self._fallback_sequential(pods)
            return len(pods)

        try:
            # span handed over via attribute: _run_kernel's (nodes, existing,
            # pending) signature is a seam tests replace wholesale
            self._batch_span = batch_span
            with METRICS.time("scheduler_scheduling_algorithm_latency_seconds"):
                results = self._run_kernel(nodes, existing, pods)
            if len(results) != len(pods):
                raise RuntimeError(
                    f"kernel returned {len(results)} results for "
                    f"{len(pods)} pods")
        except Exception as e:
            self._on_kernel_failure(e, len(pods))
            batch_span.attrs["error"] = repr(e)
            if not _is_device_error(e):
                # a corrupted incremental mirror would reproduce a BUG
                # forever: rebuild it from the cache before the next attempt
                # (transport errors can't corrupt host state — no resync)
                try:
                    self.resync_incremental()
                except Exception:
                    log.exception("incremental resync failed")
            # fallback first — the drained batch must never be dropped, even
            # when strict mode re-raises below
            self._fallback_sequential(pods)
            if self.strict and not _is_device_error(e):
                raise
            return len(pods)

        self._on_kernel_success()
        self.kernel_batches += 1
        records, self._last_explain = (self._last_explain or []), None
        outcome, self._last_outcome = self._last_outcome, None
        preempted, gang_of = self._apply_outcome(outcome)
        recmap = {}
        if records:
            from kubernetes_tpu.observability.explain import LEDGER
            for rec in records:
                dec = preempted.get(rec.pod)
                if dec is not None and rec.preemption is not None:
                    # suppressed retries hand back the original eviction
                    # record — the ledger must show it too, or /explainz
                    # and the event would disagree
                    rec.preemption = {"node": dec.node,
                                      "victims": list(dec.victims)}
                LEDGER.add(rec)
            recmap = {r.pod: r for r in records}
        for pod, dest in zip(pods, results):
            key = f"{pod.metadata.namespace}/{pod.metadata.name}"
            rec = recmap.get(key)
            if dest is None:
                if key in preempted:
                    from kubernetes_tpu.scheduler.objectives.decode import (
                        PreemptionFitError,
                    )
                    err: FitError = PreemptionFitError(pod, preempted[key])
                elif key in gang_of:
                    from kubernetes_tpu.scheduler.objectives.decode import (
                        GangFitError,
                    )
                    err = GangFitError(pod, gang_of[key])
                elif rec is not None:
                    from kubernetes_tpu.observability.explain import (
                        KernelFitError,
                    )
                    err = KernelFitError(pod, rec)
                else:
                    err = FitError(pod, {
                        "*": "kernel: no feasible node in batch"})
                self._handle_failure(pod, err)
                continue
            self.kernel_pods += 1
            if rec is not None and rec.node == dest:
                from kubernetes_tpu.observability.explain import (
                    format_assigned,
                )
                self._bind_notes[key] = format_assigned(rec)
            self._assume_and_bind(pod, dest, t_start)
        return len(pods)

    def _apply_outcome(self, outcome):
        """Host side of the objective verdicts: evict preemption victims
        through the apiserver (reference-style Preempted Event on each),
        count gang placements, and hand back per-pod maps for the failure
        routing above ({preemptor key: decision}, {member key: GangResult})."""
        if outcome is None:
            return {}, {}
        preempted, gang_of = {}, {}
        for dec in outcome.preemptions:
            orig = self._nominated.get(dec.pod)
            if orig is not None:
                # this preemptor already got its eviction round on an
                # earlier solve; the retry must not kill another victim
                # set, and every surface (event/condition//explainz) must
                # repeat the ORIGINAL eviction record — the fresh
                # decision names victims that will never be deleted
                preempted[dec.pod] = orig
                METRICS.inc("scheduler_preemptions_total",
                            reason="suppressed")
                continue
            preempted[dec.pod] = dec
            while len(self._nominated) > 8192:
                # bounded (preemptors deleted while pending leak their
                # entry): shed the OLDEST nomination only — clearing all
                # would re-arm every live preemptor's eviction at once
                self._nominated.pop(next(iter(self._nominated)))
            self._nominated[dec.pod] = dec
            METRICS.observe("scheduler_preemption_victims",
                            float(len(dec.victims)),
                            buckets=(1, 2, 4, 8, 16, 32))
            for vkey in dec.victims:
                ns, _, name = vkey.partition("/")
                victim = api.Pod(metadata=api.ObjectMeta(
                    name=name, namespace=ns))
                try:
                    self.f.client.delete("pods", name, ns)
                    METRICS.inc("scheduler_preemptions_total",
                                reason="evicted")
                    self.recorder.event(
                        victim, "Normal", "Preempted",
                        f"Preempted by {dec.pod} on node {dec.node}")
                except Exception as e:
                    # the nomination stands (the kernel already planned
                    # around the relief); a failed evict must be visible,
                    # not silently retried into a double-booked node
                    log.warning("evicting %s for %s failed: %s",
                                vkey, dec.pod, e)
                    METRICS.inc("scheduler_preemptions_total",
                                reason="evict-error")
        for gr in outcome.gangs:
            if gr.placed:
                METRICS.inc("scheduler_gang_placements_total",
                            outcome="placed")
                # the name may be reused by a future gang — let it count
                self._rejected_gangs_counted.discard(gr.name)
            else:
                if gr.name not in self._rejected_gangs_counted:
                    if len(self._rejected_gangs_counted) > 8192:
                        # bounded memory for gangs deleted while rejected;
                        # worst case a long-rejected gang counts once more
                        self._rejected_gangs_counted.clear()
                    self._rejected_gangs_counted.add(gr.name)
                    METRICS.inc("scheduler_gang_placements_total",
                                outcome="rejected")
                for m in gr.members:
                    gang_of[m] = gr
        return preempted, gang_of

    def _run_kernel(self, nodes: List[api.Node], existing: List[api.Pod],
                    pending: List[api.Pod]) -> List[Optional[str]]:
        """The staged, deadlined device pipeline: every stage (tensorize ->
        upload -> compile|solve) runs under its watchdog deadline and is
        exported as a scheduler_stage_seconds series + a child span of the
        batch span."""
        batch_span = getattr(self, "_batch_span", None)
        explain = self.explain
        objective = self.objective
        self._last_explain = None
        self._last_outcome = None
        if self._inc is not None:
            inc = self._inc
            ret = run_stages(
                lambda stage: inc.schedule(pending, self.weights, stage=stage,
                                           explain=explain),
                deadlines=self.stage_deadlines, span=batch_span)
        else:
            from kubernetes_tpu.scheduler.batch import tpu_batch
            ret = run_stages(
                lambda stage: tpu_batch(nodes, existing, pending,
                                        self.f.plugin_args, self.weights,
                                        stage=stage, explain=explain,
                                        objective=objective),
                deadlines=self.stage_deadlines, span=batch_span)
        if objective is not None and isinstance(ret, tuple):
            if explain:
                results, self._last_explain, self._last_outcome = ret
            else:
                results, self._last_outcome = ret
            return results
        if explain and isinstance(ret, tuple):
            results, self._last_explain = ret
            return results
        return ret

    def resync_incremental(self):
        """Drop and re-mirror the incremental state from the cache — the
        self-heal for a corrupted mirror (called on kernel failure)."""
        if self._inc is None:
            return
        from kubernetes_tpu.ops.incremental import IncrementalTensorizer
        old = self._inc
        fresh = IncrementalTensorizer(self.f.plugin_args,
                                      pod_bucket=self.batch_size,
                                      objective=self.objective)
        self.f.cache.remove_listener(old)
        self.f.cache.add_listener(fresh)
        self._inc = fresh

    # --- loop ----------------------------------------------------------------

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.schedule_batch_once(timeout=0.5)
            except Exception:
                log.exception("scheduleBatchOnce crashed")  # HandleCrash
                if self.strict and self.health == HEALTH_FAILED:
                    # strict mode: a deterministic kernel bug HALTS the
                    # scheduler instead of degrading to the Python loop
                    log.error("strict mode: stopping scheduler loop")
                    self._stop.set()

    def stop(self):
        super().stop()
        if self._inc is not None:
            self.f.cache.remove_listener(self._inc)
        self._bind_pool.shutdown(wait=False)


def create_batch_scheduler(factory: ConfigFactory,
                           provider_name: Optional[str] = None,
                           batch_size: int = 4096,
                           weights: Optional[Weights] = None,
                           strict: bool = False,
                           stage_deadlines: Optional[dict] = None,
                           explain: Optional[bool] = None,
                           objective=None, microbatch_ms: float = 0.0
                           ) -> BatchScheduler:
    """Build a BatchScheduler whose fallback algorithm is the oracle built
    from the same provider (CreateFromProvider seam, factory.go:248-342).

    `objective` (name or ObjectiveConfig; default: the provider's
    registered objective, then KTPU_OBJECTIVE) selects the kernel's solve
    mode.  In binpack mode the sequential fallback gains the
    MostRequestedPriority at the objective's weight, so a device outage
    degrades to the SAME packing policy; preemption/gang semantics are
    kernel-only — the fallback schedules those pods plainly."""
    from kubernetes_tpu.scheduler.generic import GenericScheduler
    from kubernetes_tpu.scheduler.objectives.config import resolve_objective
    from kubernetes_tpu.scheduler.provider import (
        DEFAULT_PROVIDER, get_predicates, get_priorities, get_provider,
    )
    prov = get_provider(provider_name or DEFAULT_PROVIDER)
    if objective is None:
        objective = prov.get("objective")
    obj_cfg = resolve_objective(objective, env=True)
    predicates = get_predicates(prov["predicates"], factory.plugin_args)
    priority_keys = list(prov["priorities"])
    prio_weights = None
    if obj_cfg is not None and obj_cfg.binpack and obj_cfg.binpack_weight \
            and "MostRequestedPriority" not in priority_keys:
        priority_keys.append("MostRequestedPriority")
        prio_weights = {"MostRequestedPriority": obj_cfg.binpack_weight}
    priorities = get_priorities(priority_keys, factory.plugin_args,
                                weights=prio_weights)
    algorithm = GenericScheduler(predicates, priorities)
    return BatchScheduler(factory, algorithm, batch_size=batch_size,
                          weights=weights, strict=strict,
                          stage_deadlines=stage_deadlines, explain=explain,
                          objective=obj_cfg, microbatch_ms=microbatch_ms)
