"""The TPU batch scheduling path, wired into the live scheduler shell.

This is the in-process form of the plug-in boundary the reference reserves
for exactly this kind of backend (plugin/pkg/scheduler/extender.go:39-173,
provider registry factory/plugins.go): instead of scheduling one FIFO pod at
a time through the sequential algorithm, the BatchScheduler drains the
pending queue into a batch, tensorizes it against the schedulercache
snapshot, runs the whole batch through the device kernel (ops/kernel.py) in
one program, and assumes+binds every result through the identical
assume/bind/backoff machinery the sequential loop uses
(scheduler.go:93-155 semantics, N pods per iteration).

Failure containment:
- a pod the kernel can't place follows the normal FailedScheduling path
  (event + PodScheduled=False + exponential backoff requeue);
- a device/tensorize error falls back to the sequential oracle algorithm for
  the whole drained batch, so a broken device degrades to reference behavior
  instead of wedging the queue.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import List, Optional

from kubernetes_tpu.api import types as api
from kubernetes_tpu.ops.kernel import Weights
from kubernetes_tpu.scheduler.factory import ConfigFactory, Scheduler
from kubernetes_tpu.scheduler.generic import FitError
from kubernetes_tpu.utils.metrics import REGISTRY as METRICS

log = logging.getLogger("scheduler.tpu")


class BatchScheduler(Scheduler):
    """Scheduler whose hot loop is the batched device kernel.

    `algorithm` is the sequential fallback (normally the oracle
    GenericScheduler built from the same provider keys) used when the device
    path fails.
    """

    def __init__(self, factory: ConfigFactory, algorithm,
                 batch_size: int = 4096, weights: Optional[Weights] = None,
                 bind_workers: int = 32):
        super().__init__(factory, algorithm)
        self.batch_size = batch_size
        self.weights = weights or Weights()
        self.kernel_batches = 0     # successful device batches
        self.kernel_pods = 0        # pods placed via the device path
        self.kernel_failures = 0    # device/tensorize errors (fell back)
        from concurrent.futures import ThreadPoolExecutor
        self._bind_pool = ThreadPoolExecutor(
            max_workers=bind_workers, thread_name_prefix="binder")

    def _spawn_bind(self, pod, dest, t_start, did_assume):
        self._bind_pool.submit(self._bind, pod, dest, t_start, did_assume)

    # --- one batch (the batched scheduleOne) ---------------------------------

    def schedule_batch_once(self, timeout: Optional[float] = None) -> int:
        """Drain up to batch_size pending pods and schedule them in one
        device program. Returns the number of pods processed (0 on queue
        timeout/close)."""
        first = self.f.pending.pop(timeout=timeout)
        if first is None:
            return 0
        pods = [first] + self.f.pending.drain(self.batch_size - 1)
        t_start = time.perf_counter()

        try:
            info = self.f.cache.get_node_name_to_info_map()
            nodes = self.f.node_lister.list()
            if not nodes:
                for pod in pods:
                    self._handle_failure(pod, FitError(pod, {}))
                return len(pods)
            node_set = {n.metadata.name for n in nodes}
            # every cached pod (incl. assumed ones from previous batches) on
            # a schedulable node is device state; pods on excluded nodes
            # still matter for nothing the kernel models per-node, so drop
            existing = [p for name, ni in info.items() if name in node_set
                        for p in ni.pods]
            with METRICS.time("scheduler_scheduling_algorithm_latency_seconds"):
                results = self._run_kernel(nodes, existing, pods)
            if len(results) != len(pods):
                raise RuntimeError(
                    f"kernel returned {len(results)} results for "
                    f"{len(pods)} pods")
        except Exception as e:
            self.kernel_failures += 1
            log.warning("TPU batch of %d failed (%s); sequential fallback",
                        len(pods), e)
            for pod in pods:
                self._schedule_pod(pod)
            return len(pods)

        self.kernel_batches += 1
        for pod, dest in zip(pods, results):
            if dest is None:
                self._handle_failure(pod, FitError(pod, {
                    "*": "kernel: no feasible node in batch"}))
                continue
            self.kernel_pods += 1
            self._assume_and_bind(pod, dest, t_start)
        return len(pods)

    def _run_kernel(self, nodes: List[api.Node], existing: List[api.Pod],
                    pending: List[api.Pod]) -> List[Optional[str]]:
        from kubernetes_tpu.scheduler.batch import tpu_batch
        return tpu_batch(nodes, existing, pending, self.f.plugin_args,
                         self.weights)

    # --- loop ----------------------------------------------------------------

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.schedule_batch_once(timeout=0.5)
            except Exception:
                log.exception("scheduleBatchOnce crashed")  # HandleCrash

    def stop(self):
        super().stop()
        self._bind_pool.shutdown(wait=False)


def create_batch_scheduler(factory: ConfigFactory,
                           provider_name: Optional[str] = None,
                           batch_size: int = 4096,
                           weights: Optional[Weights] = None) -> BatchScheduler:
    """Build a BatchScheduler whose fallback algorithm is the oracle built
    from the same provider (CreateFromProvider seam, factory.go:248-342)."""
    from kubernetes_tpu.scheduler.generic import GenericScheduler
    from kubernetes_tpu.scheduler.provider import (
        DEFAULT_PROVIDER, get_predicates, get_priorities, get_provider,
    )
    prov = get_provider(provider_name or DEFAULT_PROVIDER)
    predicates = get_predicates(prov["predicates"], factory.plugin_args)
    priorities = get_priorities(prov["priorities"], factory.plugin_args)
    algorithm = GenericScheduler(predicates, priorities)
    return BatchScheduler(factory, algorithm, batch_size=batch_size,
                          weights=weights)
