"""Scheduling-objective configuration + registry (ROADMAP items 3/5).

An ``ObjectiveConfig`` is a frozen, hashable description of which solve
modes the kernel traces — it rides the jit static key exactly like
``Weights``/``Features`` (ops/kernel.py), so every named objective is one
compiled program and the default config IS the pre-objective kernel
program, bit for bit.

Three built-in modes, composable:

- ``binpack``   fragmentation-minimizing score term (MostRequested over the
                node resource tensor — "Priority Matters", arxiv 2511.08373)
- ``preempt``   priority preemption: a pod with zero feasible nodes selects
                victims as a masked argmin over (victim priority, victim
                count) among strictly-lower-priority placed pods, inside the
                same solve; never preempts equal-or-higher priority
- ``gang``      all-or-nothing gang placement co-packed onto nodes sharing
                one topology-label domain (slice/rack — Tesserae, arxiv
                2508.04953), with partial placements rolled back inside the
                greedy commit scan

The registry mirrors the algorithm-provider registry (provider.py /
reference factory/plugins.go): objectives register by name, policy files
select them by name, and an unknown name is a loud KeyError — the seam that
turns every future objective into a config choice instead of a kernel fork.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple, Union

from kubernetes_tpu.api import types as api

# pod metadata carrying the objective inputs (v1.3-era alpha style:
# annotations/labels, no new API fields)
PRIORITY_ANNOTATION = "scheduler.ktpu.io/priority"
GANG_LABEL = "scheduler.ktpu.io/gang"

# victim priorities are small integers; this sentinel sorts after any of
# them in f32 without precision loss
INF_PRIORITY = 1e9


@dataclass(frozen=True)
class ObjectiveConfig:
    """Static solve-mode selection (hashable: part of the jit static key)."""

    name: str = "default"
    binpack: bool = False
    preempt: bool = False
    gang: bool = False
    binpack_weight: int = 1
    gang_topology_key: str = api.LABEL_ZONE

    @property
    def enabled(self) -> bool:
        """Any non-default mode traced. An all-off config selects the exact
        default kernel program."""
        return self.binpack or self.preempt or self.gang


DEFAULT_OBJECTIVE = ObjectiveConfig()

_OBJECTIVES: Dict[str, ObjectiveConfig] = {}


def register_objective(name: str, config: ObjectiveConfig) -> str:
    """Register a named objective (the provider-registry pattern)."""
    if not isinstance(config, ObjectiveConfig):
        raise TypeError(f"objective {name!r} must be an ObjectiveConfig, "
                        f"got {type(config).__name__}")
    _OBJECTIVES[name] = config
    return name


def get_objective(
        name: Union[str, ObjectiveConfig, None]) -> Optional[ObjectiveConfig]:
    """Resolve a name/config/None to an ObjectiveConfig (None and the
    default config both mean "default kernel program"). Unknown names raise
    KeyError, matching get_provider/get_predicates."""
    if name is None:
        return None
    if isinstance(name, ObjectiveConfig):
        return name
    if name not in _OBJECTIVES:
        raise KeyError(f"unknown scheduling objective {name!r}")
    return _OBJECTIVES[name]


def resolve_objective(
        name: Union[str, ObjectiveConfig, None],
        env: bool = False) -> Optional[ObjectiveConfig]:
    """get_objective plus the disabled normalization every consumer needs:
    None and an all-off config both select the default kernel program and
    resolve to None, so callers gate on ``objective is not None`` alone.
    With env=True a None name falls back to KTPU_OBJECTIVE first (the
    seam the soak and smoke tools use)."""
    if name is None and env:
        import os
        name = os.environ.get("KTPU_OBJECTIVE") or None
    cfg = get_objective(name)
    return cfg if cfg is not None and cfg.enabled else None


def objective_names() -> List[str]:
    return sorted(_OBJECTIVES)


register_objective("default", DEFAULT_OBJECTIVE)
register_objective("binpack", ObjectiveConfig(name="binpack", binpack=True))
register_objective("preempt", ObjectiveConfig(name="preempt", preempt=True))
register_objective("gang", ObjectiveConfig(name="gang", gang=True))
# the training-cluster shape (Tesserae + Priority Matters together): gangs
# co-packed by topology AND priority pods preempting when the cluster fills
register_objective("gang_preempt", ObjectiveConfig(
    name="gang_preempt", gang=True, preempt=True))


# --- pod-side inputs ----------------------------------------------------------

def pod_priority(pod: api.Pod) -> float:
    """Scheduling priority from the alpha annotation; 0 when absent or
    unparseable (a malformed annotation must not unschedule the pod)."""
    ann = (pod.metadata.annotations or {}) if pod.metadata else {}
    raw = ann.get(PRIORITY_ANNOTATION)
    if raw is None:
        return 0.0
    try:
        return float(int(raw))
    except (TypeError, ValueError):
        return 0.0


def pod_gang(pod: api.Pod) -> Optional[str]:
    """Namespace-qualified gang identity from the gang label, or None.
    Qualification matters: two teams independently labelling their jobs
    gang=train must NOT be fused into one all-or-nothing unit (one team's
    infeasible member would nullify the other team's placements). This is
    the single accessor — tensors, oracle, intake, and counters all key
    gangs through it."""
    if pod.metadata is None:
        return None
    g = (pod.metadata.labels or {}).get(GANG_LABEL)
    if not g:
        return None
    return f"{pod.metadata.namespace or 'default'}/{g}"


def gang_order(pending: List[api.Pod]) -> Tuple[List[api.Pod], List[int]]:
    """Stable reorder making gang members contiguous (at the position of
    each gang's first arrival) — the batch-order policy gang mode solves
    under, so the scan holds at most ONE open gang at a time. Returns
    (ordered pods, perm) with ordered[j] == pending[perm[j]]; callers map
    kernel outputs back via out[perm[j]] = result[j]."""
    first: Dict[str, int] = {}
    for i, pod in enumerate(pending):
        g = pod_gang(pod)
        if g is not None and g not in first:
            first[g] = i
    order = sorted(range(len(pending)), key=lambda i: (
        first.get(pod_gang(pending[i]) or "", i)
        if pod_gang(pending[i]) is not None else i, i))
    return [pending[i] for i in order], order
