"""The objective oracle: a fully independent node-by-node Python replay of
the kernel's objective modes (binpack / preempt / gang), built from the SAME
predicates/priorities the default-mode oracle uses.

This is the ground truth the oracle-equivalence tests pin the kernel
against: placements, victim sets, nominated nodes, gang verdicts, survivor
rows, and score decompositions must all match EXACTLY.  Unlike
explain.oracle_breakdown (which replays scoring at the kernel's
assignments), this oracle derives its own decisions — same argmax, same
round-robin tie counter, same preemption argmin — so a kernel bug can't
vouch for itself.

State-surgery semantics deliberately mirror the kernel's cheap carries
(ops/kernel.py greedy_commit docstring):

- preemption relieves a victim's RESOURCE occupancy only (cpu/mem/gpu/
  pod-slot/nonzero rows): the victim's ports, disks, spread membership and
  affinity hits keep their shadows until the next batch.  Implemented as
  arithmetic surgery on NodeInfo.requested plus a pod-slot credit — the
  victim pod object stays in NodeInfo.pods.
- a rolled-back gang member reverses resources, pod-slot, spread counts and
  attach counts (NodeInfo.remove_pod) but leaves port/disk occupancy and
  affinity hits shadowed (a per-node shadow NodeInfo holds the rolled-back
  pods for the port/disk rows only; the member stays in the pod lister).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.api import types as api
from kubernetes_tpu.scheduler.objectives.config import (
    ObjectiveConfig, pod_gang, pod_priority,
)
from kubernetes_tpu.scheduler.objectives.decode import (
    GangResult, ObjectiveOutcome, PreemptionDecision, annotate_records,
)


def _key(pod: api.Pod) -> str:
    m = pod.metadata
    return f"{m.namespace}/{m.name}" if m else ""


@dataclass
class OracleResult:
    """names: node per pod in input order (None = not bound this round);
    outcome: the objective verdicts; records: annotated DecisionRecords
    (the decode_batch + annotate_records shape)."""

    names: List[Optional[str]] = field(default_factory=list)
    outcome: ObjectiveOutcome = field(default_factory=ObjectiveOutcome)
    records: list = field(default_factory=list)


def oracle_objective(nodes: List[api.Node], existing: List[api.Pod],
                     pending: List[api.Pod], args,
                     objective: ObjectiveConfig,
                     weights=None) -> OracleResult:
    """Replay the batch under `objective`.  `pending` must already be in
    gang order (objectives.gang_order) when the config enables gang mode —
    the same contract the kernel solves under."""
    from kubernetes_tpu.api.serialization import deep_copy
    from kubernetes_tpu.observability.explain import (
        COMPONENT_ORDER, DecisionRecord,
    )
    from kubernetes_tpu.ops.kernel import Weights
    from kubernetes_tpu.scheduler import predicates as preds
    from kubernetes_tpu.scheduler import priorities as prios
    from kubernetes_tpu.scheduler.cache import (
        NodeInfo, pod_nonzero_request, pod_request,
    )

    w = weights or Weights()
    wd = dict(w.__dict__)
    if objective.binpack:
        wd["binpack"] = objective.binpack_weight

    info: Dict[str, NodeInfo] = {n.metadata.name: NodeInfo(n) for n in nodes}
    for ep in existing:
        name = ep.spec.node_name if ep.spec else ""
        if name in info:
            info[name].add_pod(ep)

    # shadows: per-node NodeInfo holding ONLY rolled-back gang members —
    # consulted by the port/disk rows, never by resources/volcaps/spread
    shadow: Dict[str, NodeInfo] = {n.metadata.name: NodeInfo()
                                   for n in nodes}
    # pod-slot credit from preemption evictions (victims stay in .pods so
    # their port/disk/spread shadows persist; only their slot is freed)
    pod_credit: Dict[str, int] = {n.metadata.name: 0 for n in nodes}

    # victim candidate tables: the tensorizer's exact order — per node,
    # placed pods sorted ascending by (priority, ns/name), terminating
    # excluded; in-batch commits are NOT candidates (tables are built at
    # tensorize time)
    victims: Dict[str, List[Tuple[float, str, api.Pod]]] = {}
    if objective.preempt:
        for ep in existing:
            name = ep.spec.node_name if ep.spec else ""
            if name not in info:
                continue
            if ep.metadata and ep.metadata.deletion_timestamp:
                continue
            victims.setdefault(name, []).append(
                (pod_priority(ep), _key(ep), ep))
        for lst in victims.values():
            lst.sort(key=lambda e: (e[0], e[1]))
    evicted: Dict[str, int] = {}

    pvc, pv = getattr(args, "pvc_lookup", None), getattr(args, "pv_lookup",
                                                         None)
    vz = preds.VolumeZoneChecker(pvc, pv) if pvc and pv else None
    vol_ebs = preds.MaxPDVolumeCountChecker(
        "ebs", preds.DEFAULT_MAX_EBS_VOLUMES, pvc, pv)
    vol_gce = preds.MaxPDVolumeCountChecker(
        "gce-pd", preds.DEFAULT_MAX_GCE_PD_VOLUMES, pvc, pv)
    interpod = preds.InterPodAffinity(args.pod_lister, args.node_lookup)
    interpod_prio = prios.InterPodAffinityPriority(
        args.pod_lister, args.node_lookup,
        getattr(args, "hard_pod_affinity_weight", 1))
    spread = prios.SelectorSpread(args.service_lister, args.controller_lister,
                                  args.replicaset_lister)
    prio_fns = {
        "least_requested": prios.least_requested,
        "balanced": prios.balanced_resource_allocation,
        "spread": spread,
        "node_affinity": prios.node_affinity_priority,
        "taint_toleration": prios.taint_toleration_priority,
        "interpod_affinity": interpod_prio,
        "image_locality": prios.image_locality_priority,
        "equal": prios.equal_priority,
        "binpack": prios.most_requested,
    }
    comp_names = [n for n in COMPONENT_ORDER if wd.get(n)]

    topo_key = objective.gang_topology_key
    gang_domain: Dict[str, Optional[str]] = {}
    gang_failed: set = set()
    gang_commits: Dict[str, List[Tuple[api.Pod, str]]] = {}
    gang_names_seen: List[str] = []
    gang_members: Dict[str, List[str]] = {}

    rr = 0  # selectHost round-robin counter (increments per commit)
    result = OracleResult()
    outcome = result.outcome
    outcome.objective = objective.name

    def node_label(node: api.Node, key: str) -> Optional[str]:
        return ((node.metadata.labels or {}) if node.metadata else {}
                ).get(key)

    def commit(pod: api.Pod, host: str) -> api.Pod:
        nonlocal rr
        committed = deep_copy(pod)
        committed.spec.node_name = host
        info[host].add_pod(committed)
        if hasattr(args.pod_lister, "pods"):
            args.pod_lister.pods.append(committed)
        rr += 1
        return committed

    for i, pod in enumerate(pending):
        req = pod_request(pod)
        zero_req = (req.milli_cpu == 0 and req.memory == 0 and req.gpu == 0)
        g = pod_gang(pod) if objective.gang else None
        if g is not None and g not in gang_members:
            gang_names_seen.append(g)
            gang_members[g] = []
            gang_domain[g] = None
            gang_commits[g] = []
        if g is not None:
            gang_members[g].append(_key(pod))

        sel_pod = deep_copy(pod)
        if sel_pod.spec:
            sel_pod.spec.affinity = None
        aff_pod = deep_copy(pod)
        if aff_pod.spec:
            aff_pod.spec.node_selector = None

        def _sel(p, ni):
            preds.pod_matches_node_selector(sel_pod, ni)
            if vz is not None:
                vz(p, ni)

        def _pods_row(p, ni):
            allowed = ni.allowed_pod_number
            live = len(ni.pods) - pod_credit[ni.node.metadata.name]
            if live + 1 > allowed:
                raise preds.PredicateFailure("Too many pods")

        def _res_row(attr):
            def chk(p, ni):
                if zero_req:
                    return
                used = getattr(ni.requested, attr)
                alloc = getattr(ni.allocatable, attr)
                if used + getattr(req, attr) > alloc:
                    raise preds.PredicateFailure(f"Insufficient {attr}")
            return chk

        def _ports(p, ni):
            preds.pod_fits_host_ports(p, ni)
            preds.pod_fits_host_ports(p, shadow[ni.node.metadata.name])

        def _disk(p, ni):
            preds.no_disk_conflict(p, ni)
            preds.no_disk_conflict(p, shadow[ni.node.metadata.name])

        def _volcap(p, ni):
            vol_ebs(p, ni)
            vol_gce(p, ni)

        checks = [
            _sel,
            lambda p, ni: preds.pod_matches_node_selector(aff_pod, ni),
            preds.pod_tolerates_node_taints,
            preds.check_node_memory_pressure,
            preds.pod_fits_host,
            _pods_row, _res_row("milli_cpu"), _res_row("memory"),
            _res_row("gpu"),
            _ports, _disk, _volcap,
            interpod,
        ]
        # resource-row indices (preemption can only relieve these)
        RES_ROWS = (5, 6, 7, 8)

        gang_row = None
        if objective.gang:
            failed = g is not None and g in gang_failed
            dom = gang_domain.get(g) if g is not None else None

            def gang_row(p, ni, _failed=failed, _dom=dom, _is_gang=g is not None):
                if not _is_gang:
                    return
                if _failed:
                    raise preds.PredicateFailure("gang already failed")
                val = node_label(ni.node, topo_key)
                if not val:
                    raise preds.PredicateFailure("no gang topology label")
                if _dom is not None and val != _dom:
                    raise preds.PredicateFailure("wrong gang domain")
            checks.append(gang_row)

        interpod.begin_pod(pod)
        cand = list(nodes)
        surv = []
        for chk in checks:
            kept = []
            for nd in cand:
                try:
                    chk(pod, info[nd.metadata.name])
                    kept.append(nd)
                except preds.PredicateFailure:
                    pass
            cand = kept
            surv.append(len(cand))

        rec = DecisionRecord(pod=_key(pod), node=None,
                             nodes_total=len(nodes), survivors=tuple(surv))
        result.records.append(rec)

        if cand:
            # --- score + selectHost (the kernel's exact argmax/tie-break) ---
            raw = {name: prio_fns[name](pod, info, cand)
                   for name in comp_names}
            totals = {nd.metadata.name: float(sum(
                wd[name] * raw[name][nd.metadata.name]
                for name in comp_names)) for nd in cand}
            best_score = max(totals.values())
            ties = [nd.metadata.name for nd in cand
                    if totals[nd.metadata.name] == best_score]
            host = ties[rr % len(ties)]
            rec.node = host
            rec.score = best_score
            rec.components = {
                name: float(wd[name] * raw[name][host])
                for name in COMPONENT_ORDER if name in comp_names}
            run_name, run_score = None, None
            for nd in cand:
                nm = nd.metadata.name
                if nm == host:
                    continue
                if run_score is None or totals[nm] > run_score:
                    run_name, run_score = nm, totals[nm]
            rec.runner_up, rec.runner_up_score = run_name, run_score
            if run_name is not None:
                rec.runner_up_components = {
                    name: float(wd[name] * raw[name][run_name])
                    for name in COMPONENT_ORDER if name in comp_names}
            committed = commit(pod, host)
            if g is not None:
                gang_commits[g].append((committed, host))
                if gang_domain[g] is None:
                    gang_domain[g] = node_label(info[host].node, topo_key)
            result.names.append(host)
            continue

        # --- no feasible node ------------------------------------------------
        if g is not None and g not in gang_failed:
            # all-or-nothing: fail the gang, roll prior members back
            gang_failed.add(g)
            for member, host in gang_commits[g]:
                info[host].remove_pod(member)
                # port/disk occupancy deliberately persists (the kernel's
                # vocab carry is not rolled back) — shadow it
                shadow[host].pods.append(member)
            gang_commits[g] = []
            result.names.append(None)
            continue

        if objective.preempt and g is None and not zero_req:
            decision = _try_preempt(pod, req, nodes, info, checks, RES_ROWS,
                                    victims, evicted, pod_credit)
        elif objective.preempt and g is None and zero_req:
            # a zero-request pod gains nothing from resource relief: the
            # kernel's fit rows are all vacuously true but okk requires a
            # strictly-lower-priority victim AND the pods row must fit —
            # replay the same arithmetic
            decision = _try_preempt(pod, req, nodes, info, checks, RES_ROWS,
                                    victims, evicted, pod_credit,
                                    zero_req=True)
        else:
            decision = None
        if decision is not None:
            pnode, k = decision
            vl = victims.get(pnode, [])
            e = evicted.get(pnode, 0)
            chosen = vl[e:e + k]
            evicted[pnode] = e + k
            for _prio, _vkey, vpod in chosen:
                vr = pod_request(vpod)
                vnz = pod_nonzero_request(vpod)
                ni = info[pnode]
                ni.requested.milli_cpu -= vr.milli_cpu
                ni.requested.memory -= vr.memory
                ni.requested.gpu -= vr.gpu
                ni.non_zero_requested.milli_cpu -= vnz.milli_cpu
                ni.non_zero_requested.memory -= vnz.memory
                pod_credit[pnode] += 1
            commit(pod, pnode)  # occupies the nominated node in-batch
            outcome.preemptions.append(PreemptionDecision(
                pod=_key(pod), node=pnode,
                victims=[vkey for _p, vkey, _pod in chosen]))
            result.names.append(None)  # nominated, not bound this round
            continue

        result.names.append(None)

    for g in gang_names_seen:
        outcome.gangs.append(GangResult(
            name=g, members=list(gang_members[g]),
            placed=g not in gang_failed))
    # the failed-gang / preemption view of names + records, via the SAME
    # transformation the kernel decode applies
    key_to_idx = {_key(p): i for i, p in enumerate(pending)}
    for gr in outcome.gangs:
        if gr.placed:
            continue
        for m in gr.members:
            result.names[key_to_idx[m]] = None
    annotate_records(result.records, outcome)
    return result


def _try_preempt(pod, req, nodes, info, checks, res_rows, victims, evicted,
                 pod_credit, zero_req: bool = False):
    """The kernel's masked-argmin victim selection, node by node: returns
    (node, k) — the nomination with the lowest (highest-victim-priority,
    victim-count, node-order) — or None.  `checks` is this pod's row list;
    everything except the resource rows must pass on CURRENT state (the
    kernel's `nonres` mask)."""
    from kubernetes_tpu.scheduler import predicates as preds
    from kubernetes_tpu.scheduler.cache import pod_request

    prio = pod_priority(pod)
    cands = []  # (top_victim_priority, k, node_order)
    for order, nd in enumerate(nodes):
        name = nd.metadata.name
        ni = info[name]
        ok = True
        for row, chk in enumerate(checks):
            if row in res_rows:
                continue
            try:
                chk(pod, ni)
            except preds.PredicateFailure:
                ok = False
                break
        if not ok:
            continue
        vl = victims.get(name, [])
        e = evicted.get(name, 0)
        relief_cpu = relief_mem = relief_gpu = 0
        found_k = None
        for k in range(1, len(vl) - e + 1):
            vprio, _vkey, vpod = vl[e + k - 1]
            if vprio >= prio:
                break  # sorted ascending: no larger k can qualify either
            vr = pod_request(vpod)
            relief_cpu += vr.milli_cpu
            relief_mem += vr.memory
            relief_gpu += vr.gpu
            alloc = ni.allocatable
            live = len(ni.pods) - pod_credit[name]
            if live - k + 1 > ni.allowed_pod_number:
                continue
            if not zero_req:
                if ni.requested.milli_cpu - relief_cpu + req.milli_cpu \
                        > alloc.milli_cpu:
                    continue
                if ni.requested.memory - relief_mem + req.memory \
                        > alloc.memory:
                    continue
                if ni.requested.gpu - relief_gpu + req.gpu > alloc.gpu:
                    continue
            found_k = k
            break
        if found_k is not None:
            top = vl[e + found_k - 1][0]
            cands.append((top, found_k, order))
    if not cands:
        return None
    top, k, order = min(cands)
    return nodes[order].metadata.name, k
