"""Scheduling objectives: bin-packing, priority preemption, gang placement
as tensor solve modes behind the algorithm-provider seam (ROADMAP 3/5).

- ``config``  ObjectiveConfig + the named-objective registry (the provider
              pattern: objectives are config choices, not kernel forks)
- ``tensors`` the extra device operands each mode solves on, shared by the
              full Tensorizer and the incremental mirror
- ``decode``  host decode of kernel objective outputs -> ObjectiveOutcome
              (victim sets, nominated nodes, gang verdicts)
- ``oracle``  the node-by-node Python replay every mode must match exactly
"""

from kubernetes_tpu.scheduler.objectives.config import (  # noqa: F401
    DEFAULT_OBJECTIVE, GANG_LABEL, PRIORITY_ANNOTATION, ObjectiveConfig,
    gang_order, get_objective, objective_names, pod_gang, pod_priority,
    register_objective,
)
