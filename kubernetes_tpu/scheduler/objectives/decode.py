"""Host decode of the kernel's objective outputs into an ObjectiveOutcome.

The kernel surfaces two raw facts per solve: a per-pod victim count
(``pk``, 0 = no preemption) and the final per-gang failed flags. Everything
operator-facing — which victims, which nominated node, which gangs placed —
is reconstructed here by replaying the scan's pod order against the
host-side victim order the tensorizer recorded, exactly like
assignments_to_names is the one decoder for assignments.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kubernetes_tpu.scheduler.generic import FitError


@dataclass
class PreemptionDecision:
    """One preemptor's nomination: the node and the exact victim set."""

    pod: str                      # preemptor, ns/name
    node: str                     # nominated node
    victims: List[str]            # ns/name, eviction order (priority asc)


@dataclass
class GangResult:
    name: str
    members: List[str]
    placed: bool


@dataclass
class ObjectiveOutcome:
    objective: str = "default"
    preemptions: List[PreemptionDecision] = field(default_factory=list)
    gangs: List[GangResult] = field(default_factory=list)

    @property
    def gangs_placed(self) -> int:
        return sum(1 for g in self.gangs if g.placed)

    @property
    def gangs_rejected(self) -> int:
        return sum(1 for g in self.gangs if not g.placed)

    def to_dict(self) -> dict:
        return {
            "objective": self.objective,
            "preemptions": [
                {"pod": p.pod, "node": p.node, "victims": list(p.victims)}
                for p in self.preemptions],
            "gangs": [{"name": g.name, "members": list(g.members),
                       "placed": g.placed} for g in self.gangs],
        }


def preemption_message(node: str, victims: List[str]) -> str:
    """The ONE preemption sentence every surface carries (FailedScheduling
    event, Unschedulable condition, /explainz reason) — agreement across
    them is asserted live by tools/objectives_smoke.py."""
    return (f"0 nodes were immediately available; nominated node "
            f"{node} after preempting {len(victims)} "
            f"lower-priority pod(s): {', '.join(victims)}")


class PreemptionFitError(FitError):
    """The preemptor's scheduling 'failure': not bound this round, but with
    victims evicted and the nominated node on the condition/event (the
    reference's nominatedNodeName flow)."""

    def __init__(self, pod, decision: PreemptionDecision):
        FitError.__init__(self, pod, {})
        self.decision = decision
        self.signature = ("Preemption",)
        self._message = preemption_message(decision.node, decision.victims)

    def __str__(self) -> str:
        return self._message


class GangFitError(FitError):
    """A gang member rejected because its gang could not be co-placed."""

    def __init__(self, pod, gang: GangResult, message: Optional[str] = None):
        FitError.__init__(self, pod, {})
        self.gang = gang
        self.signature = ("GangRejected", gang.name)
        self._message = message or (
            f"gang {gang.name!r} rejected: {len(gang.members)} member(s) "
            f"could not be co-placed all-or-nothing on one "
            f"topology domain")

    def __str__(self) -> str:
        return self._message


def decode_objective(ct, out, objout: dict, objective,
                     names: List[Optional[str]]) -> ObjectiveOutcome:
    """Decode raw kernel objective outputs; mutates `names` to the
    host-visible all-or-nothing / not-bound view (gang-rejected members and
    preemptors read as unplaced)."""
    import numpy as np

    outcome = ObjectiveOutcome(objective=objective.name)
    oi = getattr(ct, "objective_info", None)

    if objective.preempt and "pk" in objout:
        pk = np.asarray(objout["pk"])
        evicted: Dict[int, int] = {}
        order = oi.victim_order if oi is not None else []
        for i in range(ct.n_real_pods):
            k = int(pk[i])
            if k <= 0:
                continue
            n = int(out[i])
            e = evicted.get(n, 0)
            victims = (order[n][e:e + k]
                       if 0 <= n < len(order) else [])
            evicted[n] = e + k
            outcome.preemptions.append(PreemptionDecision(
                pod=ct.pod_keys[i],
                node=ct.node_names[n] if 0 <= n < len(ct.node_names) else "",
                victims=list(victims)))
            names[i] = None   # nominated, not bound this round

    if objective.gang and "gang_failed" in objout and oi is not None:
        failed = np.asarray(objout["gang_failed"])
        by_name = {g: bool(failed[gid] > 0)
                   for gid, g in enumerate(oi.gang_names)}
        for g in oi.gang_names:
            outcome.gangs.append(GangResult(
                name=g, members=list(oi.gang_members.get(g, [])),
                placed=not by_name[g]))
        if any(by_name.values()):
            gang_of = {}
            for gid, g in enumerate(oi.gang_names):
                for m in oi.gang_members.get(g, []):
                    gang_of[m] = g
            for i in range(ct.n_real_pods):
                g = gang_of.get(ct.pod_keys[i])
                if g is not None and by_name[g]:
                    names[i] = None   # all-or-nothing: the gang failed

    return outcome


def _clear_placement(rec) -> None:
    """A record with an objective verdict (preemption pending, gang
    rejected) has no winner this round — blank the placement fields."""
    rec.node = None
    rec.score = None
    rec.components = {}
    rec.runner_up = None
    rec.runner_up_score = None
    rec.runner_up_components = {}


def annotate_records(records, outcome: ObjectiveOutcome) -> None:
    """Stamp decision records (observability/explain.py) with the
    objective verdicts so /explainz, the FailedScheduling event, and
    kubectl describe stay truthful in every mode."""
    by_pod = {r.pod: r for r in records}
    for pd in outcome.preemptions:
        rec = by_pod.get(pd.pod)
        if rec is None:
            continue
        _clear_placement(rec)
        rec.preemption = {"node": pd.node, "victims": list(pd.victims)}
    for g in outcome.gangs:
        for m in g.members:
            rec = by_pod.get(m)
            if rec is None:
                continue
            rec.gang = {"name": g.name,
                        "outcome": "placed" if g.placed else "rejected"}
            if not g.placed:
                _clear_placement(rec)
