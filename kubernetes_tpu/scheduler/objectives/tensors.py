"""Objective tensorization: the extra operands the objective modes solve on.

Shared by the full Tensorizer (ops/tensorize.py) and the incremental mirror
(ops/incremental.py) — both hand this module a slot-indexed view of their
node space and the placed-pod set, and get back the SAME tensor layout, so
the kernel traces one program regardless of which tensorize path fed it.

Arrays (absent entirely when the objective doesn't need them — the default
program's input signature, and therefore its jit key and compiled HLO, is
untouched):

- ``pod_priority``  [P]        f32   preempt: pending-pod priorities
- ``vict_prio``     [KV, N]    f32   preempt: priority of the k-th
                                     lowest-priority victim candidate per
                                     node slot (INF_PRIORITY padded)
- ``vict_cum``      [6, KV+1, N] f32 preempt: cumulative resource relief of
                                     evicting the k lowest-priority victims
                                     (rows: cpu, mem MiB, gpu, pods,
                                     nonzero-cpu, nonzero-mem MiB)
- ``pod_gang``      [P]        i32   gang: gang slot per pod (null = GG-1)
- ``gang_dom0``     [GG]       i32   gang: chosen topology domain carry
                                     init (-1 = none yet)
- ``gang_failed0``  [GG]       f32   gang: failed-flag carry init (0)
- ``node_gang_dom`` [N]        i32   gang: topology-domain id per node slot
                                     under the objective's topology key
                                     (-1 = node lacks the label)

Host-side decode info (never uploaded): per-slot victim order (the k-prefix
the kernel's victim count indexes into) and gang names/members.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np

from kubernetes_tpu.api import types as api
from kubernetes_tpu.scheduler.objectives.config import (
    INF_PRIORITY, ObjectiveConfig, pod_gang, pod_priority,
)


def _pow2(n: int, floor: int = 8) -> int:
    out = floor
    while out < n:
        out *= 2
    return out


class ObjectiveInfo:
    """Host-side decode companion to the objective arrays."""

    def __init__(self):
        self.victim_order: List[List[str]] = []   # per node slot, ns/name
        self.gang_names: List[str] = []           # gang slot -> name
        self.gang_members: Dict[str, List[str]] = {}   # name -> pod keys
        self.n_gangs: int = 0


def build_objective_tensors(
        objective: ObjectiveConfig,
        pending: List[api.Pod],
        Pp: int,
        n_cap: int,
        node_labels_of: Callable[[int], dict],
        placed: Iterable[Tuple[api.Pod, int]],
) -> Tuple[Dict[str, np.ndarray], ObjectiveInfo]:
    """Build the mode-gated objective arrays.

    `node_labels_of(slot)` returns the labels dict for a node slot (empty
    for holes); `placed` yields (pod, slot) for every evictable placed pod
    (callers exclude terminating pods — a pod already on its way out is not
    a victim worth nominating).
    """
    arrays: Dict[str, np.ndarray] = {}
    info = ObjectiveInfo()
    P = len(pending)

    if objective.preempt:
        prio = np.zeros(Pp, np.float32)
        for p, pod in enumerate(pending):
            prio[p] = pod_priority(pod)
        arrays["pod_priority"] = prio

        # victim candidates per slot, sorted ascending (priority, pod key)
        # — the deterministic order the kernel's k-prefix eviction and the
        # oracle replay both index into
        per_slot: Dict[int, list] = {}
        for pod, slot in placed:
            key = (f"{pod.metadata.namespace}/{pod.metadata.name}"
                   if pod.metadata else "")
            per_slot.setdefault(slot, []).append(
                (pod_priority(pod), key, pod))
        vmax = max((len(v) for v in per_slot.values()), default=0)
        KV = _pow2(max(vmax, 1))
        vict_prio = np.full((KV, n_cap), INF_PRIORITY, np.float32)
        vict_cum = np.zeros((6, KV + 1, n_cap), np.float32)
        info.victim_order = [[] for _ in range(n_cap)]
        from kubernetes_tpu.ops.tensorize import _pod_req_vec
        for slot, entries in per_slot.items():
            entries.sort(key=lambda e: (e[0], e[1]))
            info.victim_order[slot] = [k for _, k, _ in entries]
            acc = np.zeros(6, np.float32)
            for j, (pr, _key, pod) in enumerate(entries):
                vict_prio[j, slot] = pr
                rq, nz = _pod_req_vec(pod)
                acc = acc + np.concatenate([rq, nz]).astype(np.float32)
                vict_cum[:, j + 1, slot] = acc
            # beyond the last victim the prefix stays flat (clipped gathers
            # then read "no further relief")
            for j in range(len(entries) + 1, KV + 1):
                vict_cum[:, j, slot] = acc
        arrays["vict_prio"] = vict_prio
        arrays["vict_cum"] = vict_cum

    if objective.gang:
        gang_ids: Dict[str, int] = {}
        for pod in pending:
            g = pod_gang(pod)
            if g is not None and g not in gang_ids:
                gang_ids[g] = len(gang_ids)
                info.gang_names.append(g)
                info.gang_members[g] = []
        info.n_gangs = len(gang_ids)
        GG = _pow2(info.n_gangs + 1)      # last slot = the null gang
        null = GG - 1
        pg = np.full(Pp, null, np.int32)
        for p, pod in enumerate(pending):
            g = pod_gang(pod)
            if g is not None:
                pg[p] = gang_ids[g]
                info.gang_members[g].append(
                    f"{pod.metadata.namespace}/{pod.metadata.name}")
        arrays["pod_gang"] = pg
        arrays["gang_dom0"] = np.full(GG, -1, np.int32)
        arrays["gang_failed0"] = np.zeros(GG, np.float32)

        dom_ids: Dict[str, int] = {}
        ngd = np.full(n_cap, -1, np.int32)
        key = objective.gang_topology_key
        for slot in range(n_cap):
            val = (node_labels_of(slot) or {}).get(key)
            if val:
                if val not in dom_ids:
                    dom_ids[val] = len(dom_ids)
                ngd[slot] = dom_ids[val]
        arrays["node_gang_dom"] = ngd

    return arrays, info
