"""kube-scheduler entrypoint: python -m kubernetes_tpu.scheduler

Flags bind to KubeSchedulerConfiguration (componentconfig), served at
/configz on the scheduler's own debug port alongside /healthz (fed by the
kernel health state) and /metrics — the reference mux on :10251
(plugin/cmd/kube-scheduler/app/server.go:71-181, options.go:40-74).

--tpu-backend (default on) runs the batched device kernel behind the
provider seam; off = the sequential oracle loop."""

from __future__ import annotations

import argparse
import json
import logging
import signal
import sys
import threading

from kubernetes_tpu.apis.componentconfig import (
    KubeSchedulerConfiguration, LeaderElectionConfiguration,
)
from kubernetes_tpu.scheduler.factory import ConfigFactory
from kubernetes_tpu.utils.debugserver import DebugServer, client_from_url


def build_config(argv=None) -> KubeSchedulerConfiguration:
    p = argparse.ArgumentParser(prog="kube-scheduler")
    p.add_argument("--master", default="http://127.0.0.1:8080")
    p.add_argument("--port", type=int, default=10251)
    p.add_argument("--scheduler-name", default="default-scheduler")
    p.add_argument("--algorithm-provider", default="DefaultProvider")
    p.add_argument("--policy-config-file", default="")
    p.add_argument("--hard-pod-affinity-symmetric-weight", type=int, default=1)
    p.add_argument("--kube-api-qps", type=float, default=5000.0)
    p.add_argument("--kube-api-burst", type=int, default=5000)
    p.add_argument("--leader-elect", action="store_true")
    p.add_argument("--tpu-backend", default="true",
                   choices=("true", "false"))
    p.add_argument("--batch-size", type=int, default=4096)
    a = p.parse_args(argv)
    cfg = KubeSchedulerConfiguration(
        scheduler_name=a.scheduler_name,
        algorithm_provider=a.algorithm_provider,
        policy_config_file=a.policy_config_file,
        hard_pod_affinity_symmetric_weight=a.hard_pod_affinity_symmetric_weight,
        kube_api_qps=a.kube_api_qps, kube_api_burst=a.kube_api_burst,
        leader_election=LeaderElectionConfiguration(leader_elect=a.leader_elect),
        port=a.port, master=a.master, tpu_backend=a.tpu_backend == "true",
        batch_size=a.batch_size)
    return cfg


def build_scheduler(cfg: KubeSchedulerConfiguration, client):
    factory = ConfigFactory(
        client, scheduler_name=cfg.scheduler_name,
        hard_pod_affinity_weight=cfg.hard_pod_affinity_symmetric_weight)
    factory.run()
    if cfg.policy_config_file:
        with open(cfg.policy_config_file, encoding="utf-8") as f:
            policy = json.load(f)
        sched = factory.create_from_policy(policy)
    elif cfg.tpu_backend:
        # warm-start discipline: the persistent compilation cache makes a
        # restarted scheduler's first compile a disk load, not a ~30s XLA
        # run (the batch bucketing pins shapes, so the key is stable)
        from kubernetes_tpu.utils.platform import (
            enable_persistent_compilation_cache,
        )
        try:
            enable_persistent_compilation_cache()
        except Exception as e:
            # the cache is an optimization, never a startup blocker — but a
            # cold compile on every restart is worth a visible warning
            logging.getLogger("scheduler").warning(
                "persistent compilation cache unavailable "
                "(every restart pays a cold XLA compile): %s", e)
        sched = factory.create_batch_from_provider(
            cfg.algorithm_provider, batch_size=cfg.batch_size)
    else:
        sched = factory.create_from_provider(cfg.algorithm_provider)
    return factory, sched


def main(argv=None) -> int:
    cfg = build_config(argv)
    client = client_from_url(cfg.master, qps=cfg.kube_api_qps,
                             burst=cfg.kube_api_burst)
    factory, sched = build_scheduler(cfg, client)
    debug = DebugServer(
        port=cfg.port,
        healthz=lambda: (sched.healthy() if hasattr(sched, "healthy")
                         else True),
        configz={"componentconfig": cfg}).start()
    print(f"scheduler debug on http://127.0.0.1:{debug.port}", flush=True)

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    signal.signal(signal.SIGINT, lambda *a: stop.set())

    if cfg.leader_election and cfg.leader_election.leader_elect:
        from kubernetes_tpu.client.leaderelection import (
            LeaderElectionConfig, LeaderElector,
        )
        import os
        elector = LeaderElector(
            client, LeaderElectionConfig(
                lock_name="kube-scheduler",
                identity=f"{cfg.scheduler_name}-{os.getpid()}"),
            on_started_leading=lambda: sched.run(),
            on_stopped_leading=lambda: stop.set())
        elector.run()
    else:
        sched.run()
    stop.wait()
    sched.stop()
    factory.stop()
    debug.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
