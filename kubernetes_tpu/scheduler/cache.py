"""The scheduler's world model: per-node aggregates + assume/confirm/expire.

Parity target: reference plugin/pkg/scheduler/schedulercache —
NodeInfo (node_info.go:32-49: node, requestedResource, nonzeroRequest, pods,
allowedPodNumber) and the optimistic assume protocol (cache.go:101-127,
278-308): AssumePod books resources immediately with a TTL; the informer's
Add for the same pod confirms it (cancels the deadline); if confirmation
never arrives the assume expires and the booking is rolled back — the system
self-repairs failed bindings by timeout, not rollback (SURVEY §3.2).

Time is injected everywhere (assume_pod takes `now`) exactly like the
reference's cache_test.go:536 pattern, so the state machine is testable
deterministically.
"""

from __future__ import annotations

import logging
import threading
import time as _time
from typing import Callable, Dict, List, Optional

from kubernetes_tpu.api import types as api
from kubernetes_tpu.api.quantity import parse_cpu, parse_quantity
from kubernetes_tpu.client.cache import meta_namespace_key

# Non-zero request defaults (reference plugin/pkg/scheduler/algorithm/
# priorities/util/non_zero.go): pods with no requests still consume
# *something*; spreading math uses these floors.
DEFAULT_MILLI_CPU_REQUEST = 100
DEFAULT_MEMORY_REQUEST = 200 * 1024 * 1024

_log = logging.getLogger("scheduler.cache")


class Resource:
    """Canonical integer resource vector (milliCPU, bytes, gpu count)."""

    __slots__ = ("milli_cpu", "memory", "gpu")

    def __init__(self, milli_cpu: int = 0, memory: int = 0, gpu: int = 0):
        self.milli_cpu = milli_cpu
        self.memory = memory
        self.gpu = gpu

    def __repr__(self):
        return f"Resource(cpu={self.milli_cpu}m, mem={self.memory}, gpu={self.gpu})"

    def __eq__(self, other):
        return (self.milli_cpu, self.memory, self.gpu) == (
            other.milli_cpu, other.memory, other.gpu)


def pod_request(pod: api.Pod) -> Resource:
    r = api.pod_resource_request(pod)
    return Resource(r[api.RESOURCE_CPU], r[api.RESOURCE_MEMORY],
                    r[api.RESOURCE_GPU])


def pod_nonzero_request(pod: api.Pod) -> Resource:
    """Requests with per-container floors for cpu/mem (non_zero.go)."""
    cpu = mem = 0
    for c in (pod.spec.containers if pod.spec and pod.spec.containers else []):
        req = (c.resources.requests if c.resources and c.resources.requests else {})
        ccpu = parse_cpu(req.get(api.RESOURCE_CPU, 0))
        cmem = parse_quantity(req.get(api.RESOURCE_MEMORY, 0))
        cpu += ccpu if ccpu else DEFAULT_MILLI_CPU_REQUEST
        mem += cmem if cmem else DEFAULT_MEMORY_REQUEST
    return Resource(cpu, mem, 0)


class NodeInfo:
    """Aggregated per-node view (node_info.go:32-49)."""

    def __init__(self, node: Optional[api.Node] = None):
        self.node: Optional[api.Node] = node
        self.pods: List[api.Pod] = []
        self.requested = Resource()
        self.non_zero_requested = Resource()

    # --- derived -------------------------------------------------------------

    @property
    def allocatable(self) -> Resource:
        if self.node is None:
            return Resource()
        a = api.node_allocatable(self.node)
        return Resource(a[api.RESOURCE_CPU], a[api.RESOURCE_MEMORY],
                        a[api.RESOURCE_GPU])

    @property
    def allowed_pod_number(self) -> int:
        if self.node is None:
            return 0
        return api.node_allocatable(self.node)[api.RESOURCE_PODS]

    def used_ports(self) -> set:
        ports = set()
        for p in self.pods:
            for c in (p.spec.containers or []) if p.spec else []:
                for port in c.ports or []:
                    if port.host_port:
                        ports.add((port.protocol or "TCP", port.host_port))
        return ports

    # --- mutation (addPod/removePod, node_info.go:118-156) -------------------

    def add_pod(self, pod: api.Pod):
        r = pod_request(pod)
        nz = pod_nonzero_request(pod)
        self.requested.milli_cpu += r.milli_cpu
        self.requested.memory += r.memory
        self.requested.gpu += r.gpu
        self.non_zero_requested.milli_cpu += nz.milli_cpu
        self.non_zero_requested.memory += nz.memory
        self.pods.append(pod)

    def remove_pod(self, pod: api.Pod) -> bool:
        key = meta_namespace_key(pod)
        for i, p in enumerate(self.pods):
            if meta_namespace_key(p) == key:
                r = pod_request(p)
                nz = pod_nonzero_request(p)
                self.requested.milli_cpu -= r.milli_cpu
                self.requested.memory -= r.memory
                self.requested.gpu -= r.gpu
                self.non_zero_requested.milli_cpu -= nz.milli_cpu
                self.non_zero_requested.memory -= nz.memory
                del self.pods[i]
                return True
        return False

    def clone(self) -> "NodeInfo":
        ni = NodeInfo(self.node)
        ni.pods = list(self.pods)
        ni.requested = Resource(self.requested.milli_cpu, self.requested.memory,
                                self.requested.gpu)
        ni.non_zero_requested = Resource(self.non_zero_requested.milli_cpu,
                                         self.non_zero_requested.memory, 0)
        return ni


class SchedulerCache:
    """Thread-safe assume/confirm/expire cache (cache.go).

    State machine per pod key:
      assume_pod    -> assumed (deadline = now+ttl), resources booked
      add_pod       -> confirmed if assumed (deadline cleared), else added
      update_pod    -> re-aggregate
      remove_pod    -> unbooked
      cleanup(now)  -> expired assumes rolled back
    """

    def __init__(self, ttl: float = 30.0, clock: Callable[[], float] = _time.monotonic):
        self._lock = threading.Lock()
        self.ttl = ttl
        self._clock = clock
        self._nodes: Dict[str, NodeInfo] = {}
        self._assumed: Dict[str, float] = {}   # pod key -> deadline (None=confirmed)
        self._pod_states: Dict[str, api.Pod] = {}  # key -> pod as last cached
        self._listeners: List[object] = []

    # --- delta listeners ------------------------------------------------------
    #
    # The incremental tensorizer (ops/incremental.py) mirrors this cache as
    # device-ready arrays. Listeners get every placed-pod and node mutation
    # *under the cache lock*, so they observe the exact serialized order of
    # state changes — the delta stream that replaces the per-batch world
    # rebuild (the cache.go:77-85 clone-per-decision anti-pattern).

    def add_listener(self, listener) -> None:
        """listener may implement pod_added(pod), pod_removed(pod),
        node_added(node), node_updated(node), node_removed(node); pod events
        fire only for pods with a node assignment (placed or assumed)."""
        with self._lock:
            self._listeners.append(listener)
            for name, ni in self._nodes.items():
                if ni.node is not None:
                    _notify(listener, "node_added", ni.node)
                for p in ni.pods:
                    _notify(listener, "pod_added", p)

    def remove_listener(self, listener) -> None:
        with self._lock:
            try:
                self._listeners.remove(listener)
            except ValueError:
                pass

    def _fire(self, event: str, obj) -> None:
        for l in self._listeners:
            _notify(l, event, obj)

    # --- pods ----------------------------------------------------------------

    def assume_pod(self, pod: api.Pod, now: Optional[float] = None) -> None:
        """Book the pod's resources on its (just-decided) node immediately,
        before the binding round-trips (cache.go:101-127)."""
        key = meta_namespace_key(pod)
        with self._lock:
            if key in self._pod_states:
                raise ValueError(f"pod {key} already in cache")
            self._add_locked(pod)
            self._assumed[key] = (now if now is not None else self._clock()) + self.ttl

    def add_pod(self, pod: api.Pod) -> None:
        """Informer-confirmed add (cache.go AddPod): confirms an assume or
        adds fresh state."""
        key = meta_namespace_key(pod)
        with self._lock:
            if key in self._assumed:
                # confirmation: re-aggregate with the authoritative object
                self._remove_locked(self._pod_states[key])
                del self._assumed[key]
                self._add_locked(pod)
            elif key in self._pod_states:
                self._remove_locked(self._pod_states[key])
                self._add_locked(pod)
            else:
                self._add_locked(pod)

    def update_pod(self, pod: api.Pod) -> None:
        self.add_pod(pod)

    def remove_pod(self, pod: api.Pod) -> None:
        key = meta_namespace_key(pod)
        with self._lock:
            cached = self._pod_states.get(key)
            if cached is not None:
                self._remove_locked(cached)
                self._assumed.pop(key, None)

    def is_assumed(self, pod: api.Pod) -> bool:
        with self._lock:
            return meta_namespace_key(pod) in self._assumed

    def cleanup_expired(self, now: Optional[float] = None) -> List[str]:
        """Roll back assumes whose confirmation never arrived
        (cache.go:278-308 cleanupAssumedPods). Returns expired keys."""
        now = now if now is not None else self._clock()
        expired = []
        with self._lock:
            for key, deadline in list(self._assumed.items()):
                if deadline <= now:
                    self._remove_locked(self._pod_states[key])
                    del self._assumed[key]
                    expired.append(key)
        return expired

    # --- nodes ---------------------------------------------------------------

    def add_node(self, node: api.Node) -> None:
        with self._lock:
            ni = self._nodes.get(node.metadata.name)
            if ni is None:
                ni = self._nodes[node.metadata.name] = NodeInfo(node)
                self._fire("node_added", node)
            else:
                fresh = ni.node is None
                ni.node = node
                self._fire("node_added" if fresh else "node_updated", node)

    def update_node(self, node: api.Node) -> None:
        self.add_node(node)

    def remove_node(self, node: api.Node) -> None:
        with self._lock:
            ni = self._nodes.get(node.metadata.name)
            if ni is not None:
                ni.node = None
                if not ni.pods:
                    del self._nodes[node.metadata.name]
                self._fire("node_removed", node)

    # --- reads ---------------------------------------------------------------

    def get_node_name_to_info_map(self) -> Dict[str, NodeInfo]:
        """Full snapshot clone under the lock (cache.go:77-85) — the hot-path
        cost the TPU backend's incremental tensor sync exists to avoid."""
        with self._lock:
            return {name: ni.clone() for name, ni in self._nodes.items()}

    def pod_count(self) -> int:
        with self._lock:
            return len(self._pod_states)

    # --- internals (lock held) -----------------------------------------------

    def _add_locked(self, pod: api.Pod):
        node_name = pod.spec.node_name if pod.spec else ""
        placed = bool(node_name)
        if placed:
            ni = self._nodes.get(node_name)
            if ni is None:
                # pod observed before its node: keep aggregates anyway
                ni = self._nodes[node_name] = NodeInfo(None)
            ni.add_pod(pod)
        # fire only after the cache mutation is complete, so a throwing
        # listener can never leave a booked-but-untracked phantom pod
        self._pod_states[meta_namespace_key(pod)] = pod
        if placed:
            self._fire("pod_added", pod)

    def _remove_locked(self, pod: api.Pod):
        node_name = pod.spec.node_name if pod.spec else ""
        if node_name:
            ni = self._nodes.get(node_name)
            if ni is not None:
                if ni.remove_pod(pod):
                    self._fire("pod_removed", pod)
                if ni.node is None and not ni.pods:
                    del self._nodes[node_name]
        self._pod_states.pop(meta_namespace_key(pod), None)


def _notify(listener, event: str, obj) -> None:
    fn = getattr(listener, event, None)
    if fn is None:
        return
    try:
        fn(obj)
    except Exception:  # a broken mirror must never corrupt the cache or
        _log.exception("cache listener %s(%s) failed",  # drop a batch
                       event, getattr(listener, "__class__", type(listener)).__name__)
