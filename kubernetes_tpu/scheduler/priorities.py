"""The score stage: priority functions, each mapping nodes to 0-10 scores.

Parity target: reference plugin/pkg/scheduler/algorithm/priorities (1,016 ln).
Signature: `fn(pod, node_name_to_info, nodes) -> Dict[node_name, int]`;
the generic scheduler weight-sums them (generic_scheduler.go:242-298).

Inventory (SURVEY §2.5) with reference anchors and the exact integer math
(truncation points matter for bit-identical parity with the Go code):
  least_requested          priorities.go:33-92   ((cap-req)*10/cap, int-div,
                                                 avg of cpu+mem, int-div by 2)
  balanced_resource        priorities.go:215-268 (10 - |cpuFrac-memFrac|*10)
  selector_spread          selector_spreading.go:84-235 (zoneWeighting=2/3)
  service_anti_affinity    selector_spreading.go:238-319
  inter_pod_affinity       interpod_affinity.go:86-216 (weighted terms +
                                                 symmetry, min-max normalized)
  node_affinity            node_affinity.go:44-95 (preferred weight sum)
  taint_toleration         taint_toleration.go:65-110 (PreferNoSchedule count)
  node_label               priorities.go:99-135
  image_locality           priorities.go:137-207 (23MB..1GB buckets)
  equal                    generic_scheduler.go:308
"""

from __future__ import annotations

from typing import Dict, List, Optional

from kubernetes_tpu.api import labels as labelsel
from kubernetes_tpu.api import types as api
from kubernetes_tpu.scheduler.cache import NodeInfo, pod_nonzero_request
from kubernetes_tpu.scheduler.predicates import (
    _pod_matches_term, _same_topology, node_taints, pod_tolerations,
)

MAX_PRIORITY = 10

Scores = Dict[str, int]


def _calculate_score(requested: int, capacity: int) -> int:
    """(cap-req)*10/cap with integer truncation (priorities.go:33-43)."""
    if capacity == 0 or requested > capacity:
        return 0
    return ((capacity - requested) * MAX_PRIORITY) // capacity


def _pod_nonzero_totals(pod: api.Pod, ni: NodeInfo):
    nz = pod_nonzero_request(pod)
    total_cpu = ni.non_zero_requested.milli_cpu + nz.milli_cpu
    total_mem = ni.non_zero_requested.memory + nz.memory
    return total_cpu, total_mem


def least_requested(pod: api.Pod, info: Dict[str, NodeInfo],
                    nodes: List[api.Node]) -> Scores:
    out = {}
    for node in nodes:
        ni = info.get(node.metadata.name) or NodeInfo(node)
        cpu, mem = _pod_nonzero_totals(pod, ni)
        alloc = ni.allocatable if ni.node else NodeInfo(node).allocatable
        cpu_score = _calculate_score(cpu, alloc.milli_cpu)
        mem_score = _calculate_score(mem, alloc.memory)
        out[node.metadata.name] = (cpu_score + mem_score) // 2
    return out


def most_requested(pod: api.Pod, info: Dict[str, NodeInfo],
                   nodes: List[api.Node]) -> Scores:
    """MostRequested: _calculate_score inverted — fuller nodes score higher,
    minimizing fragmentation across the cluster (the binpack objective's
    Python reference; "Priority Matters", arxiv 2511.08373)."""
    out = {}
    for node in nodes:
        ni = info.get(node.metadata.name) or NodeInfo(node)
        cpu, mem = _pod_nonzero_totals(pod, ni)
        alloc = ni.allocatable if ni.node else NodeInfo(node).allocatable
        cpu_score = _calculate_inverted(cpu, alloc.milli_cpu)
        mem_score = _calculate_inverted(mem, alloc.memory)
        out[node.metadata.name] = (cpu_score + mem_score) // 2
    return out


def _calculate_inverted(requested: int, capacity: int) -> int:
    """req*10/cap with integer truncation; 0 when over capacity or the
    capacity is unknown — the exact mirror of the kernel's binpack term."""
    if capacity == 0 or requested > capacity:
        return 0
    return (requested * MAX_PRIORITY) // capacity


def balanced_resource_allocation(pod: api.Pod, info: Dict[str, NodeInfo],
                                 nodes: List[api.Node]) -> Scores:
    out = {}
    for node in nodes:
        ni = info.get(node.metadata.name) or NodeInfo(node)
        cpu, mem = _pod_nonzero_totals(pod, ni)
        alloc = ni.allocatable if ni.node else NodeInfo(node).allocatable
        cpu_frac = (cpu / alloc.milli_cpu) if alloc.milli_cpu else 1.0
        mem_frac = (mem / alloc.memory) if alloc.memory else 1.0
        if cpu_frac >= 1 or mem_frac >= 1:
            score = 0
        else:
            score = int(MAX_PRIORITY - abs(cpu_frac - mem_frac) * MAX_PRIORITY)
        out[node.metadata.name] = score
    return out


def _zone_key(node: api.Node) -> str:
    """region:zone composite (selector_spreading.go getZoneKey)."""
    lbls = (node.metadata.labels or {}) if node.metadata else {}
    region = lbls.get(api.LABEL_REGION, "")
    zone = lbls.get(api.LABEL_ZONE, "")
    if not region and not zone:
        return ""
    return f"{region}:{zone}"


ZONE_WEIGHTING = 2.0 / 3.0  # selector_spreading.go:36


class SelectorSpread:
    """Spread same-service/RC/RS pods across nodes and zones
    (selector_spreading.go:84-235)."""

    def __init__(self, service_lister, controller_lister, replicaset_lister):
        self.service_lister = service_lister
        self.controller_lister = controller_lister
        self.replicaset_lister = replicaset_lister

    def _selectors(self, pod: api.Pod) -> List[labelsel.Selector]:
        sels = []
        for svc in self.service_lister.get_pod_services(pod):
            sels.append(labelsel.selector_from_map(svc.spec.selector))
        for rc in self.controller_lister.get_pod_controllers(pod):
            sels.append(labelsel.selector_from_map(rc.spec.selector))
        for rs in self.replicaset_lister.get_pod_replica_sets(pod):
            sels.append(labelsel.selector_from_label_selector(rs.spec.selector))
        return sels

    def __call__(self, pod: api.Pod, info: Dict[str, NodeInfo],
                 nodes: List[api.Node]) -> Scores:
        selectors = self._selectors(pod)
        counts: Dict[str, int] = {}
        if selectors:
            for node in nodes:
                ni = info.get(node.metadata.name)
                count = 0
                for np in (ni.pods if ni else []):
                    if np.metadata.namespace != pod.metadata.namespace:
                        continue
                    if np.metadata.deletion_timestamp:
                        continue  # replacement-scheduling: ignore dying pods
                    np_labels = np.metadata.labels or {}
                    if any(s.matches(np_labels) for s in selectors):
                        count += 1
                counts[node.metadata.name] = count
        max_by_node = max(counts.values(), default=0)
        zone_counts: Dict[str, int] = {}
        for node in nodes:
            c = counts.get(node.metadata.name)
            if c is None:
                continue
            zk = _zone_key(node)
            if zk:
                zone_counts[zk] = zone_counts.get(zk, 0) + c
        max_by_zone = max(zone_counts.values(), default=0)
        out = {}
        for node in nodes:
            fscore = float(MAX_PRIORITY)
            if max_by_node > 0:
                fscore = MAX_PRIORITY * (
                    (max_by_node - counts.get(node.metadata.name, 0)) / max_by_node)
            # max_by_zone == 0 with zones present would be 0/0 (the reference
            # hits float32 NaN here); canonical semantics: skip the blend
            if zone_counts and max_by_zone > 0:
                zk = _zone_key(node)
                if zk:
                    zscore = MAX_PRIORITY * ((max_by_zone - zone_counts[zk]) / max_by_zone)
                    fscore = fscore * (1.0 - ZONE_WEIGHTING) + ZONE_WEIGHTING * zscore
            out[node.metadata.name] = int(fscore)
        return out


class ServiceAntiAffinity:
    """Spread a service's pods across values of a node label
    (selector_spreading.go:238-319)."""

    def __init__(self, pod_lister, service_lister, label: str):
        self.pod_lister = pod_lister
        self.service_lister = service_lister
        self.label = label

    def __call__(self, pod: api.Pod, info: Dict[str, NodeInfo],
                 nodes: List[api.Node]) -> Scores:
        # pods of this pod's service(s), grouped by the label value of their node
        services = self.service_lister.get_pod_services(pod)
        matched: List[api.Pod] = []
        if services:
            sel = labelsel.selector_from_map(services[0].spec.selector)
            matched = [p for p in self.pod_lister.list(sel)
                       if p.metadata.namespace == pod.metadata.namespace
                       and p.spec and p.spec.node_name]
        node_by_name = {n.metadata.name: n for n in nodes}
        value_counts: Dict[str, int] = {}
        for p in matched:
            n = node_by_name.get(p.spec.node_name)
            if n is None:
                continue
            v = (n.metadata.labels or {}).get(self.label, "")
            value_counts[v] = value_counts.get(v, 0) + 1
        max_count = max(value_counts.values(), default=0)
        out = {}
        for node in nodes:
            v = (node.metadata.labels or {}).get(self.label, "")
            c = value_counts.get(v, 0)
            score = MAX_PRIORITY if max_count == 0 else int(
                MAX_PRIORITY * ((max_count - c) / max_count))
            out[node.metadata.name] = score
        return out


def node_affinity_priority(pod: api.Pod, info: Dict[str, NodeInfo],
                           nodes: List[api.Node]) -> Scores:
    """Sum weights of matching PreferredDuringScheduling terms, normalized to
    0-10 by the max (node_affinity.go:44-95)."""
    from kubernetes_tpu.scheduler.predicates import _term_matches_node
    counts: Dict[str, int] = {n.metadata.name: 0 for n in nodes}
    aff = pod.spec.affinity if pod.spec else None
    na = aff.node_affinity if aff else None
    terms = (na.preferred_during_scheduling_ignored_during_execution or []) if na else []
    for pref in terms:
        if not pref.weight or pref.preference is None:
            continue
        for node in nodes:
            if _term_matches_node(pref.preference, node):
                counts[node.metadata.name] += pref.weight
    max_count = max(counts.values(), default=0)
    return {name: (int(MAX_PRIORITY * c / max_count) if max_count else 0)
            for name, c in counts.items()}


def taint_toleration_priority(pod: api.Pod, info: Dict[str, NodeInfo],
                              nodes: List[api.Node]) -> Scores:
    """Fewer intolerable PreferNoSchedule taints is better
    (taint_toleration.go:65-110)."""
    prefer_tolerations = [t for t in pod_tolerations(pod)
                          if t.effect == api.TAINT_PREFER_NO_SCHEDULE or not t.effect]
    counts = {}
    for node in nodes:
        count = 0
        for taint in node_taints(node):
            if taint.effect != api.TAINT_PREFER_NO_SCHEDULE:
                continue
            if not any(t.tolerates(taint) for t in prefer_tolerations):
                count += 1
        counts[node.metadata.name] = count
    max_count = max(counts.values(), default=0)
    out = {}
    for node in nodes:
        if max_count > 0:
            out[node.metadata.name] = int(
                (1.0 - counts[node.metadata.name] / max_count) * MAX_PRIORITY)
        else:
            out[node.metadata.name] = MAX_PRIORITY
    return out


class NodeLabelPriority:
    """10 for nodes with (presence=True) / without (False) the label
    (priorities.go:99-135)."""

    def __init__(self, label: str, presence: bool):
        self.label = label
        self.presence = presence

    def __call__(self, pod: api.Pod, info: Dict[str, NodeInfo],
                 nodes: List[api.Node]) -> Scores:
        out = {}
        for node in nodes:
            exists = self.label in ((node.metadata.labels or {}) if node.metadata else {})
            out[node.metadata.name] = MAX_PRIORITY if exists == self.presence else 0
        return out


_MB = 1024 * 1024
MIN_IMG_SIZE = 23 * _MB
MAX_IMG_SIZE = 1000 * _MB


def image_locality_priority(pod: api.Pod, info: Dict[str, NodeInfo],
                            nodes: List[api.Node]) -> Scores:
    """Nodes already holding the pod's images score by total present size,
    bucketed 23MB..1GB -> 0..10 (priorities.go:137-207)."""
    out = {}
    for node in nodes:
        total = 0
        images = (node.status.images or []) if node.status else []
        for c in (pod.spec.containers or []) if pod.spec else []:
            for img in images:
                if c.image in (img.names or []):
                    total += img.size_bytes
                    break
        if total == 0 or total < MIN_IMG_SIZE:
            score = 0
        elif total >= MAX_IMG_SIZE:
            score = MAX_PRIORITY
        else:
            score = int((MAX_PRIORITY * (total - MIN_IMG_SIZE)
                         ) // (MAX_IMG_SIZE - MIN_IMG_SIZE) + 1)
        out[node.metadata.name] = score
    return out


def equal_priority(pod: api.Pod, info: Dict[str, NodeInfo],
                   nodes: List[api.Node]) -> Scores:
    """(generic_scheduler.go:308)."""
    return {n.metadata.name: 1 for n in nodes}


class InterPodAffinityPriority:
    """Weighted preferred affinity/anti-affinity in both directions plus the
    implicit weight for existing pods' *hard* affinity terms that match the
    incoming pod, min-max normalized to 0-10 (interpod_affinity.go:86-216)."""

    def __init__(self, pod_lister, node_lookup, hard_pod_affinity_weight: int = 1,
                 failure_domains=(api.LABEL_HOSTNAME, api.LABEL_ZONE, api.LABEL_REGION)):
        self.pod_lister = pod_lister
        self.node_lookup = node_lookup
        self.hard_weight = hard_pod_affinity_weight
        self.failure_domains = tuple(failure_domains)

    def _count_matches(self, pod, all_pods, node, term) -> int:
        """Existing pods matching `pod`'s term within node's topology."""
        n = 0
        for ep in all_pods:
            if not (ep.spec and ep.spec.node_name):
                continue
            if not _pod_matches_term(ep, pod, term):
                continue
            ep_node = self.node_lookup(ep.spec.node_name)
            if _same_topology(ep_node, node, term.topology_key, self.failure_domains):
                n += 1
        return n

    def _matches_reverse(self, pod, node, ep, term) -> bool:
        """Does the incoming pod (placed on `node`) match existing pod `ep`'s
        term within ep's topology?"""
        if not _pod_matches_term(pod, ep, term):
            return False
        ep_node = self.node_lookup(ep.spec.node_name) if ep.spec and ep.spec.node_name else None
        return _same_topology(node, ep_node, term.topology_key, self.failure_domains)

    def __call__(self, pod: api.Pod, info: Dict[str, NodeInfo],
                 nodes: List[api.Node]) -> Scores:
        all_pods = self.pod_lister.list()
        aff = pod.spec.affinity if pod.spec else None
        counts: Dict[str, int] = {}
        for node in nodes:
            total = 0
            if aff and aff.pod_affinity:
                for wt in (aff.pod_affinity.preferred_during_scheduling_ignored_during_execution or []):
                    if wt.weight and wt.pod_affinity_term:
                        total += wt.weight * self._count_matches(
                            pod, all_pods, node, wt.pod_affinity_term)
            if aff and aff.pod_anti_affinity:
                for wt in (aff.pod_anti_affinity.preferred_during_scheduling_ignored_during_execution or []):
                    if wt.weight and wt.pod_affinity_term:
                        total -= wt.weight * self._count_matches(
                            pod, all_pods, node, wt.pod_affinity_term)
            # reverse direction: existing pods' preferences about us
            for ep in all_pods:
                ep_aff = ep.spec.affinity if ep.spec else None
                if ep_aff and ep_aff.pod_affinity:
                    if self.hard_weight > 0:
                        for term in (ep_aff.pod_affinity.required_during_scheduling_ignored_during_execution or []):
                            if self._matches_reverse(pod, node, ep, term):
                                total += self.hard_weight
                    for wt in (ep_aff.pod_affinity.preferred_during_scheduling_ignored_during_execution or []):
                        if wt.weight and wt.pod_affinity_term and self._matches_reverse(
                                pod, node, ep, wt.pod_affinity_term):
                            total += wt.weight
                if ep_aff and ep_aff.pod_anti_affinity:
                    for wt in (ep_aff.pod_anti_affinity.preferred_during_scheduling_ignored_during_execution or []):
                        if wt.weight and wt.pod_affinity_term and self._matches_reverse(
                                pod, node, ep, wt.pod_affinity_term):
                            total -= wt.weight
            counts[node.metadata.name] = total
        # the reference's max/min start at 0 (`var maxCount int`), so the
        # normalization window always includes zero
        max_c = max(list(counts.values()) + [0])
        min_c = min(list(counts.values()) + [0])
        out = {}
        for node in nodes:
            if max_c - min_c > 0:
                out[node.metadata.name] = int(
                    MAX_PRIORITY * (counts[node.metadata.name] - min_c) / (max_c - min_c))
            else:
                out[node.metadata.name] = 0
        return out
