"""The generic scheduler: filter -> score -> select.

Parity target: reference plugin/pkg/scheduler/generic_scheduler.go —
Schedule() (:70-114): list nodes, snapshot cache, findNodesThatFit (:137,
16-way parallel in Go; a thread pool here), extender filters (:164-175),
PrioritizeNodes (:220-305, weighted sum), selectHost (:116-133, sort desc +
round-robin among max-score ties).

The oracle path runs these sequentially per pod; the TPU backend computes the
same mask/score matrices batched (ops/) and must agree bit-for-bit — ties are
resolved against a canonical node order (the node list order) since the Go
implementation's own tie order is map-iteration dependent (SURVEY §7 "hard
parts" #1: we match the *set* of valid outcomes with a deterministic choice).
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, List, Optional, Tuple

from kubernetes_tpu.api import types as api
from kubernetes_tpu.scheduler.cache import NodeInfo
from kubernetes_tpu.scheduler.predicates import PredicateFailure
from kubernetes_tpu.utils.metrics import REGISTRY as METRICS
from kubernetes_tpu.utils.trace import Trace

PARALLEL_WORKERS = 16  # generic_scheduler.go:159 workqueue.Parallelize(16, ...)


class FitError(Exception):
    """No node fits; carries per-node failure reasons
    (generic_scheduler.go:40-67)."""

    def __init__(self, pod: api.Pod, failed_predicates: Dict[str, str]):
        self.pod = pod
        self.failed_predicates = failed_predicates
        name = pod.metadata.name if pod.metadata else "?"
        super().__init__(
            f"pod ({name}) failed to fit in any node: "
            + "; ".join(f"{n}: {r}" for n, r in sorted(failed_predicates.items())[:5]))


class PriorityConfig:
    def __init__(self, function: Callable, weight: int = 1, name: str = ""):
        assert weight >= 0
        self.function = function
        self.weight = weight
        self.name = name or getattr(function, "__name__", "priority")


class GenericScheduler:
    def __init__(self, predicates: Dict[str, Callable],
                 priorities: List[PriorityConfig],
                 extenders: Optional[list] = None,
                 parallel: bool = True):
        self.predicates = predicates
        self.priorities = priorities
        self.extenders = extenders or []
        self._last_node_index = 0  # selectHost round-robin state (:37)
        self._pool = ThreadPoolExecutor(max_workers=PARALLEL_WORKERS) if parallel else None

    # --- Schedule (generic_scheduler.go:70) ----------------------------------

    def schedule(self, pod: api.Pod, info: Dict[str, NodeInfo],
                 nodes: List[api.Node]) -> str:
        trace = Trace("Scheduling", pod=(pod.metadata.name if pod.metadata else "?"))
        if not nodes:
            raise FitError(pod, {"": "no nodes available to schedule pods"})
        with trace.step("Computing predicates"):
            fit_nodes, failures = self.find_nodes_that_fit(pod, info, nodes)
        if not fit_nodes:
            raise FitError(pod, failures)
        with trace.step("Prioritizing"):
            scores = self.prioritize_nodes(pod, info, fit_nodes)
        with trace.step("Selecting host"):
            host = self.select_host(scores, fit_nodes)
        trace.log_if_slow(0.020)  # 20ms threshold (generic_scheduler.go:77)
        return host

    # --- filter (findNodesThatFit, :137) -------------------------------------

    def find_nodes_that_fit(self, pod: api.Pod, info: Dict[str, NodeInfo],
                            nodes: List[api.Node]
                            ) -> Tuple[List[api.Node], Dict[str, str]]:
        failures: Dict[str, str] = {}
        lock = threading.Lock()

        # per-decision precomputation (predicate metadata): one snapshot,
        # not one per node under the parallel filter
        for pred in self.predicates.values():
            begin = getattr(pred, "begin_pod", None)
            if begin is not None:
                begin(pod)

        def check(node: api.Node) -> Optional[api.Node]:
            ni = info.get(node.metadata.name) or NodeInfo(node)
            for name, pred in self.predicates.items():
                try:
                    pred(pod, ni)
                except PredicateFailure as e:
                    with lock:
                        failures[node.metadata.name] = f"{name}: {e.reason}"
                    return None
            return node

        if self._pool is not None and len(nodes) > 1:
            results = list(self._pool.map(check, nodes))
        else:
            results = [check(n) for n in nodes]
        fit = [n for n in results if n is not None]
        # extender filters run serially after local predicates (:164-175)
        for ext in self.extenders:
            if not fit:
                break
            fit, ext_failures = ext.filter(pod, fit)
            failures.update(ext_failures)
        return fit, failures

    # --- score (PrioritizeNodes, :220) ---------------------------------------

    def prioritize_nodes(self, pod: api.Pod, info: Dict[str, NodeInfo],
                         nodes: List[api.Node]) -> Dict[str, int]:
        if not self.priorities and not self.extenders:
            return {n.metadata.name: 1 for n in nodes}
        combined: Dict[str, int] = {n.metadata.name: 0 for n in nodes}
        lock = threading.Lock()

        def run_one(cfg: PriorityConfig):
            if cfg.weight == 0:
                return
            scores = cfg.function(pod, info, nodes)
            with lock:
                for name, s in scores.items():
                    if name in combined:
                        combined[name] += s * cfg.weight

        if self._pool is not None and len(self.priorities) > 1:
            list(self._pool.map(run_one, self.priorities))
        else:
            for cfg in self.priorities:
                run_one(cfg)
        for ext in self.extenders:
            ext_scores = ext.prioritize(pod, nodes)
            for name, s in ext_scores.items():
                if name in combined:
                    combined[name] += s
        return combined

    # --- select (selectHost, :116-133) ---------------------------------------

    def select_host(self, scores: Dict[str, int], nodes: List[api.Node]) -> str:
        """Max score wins; ties broken round-robin over the canonical node
        order with persistent state, mirroring lastNodeIndex (:118-133)."""
        if not scores:
            raise ValueError("empty priority list")
        max_score = max(scores.values())
        best = [n.metadata.name for n in nodes
                if scores.get(n.metadata.name, 0) == max_score]
        if not best:  # scores for nodes not in list (extender edge); fallback
            best = sorted(k for k, v in scores.items() if v == max_score)
        idx = self._last_node_index % len(best)
        self._last_node_index += 1
        return best[idx]
